"""Parameter initializers (reference: python/paddle/fluid/initializer.py).

Each initializer appends ONE op to the startup program that produces the
parameter value; the Executor runs the startup program once, on device, so
even ResNet-scale init happens as a single compiled XLA program.
"""

import math

from .core.program import default_startup_program


class Initializer(object):
    def __call__(self, var, block=None):
        raise NotImplementedError

    @staticmethod
    def _startup_block(block):
        return block if block is not None else \
            default_startup_program().global_block()

    @staticmethod
    def _fan_in_out(var):
        shape = var.shape
        if len(shape) < 2:
            return (shape[0] if shape else 1,) * 2
        if len(shape) == 2:
            return shape[0], shape[1]
        receptive = 1
        for s in shape[2:]:
            receptive *= s
        # conv OIHW: fan_in = I*r, fan_out = O*r
        return shape[1] * receptive, shape[0] * receptive


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block=None):
        b = self._startup_block(block)
        b.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                     persistable=True)
        b.append_op(type='fill_constant', outputs={'Out': [var.name]},
                    attrs={'shape': list(var.shape), 'value': self.value})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block=None):
        b = self._startup_block(block)
        b.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                     persistable=True)
        b.append_op(type='uniform_random', outputs={'Out': [var.name]},
                    attrs={'shape': list(var.shape), 'min': self.low,
                           'max': self.high, 'seed': self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block=None):
        b = self._startup_block(block)
        b.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                     persistable=True)
        b.append_op(type='gaussian_random', outputs={'Out': [var.name]},
                    attrs={'shape': list(var.shape), 'mean': self.loc,
                           'std': self.scale, 'seed': self.seed})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block=None):
        b = self._startup_block(block)
        b.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                     persistable=True)
        b.append_op(type='truncated_gaussian_random',
                    outputs={'Out': [var.name]},
                    attrs={'shape': list(var.shape), 'mean': self.loc,
                           'std': self.scale, 'seed': self.seed})


class XavierInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in, self.fan_out, self.seed = fan_in, fan_out, seed

    def __call__(self, var, block=None):
        fan_in, fan_out = self._fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fan_in
        fan_out = self.fan_out if self.fan_out is not None else fan_out
        if self.uniform:
            limit = math.sqrt(6.0 / (fan_in + fan_out))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fan_in + fan_out))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block=None):
        fan_in, _ = self._fan_in_out(var)
        fan_in = self.fan_in if self.fan_in is not None else fan_in
        if self.uniform:
            limit = math.sqrt(6.0 / fan_in)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fan_in)
            NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """For conv_transpose upsampling kernels (initializer.py Bilinear)."""

    def __call__(self, var, block=None):
        import numpy as np
        shape = var.shape
        if len(shape) != 4:
            raise ValueError('Bilinear initializer needs a 4-D weight')
        c_out, c_in, h, w = shape
        f = np.ceil(w / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype='float32')
        og = np.ogrid[:h, :w]
        filt = (1 - abs(og[0] / f - c)) * (1 - abs(og[1] / f - c))
        weight[range(min(c_out, c_in)), range(min(c_out, c_in)), :, :] = filt
        b = self._startup_block(block)
        b.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                     persistable=True)
        b.append_op(type='assign_value', outputs={'Out': [var.name]},
                    attrs={'values': weight.tolist(),
                           'shape': list(shape)})


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, var, block=None):
        import numpy as np
        b = self._startup_block(block)
        b.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                     persistable=True)
        b.append_op(type='assign_value', outputs={'Out': [var.name]},
                    attrs={'values': np.asarray(self.value).tolist(),
                           'shape': list(np.asarray(self.value).shape)})


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def force_init_on_cpu():
    return False


import contextlib


@contextlib.contextmanager
def init_on_cpu():
    yield
