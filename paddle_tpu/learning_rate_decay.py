"""LR decay schedules (reference: python/paddle/fluid/learning_rate_decay.py).

Each schedule is ONE fused lr_decay op reading the auto-incremented global
step counter (ops/lr_ops.py)."""

from .layers import nn as _nn
from .layers.helper import LayerHelper

__all__ = ['exponential_decay', 'natural_exp_decay', 'inverse_time_decay',
           'polynomial_decay', 'piecewise_decay', 'cosine_decay',
           'noam_decay']


def _decay_op(attrs):
    helper = LayerHelper('lr_decay')
    step = _nn.autoincreased_step_counter(counter_name='@LR_DECAY_COUNTER@',
                                          begin=0)
    out = helper.create_variable_for_type_inference('float32')
    out.shape = (1,)
    out.stop_gradient = True
    helper.append_op(type='lr_decay', inputs={'Step': [step]},
                     outputs={'Out': [out]}, attrs=attrs)
    return out


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    return _decay_op({'kind': 'exponential',
                      'learning_rate': float(learning_rate),
                      'decay_steps': decay_steps, 'decay_rate': decay_rate,
                      'staircase': staircase})


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    return _decay_op({'kind': 'natural_exp',
                      'learning_rate': float(learning_rate),
                      'decay_steps': decay_steps, 'decay_rate': decay_rate,
                      'staircase': staircase})


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    return _decay_op({'kind': 'inverse_time',
                      'learning_rate': float(learning_rate),
                      'decay_steps': decay_steps, 'decay_rate': decay_rate,
                      'staircase': staircase})


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    return _decay_op({'kind': 'polynomial',
                      'learning_rate': float(learning_rate),
                      'decay_steps': decay_steps,
                      'end_learning_rate': end_learning_rate,
                      'power': power, 'cycle': cycle})


def piecewise_decay(boundaries, values):
    if len(values) - len(boundaries) != 1:
        raise ValueError('len(values) must be len(boundaries) + 1')
    return _decay_op({'kind': 'piecewise',
                      'learning_rate': float(values[0]),
                      'boundaries': [float(b) for b in boundaries],
                      'values': [float(v) for v in values]})


def cosine_decay(learning_rate, step_each_epoch, epochs):
    return _decay_op({'kind': 'cosine',
                      'learning_rate': float(learning_rate),
                      'total_steps': float(step_each_epoch * epochs)})


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    return _decay_op({'kind': 'noam', 'learning_rate': float(learning_rate),
                      'd_model': float(d_model),
                      'warmup_steps': float(warmup_steps)})
