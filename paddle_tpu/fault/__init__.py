"""Fault tolerance: preemption-tolerant training.

Reference analog: the reference survived worker death through the
etcd-backed master (go/master/service.go persists the task queue and
recovers mid-epoch) and parameter servers that outlived trainers. The
TPU-native, masterless rebuild gets the same guarantees from three
local pieces wired through the Trainer:

- periodic mid-epoch checkpoints with keep-last-K retention and an
  atomically-updated LATEST pointer (`manager.CheckpointManager`),
- auto-resume from the newest COMPLETE checkpoint — manifest
  sha1-verified, falling back to the previous one on corruption
  (`CheckpointConfig(dirname, resume=True)`),
- bad-step guards: a NaN/Inf sentinel on the fetched loss with a
  configurable policy (`guards.BadStepGuard`) and `reader.retry` for
  transient input errors,
- ELASTIC resume: checkpoints are topology-neutral (io.py records the
  writing mesh + per-var logical sharding specs), so a run preempted
  on one slice restores on whatever slice comes back — params and
  optimizer state reshard onto the new mesh, and the reader position
  (kept in global stream units) replays exactly the untrained
  remainder at the new dp width.

`inject` is the deterministic fault-injection harness that proves the
above end-to-end: kill or SIGTERM-preempt at step k, truncate a
checkpoint mid-write, poison batch k with NaNs, make a reader raise
transiently.
"""

from .config import CheckpointConfig  # noqa: F401
from .manager import (CheckpointManager, LATEST_FILE,  # noqa: F401
                      NoUsableCheckpointError)
from .guards import BadStepError, BadStepGuard, NAN_POLICIES, is_bad  # noqa
from . import inject  # noqa: F401

__all__ = ['CheckpointConfig', 'CheckpointManager', 'LATEST_FILE',
           'NoUsableCheckpointError', 'BadStepError', 'BadStepGuard',
           'NAN_POLICIES', 'is_bad', 'inject']
