"""Deterministic fault injection: the harness that PROVES the
fault-tolerance layer instead of trusting it.

Five injectable faults, each deterministic (fixed step index, no
randomness — reruns reproduce exactly):

- kill the process once the global step reaches k (os._exit — the
  abrupt end of a preemption's grace window),
- SIGTERM the process at step k (the preemption NOTICE itself: the
  flight-recorder SIGTERM handler gets to dump a postmortem before the
  default action terminates — what a real TPU preemption looks like
  from inside),
- truncate a checkpoint file right after it commits (a write torn by
  preemption, or bit-rot/partial copy that survived the atomic rename),
- poison batch k's float arrays with NaNs (corrupt input),
- make a reader raise transiently (flaky storage),
- kill one serving replica mid-load (``kill_replica`` — the fleet
  chaos scenario's replica-down event; the router's failover and the
  /readyz flip are asserted against it).

Hook points: the Trainer calls fire('step_end', step=...) after each
step, the CheckpointManager calls fire('checkpoint_saved', ...) after
each commit. Both are no-ops without an installed plan.

Env contract (for subprocess crash/resume drills — the resumed run must
NOT set these again or it re-dies at the same step; an elastic-resume
drill relaunches on a DIFFERENT mesh, see tests/fault_injection_child.py
FT_MESH_DP):

    PADDLE_TPU_FI_KILL_AT_STEP=k     os._exit(42) at global step >= k
    PADDLE_TPU_FI_PREEMPT_AT_STEP=k  SIGTERM self at global step >= k
                                     (subprocess exit code -SIGTERM)
    PADDLE_TPU_FI_CORRUPT_CKPT_AT=k  truncate params.npz of the
                                     checkpoint committed at step k
"""

import os

__all__ = ['KILL_EXIT_CODE', 'FaultPlan', 'TransientReaderError',
           'install', 'install_from_env', 'clear', 'active', 'fire',
           'truncate_file', 'poison_nans', 'flaky', 'kill_replica',
           'crash_loop', 'kill_process']

KILL_EXIT_CODE = 42
_ENV_KILL = 'PADDLE_TPU_FI_KILL_AT_STEP'
_ENV_PREEMPT = 'PADDLE_TPU_FI_PREEMPT_AT_STEP'
_ENV_CORRUPT = 'PADDLE_TPU_FI_CORRUPT_CKPT_AT'


class TransientReaderError(IOError):
    """Injected transient input failure (reader.retry's target class)."""


class FaultPlan(object):
    def __init__(self, kill_at_step=None, corrupt_checkpoint_at_step=None,
                 preempt_at_step=None):
        self.kill_at_step = kill_at_step
        self.corrupt_checkpoint_at_step = corrupt_checkpoint_at_step
        self.preempt_at_step = preempt_at_step


_active = None


def install(plan):
    global _active
    _active = plan


def clear():
    global _active
    _active = None


def active():
    return _active


def install_from_env(environ=None):
    """Install a plan from the PADDLE_TPU_FI_* vars. No-op when none are
    set or when a plan was already installed programmatically."""
    env = os.environ if environ is None else environ
    if _active is not None:
        return _active
    kill = env.get(_ENV_KILL)
    preempt = env.get(_ENV_PREEMPT)
    corrupt = env.get(_ENV_CORRUPT)
    if kill is None and corrupt is None and preempt is None:
        return None
    plan = FaultPlan(
        kill_at_step=int(kill) if kill else None,
        corrupt_checkpoint_at_step=int(corrupt) if corrupt else None,
        preempt_at_step=int(preempt) if preempt else None)
    install(plan)
    return plan


def fire(point, step=None, dirname=None):
    plan = _active
    if plan is None:
        return
    if (point == 'step_end' and plan.preempt_at_step is not None
            and step is not None and step >= plan.preempt_at_step):
        import signal
        # one-shot: if a handler absorbs the signal (a unit test, or a
        # grace-window drain), training continues instead of re-dying
        # on every subsequent step — matching a real preemption notice,
        # which is delivered once
        plan.preempt_at_step = None
        try:
            from .. import observe as _obs
            _obs.flight_event('preempt', step=step)
        except Exception:
            pass
        # SIGTERM, not a hard kill: the armed flight-recorder handler
        # (observe._install_sigterm_handler) dumps its postmortem, then
        # chains to the default action, which terminates the process —
        # exactly the shape of a cloud preemption notice
        os.kill(os.getpid(), signal.SIGTERM)
        return
    if (point == 'step_end' and plan.kill_at_step is not None
            and step is not None and step >= plan.kill_at_step):
        # The one concession before the hard kill: a flight-recorder
        # postmortem (no-op unless armed) — exactly what a real
        # preemption's SIGTERM grace window would leave behind.
        try:
            from .. import observe as _obs
            _obs.flight_event('kill', step=step,
                              kill_at_step=plan.kill_at_step)
            _obs.flight_dump('fault_injection_kill')
        except Exception:
            pass
        # os._exit: no atexit, no flushes, no thread joins — the closest
        # in-process stand-in for a preempted VM. >= (not ==) so a
        # windowed dispatch that jumps past k still dies.
        os._exit(KILL_EXIT_CODE)
    if (point == 'checkpoint_saved'
            and plan.corrupt_checkpoint_at_step is not None
            and step == plan.corrupt_checkpoint_at_step and dirname):
        truncate_file(os.path.join(dirname, 'params.npz'))


def kill_replica(engine, drain=False):
    """Chaos action for the serving fleet: abruptly take one replica
    down mid-load (``drain=False``, the default, is the preemption
    shape — queued-but-unbatched requests fail with the typed
    EngineClosedError, which the router's failover resubmits
    elsewhere; batches already handed to dispatch still complete).
    The flight event makes the kill findable in postmortems and the
    chaos bench's assertion windows. Returns the engine."""
    name = getattr(engine, 'name', None) or type(engine).__name__
    try:
        from .. import observe as _obs
        _obs.flight_event('replica_kill', replica=str(name),
                          drain=bool(drain))
        _obs.inc('fault.replica_kills_total', replica=str(name))
    except Exception:
        _obs = None
    engine.shutdown(drain=drain)
    # a killed replica doesn't get to tidy its own grave: graceful
    # shutdown unregisters the engine's /readyz check, but a chaos kill
    # re-registers it so the corpse shows NOT-ready (the balancer-visible
    # flip the failover tests assert) instead of silently vanishing
    check = getattr(engine, '_ready_check', None)
    if _obs is not None and callable(check):
        try:
            _obs.register_health_check('serving.%s' % name, check,
                                       readiness_only=True)
        except Exception:
            pass
    return engine


def crash_loop(engine, kills, interval_s):
    """Chaos action for the self-healing fleet: kill the same replica
    SLOT repeatedly — the scenario that must trip the fleet
    controller's crash-loop circuit breaker (quarantine) instead of
    thrashing it with doomed restarts.

    ``engine`` is either a live engine (killed once; later iterations
    find nothing new to kill) or, the interesting form, a zero-arg
    callable returning the slot's CURRENT live engine or None —
    ``lambda: controller.current('replica2')`` aims every kill at
    whatever replacement the controller just spawned. Each iteration
    waits ``interval_s`` (so heals can land in between), resolves the
    target, and ``kill_replica``s it with a ``crash_loop_kill`` flight
    event. Returns the number of kills actually performed (a
    quarantined slot stops producing victims — fewer kills than asked
    is the breaker WORKING)."""
    import time as _time
    resolve = engine if callable(engine) else (lambda: engine)
    killed = 0
    last = None
    for i in range(int(kills)):
        if i:
            _time.sleep(float(interval_s))
        victim = resolve()
        if victim is None or victim is last and not victim.ready():
            continue                 # slot is down/benched: no victim
        try:
            from .. import observe as _obs
            _obs.flight_event('crash_loop_kill', iteration=i,
                              replica=str(getattr(victim, 'name',
                                                  '?')))
        except Exception:
            pass
        kill_replica(victim, drain=False)
        last = victim
        killed += 1
    return killed


def kill_process(proc_or_resolver, sig=None):
    """Chaos action for the CROSS-HOST fleet: deliver a real signal
    (default SIGKILL) to a live replica worker PID — death the kernel
    enforces, not a flipped flag. Mirrors ``kill_replica`` /
    ``crash_loop``:

    ``proc_or_resolver`` is any of
      - a ``subprocess.Popen`` (or anything with ``.pid``),
      - a ``serving.rpc.RemoteReplica`` (its ``.proc`` is the victim),
      - a raw integer PID, or
      - the interesting form: a zero-arg callable returning any of the
        above or None — ``lambda: ctl.current('r2')`` aims every kill
        at whatever replacement the controller just spawned.

    Emits the ``process_kill`` flight event +
    ``fault.process_kills_total`` before the signal (the postmortem
    must show the kill even if this process dies next). Returns the
    PID signalled, or None when there was no victim (slot empty /
    process already reaped) — a quarantined slot producing no victims
    is the breaker WORKING, same contract as ``crash_loop``."""
    import signal
    victim = (proc_or_resolver() if callable(proc_or_resolver)
              else proc_or_resolver)
    if victim is None:
        return None
    proc = getattr(victim, 'proc', None) or victim   # RemoteReplica
    if isinstance(proc, int):
        pid, alive = proc, True
    else:
        pid = getattr(proc, 'pid', None)
        if pid is None:
            return None
        poll = getattr(proc, 'poll', None)
        alive = poll() is None if callable(poll) else True
    if not alive:
        return None                 # already a reaped corpse
    signum = int(sig) if sig is not None else signal.SIGKILL
    try:
        from .. import observe as _obs
        _obs.flight_event('process_kill', pid=int(pid), sig=signum,
                          replica=str(getattr(victim, 'name', pid)))
        _obs.inc('fault.process_kills_total',
                 replica=str(getattr(victim, 'name', pid)))
    except Exception:
        pass
    try:
        os.kill(int(pid), signum)
    except ProcessLookupError:
        return None                 # raced with its own death
    return int(pid)


def truncate_file(path, keep_fraction=0.5):
    """Cut a file to a prefix of itself — the on-disk shape of a write
    torn mid-stream."""
    size = os.path.getsize(path)
    with open(path, 'r+b') as f:
        f.truncate(int(size * keep_fraction))


def poison_nans(reader, at_step):
    """Wrap a reader: the item at stream index at_step has every float
    array replaced with NaNs (dict / tuple / list items supported)."""
    import numpy as np

    def _poison_val(v):
        arr = np.asarray(v)
        if arr.dtype.kind == 'f':
            return np.full_like(arr, np.nan)
        return v

    def _poison(item):
        if isinstance(item, dict):
            return {k: _poison_val(v) for k, v in item.items()}
        if isinstance(item, (list, tuple)):
            return type(item)(_poison_val(v) for v in item)
        return _poison_val(item)

    def wrapper():
        for i, item in enumerate(reader()):
            yield _poison(item) if i == at_step else item
    return wrapper


def flaky(reader, fail_times, fail_after=0, exc=TransientReaderError):
    """Wrap a reader factory: the first fail_times iterations raise exc
    after yielding fail_after items; later passes run clean. State is
    exposed as wrapper.state ({'fails', 'calls'}) for assertions."""
    state = {'fails': 0, 'calls': 0}

    def wrapper():
        state['calls'] += 1
        if state['fails'] < fail_times:
            state['fails'] += 1
            n = 0
            for item in reader():
                if n >= fail_after:
                    raise exc('injected transient failure %d/%d'
                              % (state['fails'], fail_times))
                yield item
                n += 1
            raise exc('injected transient failure %d/%d (at stream end)'
                      % (state['fails'], fail_times))
        for item in reader():
            yield item
    wrapper.state = state
    return wrapper
