"""Bad-step guards: NaN/Inf sentinel on the fetched loss.

A poisoned batch (corrupt input) or a numerical blowup shows up as a
non-finite loss — and by the time it is fetched, the same dispatch has
already applied the (equally non-finite) gradient update. Recovery
therefore means UNDOING state, not just skipping a batch:

- 'raise': surface a BadStepError immediately (default — loud failure
  beats silent NaN params).
- 'skip_step': restore the host-side snapshot taken just before the
  dispatch; net effect is that the bad batch was never trained on. The
  per-step device->host snapshot is the cost of exact undo — enable
  only when corrupt inputs are an expected, routine event.
- 'rollback': reload params + optimizer state from the newest COMPLETE
  checkpoint (the data stream continues FORWARD past the bad batch —
  rewinding the reader would replay the same poison forever).

All policies escalate to 'raise' after max_bad_steps consecutive bad
steps: an unbroken NaN run means the model state, not the input, is
poisoned.

Pipelined training (Trainer.train(pipeline_depth=D)) widens these
semantics explicitly — a bad loss is only SEEN when its step resolves,
up to D-1 dispatches after later steps already applied their (equally
poisoned) updates:

- 'raise' surfaces the BadStepError at resolve time, ≤ D-1 steps after
  the bad dispatch.
- 'skip_step' snapshots once per drain group (cadence = D, taken at
  pipeline-empty points, where the device->host readback cannot stall
  in-flight work) and undoes the WHOLE group on any bad step —
  rollback granularity ≤ D steps, including good steps that resolved
  earlier in the same group. Both detections force a documented
  re-sync: the trainer drains every in-flight dispatch before the
  guard restores state, so the restore wins over all prior scope
  writes.
- 'rollback' keeps its granularity (newest complete checkpoint); the
  in-flight steps behind the bad one are drained and discarded.
"""

import numpy as np

from .. import observe as _obs

__all__ = ['NAN_POLICIES', 'BadStepError', 'BadStepGuard', 'is_bad']

NAN_POLICIES = ('raise', 'skip_step', 'rollback')


class BadStepError(RuntimeError):
    """Non-finite loss the configured policy could not absorb."""

    def __init__(self, message, step=None, loss=None):
        super(BadStepError, self).__init__(message)
        self.step = step
        self.loss = loss


def is_bad(value):
    """True when a fetched metric contains NaN or +/-Inf."""
    arr = np.asarray(value)
    if arr.dtype.kind not in 'fc':
        return False
    return not bool(np.all(np.isfinite(arr)))


class BadStepGuard(object):
    """Trainer-side policy engine. Call snapshot() before a dispatch
    (only required when needs_snapshot), handle() on its fetched loss
    after; handle returns 'ok' | 'skipped' | 'rolled_back' or raises."""

    def __init__(self, policy, max_bad_steps=8, manager=None,
                 executor=None, program=None):
        if policy not in NAN_POLICIES:
            raise ValueError('nan_policy must be one of %s, got %r'
                             % (NAN_POLICIES, policy))
        self.policy = policy
        self.max_bad_steps = int(max_bad_steps)
        self._manager = manager
        self._executor = executor
        self._program = program
        self._consecutive = 0
        self._snap = None

    @property
    def needs_snapshot(self):
        return self.policy == 'skip_step'

    def snapshot(self):
        from .. import io as _io
        self._snap = _io._snapshot_vars(self._program,
                                        predicate=_io._is_persistable)

    def _restore_snapshot(self):
        from .. import io as _io
        from ..core.scope import global_scope
        arrays, manifest = self._snap
        scope = global_scope()
        for name, arr in arrays.items():
            scope.set(name, _io._from_numpy(arr, manifest[name]['dtype']))

    def handle(self, loss, step, steps=1):
        """`steps`: how many training steps this verdict covers — 1 for
        a per-step dispatch, w for a run_steps window, and the whole
        drain group (≤ pipeline_depth) under pipelined skip_step, where
        the snapshot restore undoes every step since the last
        pipeline-empty point."""
        if not is_bad(loss):
            self._consecutive = 0
            return 'ok'
        self._consecutive += 1
        _obs.inc('fault.bad_steps_total')
        head = ('non-finite loss at global step %d (%r)'
                % (step, np.asarray(loss).ravel()[:4].tolist()))
        if steps > 1:
            head += ' [undo unit: %d steps]' % int(steps)
        if self.policy == 'raise':
            _obs.inc('fault.guard_triggers_total', policy='raise',
                     action='raise')
            err = BadStepError(head + " — nan_policy='raise'",
                               step=step, loss=loss)
            self._flight_raise(err, step, 'raise', 'bad_step')
            raise err
        if self._consecutive > self.max_bad_steps:
            _obs.inc('fault.guard_triggers_total', policy=self.policy,
                     action='escalate')
            err = BadStepError(
                head + ' — %d consecutive bad steps exceed max_bad_steps='
                '%d; the model state itself is likely poisoned'
                % (self._consecutive, self.max_bad_steps),
                step=step, loss=loss)
            self._flight_raise(err, step, self.policy, 'max_bad_steps')
            raise err
        if self.policy == 'skip_step':
            if self._snap is None:
                err = BadStepError(
                    head + " — nan_policy='skip_step' but no pre-step "
                    'snapshot was taken', step=step, loss=loss)
                self._flight_raise(err, step, 'skip_step', 'bad_step')
                raise err
            self._restore_snapshot()
            _obs.inc('fault.guard_triggers_total', policy='skip_step',
                     action='skipped')
            _obs.flight_event('guard_trip', step=step, policy='skip_step',
                              action='skipped', undo_steps=int(steps))
            return 'skipped'
        # rollback
        meta = None
        if self._manager is not None:
            from .manager import NoUsableCheckpointError
            try:
                meta = self._manager.restore(self._executor, self._program)
            except NoUsableCheckpointError:
                # keep-last-K exhaustion: same terminal state as an
                # empty tree for this policy — nothing to roll back to
                meta = None
        if meta is None:
            err = BadStepError(
                head + " — nan_policy='rollback' but no complete "
                'checkpoint exists to roll back to', step=step, loss=loss)
            self._flight_raise(err, step, 'rollback', 'bad_step')
            raise err
        _obs.inc('fault.guard_triggers_total', policy='rollback',
                 action='rolled_back')
        _obs.flight_event('guard_trip', step=step, policy='rollback',
                          action='rolled_back',
                          restored_step=meta.get('step'))
        return 'rolled_back'

    @staticmethod
    def _flight_raise(err, step, policy, reason):
        """A guard raise is the run's death sentence: record the trip
        and dump the postmortem HERE, while the exception context is
        richest (the trainer's outer handler dedupes on the same
        exception object)."""
        _obs.flight_event('guard_trip', step=step, policy=policy,
                          action='raise', error=str(err))
        _obs.flight_dump(reason, exc=err)
