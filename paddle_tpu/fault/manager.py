"""Managed checkpoint tree: step dirs + LATEST pointer + retention GC.

Layout under config.dirname:

    step_00000012/
        manifest.json       var dtypes/shapes
        params.npz          every persistable (params + optimizer state)
        checkpoint.json     written LAST; records both files' sha1 plus
                            step / reader / trainer state
    step_00000024/ ...
    LATEST                  name of the newest committed step dir,
                            written atomically AFTER the dir completes

A checkpoint is COMPLETE iff checkpoint.json exists and both recorded
sha1s verify (io.verify_checkpoint). LATEST is an optimization, not the
source of truth: restore() tries the pointer first, then scans step
dirs newest-first, skipping torn/corrupt candidates — so a write torn
by preemption (or bit-rot that survives the atomic rename) falls back
to the previous complete checkpoint instead of failing the job.
"""

import os
import re
import shutil
import threading
import time
import warnings

from .. import io as _io
from .. import observe as _obs
from . import inject

__all__ = ['CheckpointManager', 'NoUsableCheckpointError', 'LATEST_FILE',
           'STEP_DIR_FMT']

LATEST_FILE = 'LATEST'
STEP_DIR_FMT = 'step_%08d'
_STEP_RE = re.compile(r'^step_(\d{8,})$')


class NoUsableCheckpointError(RuntimeError):
    """restore() found checkpoint candidates but every one was torn,
    corrupt, or incompatible with the restoring topology (keep-last-K
    exhaustion). Distinct from an EMPTY tree, which restores nothing
    and returns None — exhaustion means training state EXISTED and was
    lost, so silently starting from scratch would be data loss."""


class CheckpointManager(object):
    def __init__(self, config):
        self.config = config
        self.dirname = config.dirname
        self._pending = None
        self._errbox = []
        self._gc_lock = threading.Lock()

    # ----------------------------------------------------------- paths
    def step_dir(self, step):
        return os.path.join(self.dirname, STEP_DIR_FMT % int(step))

    def _scan(self):
        """[(step, path)] of step dirs, newest first."""
        try:
            names = os.listdir(self.dirname)
        except OSError:
            return []
        out = []
        for n in names:
            m = _STEP_RE.match(n)
            if m:
                out.append((int(m.group(1)), os.path.join(self.dirname, n)))
        out.sort(reverse=True)
        return out

    def latest_pointer(self):
        """(step, path) named by LATEST, or None."""
        try:
            with open(os.path.join(self.dirname, LATEST_FILE)) as f:
                name = f.read().strip()
        except OSError:
            return None
        m = _STEP_RE.match(name)
        if not m:
            return None
        path = os.path.join(self.dirname, name)
        return (int(m.group(1)), path) if os.path.isdir(path) else None

    def _candidates(self):
        # newest-first SCAN, not the pointer: a crash between the
        # checkpoint.json rename and the LATEST write leaves a complete
        # checkpoint the pointer doesn't name yet — verification (not
        # LATEST) is the source of truth for completeness
        return self._scan()

    # ------------------------------------------------------------ save
    def save(self, executor, main_program, step, reader=None,
             trainer_state=None, reader_pending=0):
        """Checkpoint at `step`. With config.async_save the disk write
        AND the commit (LATEST + GC) run on a background thread; call
        wait() for the completeness point. Saves are serialized: a new
        save first joins the previous commit, so GC never races an
        in-flight write."""
        self.wait()
        d = self.step_dir(step)
        t0 = time.monotonic()
        _obs.inc('fault.checkpoint_saves_total')
        _obs.flight_event('checkpoint_save', step=int(step),
                          mode='async' if self.config.async_save
                          else 'sync')
        handle = _io.save_checkpoint(
            executor, d, main_program=main_program, step=step,
            reader=reader, trainer_state=trainer_state,
            reader_pending=reader_pending,
            async_save=self.config.async_save)
        if handle is None or handle.done():
            self._commit(step, d)
            _obs.record('fault.checkpoint_save_seconds',
                        time.monotonic() - t0, mode='sync')
            return
        def _finalize():
            try:
                handle.result()
                self._commit(step, d)
                # async latency: save() call to durable commit
                _obs.record('fault.checkpoint_save_seconds',
                            time.monotonic() - t0, mode='async')
            except BaseException as e:
                self._errbox.append(e)
        t = threading.Thread(target=_finalize, daemon=True,
                             name='paddle_tpu_ckpt_commit')
        t.start()
        self._pending = t

    def wait(self, timeout=None):
        """Join the in-flight async commit; re-raise its error if any."""
        t = self._pending
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError('checkpoint commit still in progress')
            self._pending = None
        if self._errbox:
            raise self._errbox.pop(0)

    def _commit(self, step, d):
        import jax
        if jax.process_index() == 0:
            _io._write_atomic(
                os.path.join(self.dirname, LATEST_FILE),
                lambda f: f.write(os.path.basename(d).encode()))
            self._gc()
        # fires AFTER the pointer lands so injected corruption exercises
        # the worst case: LATEST names a checkpoint whose sha1s no
        # longer verify, and restore must fall back by scanning
        inject.fire('checkpoint_saved', step=step, dirname=d)

    def _gc(self):
        with self._gc_lock:
            for _, path in self._scan()[self.config.keep_last:]:
                shutil.rmtree(path, ignore_errors=True)

    # --------------------------------------------------------- restore
    def find_latest(self):
        """(step, path, meta) of the newest COMPLETE checkpoint, or
        None. Torn/corrupt candidates are warned about and skipped."""
        for step, path in self._candidates():
            try:
                return step, path, _io.verify_checkpoint(path)
            except ValueError as e:
                warnings.warn('CheckpointManager: skipping %r (%s)'
                              % (path, e))
        return None

    def restore(self, executor, main_program=None, reader=None):
        """Restore from the newest complete checkpoint; on a load
        failure (corruption the sha1 pass could not see) fall back to
        the next older one. Detects an elastic-topology resume — the
        recorded mesh/host count differs from the restoring program's —
        and lets io.load_checkpoint reshard, emitting an
        `elastic_reshard` flight event + `fault.reshard_total` counter;
        candidates whose format predates the sharding specs are skipped
        on a changed topology (they cannot be proven compatible).
        Returns the checkpoint meta dict (step / reader / trainer keys),
        None when the tree holds no checkpoints at all, or raises
        NoUsableCheckpointError when candidates existed but every one
        was unusable (keep-last-K exhaustion)."""
        failures = []
        for step, path in self._candidates():
            try:
                t0 = time.monotonic()
                meta = _io.verify_checkpoint(path)
                reshard = _io.topology_changed(meta, main_program)
                if reshard and not meta.get('format_version'):
                    raise ValueError(
                        'predates the elastic checkpoint format (no '
                        'per-variable sharding specs recorded) and the '
                        'restoring topology differs from the unsharded '
                        'legacy contract')
                _io.load_checkpoint(
                    executor, path, main_program,
                    reader=reader if (reader is not None and
                                      meta.get('reader')) else None)
                _obs.record('fault.checkpoint_restore_seconds',
                            time.monotonic() - t0)
                _obs.inc('fault.resume_total')
                _obs.flight_event('checkpoint_restore', step=int(step),
                                  path=os.path.basename(path))
                if reshard:
                    rec = _io.checkpoint_topology(meta) or (1, {})
                    cur = _io.current_topology(main_program)
                    _obs.inc('fault.reshard_total')
                    _obs.flight_event(
                        'elastic_reshard', step=int(step),
                        from_topology=_io.topology_str(*rec),
                        to_topology=_io.topology_str(*cur))
                return meta
            except Exception as e:
                _obs.inc('fault.checkpoint_unusable_total')
                failures.append('%s: %s: %s'
                                % (os.path.basename(path),
                                   type(e).__name__, e))
                warnings.warn('CheckpointManager: checkpoint %r unusable '
                              '(%s: %s); falling back to the previous one'
                              % (path, type(e).__name__, e))
        if failures:
            raise NoUsableCheckpointError(
                'CheckpointManager: %d checkpoint candidate(s) under %r '
                'and NONE is usable — keep-last-%d retention is '
                'exhausted:\n  %s\nTraining state existed here; starting '
                'from scratch silently would be data loss. Repair or '
                'remove the tree (or raise keep_last) and rerun.'
                % (len(failures), self.dirname, self.config.keep_last,
                   '\n  '.join(failures)))
        return None
