"""Checkpoint/resume policy for the Trainer (see fault/__init__.py)."""

from .guards import NAN_POLICIES

__all__ = ['CheckpointConfig']


class CheckpointConfig(object):
    """Declarative fault-tolerance policy, passed as
    ``Trainer(..., checkpoint_config=CheckpointConfig(dirname, ...))``.

    dirname: root of the managed checkpoint tree — one ``step_XXXXXXXX/``
        directory per checkpoint plus a ``LATEST`` pointer file.
    save_every_steps: mid-epoch save cadence in global steps (None
        disables the step trigger).
    save_every_secs: mid-epoch save cadence in wall seconds (None
        disables the time trigger). Either trigger firing saves.
    keep_last: retention — GC deletes all but the newest K step dirs
        after each commit.
    resume: at train() start, restore params/optimizer state/global
        step/epoch/reader position from the newest COMPLETE checkpoint
        (sha1-verified; falls back to older ones on corruption) and
        continue mid-epoch. A no-op when the tree is empty; raises
        NoUsableCheckpointError when checkpoints exist but every one is
        torn/incompatible (keep-last exhaustion is surfaced, never
        silently retrained from scratch). Resume is ELASTIC: a
        format-v2 checkpoint written on one mesh/host topology restores
        on a different one — arrays reshard onto the restoring
        program's mesh and the reader replays exactly the untrained
        remainder at the new dp width (pre-elastic checkpoints are only
        accepted on an unsharded single-host topology).
    async_save: device->host snapshot synchronously, serialize + write
        on a background thread (io.save_checkpoint's async path).
    epoch_end: also checkpoint at every epoch boundary (the legacy
        Trainer cadence).
    nan_policy: None (off) | 'raise' | 'skip_step' | 'rollback' — what
        to do when the fetched loss goes NaN/Inf (guards.BadStepGuard).
    max_bad_steps: consecutive bad steps tolerated by the skip/rollback
        policies before escalating to BadStepError.
    """

    def __init__(self, dirname, save_every_steps=None, save_every_secs=None,
                 keep_last=3, resume=False, async_save=True, epoch_end=True,
                 nan_policy='raise', max_bad_steps=8):
        if not dirname:
            raise ValueError('CheckpointConfig: dirname is required')
        if int(keep_last) < 1:
            raise ValueError('CheckpointConfig: keep_last must be >= 1, '
                             'got %r' % (keep_last,))
        if save_every_steps is not None and int(save_every_steps) < 1:
            raise ValueError('CheckpointConfig: save_every_steps must be '
                             '>= 1, got %r' % (save_every_steps,))
        if nan_policy is not None and nan_policy not in NAN_POLICIES:
            raise ValueError('CheckpointConfig: nan_policy must be None or '
                             'one of %s, got %r' % (NAN_POLICIES, nan_policy))
        self.dirname = str(dirname)
        self.save_every_steps = (None if save_every_steps is None
                                 else int(save_every_steps))
        self.save_every_secs = (None if save_every_secs is None
                                else float(save_every_secs))
        self.keep_last = int(keep_last)
        self.resume = bool(resume)
        self.async_save = bool(async_save)
        self.epoch_end = bool(epoch_end)
        self.nan_policy = nan_policy
        self.max_bad_steps = int(max_bad_steps)
