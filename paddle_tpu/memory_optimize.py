"""Memory optimization (reference: python/paddle/fluid/
memory_optimization_transpiler.py — var reuse by liveness analysis).

TPU-native translation: XLA already does buffer reuse/liveness inside a
compiled program, so the wins here are the knobs XLA can't choose for
you:
- rematerialization (jax.checkpoint) of the forward pass — trade FLOPs
  for activation memory, essential for long-sequence training;
- donation is already on by default in the Executor (params alias their
  updates in HBM).

memory_optimize(program) therefore sets the program's remat policy; the
Executor wraps the traced forward in jax.checkpoint with it.
"""

__all__ = ['memory_optimize', 'release_memory', 'REMAT_POLICIES']

REMAT_POLICIES = ('none', 'full', 'dots_saveable', 'nothing_saveable')


def memory_optimize(input_program=None, print_log=False, level=0,
                    policy=None):
    """level 0 -> save matmul outputs (cheap recompute of elementwise);
    level 1 -> full remat (recompute everything in backward)."""
    from .core.program import default_main_program
    program = input_program or default_main_program()
    if policy is None:
        policy = 'dots_saveable' if level == 0 else 'full'
    if policy not in REMAT_POLICIES:
        raise ValueError('unknown remat policy %r (choose from %s)'
                         % (policy, REMAT_POLICIES))
    program.remat_policy = None if policy == 'none' else policy
    if print_log:
        print('memory_optimize: remat policy = %s' % policy)
    return program


def release_memory(input_program=None, skip_opt_set=None):
    """Reference-API shim: with XLA managing buffers there is nothing to
    release eagerly; kept for ported scripts."""
    return input_program
