"""Model save/load (reference: python/paddle/fluid/io.py).

Persistables (params + optimizer state + BN stats) are serialized from the
Scope to an .npz bundle plus a JSON manifest — a single-file, orbax-free
checkpoint format that round-trips bf16 via uint16 views.
"""

import json
import os

import numpy as np

from .core.program import Parameter, default_main_program
from .core.scope import global_scope

__all__ = ['save_vars', 'save_params', 'save_persistables', 'load_vars',
           'load_params', 'load_persistables', 'save_inference_model',
           'load_inference_model', 'get_inference_program',
           'save_checkpoint', 'load_checkpoint']

_PARAMS_FILE = 'params.npz'
_MANIFEST_FILE = 'manifest.json'


def _is_parameter(var):
    return isinstance(var, Parameter)


def _is_persistable(var):
    return bool(getattr(var, 'persistable', False)) and not var.is_data


def _to_numpy(value):
    arr = np.asarray(value)
    if arr.dtype.name == 'bfloat16':
        return arr.view(np.uint16), 'bfloat16'
    return arr, arr.dtype.name


def _from_numpy(arr, dtype_name):
    if dtype_name == 'bfloat16':
        import jax.numpy as jnp
        return np.asarray(arr).view(jnp.bfloat16)
    return arr


def _gather_to_host(value):
    """Multihost-sharded arrays are not fully addressable from one
    process; allgather the global value before serializing (the
    reference's pserver owned whole params — here GSPMD shards them)."""
    import jax
    if isinstance(value, jax.Array) and not value.is_fully_addressable:
        from jax.experimental import multihost_utils
        value = multihost_utils.process_allgather(value, tiled=True)
    return value


def _snapshot_vars(main_program, vars=None, predicate=None):
    """Device->host snapshot of the requested vars: the synchronous half
    of a save. Must run on the caller's thread BEFORE the next training
    step — donated parameter buffers are reused by the step, so a
    deferred read would touch deleted buffers."""
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    arrays, manifest = {}, {}
    for v in vars:
        value = scope.find(v.name)
        if value is None:
            continue
        arr, dtype_name = _to_numpy(_gather_to_host(value))
        arrays[v.name] = arr
        manifest[v.name] = {'dtype': dtype_name,
                            'shape': list(np.asarray(arr).shape)}
    return arrays, manifest


def _write_snapshot(dirname, arrays, manifest, filename=None):
    """Disk half of a save: atomic via tmp + rename, so a crash mid-
    write cannot corrupt a previous checkpoint in the same dirname."""
    os.makedirs(dirname, exist_ok=True)
    params_path = os.path.join(dirname, filename or _PARAMS_FILE)
    if not params_path.endswith('.npz'):
        params_path += '.npz'
    tmp = params_path + '.tmp'
    with open(tmp, 'wb') as f:
        np.savez(f, **arrays)
    os.replace(tmp, params_path)
    man_path = os.path.join(dirname, _MANIFEST_FILE)
    with open(man_path + '.tmp', 'w') as f:
        json.dump(manifest, f, indent=1)
    os.replace(man_path + '.tmp', man_path)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import jax
    main_program = main_program or default_main_program()
    arrays, manifest = _snapshot_vars(main_program, vars, predicate)
    # one writer per pod: every host gathered the same global values
    if jax.process_index() == 0:
        _write_snapshot(dirname, arrays, manifest, filename)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices('paddle_tpu_save_vars')


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_parameter,
              filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    main_program = main_program or default_main_program()
    path = os.path.join(dirname, filename or _PARAMS_FILE)
    if not path.endswith('.npz'):
        path += '.npz'
    data = np.load(path)
    with open(os.path.join(dirname, _MANIFEST_FILE)) as f:
        manifest = json.load(f)
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    wanted = set(v.name for v in vars)
    for name in data.files:
        if name not in wanted:
            continue
        arr = _from_numpy(data[name], manifest[name]['dtype'])
        scope.var(name)
        scope.set(name, arr)


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_parameter,
              filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def get_inference_program(target_vars, main_program=None):
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    test_program = main_program.clone(for_test=True)
    return test_program.prune(target_vars)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None):
    """Serialize a pruned inference program + params (reference io.py:
    save_inference_model / paddle/fluid/inference/io.cc)."""
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    inference_program = get_inference_program(target_vars, main_program)
    os.makedirs(dirname, exist_ok=True)
    from .core.serialize import program_to_dict
    meta = {
        'feed_names': list(feeded_var_names),
        'fetch_names': [v.name if not isinstance(v, str) else v
                        for v in target_vars],
        'program': program_to_dict(inference_program),
    }
    with open(os.path.join(dirname,
                           model_filename or '__model__.json'), 'w') as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, main_program,
                      filename=params_filename)
    return inference_program


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    with open(os.path.join(dirname,
                           model_filename or '__model__.json')) as f:
        meta = json.load(f)
    from .core.serialize import program_from_dict
    program = program_from_dict(meta['program'])
    load_vars(executor, dirname, program, predicate=_is_persistable,
              filename=params_filename)
    fetch_vars = [program.global_block().var(n) for n in meta['fetch_names']]
    return program, meta['feed_names'], fetch_vars


class AsyncSaveHandle(object):
    """Returned by save_checkpoint(async_save=True). result() joins the
    writer thread and re-raises any write error."""

    def __init__(self, thread, errbox):
        self._thread = thread
        self._errbox = errbox

    def done(self):
        return not self._thread.is_alive()

    def result(self, timeout=None):
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError('checkpoint write still in progress')
        if self._errbox:
            raise self._errbox[0]


def save_checkpoint(executor, dirname, main_program=None, step=None,
                    reader=None, async_save=False):
    """Full training checkpoint: every persistable incl. optimizer state.

    reader: a reader.CheckpointableReader — its (epoch, offset, seed)
    is persisted alongside, so load_checkpoint resumes the data stream
    mid-epoch with exactly the untrained remainder (the reference data
    master's etcd task-queue recovery, go/master/service.go:165-213,
    done masterless via deterministic replay).

    async_save: snapshot device->host synchronously (donated buffers
    make deferred reads unsafe), then serialize + write on a background
    thread; training continues immediately. Returns an AsyncSaveHandle
    whose result() is the completeness point; writes are atomic (tmp +
    rename), so a crash mid-write leaves the previous checkpoint
    intact. Multihost runs fall back to the synchronous path — the
    completion barrier may not run off-thread (it would race the
    training step's collectives)."""
    import jax
    meta = {}
    if step is not None:
        meta['step'] = int(step)
    if reader is not None:
        meta['reader'] = reader.state_dict()

    def _write_meta():
        if meta:
            # single writer, like save_persistables; positional sharding
            # advances every host's reader identically, so process 0's
            # (epoch, offset) is valid for all shards
            path = os.path.join(dirname, 'checkpoint.json')
            with open(path + '.tmp', 'w') as f:
                json.dump(meta, f)
            os.replace(path + '.tmp', path)

    if async_save and jax.process_count() == 1:
        main = main_program or default_main_program()
        arrays, manifest = _snapshot_vars(main, predicate=_is_persistable)
        errbox = []

        def _writer():
            try:
                _write_snapshot(dirname, arrays, manifest)
                _write_meta()
            except BaseException as e:  # surfaced via handle.result()
                errbox.append(e)

        import threading
        t = threading.Thread(target=_writer, daemon=True,
                             name='paddle_tpu_async_save')
        t.start()
        return AsyncSaveHandle(t, errbox)

    save_persistables(executor, dirname, main_program)
    if jax.process_index() == 0:
        _write_meta()
    return None


def load_checkpoint(executor, dirname, main_program=None, reader=None):
    load_persistables(executor, dirname, main_program)
    path = os.path.join(dirname, 'checkpoint.json')
    if not os.path.exists(path):
        if reader is not None:
            raise ValueError(
                'load_checkpoint: a reader was passed but %r holds no '
                'checkpoint.json — resuming would silently re-consume '
                'already-trained data (was save_checkpoint called with '
                'reader=...?)' % dirname)
        return None
    with open(path) as f:
        meta = json.load(f)
    if reader is not None:
        state = meta.get('reader')
        if state is None:
            raise ValueError(
                'load_checkpoint: a reader was passed but %r holds no '
                'reader state (was save_checkpoint called with '
                'reader=...?)' % dirname)
        reader.load_state_dict(state)
    return meta.get('step')
