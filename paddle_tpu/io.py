"""Model save/load (reference: python/paddle/fluid/io.py).

Persistables (params + optimizer state + BN stats) are serialized from the
Scope to an .npz bundle plus a JSON manifest — a single-file, orbax-free
checkpoint format that round-trips bf16 via uint16 views.

Checkpoints are TOPOLOGY-NEUTRAL (format_version 2): save gathers every
var to its global host value and records the writing mesh (dp/pp/sp/tp/
ep sizes + host count) in checkpoint.json plus each var's LOGICAL
sharding spec (PartitionSpec axis names, never device positions) in the
manifest. load_checkpoint compares the recorded topology against the
restoring program's mesh and, when they differ, reshards every restored
array onto the new mesh's NamedSharding — a run preempted on one slice
resumes on whatever slice comes back (SNIPPETS [2]'s NamedSharding/
GSPMD pattern: the checkpoint is independent of the mesh that wrote
it). Pre-elastic checkpoints (no format_version) keep working on the
same topology and fail with an actionable error on a different one.
"""

import hashlib
import json
import os
import tempfile
import threading
import warnings

import numpy as np

from . import observe as _obs
from .core.program import Parameter, default_main_program
from .core.scope import global_scope

__all__ = ['save_vars', 'save_params', 'save_persistables', 'load_vars',
           'load_params', 'load_persistables', 'save_inference_model',
           'load_inference_model', 'get_inference_program',
           'save_checkpoint', 'load_checkpoint', 'verify_checkpoint',
           'checkpoint_topology', 'current_topology', 'topology_changed',
           'topology_str', 'CHECKPOINT_FORMAT_VERSION']

_PARAMS_FILE = 'params.npz'
_MANIFEST_FILE = 'manifest.json'

# 2: checkpoint.json records format_version / mesh / hosts, the manifest
# records per-var logical sharding specs, and the reader state carries
# its positional-shard width — together they make restore elastic.
# Absent (format 1): the pre-elastic layout; valid only on the topology
# that wrote it.
CHECKPOINT_FORMAT_VERSION = 2


def _is_parameter(var):
    return isinstance(var, Parameter)


def _is_persistable(var):
    return bool(getattr(var, 'persistable', False)) and not var.is_data


def _to_numpy(value):
    arr = np.asarray(value)
    if arr.dtype.name == 'bfloat16':
        return arr.view(np.uint16), 'bfloat16'
    return arr, arr.dtype.name


def _from_numpy(arr, dtype_name):
    if dtype_name == 'bfloat16':
        import jax.numpy as jnp
        return np.asarray(arr).view(jnp.bfloat16)
    return arr


# ----------------------------------------------------- elastic topology
def _spec_to_json(spec):
    """PartitionSpec -> JSON list of axis names (None = replicated dim,
    nested list = multi-axis dim). Logical names only — nothing about
    device positions survives, which is what makes the record valid on
    any future mesh."""
    if spec is None:
        return []
    return [list(e) if isinstance(e, (tuple, list)) else e for e in spec]


def _spec_from_json(entries, axis_names):
    """Rebuild a PartitionSpec from its JSON form, dropping axis names
    the restoring mesh does not have (a tp-split var written on a
    dp x tp mesh restores replicated on a pure-dp mesh; GSPMD re-derives
    the layout from whatever the new program's transpile says)."""
    from jax.sharding import PartitionSpec
    parts = []
    for e in (entries or []):
        if isinstance(e, (list, tuple)):
            kept = [a for a in e if a in axis_names]
            parts.append(tuple(kept) if len(kept) > 1
                         else (kept[0] if kept else None))
        else:
            parts.append(e if (e is None or e in axis_names) else None)
    while parts and parts[-1] is None:
        parts.pop()
    return PartitionSpec(*parts)


# Public names for the spec (de)serializers: the elastic-checkpoint
# writer (above) and the KV-handoff packet header
# (serving/handoff.py) share one wire form for logical shardings —
# moving a sequence's KV pages between meshes is the same problem as
# resuming a checkpoint on a different slice, so they must stay one
# format.
spec_to_json = _spec_to_json
spec_from_json = _spec_from_json


def checkpoint_topology(meta):
    """(hosts, {axis: size}) recorded in a checkpoint meta dict, or None
    for a pre-elastic checkpoint (format_version absent)."""
    if not meta or not meta.get('format_version'):
        return None
    sizes = {str(k): int(v) for k, v in (meta.get('mesh') or {}).items()}
    return int(meta.get('hosts', 1)), sizes


def current_topology(main_program=None):
    """(hosts, {axis: size}) the restoring side runs on — process count
    plus the program's mesh axis sizes (all ones when unsharded)."""
    import jax
    from .parallel.mesh import axis_sizes
    main = main_program or default_main_program()
    return jax.process_count(), axis_sizes(getattr(main, 'mesh', None))


def topology_str(hosts, sizes):
    """Compact human form: 'hosts=2 dp4xtp2', or 'single' when trivial."""
    axes = 'x'.join('%s%d' % (a, s) for a, s in sorted(sizes.items())
                    if int(s) > 1)
    if hosts <= 1 and not axes:
        return 'single'
    return ('hosts=%d %s' % (hosts, axes or 'unsharded')).strip()


def topology_changed(meta, main_program=None):
    """True when the topology recorded in `meta` differs from the one
    `main_program` restores on. A pre-elastic meta (None / no
    format_version) recorded nothing, so it counts as changed whenever
    the restoring topology is non-trivial — the caller cannot prove the
    layouts line up."""
    hosts, sizes = current_topology(main_program)
    rec = checkpoint_topology(meta)
    if rec is None:
        return hosts > 1 or any(int(v) > 1 for v in sizes.values())
    rhosts, rsizes = rec
    axes = set(sizes) | set(rsizes)
    return rhosts != hosts or any(
        int(sizes.get(a, 1)) != int(rsizes.get(a, 1)) for a in axes)


def _gather_to_host(value):
    """Multihost-sharded arrays are not fully addressable from one
    process; allgather the global value before serializing (the
    reference's pserver owned whole params — here GSPMD shards them)."""
    import jax
    if isinstance(value, jax.Array) and not value.is_fully_addressable:
        from jax.experimental import multihost_utils
        value = multihost_utils.process_allgather(value, tiled=True)
    return value


def _snapshot_vars(main_program, vars=None, predicate=None):
    """Device->host snapshot of the requested vars: the synchronous half
    of a save. Must run on the caller's thread BEFORE the next training
    step — donated parameter buffers are reused by the step, so a
    deferred read would touch deleted buffers."""
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    # logical shardings travel with the manifest: the spec names mesh
    # AXES, not devices, so a restore on any other mesh can rebuild the
    # layout (or fall back to the new program's own transpile)
    specs = (main_program.var_shardings
             if main_program is not None and
             getattr(main_program, 'mesh', None) is not None else None)
    arrays, manifest = {}, {}
    for v in vars:
        value = scope.find(v.name)
        if value is None:
            continue
        arr, dtype_name = _to_numpy(_gather_to_host(value))
        arrays[v.name] = arr
        entry = {'dtype': dtype_name,
                 'shape': list(np.asarray(arr).shape)}
        if specs is not None:
            entry['spec'] = _spec_to_json(specs.get(v.name))
        manifest[v.name] = entry
    return arrays, manifest


# Serializes snapshot installs within this process: overlapping saves
# (an async writer still in flight when the next save starts) must not
# interleave their renames in one dirname.
_SAVE_LOCK = threading.Lock()


def _write_atomic(path, write_fn, mode='wb'):
    """Write via a UNIQUE tmp file in the target directory + rename —
    unique so concurrent writers never share a tmp (a fixed '.tmp'
    suffix would let a second save corrupt an in-flight first one)."""
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or '.',
                               prefix=os.path.basename(path) + '.')
    try:
        with os.fdopen(fd, mode) as f:
            write_fn(f)
        # mkstemp creates 0600; restore umask-governed perms so other
        # accounts (eval/serving jobs on shared storage) can read
        umask = os.umask(0)
        os.umask(umask)
        os.chmod(tmp, 0o666 & ~umask)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _sha1_of(path):
    h = hashlib.sha1()
    with open(path, 'rb') as f:
        for chunk in iter(lambda: f.read(1 << 20), b''):
            h.update(chunk)
    return h.hexdigest()


def _write_snapshot_locked(dirname, arrays, manifest, filename=None):
    """Disk half of a save — caller must hold _SAVE_LOCK. Each file
    lands atomically (unique tmp + rename). Returns the sha1 digests of
    the installed (manifest, params) files; checkpoint meta records both
    so load_checkpoint can detect a torn pairing (crash between any of
    the renames)."""
    os.makedirs(dirname, exist_ok=True)
    params_path = os.path.join(dirname, filename or _PARAMS_FILE)
    if not params_path.endswith('.npz'):
        params_path += '.npz'
    man_path = os.path.join(dirname, _MANIFEST_FILE)
    _write_atomic(man_path,
                  lambda f: f.write(json.dumps(manifest,
                                               indent=1).encode()))
    _write_atomic(params_path, lambda f: np.savez(f, **arrays))
    return _sha1_of(man_path), _sha1_of(params_path)


def _write_snapshot(dirname, arrays, manifest, filename=None):
    with _SAVE_LOCK:
        return _write_snapshot_locked(dirname, arrays, manifest, filename)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    import jax
    main_program = main_program or default_main_program()
    arrays, manifest = _snapshot_vars(main_program, vars, predicate)
    # one writer per pod: every host gathered the same global values;
    # the commit barrier is timeout-bounded so a host preempted mid-save
    # surfaces as TimeoutError instead of hanging the pod forever
    if jax.process_index() == 0:
        _write_snapshot(dirname, arrays, manifest, filename)
    from .parallel.multihost import barrier
    barrier('paddle_tpu_save_vars')


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_parameter,
              filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    main_program = main_program or default_main_program()
    path = os.path.join(dirname, filename or _PARAMS_FILE)
    if not path.endswith('.npz'):
        path += '.npz'
    data = np.load(path)
    with open(os.path.join(dirname, _MANIFEST_FILE)) as f:
        manifest = json.load(f)
    if vars is None:
        vars = [v for v in main_program.list_vars()
                if predicate is None or predicate(v)]
    scope = global_scope()
    wanted = set(v.name for v in vars)
    for name in data.files:
        if name not in wanted:
            continue
        arr = _from_numpy(data[name], manifest[name]['dtype'])
        scope.var(name)
        scope.set(name, arr)


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_parameter,
              filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def get_inference_program(target_vars, main_program=None):
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    test_program = main_program.clone(for_test=True)
    return test_program.prune(target_vars)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None):
    """Serialize a pruned inference program + params (reference io.py:
    save_inference_model / paddle/fluid/inference/io.cc)."""
    main_program = main_program or default_main_program()
    if not isinstance(target_vars, (list, tuple)):
        target_vars = [target_vars]
    inference_program = get_inference_program(target_vars, main_program)
    os.makedirs(dirname, exist_ok=True)
    from .core.serialize import program_to_dict
    meta = {
        'feed_names': list(feeded_var_names),
        'fetch_names': [v.name if not isinstance(v, str) else v
                        for v in target_vars],
        'program': program_to_dict(inference_program),
    }
    # atomic like every other artifact (fault's unique-tmp + rename
    # convention): a crash mid-dump must not leave a torn __model__.json
    # that load_inference_model parses as corrupt
    _write_atomic(os.path.join(dirname, model_filename or '__model__.json'),
                  lambda f: f.write(json.dumps(meta).encode()))
    save_persistables(executor, dirname, main_program,
                      filename=params_filename)
    return inference_program


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    with open(os.path.join(dirname,
                           model_filename or '__model__.json')) as f:
        meta = json.load(f)
    from .core.serialize import program_from_dict
    program = program_from_dict(meta['program'])
    load_vars(executor, dirname, program, predicate=_is_persistable,
              filename=params_filename)
    fetch_vars = [program.global_block().var(n) for n in meta['fetch_names']]
    return program, meta['feed_names'], fetch_vars


class AsyncSaveHandle(object):
    """Returned by save_checkpoint(async_save=True). result() joins the
    writer thread and re-raises any write error."""

    def __init__(self, thread, errbox):
        self._thread = thread
        self._errbox = errbox

    def done(self):
        return not self._thread.is_alive()

    def result(self, timeout=None):
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise TimeoutError('checkpoint write still in progress')
        if self._errbox:
            raise self._errbox[0]


def save_checkpoint(executor, dirname, main_program=None, step=None,
                    reader=None, async_save=False, trainer_state=None,
                    reader_pending=0):
    """Full training checkpoint: every persistable incl. optimizer state.

    reader: a reader.CheckpointableReader — its (epoch, offset, seed)
    is persisted alongside, so load_checkpoint resumes the data stream
    mid-epoch with exactly the untrained remainder (the reference data
    master's etcd task-queue recovery, go/master/service.go:165-213,
    done masterless via deterministic replay).

    trainer_state: opaque JSON-able dict recorded as meta['trainer']
    (the Trainer stores its epoch / in-epoch step here).

    async_save: snapshot device->host synchronously (donated buffers
    make deferred reads unsafe), then serialize + write on a background
    thread; training continues immediately. Returns an AsyncSaveHandle
    whose result() is the completeness point (on multihost the write
    runs synchronously — off-thread it would race the training step's
    collectives — and an already-completed handle is returned so the
    caller's .result() chain is portable). Each file lands atomically
    via unique-tmp + rename, overlapping saves to one dirname
    serialize, and checkpoint.json — written LAST — records the params
    sha1: a crash between the renames leaves a pairing load_checkpoint
    detects and refuses instead of silently resuming the wrong step."""
    import jax
    main = main_program or default_main_program()
    meta = {}
    if step is not None:
        meta['step'] = int(step)
    if reader is not None:
        # reader_pending: items pulled into a not-yet-run dispatch
        # window — recorded as unconsumed so resume replays them (the
        # reader state converts per-host pending into global stream
        # units via its positional-shard width; see reader/state.py)
        meta['reader'] = reader.state_dict(pending=reader_pending)
    if trainer_state is not None:
        meta['trainer'] = dict(trainer_state)
    # elastic format: the writing topology rides in the meta so restore
    # can tell whether it is coming back on a different slice
    from .parallel.mesh import axis_sizes
    meta['format_version'] = CHECKPOINT_FORMAT_VERSION
    meta['mesh'] = axis_sizes(getattr(main, 'mesh', None))
    meta['hosts'] = jax.process_count()

    def _install(arrays, manifest):
        # snapshot AND meta land under ONE lock acquisition: with the
        # meta write outside it, two overlapping saves could install
        # params from one and checkpoint.json from the other, tripping
        # the torn check on a healthy directory. Single writer, like
        # save_persistables; the reader state is recorded in GLOBAL
        # stream units (the positional shard advances every host's
        # underlying reader identically and state_dict scales pending
        # by the shard width), so process 0's (epoch, offset) is valid
        # for all shards — at the writing host count or any other.
        with _SAVE_LOCK:
            man_sha, params_sha = _write_snapshot_locked(
                dirname, arrays, manifest)
            meta['manifest_sha1'] = man_sha
            meta['params_sha1'] = params_sha
            path = os.path.join(dirname, 'checkpoint.json')
            _write_atomic(path,
                          lambda f: f.write(json.dumps(meta).encode()))

    if async_save and jax.process_count() == 1:
        arrays, manifest = _snapshot_vars(main, predicate=_is_persistable)
        errbox = []

        def _writer():
            try:
                _install(arrays, manifest)
            except BaseException as e:  # surfaced via handle.result()
                errbox.append(e)

        t = threading.Thread(target=_writer, daemon=True,
                             name='paddle_tpu_async_save')
        t.start()
        return AsyncSaveHandle(t, errbox)

    arrays, manifest = _snapshot_vars(main, predicate=_is_persistable)
    if jax.process_index() == 0:
        _install(arrays, manifest)
    from .parallel.multihost import barrier
    barrier('paddle_tpu_save_checkpoint')
    if async_save:  # multihost fallback: completed no-op handle
        t = threading.Thread(target=lambda: None)
        t.start()
        t.join()
        return AsyncSaveHandle(t, [])
    return None


def verify_checkpoint(dirname):
    """Validate that dirname holds a COMPLETE checkpoint: checkpoint.json
    parses and both recorded sha1s match the installed files. Returns the
    parsed meta dict; raises ValueError on a missing/torn checkpoint
    (fault.CheckpointManager uses this to fall back to an older one)."""
    path = os.path.join(dirname, 'checkpoint.json')
    try:
        with open(path) as f:
            recorded = json.load(f)
    except FileNotFoundError:
        raise ValueError(
            'verify_checkpoint: %r holds no checkpoint.json — not a '
            '(complete) checkpoint directory' % dirname)
    except ValueError:
        raise ValueError(
            'verify_checkpoint: %r is a torn/incomplete checkpoint — '
            'checkpoint.json does not parse' % dirname)
    for key, fname in (('params_sha1', _PARAMS_FILE),
                       ('manifest_sha1', _MANIFEST_FILE)):
        want = recorded.get(key)
        fpath = os.path.join(dirname, fname)
        # a recorded-but-missing file is the same torn state as a
        # sha mismatch (partial delete/copy) — diagnose it here
        # instead of letting _sha1_of raise a bare FileNotFoundError
        # (caught too: the file can vanish between exists and read)
        if want is None:
            continue
        try:
            missing = not os.path.exists(fpath)
            mismatch = (not missing) and _sha1_of(fpath) != want
        except FileNotFoundError:
            missing, mismatch = True, False
        if missing or mismatch:
            reason = 'is missing' if missing else \
                'does not match the sha1 recorded in checkpoint.json'
            raise ValueError(
                'load_checkpoint: %r is a torn/incomplete checkpoint '
                '— %s %s (a save was interrupted between renames, or '
                'the directory was partially copied). Restore from '
                'an older checkpoint; resuming here would pair '
                'weights with the wrong step/reader state.'
                % (dirname, fname, reason))
    return recorded


def _reshard_restored(main, dirname):
    """Eagerly rebuild every restored array under the restoring mesh's
    NamedSharding (jax.device_put) instead of assuming the written
    layout still applies. Spec priority: the new program's transpiled
    var_shardings, then the manifest's recorded logical spec filtered
    to the new mesh's axes, then replicated. Single-process only — on a
    pod every host holds the full gathered value and the executor's
    dispatch-time sharding path owns cross-host placement. Returns the
    number of arrays placed."""
    import jax
    if jax.process_count() > 1:
        return 0
    from jax.sharding import NamedSharding
    mesh = main.mesh
    axis_names = set(str(a) for a in mesh.axis_names)
    try:
        with open(os.path.join(dirname, _MANIFEST_FILE)) as f:
            manifest = json.load(f)
    except (OSError, ValueError):
        return 0
    scope = global_scope()
    n = 0
    for name, entry in manifest.items():
        value = scope.find(name)
        if value is None:
            continue
        spec = main.var_shardings.get(name)
        if spec is None:
            spec = _spec_from_json(entry.get('spec'), axis_names)
        try:
            scope.set(name, jax.device_put(value,
                                           NamedSharding(mesh, spec)))
            n += 1
        except Exception as e:
            # an indivisible dim under the new mesh: leave the host
            # array; the executor's with_sharding_constraint path pads
            # inside the jitted step where uneven shards are legal
            warnings.warn('load_checkpoint: could not reshard %r onto '
                          'the restoring mesh (%s: %s); leaving it for '
                          'dispatch-time placement' % (name,
                                                       type(e).__name__,
                                                       e))
    return n


def load_checkpoint(executor, dirname, main_program=None, reader=None):
    """Restore a checkpoint, elastically: when the recorded topology
    (mesh axis sizes + host count) differs from the restoring
    program's, every array is re-placed under the new mesh's
    NamedSharding and the reader state — kept in global stream units —
    replays exactly the untrained remainder at the new dp width.
    Pre-elastic checkpoints (no format_version) restore unchanged on
    the same topology and are refused on a different one."""
    main = main_program or default_main_program()
    path = os.path.join(dirname, 'checkpoint.json')
    meta = None
    if os.path.exists(path):
        meta = verify_checkpoint(dirname)
    elif reader is not None:
        raise ValueError(
            'load_checkpoint: a reader was passed but %r holds no '
            'checkpoint.json — resuming would silently re-consume '
            'already-trained data (was save_checkpoint called with '
            'reader=...?)' % dirname)
    changed = topology_changed(meta, main)
    if changed and not (meta and meta.get('format_version')):
        cur = topology_str(*current_topology(main))
        raise ValueError(
            'load_checkpoint: %r predates the elastic checkpoint format '
            '(no format_version and no per-variable sharding specs '
            'recorded) but the restoring topology is %s — the layouts '
            'cannot be verified to line up. Restore it on an unsharded '
            'single-host program (and re-save to upgrade it to format '
            'version %d), or retrain.'
            % (dirname, cur, CHECKPOINT_FORMAT_VERSION))
    if meta is None:
        # legacy save_persistables layout: restorable, but with zero
        # integrity guarantees — make that visible in postmortems
        warnings.warn(
            'load_checkpoint: %r holds no checkpoint.json — restoring '
            'WITHOUT sha1 verification; a torn write or partial copy '
            'would go undetected here' % dirname)
        _obs.flight_event('ckpt_unverified_restore', dirname=dirname)
        _obs.inc('fault.unverified_restores_total')
    load_persistables(executor, dirname, main)
    if changed and getattr(main, 'mesh', None) is not None:
        _reshard_restored(main, dirname)
    if meta is None:
        return None
    if reader is not None:
        state = meta.get('reader')
        if state is None:
            raise ValueError(
                'load_checkpoint: a reader was passed but %r holds no '
                'reader state (was save_checkpoint called with '
                'reader=...?)' % dirname)
        reader.load_state_dict(state)
    return meta.get('step')
