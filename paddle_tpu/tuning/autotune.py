"""Per-shape kernel autotuner.

On first sight of an (op, shape, dtype, device_kind) key, microbenchmark
the candidate variants — XLA vs Pallas, and a small grid of Pallas block
sizes — and record the winner in the persisted :class:`TuningTable`.
Dispatch sites (``ops/attention_ops.py``, ``ops/pallas/paged_attention
.py`` — which also covers ``ops/paged_decode_ops.py`` — and the
layer/batch-norm wrappers) consult ``decide()`` instead of the global
env gates when autotuning is on; the explicit env gates
(``PADDLE_TPU_USE_PALLAS`` etc.) always override the table.

Knobs::

    PADDLE_TPU_AUTOTUNE      off (default) | on | record
    PADDLE_TPU_TUNING_TABLE  table path (default: per-user tmp file)

``on`` trusts existing table entries and only measures unseen keys;
``record`` re-measures every key it encounters (refreshing a stale
table — the record-vs-replay workflow: record once on the target chip,
replay everywhere with ``on``).

Measurement runs eagerly at trace time: candidates execute on synthetic
inputs of the live shape (concrete arrays, so a nested ``jax.jit``
dispatches for real even while an outer trace is active), timed with an
``np.asarray`` sync — ``block_until_ready`` returns at enqueue on the
tunneled relay (SURVEY §5.1). A candidate that fails to compile (e.g. a
real Pallas kernel on a CPU host) scores +inf and simply loses. Tests
inject deterministic timings via :func:`set_timer`.
"""

import math
import os
import time

import numpy as np

from .. import observe as _obs
from .table import TuningTable

__all__ = ['autotune_mode', 'decide', 'reset', 'set_timer', 'table_path',
           'current_table', 'device_kind', 'env_gate_set',
           'decide_summa_panel', 'decide_linalg_block',
           'decide_matmul_dtype']

_STATE = {'table': None, 'table_path': None, 'memo': {}, 'timer': None}


# ---------------------------------------------------------------- knobs
def autotune_mode(environ=None):
    """'off' | 'on' | 'record' from PADDLE_TPU_AUTOTUNE."""
    env = os.environ if environ is None else environ
    raw = (env.get('PADDLE_TPU_AUTOTUNE') or 'off').strip().lower()
    if raw in ('on', '1', 'true', 'yes'):
        return 'on'
    if raw == 'record':
        return 'record'
    return 'off'


def table_path():
    """PADDLE_TPU_TUNING_TABLE, or a per-user tmp default (same rationale
    as platform_boot.arm_compile_cache: a fixed shared-tmp name would
    poison across users on a shared machine)."""
    import tempfile
    p = os.environ.get('PADDLE_TPU_TUNING_TABLE')
    if p:
        return p
    try:
        import getpass
        user = getpass.getuser()
    except Exception:
        user = str(os.getuid()) if hasattr(os, 'getuid') else 'default'
    return os.path.join(tempfile.gettempdir(),
                        'paddle_tpu_tuning_%s.json' % user)


def env_gate_set(*names):
    """True when any of the named env gates is EXPLICITLY set — the
    operator pinned a kernel choice, which overrides the table."""
    return any(os.environ.get(n) is not None for n in names)


def device_kind():
    """The backend's device kind string ('cpu', 'TPU v5e', ...) — the
    table's top-level key, so one file can hold tables for several chip
    generations."""
    kind = _STATE.get('device_kind')
    if kind is None:
        try:
            import jax
            kind = str(jax.devices()[0].device_kind)
        except Exception:
            kind = 'unknown'
        _STATE['device_kind'] = kind
    return kind


def reset():
    """Drop every cached decision and the in-memory table (tests, and
    bench legs that re-point PADDLE_TPU_TUNING_TABLE mid-process)."""
    _STATE['table'] = None
    _STATE['table_path'] = None
    _STATE['memo'] = {}
    _STATE.pop('device_kind', None)


def set_timer(fn):
    """Inject a timing function ``fn(op, key, variant, thunk) ->
    seconds`` (None restores the real timer). Tests use this for
    deterministic winner selection without touching hardware."""
    _STATE['timer'] = fn


def current_table():
    """The table for the current PADDLE_TPU_TUNING_TABLE path, loading
    it on first access (and reloading if the path knob changed)."""
    path = table_path()
    if _STATE['table'] is None or _STATE['table_path'] != path:
        _STATE['table'] = TuningTable.load(path)
        _STATE['table_path'] = path
        if _STATE['table'].loaded_from_disk:
            _obs.flight_event('tuning_table_loaded', path=path,
                              entries=_STATE['table'].size())
        _obs.set_gauge('tuning.table_size', _STATE['table'].size())
    return _STATE['table']


# ------------------------------------------------------------ measuring
def _time_thunk(op, key, variant, thunk, warmup=1, iters=3):
    """Best-of-`iters` wall seconds for one candidate. The thunk builds
    its own synthetic inputs and returns a device array; np.asarray is
    the sync (relay-safe). +inf when the candidate cannot run here."""
    try:
        for _ in range(max(0, warmup)):
            np.asarray(thunk())
        best = math.inf
        for _ in range(max(1, iters)):
            t0 = time.perf_counter()
            np.asarray(thunk())
            best = min(best, time.perf_counter() - t0)
        return best
    except Exception as e:
        _obs.flight_event('tuning_candidate_failed', op=op, key=key,
                          variant=_label(variant),
                          error='%s: %s' % (type(e).__name__, e))
        return math.inf


def _label(variant):
    """Stable short label for a variant dict ('pallas bq512 bk256')."""
    impl = variant.get('impl', '?')
    extras = ' '.join('%s%s' % (k.replace('block_', 'b'), v)
                      for k, v in sorted(variant.items()) if k != 'impl')
    return ('%s %s' % (impl, extras)).strip()


def _measure(op, key, candidates):
    """Time every candidate; returns (winner_variant, {label: secs}).
    Falls back to the first candidate when nothing ran (all +inf)."""
    timer = _STATE['timer'] or _time_thunk
    timings = {}
    best, best_t = None, math.inf
    t0 = time.perf_counter()
    for variant, thunk in candidates:
        dt = timer(op, key, variant, thunk)
        timings[_label(variant)] = dt if math.isfinite(dt) else -1.0
        if dt < best_t:
            best, best_t = variant, dt
    if best is None:
        best = candidates[0][0]
    _obs.record('tuning.tune_seconds', time.perf_counter() - t0, op=op)
    return best, timings


# -------------------------------------------------------------- deciding
def decide(op, key, candidates):
    """The tuned variant dict for (op, key), or None when autotuning is
    off (callers then fall back to the default env-gate logic).

    ``candidates`` is ``[(variant_dict, thunk), ...]``; thunks only run
    when the key has never been measured (mode 'on') or always (mode
    'record'). Decisions are memoized per process — the hot path after
    the first trace is one dict hit — and persisted to the table file
    the moment they are measured, so a restarted process replays them
    without re-benchmarking."""
    mode = autotune_mode()
    if mode == 'off' or not candidates:
        return None
    kind = device_kind()
    memo_key = (kind, key)
    hit = _STATE['memo'].get(memo_key)
    if hit is not None:
        return hit
    table = current_table()
    if mode == 'on':
        ent = table.lookup(kind, key)
        if ent and isinstance(ent.get('winner'), dict):
            winner = dict(ent['winner'])
            _STATE['memo'][memo_key] = winner
            _obs.inc('tuning.decisions_total', op=op, source='table',
                     impl=winner.get('impl', '?'))
            return winner
    winner, timings = _measure(op, key, candidates)
    table.put(kind, key, winner, timings,
              mode='recorded' if mode == 'record' else 'measured')
    table.save()
    _STATE['memo'][memo_key] = dict(winner)
    _obs.inc('tuning.decisions_total', op=op, source='measured',
             impl=winner.get('impl', '?'))
    _obs.set_gauge('tuning.table_size', table.size())
    _obs.flight_event('tune', op=op, key=key, winner=_label(winner),
                      device_kind=kind)
    return dict(winner)


# ------------------------------------------------- per-op decision hooks
# Each hook renders the shape key, enumerates candidates with synthetic-
# input thunks, and returns decide()'s verdict. They are called from
# inside jit traces: thunks build CONCRETE arrays, so the nested
# executions run eagerly and never leak tracers into the outer program.

def decide_attention(b, h, tq, tk, d, dtype, causal, masked):
    """xla vs pallas-flash, over the (block_q, block_k) grid. `masked`
    keys variable-length batches separately (the kernel skips masked key
    blocks, so its ranking differs from the dense case)."""
    import jax
    import jax.numpy as jnp
    from ..ops.pallas.flash_attention import (attention_block_variants,
                                              flash_attention)
    from ..ops.attention_ops import reference_attention

    key = ('flash_attention|b%d h%d tq%d tk%d d%d causal%d masked%d|%s'
           % (b, h, tq, tk, d, int(bool(causal)), int(bool(masked)),
              dtype))

    def mk_inputs():
        q = jnp.ones((b, h, tq, d), dtype)
        k = jnp.ones((b, h, tk, d), dtype)
        v = jnp.ones((b, h, tk, d), dtype)
        lens = (jnp.full((b,), max(1, (3 * tk) // 4), jnp.int32)
                if masked else None)
        return q, k, v, lens

    def xla_thunk():
        q, k, v, lens = mk_inputs()
        return jax.jit(lambda q, k, v: reference_attention(
            q, k, v, causal=causal, key_length=lens))(q, k, v)

    candidates = [({'impl': 'xla'}, xla_thunk)]
    for bq, bk in attention_block_variants(tq, tk):
        def pallas_thunk(bq=bq, bk=bk):
            q, k, v, lens = mk_inputs()
            return jax.jit(lambda q, k, v: flash_attention(
                q, k, v, causal=causal, kv_len=lens,
                block_q=bq, block_k=bk))(q, k, v)
        candidates.append(
            ({'impl': 'pallas', 'block_q': bq, 'block_k': bk},
             pallas_thunk))
    return decide('flash_attention', key, candidates)


def decide_paged_attention(b, p, h, bs, d, dv, dtype):
    """XLA gather path vs the scalar-prefetch Pallas kernel for one
    ragged paged-attention shape (the decode hot loop)."""
    import jax
    import jax.numpy as jnp
    from ..ops.pallas import paged_attention as _pa

    key = ('paged_attention|b%d p%d h%d bs%d d%d dv%d|%s'
           % (b, p, h, bs, d, dv, dtype))

    def mk_inputs():
        q = jnp.ones((b, h, d), dtype)
        kp = jnp.ones((b * p, h, bs, d), dtype)
        vp = jnp.ones((b * p, h, bs, dv), dtype)
        tables = jnp.arange(b * p, dtype=jnp.int32).reshape(b, p)
        lens = jnp.full((b,), p * bs - 1, jnp.int32)
        return q, kp, vp, tables, lens

    def xla_thunk():
        args = mk_inputs()
        return jax.jit(_pa.paged_attention_reference)(*args)

    candidates = [({'impl': 'xla'}, xla_thunk)]
    if bs % 8 == 0 and d % 8 == 0:   # kernel wants lane-aligned tiles
        def pallas_thunk():
            q, kp, vp, tables, lens = mk_inputs()
            return jax.jit(lambda *a: _pa._paged_pallas(
                *a, sm_scale=d ** -0.5))(q, kp, vp, tables, lens)
        candidates.append(({'impl': 'pallas'}, pallas_thunk))
    return decide('paged_attention', key, candidates)


def decide_layer_norm(n, d, dtype):
    """xla vs the fused Pallas row kernel over a small block_rows grid
    (the kernel's win is long rows; the grid lets short-row shapes keep
    the XLA fusion)."""
    import jax
    import jax.numpy as jnp
    from ..ops.pallas import layer_norm as _ln

    key = 'layer_norm|n%d d%d|%s' % (n, d, dtype)

    def mk_inputs():
        return (jnp.ones((n, d), dtype), jnp.ones((d,), jnp.float32),
                jnp.zeros((d,), jnp.float32))

    def xla_thunk():
        x, g, b = mk_inputs()
        return jax.jit(lambda x, g, b: _ln._ln_reference(
            x, g, b, 1e-5))(x, g, b)

    candidates = [({'impl': 'xla'}, xla_thunk)]
    if d % 128 == 0:
        for rows in (512, 256, 128):
            if rows > n:
                continue
            def pallas_thunk(rows=rows):
                x, g, b = mk_inputs()
                return jax.jit(lambda x, g, b: _ln._ln_pallas(
                    x, g, b, 1e-5, block_rows=rows))(x, g, b)
            candidates.append(({'impl': 'pallas', 'block_rows': rows},
                               pallas_thunk))
    return decide('layer_norm', key, candidates)


def _ladder(sizes, cap=6):
    """Trim a legal-size ladder to at most `cap` candidates, keeping
    the largest (each candidate runs the real distributed kernel, so
    the sweep cost is bounded; the small end of the ladder loses on
    per-step collective latency everywhere we have measured)."""
    sizes = [s for s in sizes if s >= 8] or sizes[-1:]
    return sizes[-cap:]


def decide_summa_panel(n, k, m, dtype, mesh):
    """SUMMA k-panel size over the legal ladder (divisors of
    gcd(K/tp, K/dp)) — the `linalg` op family, keyed by
    (op, shape, dtype, mesh grid). Candidates run the REAL shard_map
    kernel on `mesh` at the live shape: coarse panels amortize the
    broadcast chain, fine panels overlap it against the local dot, and
    which wins is a property of the chip generation the table is keyed
    by."""
    import jax
    import jax.numpy as jnp
    from ..linalg import kernels

    n_dp, n_tp = kernels.axis_sizes_of(mesh, 'dp', 'tp')
    key = ('summa_matmul|n%d k%d m%d|dp%d tp%d|%s'
           % (n, k, m, n_dp, n_tp, dtype))
    panels = _ladder(kernels.legal_panels(k, n_dp, n_tp))
    candidates = []
    for p in panels:
        def thunk(p=p):
            a = jnp.ones((n, k), dtype)
            b = jnp.ones((k, m), dtype)
            return jax.jit(lambda a_, b_: kernels.summa_matmul(
                a_, b_, mesh, panel=p))(a, b)
        candidates.append(({'impl': 'summa', 'panel': p}, thunk))
    return decide('summa_matmul', key, candidates)


def decide_linalg_block(op, n, m, dtype, mesh, axis='dp'):
    """Factorization panel width for blocked_cholesky / blocked_qr
    over the legal ladder (cholesky panels must divide the per-shard
    row extent; qr panels the column count). Same linalg family key
    shape as decide_summa_panel."""
    import jax
    import jax.numpy as jnp
    from ..linalg import kernels

    (n_dp,) = kernels.axis_sizes_of(mesh, axis)
    key = '%s|n%d m%d|dp%d|%s' % (op, n, m, n_dp, dtype)
    if op == 'blocked_cholesky':
        blocks = kernels.legal_blocks(n, local=n // n_dp)
    elif op == 'blocked_qr':
        blocks = kernels.legal_blocks(m)
    else:
        raise ValueError('decide_linalg_block: unknown op %r' % op)
    candidates = []
    for blk in _ladder(blocks):
        def thunk(blk=blk):
            if op == 'blocked_cholesky':
                # synthetic SPD: diagonally dominant, full rank
                a = jnp.eye(n, dtype=dtype) * (2.0 * n) + 1.0
                return jax.jit(lambda a_: kernels.blocked_cholesky(
                    a_, mesh, block=blk))(a)
            a = (jnp.sin(jnp.arange(n * m, dtype=jnp.float32))
                 .reshape(n, m).astype(dtype))
            return jax.jit(lambda a_: kernels.blocked_qr(
                a_, mesh, block=blk))(a)[0]
        candidates.append(({'impl': 'blocked', 'block': blk}, thunk))
    return decide(op, key, candidates)


def decide_matmul_dtype(m, k, n, dtype):
    """Native (input-dtype) vs fp8(e4m3)-cast contraction for one
    2D matmul shape — the ``matmul_dtype`` family behind the
    mul/matmul lowerings' dispatch (ops/fp8_matmul.py). The fp8
    candidate only enumerates where this jax build carries
    float8_e4m3fn; the explicit ``PADDLE_TPU_FP8_MATMUL`` gate is
    checked at the dispatch site and beats this table."""
    import jax
    import jax.numpy as jnp

    key = 'matmul_dtype|m%d k%d n%d|%s' % (m, k, n, dtype)

    def mk_inputs():
        return jnp.ones((m, k), dtype), jnp.ones((k, n), dtype)

    def native_thunk():
        x, y = mk_inputs()
        return jax.jit(jnp.matmul)(x, y)

    candidates = [({'impl': 'native'}, native_thunk)]
    from ..quant.core import kv_fp8_supported
    if kv_fp8_supported():
        def fp8_thunk():
            from ..ops.fp8_matmul import fp8_matmul
            x, y = mk_inputs()
            return jax.jit(fp8_matmul)(x, y)
        candidates.append(({'impl': 'fp8'}, fp8_thunk))
    return decide('matmul_dtype', key, candidates)


def decide_batch_norm(r, c, dtype):
    """xla two-pass stats vs the one-pass fused Pallas BN kernel over a
    block_r grid (training-mode forward only — the backward is jnp on
    both paths)."""
    import jax
    import jax.numpy as jnp
    from ..ops.pallas import batch_norm as _bn

    key = 'batch_norm|r%d c%d|%s' % (r, c, dtype)

    def mk_inputs():
        return (jnp.ones((r, c), dtype), jnp.ones((c,), jnp.float32),
                jnp.zeros((c,), jnp.float32))

    def xla_thunk():
        x, s, b = mk_inputs()
        return jax.jit(lambda x, s, b: _bn._bn_reference(
            x, s, b, 1e-5)[0])(x, s, b)

    candidates = [({'impl': 'xla'}, xla_thunk)]
    if r % 8 == 0 and (c % 128 == 0 or c < 128):
        for br in (512, 256):
            if br > r:
                continue
            def pallas_thunk(br=br):
                x, s, b = mk_inputs()
                return jax.jit(lambda x, s, b: _bn._fused_bn_fwd(
                    x, s, b, 1e-5, br)[0])(x, s, b)
            candidates.append(({'impl': 'pallas', 'block_r': br},
                               pallas_thunk))
    return decide('batch_norm', key, candidates)
