"""paddle_tpu.tuning — autotuned kernel selection (ROADMAP item 2).

Two pieces:

- a persisted **tuning table** (`table.py`): versioned JSON, keyed by
  device kind, holding the measured winner for every (op, shape, dtype)
  key — atomic writes, corrupted/stale tables ignored with a flight
  event, inspectable offline via ``tools/tuning_inspect.py``;
- the **autotuner** (`autotune.py`): on first sight of a key (with
  ``PADDLE_TPU_AUTOTUNE=on``) microbenchmarks the candidate variants —
  XLA vs Pallas, and the Pallas block-size grids — records the winner,
  and serves it to the kernel dispatch sites from then on. Explicit env
  gates (``PADDLE_TPU_USE_PALLAS``, ``PADDLE_TPU_PAGED_PALLAS``,
  ``PADDLE_TPU_BN_PALLAS``, ``PADDLE_TPU_PALLAS_BLOCK_K``) always
  override the table.

The companion cold-start lever — the AOT serialized-executable cache —
lives in ``core/aot_cache.py``; docs/performance.md "Autotuning and AOT
warm start" covers both.
"""

from .autotune import (autotune_mode, current_table, decide,  # noqa: F401
                       decide_attention, decide_batch_norm,
                       decide_layer_norm, decide_linalg_block,
                       decide_matmul_dtype, decide_paged_attention,
                       decide_summa_panel, device_kind, env_gate_set,
                       reset, set_timer, table_path)
from .table import FORMAT_VERSION, TuningTable  # noqa: F401

__all__ = ['autotune_mode', 'decide', 'decide_attention',
           'decide_batch_norm', 'decide_layer_norm',
           'decide_linalg_block', 'decide_matmul_dtype',
           'decide_paged_attention', 'decide_summa_panel',
           'device_kind', 'env_gate_set', 'reset', 'set_timer',
           'table_path', 'current_table', 'TuningTable',
           'FORMAT_VERSION']
