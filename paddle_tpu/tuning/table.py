"""Persisted per-shape kernel-selection table.

One JSON file holds, per device kind, the measured winner for every
(op, shape, dtype) key the autotuner has seen — so the microbenchmark
runs once per key per chip generation, not once per process. The r4
on-chip capture is the motivating data: XLA attention beats Pallas at
seq1024 while Pallas wins 13x at seq4096, so a single global gate is
wrong for at least one of the two shapes any long-context model runs.

Schema (format_version 1)::

    {
      "format_version": 1,
      "jax": "0.4.37",                  # writer provenance, not checked
      "tables": {
        "<device_kind>": {
          "<op>|<shape>|<dtype>": {
            "winner":  {"impl": "pallas", "block_q": 512, "block_k": 256},
            "timings": {"xla": 1.41e-3, "pallas bq512 bk256": 9.2e-4},
            "mode":    "measured",      # or "recorded"
            "ts":      1722800000.0
          }
        }
      }
    }

Durability contract matches every other artifact in this repo
(io._write_atomic): writes land via a UNIQUE tmp file + ``os.replace``
so a crashed writer never leaves a half-table, and concurrent writers
never share a tmp. A corrupted or version-mismatched table is IGNORED
(empty table + a ``tuning_table_ignored`` flight event), never raised:
a stale cache must not take a training run down.

Stdlib-only on purpose — ``tools/tuning_inspect.py`` reads the same
schema without importing jax.
"""

import json
import os
import tempfile
import time

from .. import observe as _obs

FORMAT_VERSION = 1


class TuningTable(object):
    """In-memory view of one tuning-table file."""

    def __init__(self, path=None):
        self.path = path
        self.tables = {}          # device_kind -> {key: entry}
        self.loaded_from_disk = False

    # ------------------------------------------------------------ access
    def lookup(self, device_kind, key):
        """The recorded entry for (device_kind, key), or None."""
        return self.tables.get(device_kind, {}).get(key)

    def put(self, device_kind, key, winner, timings, mode='measured'):
        self.tables.setdefault(device_kind, {})[key] = {
            'winner': dict(winner),
            'timings': {k: round(float(v), 9) for k, v in timings.items()},
            'mode': mode,
            'ts': round(time.time(), 3),
        }

    def size(self):
        return sum(len(t) for t in self.tables.values())

    def to_dict(self):
        jax_ver = None
        try:
            import jax
            jax_ver = jax.__version__
        except Exception:
            pass
        return {'format_version': FORMAT_VERSION, 'jax': jax_ver,
                'tables': self.tables}

    # ------------------------------------------------------- persistence
    @classmethod
    def load(cls, path):
        """Read *path*; a missing file is an empty table, a corrupted or
        version-mismatched one is an empty table plus a flight event —
        the autotuner re-measures, it never crashes on stale state."""
        t = cls(path)
        if not path or not os.path.exists(path):
            return t
        try:
            with open(path) as f:
                data = json.load(f)
            if not isinstance(data, dict):
                raise ValueError('not a JSON object')
            ver = data.get('format_version')
            if ver != FORMAT_VERSION:
                raise ValueError('format_version %r != %d'
                                 % (ver, FORMAT_VERSION))
            tables = data.get('tables')
            if not isinstance(tables, dict):
                raise ValueError('missing "tables" object')
            for kind, entries in tables.items():
                if not isinstance(entries, dict):
                    raise ValueError('device table %r is not an object'
                                     % kind)
        except Exception as e:
            _obs.inc('tuning.table_ignored_total')
            _obs.flight_event('tuning_table_ignored', path=str(path),
                              error='%s: %s' % (type(e).__name__, e))
            return t
        t.tables = tables
        t.loaded_from_disk = True
        return t

    def save(self, path=None):
        """Atomic write (unique tmp + os.replace). Merges with whatever
        is on disk first, so two processes tuning different keys against
        one table file compose instead of clobbering. Best-effort: a
        failed save records a flight event and returns None."""
        path = path or self.path
        if not path:
            return None
        try:
            on_disk = TuningTable.load(path)
            for kind, entries in on_disk.tables.items():
                mine = self.tables.setdefault(kind, {})
                for key, ent in entries.items():
                    mine.setdefault(key, ent)
            d = os.path.dirname(path) or '.'
            os.makedirs(d, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=d,
                                       prefix=os.path.basename(path) + '.')
            try:
                with os.fdopen(fd, 'w') as f:
                    json.dump(self.to_dict(), f, indent=1, sort_keys=True)
                umask = os.umask(0)
                os.umask(umask)
                os.chmod(tmp, 0o666 & ~umask)
                os.replace(tmp, path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except Exception as e:
            _obs.flight_event('tuning_table_save_failed', path=str(path),
                              error='%s: %s' % (type(e).__name__, e))
            return None
        return path
