/* paddle_tpu inference C ABI.
 *
 * Reference analog: paddle/capi/capi.h:1-32 (error.h, gradient_machine.h,
 * arguments/matrix accessors) — create a machine from a saved model,
 * forward, fetch outputs. TPU-native: the library embeds the CPython/JAX
 * runtime and drives the XLA-compiled Predictor, so a plain C program
 * gets the same AOT-compiled inference path Python users get. Repeated
 * runs with a stable input signature are cached XLA dispatches.
 *
 * Compilation cache: the predictor compiles one executable per input
 * SIGNATURE (shapes + dtypes) and keeps all of them. The first run with
 * a new batch size pays a fresh XLA compile (seconds); later runs with
 * any previously-seen signature are pure dispatches. Serving tip: batch
 * to a small fixed set of sizes (pad the tail batch) rather than
 * feeding every ragged size.
 *
 * Thread model (contract, tested by tests/test_capi.py's concurrent
 * client — reference analog: capi/examples/model_inference/multi_thread):
 *   - The library is thread-safe ACROSS predictors: any number of
 *     threads may create/run/destroy DISTINCT predictors concurrently;
 *     calls serialize internally on the embedded interpreter's GIL
 *     (device compute may release it, so runs can overlap on-device).
 *   - A single predictor is NOT thread-safe: its output buffers are
 *     per-predictor state overwritten by each run, so concurrent runs
 *     on the SAME predictor may interleave and swap results. Serialize
 *     externally, or use one predictor per thread (each predictor
 *     AOT-compiles its own executable on first run for its feed
 *     signature).
 * Output buffers are owned by the predictor and stay valid until the next
 * paddle_predictor_run / paddle_predictor_destroy on that predictor.
 */
#ifndef PADDLE_TPU_CAPI_H_
#define PADDLE_TPU_CAPI_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
  kPD_NO_ERROR = 0,
  kPD_NULLPTR = 1,
  kPD_OUT_OF_RANGE = 2,
  kPD_PROTOBUF_ERROR = 3,
  kPD_NOT_SUPPORTED = 4,
  kPD_UNDEFINED_ERROR = -1,
} paddle_error;

typedef enum {
  PD_FLOAT32 = 0,
  PD_INT64 = 1,
  PD_INT32 = 2,
  PD_FLOAT64 = 3,
  PD_UINT8 = 4,
  PD_BOOL = 5,
} paddle_dtype;

#define PD_MAX_NDIM 8

typedef struct {
  paddle_dtype dtype;
  int32_t ndim;
  int64_t shape[PD_MAX_NDIM];
  void* data; /* row-major, dense */
} paddle_tensor;

typedef void* paddle_predictor;

/* Start (or attach to) the embedded Python/JAX runtime. Optional —
 * paddle_predictor_create calls it implicitly. `platform` may be NULL
 * (auto), "tpu" or "cpu". */
paddle_error paddle_tpu_init(const char* platform);

/* Load a model saved by fluid.io.save_inference_model(dirname, ...). */
paddle_error paddle_predictor_create(const char* model_dir,
                                     paddle_predictor* out);

/* Run inference. inputs[i] pairs with input_names[i]; data is copied in,
 * so caller buffers may be freed immediately after the call returns. */
paddle_error paddle_predictor_run(paddle_predictor pred, int32_t n_inputs,
                                  const char** input_names,
                                  const paddle_tensor* inputs);

/* Number of fetch outputs of the loaded model. */
paddle_error paddle_predictor_output_count(paddle_predictor pred,
                                           int32_t* count);

/* Fetch output #idx from the last run. `out->data` points into
 * predictor-owned memory (valid until the next run/destroy). */
paddle_error paddle_predictor_output(paddle_predictor pred, int32_t idx,
                                     paddle_tensor* out);

paddle_error paddle_predictor_destroy(paddle_predictor pred);

/* Human-readable message for the LAST error returned on this thread
 * (empty string if none). */
const char* paddle_last_error_message(void);

const char* paddle_error_string(paddle_error err);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_CAPI_H_ */
