// paddle_tpu inference C ABI — implementation.
//
// Reference analog: paddle/capi/{gradient_machine,Arguments,Matrix}.cpp
// wrap the C++ GradientMachine; here the "machine" is the XLA-compiled
// Predictor (paddle_tpu/inference/predictor.py), reached through an
// embedded CPython interpreter. Marshalling crosses the boundary as raw
// bytes (the bridge re-views them as numpy arrays), so neither side needs
// the numpy C API.
//
// Build: handled by paddle_tpu.native.build_native('capi', python flags).

#include "capi.h"

#include <Python.h>

#include <atomic>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

paddle_error fail(paddle_error code, const std::string& msg) {
  g_last_error = msg;
  return code;
}

std::string py_exc_string() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  std::string out = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      out = PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return out;
}

struct Predictor {
  PyObject* bridge = nullptr;   // paddle_tpu.inference.capi_bridge module
  PyObject* py_pred = nullptr;  // Predictor instance
  // Output buffers from the last run; tensors point into bufs.
  std::vector<std::vector<uint8_t>> bufs;
  std::vector<paddle_tensor> tensors;
};

// Lazy init may race: the thread contract allows concurrent first
// paddle_predictor_create calls, and Py_InitializeEx must run exactly
// once BEFORE any PyGILState_Ensure — serialize the whole init.
std::mutex g_init_mutex;
std::atomic<bool> g_initialized{false};

size_t dtype_size(paddle_dtype d) {
  switch (d) {
    case PD_FLOAT32: return 4;
    case PD_INT64: return 8;
    case PD_INT32: return 4;
    case PD_FLOAT64: return 8;
    case PD_UINT8: return 1;
    case PD_BOOL: return 1;
  }
  return 0;
}

class GIL {
 public:
  GIL() : state_(PyGILState_Ensure()) {}
  ~GIL() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

}  // namespace

extern "C" {

paddle_error paddle_tpu_init(const char* platform) {
  std::lock_guard<std::mutex> init_lock(g_init_mutex);
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    // Release the GIL taken by Py_InitializeEx so every later entry
    // (any thread, including this one) uniformly uses PyGILState_Ensure.
    PyEval_SaveThread();
  }
  GIL gil;
  if (platform != nullptr && *platform != '\0') {
    // Consumed by the bridge before it touches the jax backend.
    PyObject* os = PyImport_ImportModule("os");
    if (os == nullptr) return fail(kPD_UNDEFINED_ERROR, py_exc_string());
    PyObject* environ = PyObject_GetAttrString(os, "environ");
    Py_DECREF(os);
    if (environ == nullptr) return fail(kPD_UNDEFINED_ERROR, py_exc_string());
    PyObject* r = PyObject_CallMethod(environ, "__setitem__", "ss",
                                      "PADDLE_TPU_CAPI_PLATFORM", platform);
    Py_DECREF(environ);
    if (r == nullptr) return fail(kPD_UNDEFINED_ERROR, py_exc_string());
    Py_DECREF(r);
  }
  g_initialized = true;
  return kPD_NO_ERROR;
}

paddle_error paddle_predictor_create(const char* model_dir,
                                     paddle_predictor* out) {
  if (model_dir == nullptr || out == nullptr)
    return fail(kPD_NULLPTR, "model_dir/out is NULL");
  if (!g_initialized) {
    paddle_error e = paddle_tpu_init(nullptr);
    if (e != kPD_NO_ERROR) return e;
  }
  GIL gil;
  PyObject* bridge = PyImport_ImportModule("paddle_tpu.inference.capi_bridge");
  if (bridge == nullptr)
    return fail(kPD_PROTOBUF_ERROR,
                "cannot import paddle_tpu (set PYTHONPATH): " +
                    py_exc_string());
  PyObject* pred =
      PyObject_CallMethod(bridge, "create", "s", model_dir);
  if (pred == nullptr) {
    std::string msg = py_exc_string();
    Py_DECREF(bridge);
    return fail(kPD_PROTOBUF_ERROR, "load failed: " + msg);
  }
  auto* p = new Predictor();
  p->bridge = bridge;
  p->py_pred = pred;
  *out = p;
  return kPD_NO_ERROR;
}

paddle_error paddle_predictor_run(paddle_predictor pred, int32_t n_inputs,
                                  const char** input_names,
                                  const paddle_tensor* inputs) {
  if (pred == nullptr) return fail(kPD_NULLPTR, "predictor is NULL");
  if (n_inputs > 0 && (input_names == nullptr || inputs == nullptr))
    return fail(kPD_NULLPTR, "input_names/inputs is NULL");
  auto* p = static_cast<Predictor*>(pred);
  GIL gil;

  // feed: list of (name, dtype, shape-tuple, bytes)
  PyObject* feed = PyList_New(n_inputs);
  if (feed == nullptr) return fail(kPD_UNDEFINED_ERROR, py_exc_string());
  for (int32_t i = 0; i < n_inputs; i++) {
    const paddle_tensor& t = inputs[i];
    if (t.ndim < 0 || t.ndim > PD_MAX_NDIM) {
      Py_DECREF(feed);
      return fail(kPD_OUT_OF_RANGE, "tensor ndim out of range");
    }
    size_t elems = 1;
    PyObject* shape = PyTuple_New(t.ndim);
    for (int32_t d = 0; d < t.ndim; d++) {
      elems *= static_cast<size_t>(t.shape[d]);
      PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(t.shape[d]));
    }
    size_t nbytes = elems * dtype_size(t.dtype);
    PyObject* bytes = PyBytes_FromStringAndSize(
        static_cast<const char*>(t.data), static_cast<Py_ssize_t>(nbytes));
    PyObject* item = Py_BuildValue("(siNN)", input_names[i],
                                   static_cast<int>(t.dtype), shape, bytes);
    if (item == nullptr) {
      Py_DECREF(feed);
      return fail(kPD_UNDEFINED_ERROR, py_exc_string());
    }
    PyList_SET_ITEM(feed, i, item);
  }

  PyObject* result =
      PyObject_CallMethod(p->bridge, "run", "OO", p->py_pred, feed);
  Py_DECREF(feed);
  if (result == nullptr)
    return fail(kPD_UNDEFINED_ERROR, "run failed: " + py_exc_string());

  // result: list of (dtype, shape-tuple, bytes) — copy out, then the
  // Python objects can go.
  p->bufs.clear();
  p->tensors.clear();
  Py_ssize_t n = PyList_Size(result);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* item = PyList_GetItem(result, i);  // borrowed
    int dtype = 0;
    PyObject* shape = nullptr;
    PyObject* bytes = nullptr;
    if (!PyArg_ParseTuple(item, "iOO", &dtype, &shape, &bytes)) {
      Py_DECREF(result);
      return fail(kPD_UNDEFINED_ERROR, py_exc_string());
    }
    paddle_tensor t;
    std::memset(&t, 0, sizeof(t));
    t.dtype = static_cast<paddle_dtype>(dtype);
    t.ndim = static_cast<int32_t>(PyTuple_Size(shape));
    if (t.ndim > PD_MAX_NDIM) {
      Py_DECREF(result);
      return fail(kPD_OUT_OF_RANGE, "output ndim > PD_MAX_NDIM");
    }
    for (int32_t d = 0; d < t.ndim; d++)
      t.shape[d] = PyLong_AsLongLong(PyTuple_GetItem(shape, d));
    char* data = nullptr;
    Py_ssize_t nbytes = 0;
    if (PyBytes_AsStringAndSize(bytes, &data, &nbytes) != 0) {
      Py_DECREF(result);
      return fail(kPD_UNDEFINED_ERROR, py_exc_string());
    }
    p->bufs.emplace_back(data, data + nbytes);
    t.data = p->bufs.back().data();
    p->tensors.push_back(t);
  }
  Py_DECREF(result);
  return kPD_NO_ERROR;
}

paddle_error paddle_predictor_output_count(paddle_predictor pred,
                                           int32_t* count) {
  if (pred == nullptr || count == nullptr)
    return fail(kPD_NULLPTR, "predictor/count is NULL");
  auto* p = static_cast<Predictor*>(pred);
  *count = static_cast<int32_t>(p->tensors.size());
  return kPD_NO_ERROR;
}

paddle_error paddle_predictor_output(paddle_predictor pred, int32_t idx,
                                     paddle_tensor* out) {
  if (pred == nullptr || out == nullptr)
    return fail(kPD_NULLPTR, "predictor/out is NULL");
  auto* p = static_cast<Predictor*>(pred);
  if (idx < 0 || static_cast<size_t>(idx) >= p->tensors.size())
    return fail(kPD_OUT_OF_RANGE, "output index out of range");
  *out = p->tensors[idx];
  return kPD_NO_ERROR;
}

paddle_error paddle_predictor_destroy(paddle_predictor pred) {
  if (pred == nullptr) return fail(kPD_NULLPTR, "predictor is NULL");
  auto* p = static_cast<Predictor*>(pred);
  {
    GIL gil;
    Py_XDECREF(p->py_pred);
    Py_XDECREF(p->bridge);
  }
  delete p;
  return kPD_NO_ERROR;
}

const char* paddle_last_error_message(void) { return g_last_error.c_str(); }

const char* paddle_error_string(paddle_error err) {
  switch (err) {
    case kPD_NO_ERROR: return "no error";
    case kPD_NULLPTR: return "null pointer";
    case kPD_OUT_OF_RANGE: return "out of range";
    case kPD_PROTOBUF_ERROR: return "model load error";
    case kPD_NOT_SUPPORTED: return "not supported";
    case kPD_UNDEFINED_ERROR: return "undefined error";
  }
  return "unknown";
}

}  // extern "C"
