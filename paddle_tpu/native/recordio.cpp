// Native data pipeline: recordio file format + shuffling prefetch reader.
//
// Reference analog: the reference's C++ DataProvider/recordio stack
// (python/paddle/v2/reader + paddle/fluid recordio readers) feeds the
// trainer from worker threads. Same role here: a background std::thread
// decodes records into a bounded ring with reservoir-style shuffling so
// the Python feed loop (and the TPU h2d stage behind it) never stalls on
// disk I/O. Exposed through a plain C ABI for ctypes (no pybind11 in the
// image — see paddle_tpu/native/__init__.py).
//
// File format (little-endian):
//   magic "PTRC" u32 | then per record: u32 len | u32 crc32(payload) | bytes
//
// Build: g++ -O2 -shared -fPIC -std=c++17 recordio.cpp -o librecordio.so

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x43525450;  // "PTRC"

uint32_t crc32(const uint8_t* data, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = c & 1 ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Writer {
  FILE* f;
};

struct Record {
  std::vector<uint8_t> data;
};

// Bounded ring with background producer; optional shuffle pool.
struct Reader {
  std::vector<std::string> paths;
  size_t shuffle_buf;
  uint64_t seed;
  size_t capacity;

  std::thread worker;
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  std::deque<Record> ring;
  bool done = false;
  bool stop = false;
  std::string error;

  Record current;

  void produce(Record&& r) {
    std::unique_lock<std::mutex> lk(mu);
    not_full.wait(lk, [&] { return ring.size() < capacity || stop; });
    if (stop) return;
    ring.push_back(std::move(r));
    not_empty.notify_one();
  }

  void run() {
    std::mt19937_64 rng(seed);
    std::vector<Record> pool;  // reservoir for shuffling
    bool failed = false;
    for (const auto& path : paths) {
      FILE* f = fopen(path.c_str(), "rb");
      if (!f) {
        std::lock_guard<std::mutex> lk(mu);
        error = "recordio: cannot open " + path;
        break;
      }
      uint32_t magic = 0;
      if (fread(&magic, 4, 1, f) != 1 || magic != kMagic) {
        fclose(f);
        std::lock_guard<std::mutex> lk(mu);
        error = "recordio: bad magic in " + path;
        break;
      }
      for (;;) {
        uint32_t hdr[2];
        if (fread(hdr, 4, 2, f) != 2) break;  // EOF
        Record r;
        r.data.resize(hdr[0]);
        if (fread(r.data.data(), 1, hdr[0], f) != hdr[0]) break;
        if (crc32(r.data.data(), r.data.size()) != hdr[1]) {
          {
            std::lock_guard<std::mutex> lk(mu);
            error = "recordio: crc mismatch in " + path;
          }
          failed = true;  // stop reading, but still drain the pool below
          break;
        }
        if (shuffle_buf > 1) {
          if (pool.size() < shuffle_buf) {
            pool.push_back(std::move(r));
          } else {
            size_t j = rng() % pool.size();
            std::swap(pool[j], r);
            produce(std::move(r));
          }
        } else {
          produce(std::move(r));
        }
        {
          std::lock_guard<std::mutex> lk(mu);
          if (stop) failed = true;
        }
        if (failed) break;
      }
      fclose(f);
      if (failed) break;
    }
    // drain shuffle pool in random order
    {
      std::mt19937_64 rng2(seed ^ 0x9E3779B97F4A7C15ull);
      for (size_t i = pool.size(); i > 1; i--)
        std::swap(pool[i - 1], pool[rng2() % i]);
    }
    for (auto& r : pool) {
      produce(std::move(r));
      std::lock_guard<std::mutex> lk(mu);
      if (stop) break;
    }
    std::lock_guard<std::mutex> lk(mu);
    done = true;
    not_empty.notify_all();
  }
};

}  // namespace

extern "C" {

void* recordio_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  uint32_t magic = kMagic;
  fwrite(&magic, 4, 1, f);
  return new Writer{f};
}

int recordio_writer_write(void* w, const uint8_t* data, uint32_t len) {
  auto* writer = static_cast<Writer*>(w);
  uint32_t hdr[2] = {len, crc32(data, len)};
  if (fwrite(hdr, 4, 2, writer->f) != 2) return -1;
  if (fwrite(data, 1, len, writer->f) != len) return -1;
  return 0;
}

void recordio_writer_close(void* w) {
  auto* writer = static_cast<Writer*>(w);
  fclose(writer->f);
  delete writer;
}

// paths: '\n'-joined file list. shuffle_buf<=1 disables shuffling.
void* recordio_reader_open(const char* paths, uint64_t shuffle_buf,
                           uint64_t seed, uint64_t prefetch_capacity) {
  auto* r = new Reader();
  const char* p = paths;
  while (*p) {
    const char* nl = strchr(p, '\n');
    if (!nl) { r->paths.emplace_back(p); break; }
    r->paths.emplace_back(p, nl - p);
    p = nl + 1;
  }
  r->shuffle_buf = shuffle_buf;
  r->seed = seed;
  r->capacity = prefetch_capacity ? prefetch_capacity : 256;
  r->worker = std::thread([r] { r->run(); });
  return r;
}

// Returns length of next record (0 = end of data, -1 = error).
// The record stays owned by the reader until the next call.
int64_t recordio_reader_next(void* h, const uint8_t** out) {
  auto* r = static_cast<Reader*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  r->not_empty.wait(lk, [&] { return !r->ring.empty() || r->done; });
  // Drain buffered records first: a crc failure at record N must not
  // discard the valid records 0..N-1 already sitting in the ring.
  if (r->ring.empty()) return r->error.empty() ? 0 : -1;
  r->current = std::move(r->ring.front());
  r->ring.pop_front();
  r->not_full.notify_one();
  *out = r->current.data.data();
  return static_cast<int64_t>(r->current.data.size());
}

const char* recordio_reader_error(void* h) {
  auto* r = static_cast<Reader*>(h);
  std::lock_guard<std::mutex> lk(r->mu);
  return r->error.c_str();
}

// Signal shutdown WITHOUT freeing: wakes both the decode worker and any
// thread blocked in recordio_reader_next (the worker winds down and
// sets done). For callers whose own threads hold the handle
// (pipeline.cpp): cancel, join those threads, then close.
void recordio_reader_cancel(void* h) {
  auto* r = static_cast<Reader*>(h);
  std::lock_guard<std::mutex> lk(r->mu);
  r->stop = true;
  r->not_full.notify_all();
  r->not_empty.notify_all();
}

void recordio_reader_close(void* h) {
  auto* r = static_cast<Reader*>(h);
  recordio_reader_cancel(h);
  if (r->worker.joinable()) r->worker.join();
  delete r;
}

}  // extern "C"
