// Host staging ring: page-aligned, reusable feed buffers.
//
// Reference analog: paddle/fluid/memory pinned-host allocations + the
// DataProvider double buffer — batches are assembled into page-locked
// memory so the device DMA engine never waits on pageable copies. The
// TPU-native role (reader/staging.py): a producer thread packs `steps`
// batches contiguously into one aligned superbatch buffer while the
// previous window trains; the consumer hands the buffer zero-copy
// (np.frombuffer) to ONE jax.device_put per Executor.run_steps window.
// Page alignment keeps the h2d path on the fast DMA route; buffer reuse
// means steady-state feeding allocates nothing.
//
// States per slot: FREE -> (producer) FILLING -> READY -> (consumer)
// CONSUMING -> FREE. Plain C ABI for ctypes (no pybind11 in the image).
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -pthread staging.cpp -o libstaging.so

#include <cstdint>
#include <cstdlib>
#include <condition_variable>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

constexpr size_t kAlign = 4096;  // page alignment for the DMA path

struct Ring {
  struct Slot {
    uint8_t* data = nullptr;
    uint64_t len = 0;        // committed bytes
    int state = 0;           // 0 FREE, 1 FILLING, 2 READY, 3 CONSUMING
  };
  std::vector<Slot> slots;
  uint64_t capacity = 0;
  size_t produce_idx = 0;    // next slot to hand to the producer
  size_t consume_idx = 0;    // next slot to hand to the consumer
  bool closed = false;
  std::mutex mu;
  std::condition_variable cv;

  ~Ring() {
    for (auto& s : slots) std::free(s.data);
  }
};

}  // namespace

extern "C" {

// Ring of n_buffers aligned buffers of buf_bytes each. Returns nullptr
// on allocation failure.
void* staging_open(uint64_t buf_bytes, int n_buffers) {
  if (buf_bytes == 0 || n_buffers < 2) return nullptr;
  auto* r = new Ring();
  r->capacity = buf_bytes;
  r->slots.resize(n_buffers);
  uint64_t rounded = (buf_bytes + kAlign - 1) / kAlign * kAlign;
  for (auto& s : r->slots) {
    s.data = static_cast<uint8_t*>(std::aligned_alloc(kAlign, rounded));
    if (!s.data) {
      delete r;
      return nullptr;
    }
  }
  return r;
}

uint64_t staging_capacity(void* h) {
  return static_cast<Ring*>(h)->capacity;
}

// Producer: block until a FREE slot is available, return its buffer.
// Returns nullptr if the ring was closed.
uint8_t* staging_acquire_fill(void* h) {
  auto* r = static_cast<Ring*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  auto& s = r->slots[r->produce_idx];
  r->cv.wait(lk, [&] { return r->closed || s.state == 0; });
  if (r->closed) return nullptr;
  s.state = 1;
  return s.data;
}

// Producer: mark the slot acquired by staging_acquire_fill as READY with
// `len` valid bytes. Returns 0, or -1 on misuse (no slot being filled /
// len over capacity).
int staging_commit(void* h, uint64_t len) {
  auto* r = static_cast<Ring*>(h);
  std::lock_guard<std::mutex> lk(r->mu);
  auto& s = r->slots[r->produce_idx];
  if (s.state != 1 || len > r->capacity) return -1;
  s.len = len;
  s.state = 2;
  r->produce_idx = (r->produce_idx + 1) % r->slots.size();
  r->cv.notify_all();
  return 0;
}

// Consumer: block until a READY slot exists; returns its buffer and
// writes the committed length. nullptr when closed and drained.
const uint8_t* staging_acquire_read(void* h, uint64_t* out_len) {
  auto* r = static_cast<Ring*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  auto& s = r->slots[r->consume_idx];
  r->cv.wait(lk, [&] { return r->closed || s.state == 2; });
  if (s.state != 2) return nullptr;  // closed with nothing staged
  s.state = 3;
  *out_len = s.len;
  return s.data;
}

// Consumer: return the slot from staging_acquire_read to the FREE pool.
int staging_release(void* h) {
  auto* r = static_cast<Ring*>(h);
  std::lock_guard<std::mutex> lk(r->mu);
  auto& s = r->slots[r->consume_idx];
  if (s.state != 3) return -1;
  s.state = 0;
  r->consume_idx = (r->consume_idx + 1) % r->slots.size();
  r->cv.notify_all();
  return 0;
}

// Unblock all waiters; slots already READY can still be drained.
void staging_close_ring(void* h) {
  auto* r = static_cast<Ring*>(h);
  std::lock_guard<std::mutex> lk(r->mu);
  r->closed = true;
  r->cv.notify_all();
}

void staging_free(void* h) {
  delete static_cast<Ring*>(h);
}

}  // extern "C"
