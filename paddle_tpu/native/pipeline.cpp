// C++-to-C++ feed path: recordio reader -> host staging ring with no
// Python in the per-record loop.
//
// Reference analog: the reference's C++ DataProvider hands decoded
// batches straight to the trainer thread; here a pump thread drains the
// recordio reader (its own decode/shuffle thread, recordio.cpp) and
// packs fixed-size example records contiguously into page-aligned
// superbatch windows (staging.cpp). Python touches ONE buffer per
// window: np.frombuffer with a structured dtype splits it into feeds
// (reader/recordio.py recordio_superbatch).
//
// Records must all be exactly record_bytes long (one serialized example
// of fixed-shape fields) — variable-length records are a schema error
// surfaced through pipeline_error.
//
// Build: g++ -O2 -shared -fPIC -std=c++17 -pthread pipeline.cpp

#include "recordio.cpp"
#include "staging.cpp"

#include <atomic>

namespace {

struct Pipeline {
  void* ring = nullptr;      // staging Ring
  void* reader = nullptr;    // recordio Reader
  uint64_t record_bytes = 0;
  uint64_t per_window = 0;
  std::thread pump;
  std::mutex err_mu;
  std::string error;

  void set_error(const std::string& e) {
    std::lock_guard<std::mutex> lk(err_mu);
    if (error.empty()) error = e;
  }

  void run() {
    for (;;) {
      uint8_t* buf = staging_acquire_fill(ring);
      if (!buf) return;  // consumer closed the ring
      uint64_t filled = 0;
      while (filled < per_window) {
        const uint8_t* rec = nullptr;
        int64_t n = recordio_reader_next(reader, &rec);
        if (n <= 0) {
          if (n < 0) set_error(recordio_reader_error(reader));
          staging_close_ring(ring);  // EOF/error: drop partial window
          return;
        }
        if (static_cast<uint64_t>(n) != record_bytes) {
          char msg[128];
          snprintf(msg, sizeof msg,
                   "record length %lld != schema record_bytes %llu",
                   static_cast<long long>(n),
                   static_cast<unsigned long long>(record_bytes));
          set_error(msg);
          staging_close_ring(ring);
          return;
        }
        memcpy(buf + filled * record_bytes, rec, record_bytes);
        filled++;
      }
      if (staging_commit(ring, per_window * record_bytes) != 0) {
        set_error("staging_commit failed");
        staging_close_ring(ring);
        return;
      }
    }
  }
};

}  // namespace

extern "C" {

// paths: '\n'-joined recordio files; records_per_window = steps * batch.
void* pipeline_start(const char* paths, uint64_t shuffle_buf,
                     uint64_t seed, uint64_t record_bytes,
                     uint64_t records_per_window, int n_buffers) {
  if (!record_bytes || !records_per_window) return nullptr;
  auto* p = new Pipeline();
  p->record_bytes = record_bytes;
  p->per_window = records_per_window;
  p->ring = staging_open(record_bytes * records_per_window,
                         n_buffers < 2 ? 3 : n_buffers);
  if (!p->ring) {
    delete p;
    return nullptr;
  }
  p->reader = recordio_reader_open(paths, shuffle_buf, seed, 256);
  p->pump = std::thread([p] { p->run(); });
  return p;
}

// Blocks for the next full window; returns nullptr at end of stream
// (check pipeline_error to distinguish EOF from failure). The window
// stays valid until pipeline_release.
const uint8_t* pipeline_next_window(void* h, uint64_t* out_len) {
  auto* p = static_cast<Pipeline*>(h);
  return staging_acquire_read(p->ring, out_len);
}

int pipeline_release(void* h) {
  auto* p = static_cast<Pipeline*>(h);
  return staging_release(p->ring);
}

const char* pipeline_error(void* h) {
  auto* p = static_cast<Pipeline*>(h);
  std::lock_guard<std::mutex> lk(p->err_mu);
  return p->error.c_str();
}

void pipeline_stop(void* h) {
  auto* p = static_cast<Pipeline*>(h);
  // Stop order matters: cancel the reader WITHOUT deleting it (the
  // pump may be inside recordio_reader_next), wake any acquire_fill
  // wait, join the pump, and only then tear the pieces down.
  recordio_reader_cancel(p->reader);
  staging_close_ring(p->ring);
  if (p->pump.joinable()) p->pump.join();
  recordio_reader_close(p->reader);
  staging_free(p->ring);
  delete p;
}

}  // extern "C"
