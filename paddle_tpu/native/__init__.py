"""Native (C++) runtime pieces, bound via ctypes.

Reference analog: paddle/fluid/pybind + the C++ data pipeline. pybind11
is not available in this image, so the shared library exposes a plain C
ABI and is compiled on first use with g++ (cached next to the sources).
"""

import ctypes
import hashlib
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB = None


def build_native(name, extra_flags=(), includes=()):
    """Compile paddle_tpu/native/<name>.cpp into a .so cached by source
    content hash — a stale or foreign binary can never be loaded (no
    prebuilt .so ships in the repo; everything is built from source).
    `includes` lists sources the .cpp #includes — they enter the digest
    so the cache invalidates when any part of the closure changes."""
    src = os.path.join(_HERE, name + '.cpp')
    hasher = hashlib.sha256()
    for piece in (name + '.cpp',) + tuple(includes):
        with open(os.path.join(_HERE, piece), 'rb') as f:
            hasher.update(f.read())
    hasher.update(' '.join(extra_flags).encode())
    digest = hasher.hexdigest()[:12]
    out = os.path.join(_HERE, 'lib%s-%s.so' % (name, digest))
    if os.path.exists(out):
        return out
    # Per-process tmp name + atomic rename: concurrent builders (e.g.
    # pytest-xdist workers) each produce a complete .so and the last
    # rename wins — a half-written file is never visible under `out`.
    tmp = '%s.tmp.%d' % (out, os.getpid())
    cmd = ['g++', '-O2', '-shared', '-fPIC', '-std=c++17', '-pthread',
           src, '-o', tmp] + list(extra_flags)
    subprocess.run(cmd, check=True, capture_output=True)
    for stale in os.listdir(_HERE):  # drop builds of older source revisions
        if stale.startswith('lib%s-' % name) and stale.endswith('.so'):
            try:
                os.unlink(os.path.join(_HERE, stale))
            except OSError:
                pass  # another process already removed it
    os.replace(tmp, out)
    return out


def _build_lib():
    return build_native('recordio')


_STAGING = None


def load_staging():
    """Compile (if needed) and load the host staging ring
    (staging.cpp); thread-safe."""
    global _STAGING
    with _LOCK:
        if _STAGING is not None:
            return _STAGING
        lib = ctypes.CDLL(build_native('staging'))
        lib.staging_open.restype = ctypes.c_void_p
        lib.staging_open.argtypes = [ctypes.c_uint64, ctypes.c_int]
        lib.staging_capacity.restype = ctypes.c_uint64
        lib.staging_capacity.argtypes = [ctypes.c_void_p]
        lib.staging_acquire_fill.restype = ctypes.c_void_p
        lib.staging_acquire_fill.argtypes = [ctypes.c_void_p]
        lib.staging_commit.restype = ctypes.c_int
        lib.staging_commit.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.staging_acquire_read.restype = ctypes.c_void_p
        lib.staging_acquire_read.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
        lib.staging_release.restype = ctypes.c_int
        lib.staging_release.argtypes = [ctypes.c_void_p]
        lib.staging_close_ring.argtypes = [ctypes.c_void_p]
        lib.staging_free.argtypes = [ctypes.c_void_p]
        _STAGING = lib
        return lib


_PIPELINE = None


def load_pipeline():
    """Compile (if needed) and load the C++-to-C++ feed path
    (pipeline.cpp: recordio reader -> staging ring); thread-safe."""
    global _PIPELINE
    with _LOCK:
        if _PIPELINE is not None:
            return _PIPELINE
        lib = ctypes.CDLL(build_native(
            'pipeline', includes=('recordio.cpp', 'staging.cpp')))
        lib.pipeline_start.restype = ctypes.c_void_p
        lib.pipeline_start.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int]
        lib.pipeline_next_window.restype = ctypes.c_void_p
        lib.pipeline_next_window.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
        lib.pipeline_release.restype = ctypes.c_int
        lib.pipeline_release.argtypes = [ctypes.c_void_p]
        lib.pipeline_error.restype = ctypes.c_char_p
        lib.pipeline_error.argtypes = [ctypes.c_void_p]
        lib.pipeline_stop.argtypes = [ctypes.c_void_p]
        _PIPELINE = lib
        return lib


def python_embed_flags():
    """g++ flags to embed the CPython interpreter (for capi.cpp)."""
    out = subprocess.run(
        ['python3-config', '--includes', '--ldflags', '--embed'],
        check=True, capture_output=True, text=True)
    return out.stdout.split()


def build_capi():
    """Build the inference C ABI library (capi.h / capi.cpp)."""
    return build_native('capi', tuple(python_embed_flags()))


def load_library():
    """Compile (if needed) and load the native library; thread-safe."""
    global _LIB
    with _LOCK:
        if _LIB is not None:
            return _LIB
        lib = ctypes.CDLL(_build_lib())
        lib.recordio_writer_open.restype = ctypes.c_void_p
        lib.recordio_writer_open.argtypes = [ctypes.c_char_p]
        lib.recordio_writer_write.restype = ctypes.c_int
        lib.recordio_writer_write.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8),
            ctypes.c_uint32]
        lib.recordio_writer_close.argtypes = [ctypes.c_void_p]
        lib.recordio_reader_open.restype = ctypes.c_void_p
        lib.recordio_reader_open.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64]
        lib.recordio_reader_next.restype = ctypes.c_int64
        lib.recordio_reader_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8))]
        lib.recordio_reader_error.restype = ctypes.c_char_p
        lib.recordio_reader_error.argtypes = [ctypes.c_void_p]
        lib.recordio_reader_close.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return lib
