"""v2 training-curve plotter (reference: python/paddle/v2/plot/plot.py
Ploter). Collects (step, value) series; renders with matplotlib when
available, else prints — same DISABLE_PLOT contract as the reference."""

import os

__all__ = ['Ploter', 'PlotData']


class PlotData(object):
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter(object):
    def __init__(self, *titles):
        self.__args__ = titles
        self.__plot_data__ = {t: PlotData() for t in titles}

    def _disabled(self):
        if os.environ.get('DISABLE_PLOT') == 'True':
            return True
        try:
            import matplotlib  # noqa: F401
            return False
        except ImportError:
            return True

    def append(self, title, step, value):
        self.__plot_data__[title].append(step, float(value))

    def plot(self, path=None):
        if self._disabled():
            for title, d in self.__plot_data__.items():
                if d.step:
                    print('%s step %s: %.6f' % (title, d.step[-1],
                                                d.value[-1]))
            return
        import matplotlib
        matplotlib.use('Agg')
        import matplotlib.pyplot as plt
        plt.figure()
        for title, d in self.__plot_data__.items():
            plt.plot(d.step, d.value, label=title)
        plt.legend()
        if path is not None:
            plt.savefig(path)
        plt.close()

    def reset(self):
        for d in self.__plot_data__.values():
            d.reset()
