"""v2 event-driven trainer (reference: python/paddle/v2/trainer.py:37-249
SGD.train/test with BeginPass/EndIteration events over a reader).

The reference forwards/backwards through the C++ GradientMachine per
batch; here SGD.train compiles the whole (cost, update) program once via
the fluid Executor and the loop is pure dispatch — events and feeding
keep the exact reference contract, including `feeding` as a name->tuple
-position map."""

import numpy as np

from . import event as v2_event
from ..core.executor import Executor
from ..core.place import TPUPlace
from ..core.program import default_main_program, default_startup_program
from ..parallel.multihost import shard_reader

__all__ = ['SGD']


def _build_feed(data_batch, feeding, feed_names, program=None):
    """data_batch: list of sample tuples (or dicts). feeding maps data
    layer name -> position in the tuple. Delegates to the fluid
    DataFeeder (ONE feeder implementation): padding + '<name>_len'
    emission for sequence slots, sparse densification, dtype casts,
    label [B] -> [B, 1] alignment."""
    if isinstance(data_batch, dict):
        return data_batch
    from ..data_feeder import DataFeeder
    if feeding is None:
        feeding = {name: i for i, name in enumerate(feed_names)}
    ordered = sorted(feeding.items(), key=lambda kv: kv[1])
    rows = [tuple(sample[pos] for _, pos in ordered)
            for sample in data_batch]
    feeder = DataFeeder([name for name, _ in ordered], program=program)
    return feeder.feed(rows)


def _user_feed_names(program):
    """Data vars a v2 user feeds, in declaration order — excluding the
    auto-created '<name>_len' companions (DataFeeder emits those)."""
    block = program.global_block()
    names = [v.name for v in block.vars.values()
             if getattr(v, 'is_data', False)]
    return [n for n in names
            if not (n.endswith('_len') and n[:-4] in names)]


class SGD(object):
    """paddle.v2.trainer.SGD(cost, parameters, update_equation)."""

    def __init__(self, cost, parameters, update_equation,
                 extra_layers=None, is_local=True, place=None):
        self.cost = cost
        self.parameters = parameters
        self.program = default_main_program()
        self.startup = default_startup_program()
        update_equation.minimize(cost)
        self.exe = Executor(place if place is not None else TPUPlace(0))
        # parameters.create() already ran the startup for the model params
        # (reference order: params first, update_equation later); run ONLY
        # the init ops the optimizer just appended (accumulators, lr), so
        # user-set / trained parameter values survive.
        self._init_missing_startup_vars()
        self._feed_names = _user_feed_names(self.program)
        self._extra = extra_layers or []

    def _init_missing_startup_vars(self):
        from ..core.scope import global_scope
        scope = global_scope()
        pending = self.startup.clone()
        block = pending.global_block()
        block.ops = [op for op in block.ops
                     if any(scope.find(n) is None
                            for n in op.output_names())]
        if block.ops:
            self.exe.run(pending)

    def train(self, reader, num_passes=1, event_handler=None, feeding=None):
        event_handler = event_handler or (lambda e: None)
        reader = shard_reader(reader)
        fetch = [self.cost] + list(self._extra)
        for pass_id in range(num_passes):
            event_handler(v2_event.BeginPass(pass_id))
            for batch_id, data in enumerate(reader()):
                event_handler(v2_event.BeginIteration(pass_id, batch_id))
                feed = _build_feed(data, feeding, self._feed_names,
                                   program=self.program)
                outs = self.exe.run(program=self.program, feed=feed,
                                    fetch_list=fetch)
                cost = float(np.asarray(outs[0]).reshape(()))
                event_handler(v2_event.EndIteration(
                    pass_id, batch_id, cost,
                    metrics={getattr(v, 'name', str(i)):
                             np.asarray(outs[1 + i])
                             for i, v in enumerate(self._extra)}))
            event_handler(v2_event.EndPass(pass_id))

    def test(self, reader, feeding=None):
        inference = self.program.clone(for_test=True)
        costs, n = 0.0, 0
        for data in reader():
            feed = _build_feed(data, feeding, self._feed_names,
                               program=self.program)
            out = self.exe.run(program=inference, feed=feed,
                               fetch_list=[self.cost])
            bs = len(data) if not isinstance(data, dict) else 1
            costs += float(np.asarray(out[0]).reshape(())) * bs
            n += bs
        return v2_event.TestResult(cost=costs / max(n, 1))

    def save_parameter_to_tar(self, f):
        self.parameters.to_tar(f)
