"""`paddle.v2.dataset` import-path alias (reference:
python/paddle/v2/dataset/__init__.py — the v2 era's home of the dataset
package before it moved to `paddle.dataset`). Both spellings resolve to
the same modules here, so `import paddle_tpu.v2.dataset.mnist` and
`from paddle_tpu.v2.dataset import imdb` work like the reference. The
alias enumerates the base package's modules at import time, so a
dataset added there is automatically importable under both paths."""

import sys
import types

from ... import dataset as _base

__all__ = []
for _name, _mod in sorted(vars(_base).items()):
    if isinstance(_mod, types.ModuleType) and \
            _mod.__name__.startswith('paddle_tpu.dataset.'):
        sys.modules[__name__ + '.' + _name] = _mod
        globals()[_name] = _mod
        __all__.append(_name)
