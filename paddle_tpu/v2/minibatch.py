"""v2 minibatch (reference: python/paddle/v2/minibatch.py)."""

__all__ = ['batch']


def batch(reader, batch_size, drop_last=True):
    from ..reader.decorator import batch as _batch
    return _batch(reader, batch_size, drop_last=drop_last)
