"""v2 minibatch (reference: python/paddle/v2/minibatch.py)."""

__all__ = ['batch']


def batch(reader, batch_size, drop_last=False):
    """Reference v2 minibatch yields the final partial batch, so the
    default here is drop_last=False (a dataset smaller than batch_size
    must not silently train zero iterations); the tail batch costs one
    extra XLA compile for its shape. Pass drop_last=True for fixed-shape
    SPMD training loops."""
    from ..reader.decorator import batch as _batch
    return _batch(reader, batch_size, drop_last=drop_last)
