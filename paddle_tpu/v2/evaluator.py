"""v2 evaluator namespace (reference: python/paddle/v2/evaluator.py,
which re-exports trainer_config_helpers' *_evaluator functions under
snake_case names).

The jit-friendly equivalents live in fluid layers/metrics; these shims
keep v2 config names importable. Evaluators that the reference computes
in-network map to in-graph metric layers; host-side ones map to the
metrics module."""

from .. import layers as _fl
from ..metrics import Auc as _AucMetric
from ..metrics import DetectionMAP as _MapMetric

__all__ = ['classification_error', 'auc', 'precision_recall',
           'detection_map', 'chunk']


def classification_error(input, label, **kwargs):
    """Error rate = 1 - accuracy (classification_error_evaluator)."""
    acc = _fl.accuracy(input=input, label=label)
    one = _fl.tensor.fill_constant(shape=[1], dtype='float32', value=1.0)
    return _fl.elementwise_sub(x=one, y=acc)


def auc(input, label, **kwargs):
    """Host-side AUC accumulator over fetched (probs, labels)."""
    return _AucMetric()


def precision_recall(input, label, class_number, **kwargs):
    """In-graph precision/recall states (precision_recall_evaluator)."""
    return _fl.precision_recall(input, label, class_number)


def detection_map(**kwargs):
    """Host-side VOC mAP accumulator (detection_map evaluator)."""
    return _MapMetric(**kwargs)


def chunk(input, label, chunk_scheme, num_chunk_types, **kwargs):
    from ..evaluator import ChunkEvaluator
    return ChunkEvaluator(chunk_scheme=chunk_scheme,
                          num_chunk_types=num_chunk_types)
