"""v2 parameter attributes (reference: python/paddle/v2/attr.py) mapped
onto fluid ParamAttr."""

from ..param_attr import ParamAttr
from ..initializer import NormalInitializer
from ..regularizer import L2Decay

__all__ = ['Param', 'ParamAttr', 'Extra', 'ExtraAttr']


def Param(name=None, initial_std=None, initial_mean=None, l2_rate=None,
          learning_rate=None, **kwargs):
    init = None
    if initial_std is not None or initial_mean is not None:
        init = NormalInitializer(loc=initial_mean or 0.0,
                                 scale=initial_std
                                 if initial_std is not None else 0.01)
    reg = L2Decay(l2_rate) if l2_rate else None
    return ParamAttr(name=name, initializer=init, regularizer=reg,
                     learning_rate=learning_rate
                     if learning_rate is not None else 1.0)


class ExtraAttr(object):
    def __init__(self, **kwargs):
        self.attrs = kwargs


Extra = ExtraAttr
