"""paddle.v2-compatible high-level API (reference: python/paddle/v2/
__init__.py — layer/data_type/activation/attr/pooling/parameters/
trainer/event/inference/minibatch/networks/optimizer/dataset/reader/
image).

The reference v2 stack compiles layer configs into a protobuf Topology
executed by the C++ GradientMachine; here every v2 call builds fluid IR
directly, so a v2 model is an ordinary Program that jits to one XLA
computation and shards over the mesh like any other.

Typical book-chapter usage works verbatim:

    import paddle_tpu.v2 as paddle
    paddle.init(use_gpu=False, trainer_count=1)
    x = paddle.layer.data(name='x',
                          type=paddle.data_type.dense_vector(13))
    y = paddle.layer.data(name='y', type=paddle.data_type.dense_vector(1))
    y_ = paddle.layer.fc(input=x, size=1,
                         act=paddle.activation.Linear())
    cost = paddle.layer.square_error_cost(input=y_, label=y)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=1e-3))
    trainer.train(reader=paddle.batch(train_reader, 32),
                  num_passes=10, event_handler=handler,
                  feeding={'x': 0, 'y': 1})
    out = paddle.infer(output_layer=y_, input=test_samples,
                       feeding={'x': 0})
"""

from . import activation  # noqa: F401
from . import attr  # noqa: F401
from . import data_type  # noqa: F401
from . import evaluator  # noqa: F401
from . import event  # noqa: F401
from . import inference  # noqa: F401
from . import layer  # noqa: F401
from . import minibatch  # noqa: F401
from . import networks  # noqa: F401
from . import optimizer  # noqa: F401
from . import parameters  # noqa: F401
from . import plot  # noqa: F401
from . import pooling  # noqa: F401
from . import trainer  # noqa: F401
from . import dataset  # noqa: F401  (v2 alias package)
from .. import image  # noqa: F401
from .. import reader  # noqa: F401
from .inference import infer  # noqa: F401
from .minibatch import batch  # noqa: F401

__all__ = ['init', 'layer', 'data_type', 'activation', 'attr', 'pooling',
           'evaluator',
           'parameters', 'trainer', 'event', 'inference', 'infer',
           'minibatch', 'batch', 'networks', 'optimizer', 'dataset',
           'reader', 'image', 'plot']


def init(use_gpu=False, trainer_count=1, **kwargs):
    """Reference paddle.v2.init parsed gflags and spawned trainers; the
    TPU runtime needs neither — kept for source compatibility. Multi-host
    setups call parallel.multihost.init_distributed instead."""
    return None
