"""v2 layer API over the fluid IR.

Reference: python/paddle/v2/layer.py:1-326 (wraps trainer_config_helpers
into declarative layer objects resolved by Topology). Here each call
builds fluid IR ops EAGERLY into the default program — the Program IS
the topology, so parse_network/Topology reduce to Program bookkeeping
and the whole v2 graph compiles to one XLA computation like any fluid
program.

Sequence inputs (seq_type=1) arrive as padded [B, T] batches (SURVEY §6
LoD stance); v2 sequence layers (embedding over a sequence, seq pooling)
operate on the padded time axis with an implicit nonzero mask.
"""

from .. import layers as _fl
from . import activation as _act_mod
from .data_type import InputType

__all__ = ['data', 'fc', 'embedding', 'img_conv', 'img_pool', 'concat',
           'dropout', 'batch_norm', 'pooling', 'classification_cost',
           'cross_entropy_cost', 'square_error_cost', 'mse_cost',
           'parse_network']


def _act_name(act):
    if act is None:
        return None
    return act.name if hasattr(act, 'name') else act


def data(name, type, height=None, width=None):
    """Input slot (v2/layer.py __data_layer__). `type` is a
    data_type.InputType; sequences get a padded time axis of unspecified
    length (fed per-batch, bucketed recompile) plus a companion
    '<name>_len' int32 vector DataFeeder emits — sequence layers mask
    pad positions through it (SURVEY §6 LoD stance)."""
    assert isinstance(type, InputType)
    shape = list(type.shape)
    if type.seq_type:
        # padded [T] leading time axis before the per-step shape; T is
        # set by the fed batch (executor recompiles per bucket).
        shape = [-1] + (shape if shape != [1] else [])
        var = _fl.data(name=name, shape=shape, dtype=type.dtype,
                       lod_level=1)
        var._v2_len_var = _fl.data(name=name + '_len', shape=[],
                                   dtype='int32')
    else:
        var = _fl.data(name=name, shape=shape, dtype=type.dtype)
    var._v2_type = type
    return var


def _propagate_len(src, out):
    """Sequence-preserving layers carry the length var to their output
    so downstream sequence ops mask pad positions."""
    lv = getattr(src, '_v2_len_var', None)
    if lv is not None:
        out._v2_len_var = lv
    return out


def fc(input, size, act=None, param_attr=None, bias_attr=None, name=None,
       **kwargs):
    # fluid fc flattens trailing dims itself (num_flatten_dims=1), which
    # matches v2 fc over conv feature maps.
    return _fl.fc(input=input, size=size, act=_act_name(act),
                  param_attr=param_attr,
                  bias_attr=bias_attr if bias_attr is not None else None,
                  name=name)


def embedding(input, size, param_attr=None, is_sparse=False,
              vocab_size=None, **kwargs):
    """Vocab comes from the data layer's integer_value range, like the
    reference's embedding over an id slot."""
    t = getattr(input, '_v2_type', None)
    vocab = vocab_size if vocab_size is not None else \
        (t.dim if t is not None else None)
    if vocab is None:
        raise ValueError('embedding needs an input built by v2.layer.data '
                         'with an integer_value type (or pass vocab_size=)')
    return _propagate_len(input, _fl.embedding(
        input=input, size=[vocab, size], is_sparse=is_sparse,
        param_attr=param_attr))


def img_conv(input, filter_size, num_filters, num_channels=None, stride=1,
             padding=0, act=None, param_attr=None, bias_attr=None,
             **kwargs):
    return _fl.conv2d(input=input, num_filters=num_filters,
                      filter_size=filter_size, stride=stride,
                      padding=padding, act=_act_name(act),
                      param_attr=param_attr, bias_attr=bias_attr)


def img_pool(input, pool_size, stride=1, padding=0, pool_type=None,
             **kwargs):
    name = getattr(pool_type, 'name', pool_type) or 'max'
    return _fl.pool2d(input=input, pool_size=pool_size, pool_stride=stride,
                      pool_padding=padding, pool_type=name)


def concat(input, name=None, **kwargs):
    return _fl.concat(input=list(input), axis=-1)


def dropout(input, dropout_rate, **kwargs):
    return _propagate_len(input, _fl.dropout(input,
                                             dropout_prob=dropout_rate))


def batch_norm(input, act=None, **kwargs):
    return _fl.batch_norm(input=input, act=_act_name(act))


def pooling(input, pooling_type=None, **kwargs):
    """Sequence pooling over the padded time axis; pad positions are
    masked through the data layer's '_len' var carried by
    _propagate_len (avg divides by TRUE length, last takes the last
    real step)."""
    name = getattr(pooling_type, 'name', pooling_type) or 'sum'
    from ..layers import sequence
    return sequence.sequence_pool(input=input, pool_type=name,
                                  length=getattr(input, '_v2_len_var',
                                                 None))


def classification_cost(input, label, name=None, **kwargs):
    """input must be class probabilities (fc with Softmax activation),
    like the reference's classification_cost over a softmax output."""
    return _fl.mean(_fl.cross_entropy(input=input, label=label))


cross_entropy_cost = classification_cost


def square_error_cost(input, label, **kwargs):
    return _fl.mean(_fl.square_error_cost(input=input, label=label))


mse_cost = square_error_cost


def parse_network(*outputs):
    """The Program pruned to `outputs` (reference parse_network returns
    the sub-model protobuf; here the pruned Program plays that role)."""
    from ..core.program import default_main_program
    return default_main_program().prune(list(outputs))


def __getattr__(name):
    """The reference v2 layer module was a re-export shell over
    trainer_config_helpers (v2/layer.py:15), stripping the `_layer`
    suffix from names (v1 `fc_layer` became v2 `layer.fc`). Names not
    defined above resolve the same way against the r5-complete shim —
    so v2 configs reach recurrent_group / memory / beam_search /
    lstmemory / crf and the rest of the v1 vocabulary."""
    from .. import trainer_config_helpers as _tch
    for candidate in (name, name + '_layer'):
        obj = getattr(_tch, candidate, None)
        if obj is not None:
            return obj
    raise AttributeError(name)
