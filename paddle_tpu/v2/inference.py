"""v2 inference (reference: python/paddle/v2/inference.py infer())."""

import numpy as np

from ..core.executor import Executor
from ..core.place import TPUPlace
from ..core.program import default_main_program
from .trainer import _build_feed, _user_feed_names

__all__ = ['infer', 'Inference']


class Inference(object):
    def __init__(self, output_layer, parameters=None, place=None):
        self.outputs = output_layer if isinstance(output_layer,
                                                  (list, tuple)) \
            else [output_layer]
        # Prune ONCE: repeated infer() calls hit the Executor's compile
        # cache (keyed on program identity) instead of re-jitting.
        self.program = default_main_program().clone(
            for_test=True).prune(self.outputs)
        self.exe = Executor(place if place is not None else TPUPlace(0))
        # Feed names come from the PRUNED graph, so slots the outputs
        # don't need (e.g. the label layer) aren't demanded of `input`.
        from ..core.executor import _op_reads
        consumed = set()
        for op in self.program.global_block().ops:
            consumed.update(_op_reads(op, self.program))
        self._feed_names = [n for n in _user_feed_names(self.program)
                            if n in consumed]

    def infer(self, input, feeding=None, field='value'):
        feed = _build_feed(input, feeding, self._feed_names,
                           program=self.program)
        outs = self.exe.run(program=self.program, feed=feed,
                            fetch_list=self.outputs)
        outs = [np.asarray(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs


def infer(output_layer, parameters=None, input=None, feeding=None,
          field='value'):
    return Inference(output_layer, parameters).infer(input, feeding, field)
