"""v2 inference (reference: python/paddle/v2/inference.py infer())."""

import numpy as np

from ..core.executor import Executor
from ..core.place import TPUPlace
from ..core.program import default_main_program
from .trainer import _build_feed

__all__ = ['infer', 'Inference']


class Inference(object):
    def __init__(self, output_layer, parameters=None, place=None):
        self.outputs = output_layer if isinstance(output_layer,
                                                  (list, tuple)) \
            else [output_layer]
        self.program = default_main_program().clone(for_test=True)
        self.exe = Executor(place if place is not None else TPUPlace(0))
        self._feed_names = [v.name for v in
                            self.program.global_block().vars.values()
                            if getattr(v, 'is_data', False)]

    def infer(self, input, feeding=None, field='value'):
        feed = _build_feed(input, feeding, self._feed_names)
        # drop feeds the pruned inference graph doesn't consume (e.g.
        # the label slot)
        outs = self.exe.run(program=self.program.prune(self.outputs),
                            feed=feed, fetch_list=self.outputs)
        outs = [np.asarray(o) for o in outs]
        return outs[0] if len(outs) == 1 else outs


def infer(output_layer, parameters=None, input=None, feeding=None,
          field='value'):
    return Inference(output_layer, parameters).infer(input, feeding, field)
