"""v2 input type descriptors (reference: python/paddle/v2/data_type.py).
Each describes one slot of a training sample; layer.data turns it into a
fluid data Variable. Sequence types become padded dense batches
(SURVEY §6: LoD -> pad + mask)."""

__all__ = [
    'dense_vector', 'dense_array', 'integer_value', 'dense_vector_sequence',
    'integer_value_sequence', 'sparse_binary_vector', 'sparse_float_vector',
    'InputType',
]


class InputType(object):
    def __init__(self, dim, seq_type, dtype, shape=None, kind='dense'):
        self.dim = dim
        self.seq_type = seq_type  # 0 = no sequence, 1 = sequence
        self.dtype = dtype
        self.shape = shape if shape is not None else [dim]
        # 'dense' | 'sparse_binary' (index lists) | 'sparse_float'
        # ((index, value) pairs) — consumed by DataFeeder densification
        self.kind = kind


def dense_vector(dim, seq_type=0):
    return InputType(dim, seq_type, 'float32')


def dense_array(dim, shape, seq_type=0):
    return InputType(dim, seq_type, 'float32', shape=list(shape))


def integer_value(value_range, seq_type=0):
    return InputType(value_range, seq_type, 'int64', shape=[1])


def dense_vector_sequence(dim):
    return dense_vector(dim, seq_type=1)


def integer_value_sequence(value_range):
    return integer_value(value_range, seq_type=1)


def sparse_binary_vector(dim, seq_type=0):
    """Samples are lists of active indices (reference data_type) —
    densified to a multi-hot [dim] row at feed time; CTR-scale sparsity
    belongs in row-sharded embeddings instead."""
    return InputType(dim, seq_type, 'float32', kind='sparse_binary')


def sparse_float_vector(dim, seq_type=0):
    """Samples are (index, value) pair lists, densified at feed time."""
    return InputType(dim, seq_type, 'float32', kind='sparse_float')
