"""v2 input type descriptors (reference: python/paddle/v2/data_type.py).
Each describes one slot of a training sample; layer.data turns it into a
fluid data Variable. Sequence types become padded dense batches
(SURVEY §6: LoD -> pad + mask)."""

__all__ = [
    'dense_vector', 'dense_array', 'integer_value', 'dense_vector_sequence',
    'integer_value_sequence', 'sparse_binary_vector', 'sparse_float_vector',
    'InputType',
]


class InputType(object):
    def __init__(self, dim, seq_type, dtype, shape=None):
        self.dim = dim
        self.seq_type = seq_type  # 0 = no sequence, 1 = sequence
        self.dtype = dtype
        self.shape = shape if shape is not None else [dim]


def dense_vector(dim, seq_type=0):
    return InputType(dim, seq_type, 'float32')


def dense_array(dim, shape, seq_type=0):
    return InputType(dim, seq_type, 'float32', shape=list(shape))


def integer_value(value_range, seq_type=0):
    return InputType(value_range, seq_type, 'int64', shape=[1])


def dense_vector_sequence(dim):
    return dense_vector(dim, seq_type=1)


def integer_value_sequence(value_range):
    return integer_value(value_range, seq_type=1)


def sparse_binary_vector(dim, seq_type=0):
    # dense one/multi-hot stand-in: the TPU path has no sparse tensor
    # type; CTR-scale sparsity is handled by row-sharded embeddings.
    return InputType(dim, seq_type, 'float32')


def sparse_float_vector(dim, seq_type=0):
    return InputType(dim, seq_type, 'float32')
