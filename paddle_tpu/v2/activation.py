"""v2 activation descriptors (reference: python/paddle/v2/activation.py)."""

__all__ = ['Linear', 'Relu', 'Sigmoid', 'Tanh', 'Softmax', 'Exp', 'Log',
           'Square', 'SoftRelu', 'STanh']


class _Act(object):
    name = None

    def __repr__(self):
        return 'activation.%s' % type(self).__name__


class Linear(_Act):
    name = None


class Relu(_Act):
    name = 'relu'


class Sigmoid(_Act):
    name = 'sigmoid'


class Tanh(_Act):
    name = 'tanh'


class Softmax(_Act):
    name = 'softmax'


class Exp(_Act):
    name = 'exp'


class Log(_Act):
    name = 'log'


class Square(_Act):
    name = 'square'


class SoftRelu(_Act):
    name = 'softplus'


class STanh(_Act):
    name = 'stanh'  # 1.7159 * tanh(2x/3), reference scaled-tanh
