"""v2 optimizers (reference: python/paddle/v2/optimizer.py) — thin
construction shims over the fluid optimizer classes (the update equation
runs inside the jitted step, not a separate GradientMachine pass)."""

from .. import optimizer as _fo
from ..regularizer import L2Decay

__all__ = ['Momentum', 'Adam', 'Adamax', 'AdaGrad', 'DecayedAdaGrad',
           'AdaDelta', 'RMSProp', 'ModelAverage', 'L2Regularization']


def L2Regularization(rate):
    return L2Decay(rate)


def _reg(regularization):
    return regularization


def Momentum(momentum=None, learning_rate=1e-3, regularization=None,
             sparse=False, **kwargs):
    return _fo.Momentum(learning_rate=learning_rate,
                        momentum=momentum or 0.0,
                        regularization=_reg(regularization))


def Adam(beta1=0.9, beta2=0.999, epsilon=1e-8, learning_rate=1e-3,
         regularization=None, **kwargs):
    return _fo.Adam(learning_rate=learning_rate, beta1=beta1, beta2=beta2,
                    epsilon=epsilon, regularization=_reg(regularization))


def Adamax(beta1=0.9, beta2=0.999, learning_rate=1e-3, **kwargs):
    return _fo.Adamax(learning_rate=learning_rate, beta1=beta1,
                      beta2=beta2)


def AdaGrad(learning_rate=1e-3, regularization=None, **kwargs):
    return _fo.Adagrad(learning_rate=learning_rate,
                       regularization=_reg(regularization))


def DecayedAdaGrad(rho=0.95, epsilon=1e-6, learning_rate=1e-3, **kwargs):
    return _fo.DecayedAdagrad(learning_rate=learning_rate, decay=rho,
                              epsilon=epsilon)


def AdaDelta(rho=0.95, epsilon=1e-6, learning_rate=1e-3, **kwargs):
    return _fo.Adadelta(learning_rate=learning_rate, rho=rho,
                        epsilon=epsilon)


def RMSProp(rho=0.95, epsilon=1e-6, learning_rate=1e-3, **kwargs):
    return _fo.RMSProp(learning_rate=learning_rate, rho=rho,
                       epsilon=epsilon)


def ModelAverage(average_window, **kwargs):
    raise NotImplementedError(
        'ModelAverage is not supported; use checkpoint averaging over '
        'io.save_params snapshots instead')
