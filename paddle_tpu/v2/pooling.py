"""v2 pooling descriptors (reference: python/paddle/v2/pooling.py)."""

__all__ = ['Max', 'Avg', 'Sum', 'CudnnMax', 'CudnnAvg']


class _Pool(object):
    name = None


class Max(_Pool):
    name = 'max'


class Avg(_Pool):
    name = 'avg'


class Sum(_Pool):
    name = 'sum'


CudnnMax = Max
CudnnAvg = Avg
