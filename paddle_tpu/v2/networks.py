"""v2 networks (reference: python/paddle/v2/networks.py wraps
trainer_config_helpers.networks) — composed from v2 layers."""

from . import layer as v2_layer
from .. import nets as _nets

__all__ = ['simple_img_conv_pool', 'img_conv_group', 'sequence_conv_pool',
           'simple_gru', 'simple_lstm', 'glu', 'scaled_dot_product_attention']


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride, act=None, **kwargs):
    return _nets.simple_img_conv_pool(
        input=input, num_filters=num_filters, filter_size=filter_size,
        pool_size=pool_size, pool_stride=pool_stride,
        act=getattr(act, 'name', act))


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, pool_stride=1,
                   pool_type='max', **kwargs):
    return _nets.img_conv_group(
        input=input, conv_num_filter=conv_num_filter, pool_size=pool_size,
        conv_padding=conv_padding, conv_filter_size=conv_filter_size,
        conv_act=getattr(conv_act, 'name', conv_act),
        pool_stride=pool_stride, pool_type=getattr(pool_type, 'name',
                                                   pool_type))


def sequence_conv_pool(input, context_len, hidden_size, **kwargs):
    return _nets.sequence_conv_pool(input=input, num_filters=hidden_size,
                                    filter_size=context_len)


def simple_gru(input, size, **kwargs):
    from ..layers import rnn as _rnn
    return _rnn.simple_gru(input=input, size=size) \
        if hasattr(_rnn, 'simple_gru') else _unsupported('simple_gru')


def simple_lstm(input, size, **kwargs):
    from ..layers import rnn as _rnn
    return _rnn.simple_lstm(input=input, size=size) \
        if hasattr(_rnn, 'simple_lstm') else _unsupported('simple_lstm')


def glu(input, dim=-1, **kwargs):
    return _nets.glu(input=input, dim=dim)


def scaled_dot_product_attention(queries, keys, values, **kwargs):
    return _nets.scaled_dot_product_attention(queries, keys, values,
                                              **kwargs)


def _unsupported(name):
    raise NotImplementedError('%s: build it with fluid.layers.rnn '
                              'StaticRNN/DynamicRNN instead' % name)
