"""v2 Parameters (reference: python/paddle/v2/parameters.py:27-404).

The reference Parameters shuttles numpy arrays in/out of the C++
GradientMachine; here it is a live view over the fluid global scope —
__getitem__/__setitem__ read/write the device arrays the jitted step
trains, and to_tar/from_tar serialize them, keeping the reference's
checkpoint workflow (event handler calling parameters.to_tar) intact.
"""

import io
import pickle
import tarfile

import numpy as np

__all__ = ['Parameters', 'create']


def create(*layers):
    """Materialize all parameters reachable from the cost layer(s): runs
    the startup program (init ops) and returns the Parameters view."""
    from ..core.executor import Executor
    from ..core.place import CPUPlace
    from ..core.program import (default_main_program,
                                default_startup_program)
    Executor(CPUPlace()).run(default_startup_program())
    return Parameters(default_main_program())


class Parameters(object):
    def __init__(self, program=None):
        from ..core.program import default_main_program
        self._program = program or default_main_program()

    def names(self):
        return [p.name for p in self._program.global_block()
                .all_parameters()]

    def keys(self):
        return self.names()

    def has_key(self, key):
        return key in self.names()

    def __contains__(self, key):
        return self.has_key(key)

    def __iter__(self):
        return iter(self.names())

    def __getitem__(self, key):
        from ..core.scope import global_scope
        val = global_scope().find(key)
        if val is None:
            raise KeyError('parameter %r is not initialized' % key)
        return np.asarray(val)

    def __setitem__(self, key, value):
        from ..core.scope import global_scope
        var = self._program.global_block()._find_var_recursive(key)
        if var is None:
            raise KeyError('no parameter named %r' % key)
        arr = np.asarray(value, dtype='float32').reshape(var.shape)
        global_scope().set(key, arr)

    def get(self, key):
        return self.__getitem__(key)

    def set(self, key, value):
        self.__setitem__(key, value)

    def get_shape(self, key):
        var = self._program.global_block()._find_var_recursive(key)
        if var is None:
            raise KeyError('no parameter named %r' % key)
        return tuple(var.shape)

    # ---- serialization (reference to_tar/from_tar) ----
    def to_tar(self, f):
        with tarfile.open(fileobj=f, mode='w') as tf:
            for name in self.names():
                arr = self[name]
                buf = io.BytesIO()
                np.save(buf, arr)
                data = buf.getvalue()
                info = tarfile.TarInfo(name)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
            meta = pickle.dumps({'names': self.names()}, protocol=2)
            info = tarfile.TarInfo('__meta__')
            info.size = len(meta)
            tf.addfile(info, io.BytesIO(meta))

    @staticmethod
    def from_tar(f):
        """Returns {name: ndarray}; use init_from_tar to load into a
        live topology."""
        out = {}
        with tarfile.open(fileobj=f, mode='r') as tf:
            for m in tf.getmembers():
                if m.name == '__meta__':
                    continue
                out[m.name] = np.load(io.BytesIO(tf.extractfile(m).read()))
        return out

    def init_from_tar(self, f):
        for name, arr in Parameters.from_tar(f).items():
            if self.has_key(name):
                self[name] = arr
