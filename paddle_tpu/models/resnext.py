"""SE-ResNeXt (reference: the image-classification suite's
SE_ResNeXt50/101/152). Grouped 3x3 convs + squeeze-and-excitation blocks."""

from .. import layers


def conv_bn_layer(input, num_filters, filter_size, stride=1, groups=1,
                  act=None, is_test=False):
    conv = layers.conv2d(input=input, num_filters=num_filters,
                         filter_size=filter_size, stride=stride,
                         padding=(filter_size - 1) // 2, groups=groups,
                         act=None, bias_attr=False)
    return layers.batch_norm(input=conv, act=act, is_test=is_test)


def squeeze_excitation(input, num_channels, reduction_ratio=16):
    pool = layers.pool2d(input=input, pool_type='avg', global_pooling=True)
    squeeze = layers.fc(input=pool, size=num_channels // reduction_ratio,
                        act='relu')
    excitation = layers.fc(input=squeeze, size=num_channels, act='sigmoid')
    # scale channels: [N,C,H,W] * [N,C] broadcast on axis 0..1
    excitation = layers.reshape(x=excitation,
                                shape=[-1, num_channels, 1, 1])
    return layers.elementwise_mul(x=input, y=excitation)


def bottleneck_block(input, num_filters, stride, cardinality=32,
                     reduction_ratio=16, is_test=False):
    conv0 = conv_bn_layer(input, num_filters, 1, act='relu', is_test=is_test)
    conv1 = conv_bn_layer(conv0, num_filters, 3, stride=stride,
                          groups=cardinality, act='relu', is_test=is_test)
    conv2 = conv_bn_layer(conv1, num_filters * 2, 1, act=None,
                          is_test=is_test)
    scaled = squeeze_excitation(conv2, num_filters * 2, reduction_ratio)

    ch_in = input.shape[1]
    if ch_in != num_filters * 2 or stride != 1:
        short = conv_bn_layer(input, num_filters * 2, 1, stride=stride,
                              is_test=is_test)
    else:
        short = input
    return layers.elementwise_add(x=short, y=scaled, act='relu')


def se_resnext(input, class_dim=1000, depth=50, cardinality=32,
               reduction_ratio=16, is_test=False):
    stages = {50: [3, 4, 6, 3], 101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}[depth]
    num_filters = [128, 256, 512, 1024]
    conv = conv_bn_layer(input, 64, 7, stride=2, act='relu', is_test=is_test)
    conv = layers.pool2d(input=conv, pool_size=3, pool_stride=2,
                         pool_padding=1, pool_type='max')
    for block in range(len(stages)):
        for i in range(stages[block]):
            conv = bottleneck_block(
                conv, num_filters[block],
                stride=2 if i == 0 and block != 0 else 1,
                cardinality=cardinality, reduction_ratio=reduction_ratio,
                is_test=is_test)
    pool = layers.pool2d(input=conv, pool_type='avg', global_pooling=True)
    drop = layers.dropout(x=pool, dropout_prob=0.5, is_test=is_test)
    out = layers.fc(input=drop, size=class_dim, act='softmax')
    return out


def se_resnext_with_loss(input=None, label=None, class_dim=1000,
                         image_shape=(3, 224, 224), depth=50, is_test=False):
    if input is None:
        input = layers.data(name='image', shape=list(image_shape),
                            dtype='float32')
    if label is None:
        label = layers.data(name='label', shape=[1], dtype='int64')
    predict = se_resnext(input, class_dim=class_dim, depth=depth,
                         is_test=is_test)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return predict, avg_cost, acc
