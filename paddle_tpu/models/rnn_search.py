"""RNN encoder-decoder with attention — the machine_translation book
chapter's model (reference: the seqToseq demo / book machine_translation
chapter; v1 networks.py simple_attention + gru_group decoder).

TPU-native: the bidirectional GRU encoder is two lax.scan recurrences
(layers.dynamic_gru), and the attention decoder is a fluid DynamicRNN
whose step block — additive attention over the full encoder output,
gru_unit state update, vocab projection — compiles into ONE lax.scan
body; the encoder states enter the scan as closed-over constants
(ops/control_ops.py _scan_rnn outer_env), so the whole seq2seq trains
as a single XLA computation like every other model here.
"""

import numpy as np

from .. import layers


def encoder(src_word, src_len, src_vocab, emb_dim=64, hidden_dim=64):
    """Bi-GRU over the padded source: returns [B, Ts, 2H] states plus
    the backward direction's summary (decoder boot, per the chapter)."""
    emb = layers.embedding(input=src_word, size=[src_vocab, emb_dim])
    fwd = layers.dynamic_gru(
        input=layers.fc(input=emb, size=hidden_dim * 3, bias_attr=False,
                        num_flatten_dims=2),
        size=hidden_dim, length=src_len)
    bwd = layers.dynamic_gru(
        input=layers.fc(input=emb, size=hidden_dim * 3, bias_attr=False,
                        num_flatten_dims=2),
        size=hidden_dim, is_reverse=True, length=src_len)
    encoded = layers.concat([fwd, bwd], axis=-1)          # [B, Ts, 2H]
    boot = layers.fc(input=layers.sequence_first_step(bwd, length=src_len),
                     size=hidden_dim, act='tanh')          # [B, H]
    return encoded, boot


def additive_attention(encoded, encoded_proj, state, hidden_dim,
                       length=None, transform_param_attr=None,
                       score_param_attr=None):
    """Bahdanau additive attention over a padded sequence, built from
    fluid layers — safe inside a DynamicRNN step block. This is the ONE
    home of the attention math; the v1 shim's simple_attention
    (trainer_config_helpers/networks.py) delegates here. The param
    attrs carry ParamAttr names for weight sharing across graphs."""
    dec = layers.fc(input=state, size=hidden_dim, bias_attr=False,
                    param_attr=transform_param_attr)
    combined = layers.tanh(layers.elementwise_add(
        encoded_proj, layers.unsqueeze(dec, axes=[1])))
    scores = layers.fc(input=combined, size=1, num_flatten_dims=2,
                       bias_attr=False,
                       param_attr=score_param_attr)        # [B, Ts, 1]
    weights = layers.sequence_softmax(
        layers.squeeze(scores, axes=[2]), length=length)   # [B, Ts]
    ctx = layers.matmul(layers.unsqueeze(weights, axes=[1]), encoded)
    return layers.squeeze(ctx, axes=[1])                   # [B, ...]


def rnn_search(src_vocab=1000, trg_vocab=1000, emb_dim=64, hidden_dim=64):
    """Training graph: teacher-forced attention decoder. Returns
    (avg_cost, feed names). Feeds: src_word [B,Ts] int64, src_len [B]
    int32, trg_word [B,Tt] int64 (decoder input, <s>-shifted), lbl_word
    [B,Tt] int64, lbl_mask [B,Tt] float32 (1 on real target steps)."""
    src_word = layers.data(name='src_word', shape=[-1], dtype='int64',
                           lod_level=1)
    src_len = layers.data(name='src_len', shape=[], dtype='int32')
    trg_word = layers.data(name='trg_word', shape=[-1], dtype='int64',
                           lod_level=1)
    lbl_word = layers.data(name='lbl_word', shape=[-1], dtype='int64',
                           lod_level=1)
    lbl_mask = layers.data(name='lbl_mask', shape=[-1], dtype='float32',
                           lod_level=1)

    encoded, boot = encoder(src_word, src_len, src_vocab, emb_dim,
                            hidden_dim)
    # shared attention key projection, computed once outside the scan
    encoded_proj = layers.fc(input=encoded, size=hidden_dim,
                             bias_attr=False, num_flatten_dims=2)
    trg_emb = layers.embedding(input=trg_word,
                               size=[trg_vocab, emb_dim])

    drnn = layers.DynamicRNN()
    with drnn.block():
        emb_t = drnn.step_input(trg_emb)                   # [B, E]
        state = drnn.memory(init=boot)                     # [B, H]
        context = additive_attention(encoded, encoded_proj, state,
                                     hidden_dim, length=src_len)
        step_in = layers.fc(
            input=layers.concat([emb_t, context], axis=-1),
            size=hidden_dim * 3, bias_attr=False)
        new_state, _, _ = layers.gru_unit(step_in, state,
                                          size=hidden_dim * 3)
        drnn.update_memory(state, new_state)
        logits = layers.fc(input=new_state, size=trg_vocab)
        drnn.output(logits)
    logits = drnn()                                        # [B, Tt, V]

    cost = layers.softmax_with_cross_entropy(
        logits=logits, label=layers.unsqueeze(lbl_word, axes=[2]))
    cost = layers.squeeze(cost, axes=[2])                  # [B, Tt]
    weighted = layers.elementwise_mul(cost, lbl_mask)
    avg_cost = layers.elementwise_div(
        layers.reduce_sum(weighted),
        layers.reduce_sum(lbl_mask))
    return avg_cost, ['src_word', 'src_len', 'trg_word', 'lbl_word',
                      'lbl_mask']


def make_fake_batch(batch, src_seq, trg_seq, src_vocab, trg_vocab,
                    seed=0):
    """Synthetic copy-ish task feed (zero-egress environment)."""
    rng = np.random.RandomState(seed)
    src = rng.randint(2, src_vocab, (batch, src_seq)).astype('int64')
    lbl = (src[:, :trg_seq] % (trg_vocab - 2) + 2).astype('int64')
    trg = np.concatenate([np.ones((batch, 1), 'int64'),  # <s> = 1
                          lbl[:, :-1]], axis=1)
    return {'src_word': src,
            'src_len': np.full((batch,), src_seq, 'int32'),
            'trg_word': trg, 'lbl_word': lbl,
            'lbl_mask': np.ones((batch, trg_seq), 'float32')}
