"""RNN encoder-decoder with attention — the machine_translation book
chapter's model (reference: the seqToseq demo / book machine_translation
chapter; v1 networks.py simple_attention + gru_group decoder).

TPU-native: the bidirectional GRU encoder is two lax.scan recurrences
(layers.dynamic_gru), and the attention decoder is a fluid DynamicRNN
whose step block — additive attention over the full encoder output,
gru_unit state update, vocab projection — compiles into ONE lax.scan
body; the encoder states enter the scan as closed-over constants
(ops/control_ops.py _scan_rnn outer_env), so the whole seq2seq trains
as a single XLA computation like every other model here. Greedy
generation is a single rnn_search_greedy_decode op (lax.scan with
argmax feedback) sharing the training parameters by name.
"""

import numpy as np

from .. import layers
from ..param_attr import ParamAttr


def _p(name):
    return ParamAttr(name=name)


def encoder(src_word, src_len, src_vocab, emb_dim=64, hidden_dim=64):
    """Bi-GRU over the padded source: returns [B, Ts, 2H] states plus
    the backward direction's summary (decoder boot, per the chapter).
    All parameters are named so the infer graph shares them."""
    emb = layers.embedding(input=src_word, size=[src_vocab, emb_dim],
                           param_attr=_p('rnnsearch_src_emb'))
    fwd = layers.dynamic_gru(
        input=layers.fc(input=emb, size=hidden_dim * 3, bias_attr=False,
                        num_flatten_dims=2,
                        param_attr=_p('rnnsearch_enc_fwd.w')),
        size=hidden_dim, length=src_len,
        param_attr=_p('rnnsearch_enc_fwd_gru.w'),
        bias_attr=_p('rnnsearch_enc_fwd_gru.b'))
    bwd = layers.dynamic_gru(
        input=layers.fc(input=emb, size=hidden_dim * 3, bias_attr=False,
                        num_flatten_dims=2,
                        param_attr=_p('rnnsearch_enc_bwd.w')),
        size=hidden_dim, is_reverse=True, length=src_len,
        param_attr=_p('rnnsearch_enc_bwd_gru.w'),
        bias_attr=_p('rnnsearch_enc_bwd_gru.b'))
    encoded = layers.concat([fwd, bwd], axis=-1)          # [B, Ts, 2H]
    boot = layers.fc(input=layers.sequence_first_step(bwd, length=src_len),
                     size=hidden_dim, act='tanh',
                     param_attr=_p('rnnsearch_boot.w'),
                     bias_attr=_p('rnnsearch_boot.b'))     # [B, H]
    return encoded, boot


def additive_attention(encoded, encoded_proj, state, hidden_dim,
                       length=None, transform_param_attr=None,
                       score_param_attr=None):
    """Bahdanau additive attention over a padded sequence, built from
    fluid layers — safe inside a DynamicRNN step block. This is the ONE
    home of the attention math; the v1 shim's simple_attention
    (trainer_config_helpers/networks.py) delegates here. The param
    attrs carry ParamAttr names for weight sharing across graphs."""
    dec = layers.fc(input=state, size=hidden_dim, bias_attr=False,
                    param_attr=transform_param_attr)
    combined = layers.tanh(layers.elementwise_add(
        encoded_proj, layers.unsqueeze(dec, axes=[1])))
    scores = layers.fc(input=combined, size=1, num_flatten_dims=2,
                       bias_attr=False,
                       param_attr=score_param_attr)        # [B, Ts, 1]
    weights = layers.sequence_softmax(
        layers.squeeze(scores, axes=[2]), length=length)   # [B, Ts]
    ctx = layers.matmul(layers.unsqueeze(weights, axes=[1]), encoded)
    return layers.squeeze(ctx, axes=[1])                   # [B, ...]


def _build_inputs():
    src_word = layers.data(name='src_word', shape=[-1], dtype='int64',
                           lod_level=1)
    src_len = layers.data(name='src_len', shape=[], dtype='int32')
    return src_word, src_len


def rnn_search(src_vocab=1000, trg_vocab=1000, emb_dim=64, hidden_dim=64):
    """Training graph: teacher-forced attention decoder. Returns
    (avg_cost, feed names). Feeds: src_word [B,Ts] int64, src_len [B]
    int32, trg_word [B,Tt] int64 (decoder input, <s>-shifted), lbl_word
    [B,Tt] int64, lbl_mask [B,Tt] float32 (1 on real target steps)."""
    src_word, src_len = _build_inputs()
    trg_word = layers.data(name='trg_word', shape=[-1], dtype='int64',
                           lod_level=1)
    lbl_word = layers.data(name='lbl_word', shape=[-1], dtype='int64',
                           lod_level=1)
    lbl_mask = layers.data(name='lbl_mask', shape=[-1], dtype='float32',
                           lod_level=1)

    encoded, boot = encoder(src_word, src_len, src_vocab, emb_dim,
                            hidden_dim)
    # shared attention key projection, computed once outside the scan
    encoded_proj = layers.fc(input=encoded, size=hidden_dim,
                             bias_attr=False, num_flatten_dims=2,
                             param_attr=_p('rnnsearch_encproj.w'))
    trg_emb = layers.embedding(input=trg_word,
                               size=[trg_vocab, emb_dim],
                               param_attr=_p('rnnsearch_trg_emb'))

    drnn = layers.DynamicRNN()
    with drnn.block():
        emb_t = drnn.step_input(trg_emb)                   # [B, E]
        state = drnn.memory(init=boot)                     # [B, H]
        context = additive_attention(
            encoded, encoded_proj, state, hidden_dim, length=src_len,
            transform_param_attr=_p('rnnsearch_att_trans.w'),
            score_param_attr=_p('rnnsearch_att_score.w'))
        step_in = layers.fc(
            input=layers.concat([emb_t, context], axis=-1),
            size=hidden_dim * 3, bias_attr=False,
            param_attr=_p('rnnsearch_step.w'))
        new_state, _, _ = layers.gru_unit(
            step_in, state, size=hidden_dim * 3,
            param_attr=_p('rnnsearch_gru.w'),
            bias_attr=_p('rnnsearch_gru.b'))
        drnn.update_memory(state, new_state)
        logits = layers.fc(input=new_state, size=trg_vocab,
                           param_attr=_p('rnnsearch_out.w'),
                           bias_attr=_p('rnnsearch_out.b'))
        drnn.output(logits)
    logits = drnn()                                        # [B, Tt, V]

    cost = layers.softmax_with_cross_entropy(
        logits=logits, label=layers.unsqueeze(lbl_word, axes=[2]))
    cost = layers.squeeze(cost, axes=[2])                  # [B, Tt]
    weighted = layers.elementwise_mul(cost, lbl_mask)
    avg_cost = layers.elementwise_div(
        layers.reduce_sum(weighted),
        layers.reduce_sum(lbl_mask))
    return avg_cost, ['src_word', 'src_len', 'trg_word', 'lbl_word',
                      'lbl_mask']


def _decoder_param_inputs(encoded, encoded_proj, boot, src_len,
                          src_vocab, trg_vocab, emb_dim, hidden_dim):
    """Decode-op input dict: the training decoder's parameters,
    re-declared by NAME (first-init-wins keeps one init either build
    order; is_bias=True makes the bias init Constant(0) when the infer
    graph is built first)."""
    def param(name, shape, is_bias=False):
        return layers.create_parameter(shape=shape, dtype='float32',
                                       attr=_p(name), is_bias=is_bias)

    return {
        'EncOut': [encoded], 'EncProj': [encoded_proj], 'Boot': [boot],
        'SrcLen': [src_len],
        'TrgEmb': [param('rnnsearch_trg_emb', [trg_vocab, emb_dim])],
        'AttW': [param('rnnsearch_att_trans.w', [hidden_dim, hidden_dim])],
        'ScoreW': [param('rnnsearch_att_score.w', [hidden_dim, 1])],
        'StepW': [param('rnnsearch_step.w',
                        [emb_dim + 2 * hidden_dim, 3 * hidden_dim])],
        'GruW': [param('rnnsearch_gru.w', [hidden_dim, 3 * hidden_dim])],
        'GruB': [param('rnnsearch_gru.b', [1, 3 * hidden_dim],
                       is_bias=True)],
        'OutW': [param('rnnsearch_out.w', [hidden_dim, trg_vocab])],
        'OutB': [param('rnnsearch_out.b', [trg_vocab], is_bias=True)],
    }


def rnn_search_greedy_infer(src_vocab=1000, trg_vocab=1000, emb_dim=64,
                            hidden_dim=64, max_out_len=16, bos_id=1,
                            eos_id=0):
    """Inference graph: encoder (training parameters, shared by name) +
    ONE rnn_search_greedy_decode op — a lax.scan with argmax feedback.
    Build under a program_guard on a fresh program; run with feeds
    src_word/src_len, fetch the returned [B, max_out_len] ids."""
    from ..layers.helper import LayerHelper
    src_word, src_len = _build_inputs()
    encoded, boot = encoder(src_word, src_len, src_vocab, emb_dim,
                            hidden_dim)
    encoded_proj = layers.fc(input=encoded, size=hidden_dim,
                             bias_attr=False, num_flatten_dims=2,
                             param_attr=_p('rnnsearch_encproj.w'))
    helper = LayerHelper('rnn_search_greedy_decode')
    inputs = _decoder_param_inputs(encoded, encoded_proj, boot, src_len,
                                   src_vocab, trg_vocab, emb_dim,
                                   hidden_dim)
    out = helper.create_variable_for_type_inference('int64')
    if encoded.shape is not None:
        out.shape = (encoded.shape[0], max_out_len)
    helper.append_op(type='rnn_search_greedy_decode', inputs=inputs,
                     outputs={'Out': [out]},
                     attrs={'max_out_len': max_out_len, 'bos_id': bos_id,
                            'eos_id': eos_id})
    return out, ['src_word', 'src_len']


def rnn_search_beam_infer(src_vocab=1000, trg_vocab=1000, emb_dim=64,
                          hidden_dim=64, max_out_len=16, beam_size=4,
                          bos_id=1, eos_id=0):
    """Beam-search generation (the seqToseq demo's mode): encoder +
    ONE rnn_search_beam_decode op. Returns (ids [B, beam, T] sorted
    best-first, scores [B, beam], feed names)."""
    from ..layers.helper import LayerHelper
    src_word, src_len = _build_inputs()
    encoded, boot = encoder(src_word, src_len, src_vocab, emb_dim,
                            hidden_dim)
    encoded_proj = layers.fc(input=encoded, size=hidden_dim,
                             bias_attr=False, num_flatten_dims=2,
                             param_attr=_p('rnnsearch_encproj.w'))
    helper = LayerHelper('rnn_search_beam_decode')
    inputs = _decoder_param_inputs(encoded, encoded_proj, boot, src_len,
                                   src_vocab, trg_vocab, emb_dim,
                                   hidden_dim)
    ids = helper.create_variable_for_type_inference('int64')
    scores = helper.create_variable_for_type_inference('float32')
    if encoded.shape is not None:
        ids.shape = (encoded.shape[0], beam_size, max_out_len)
        scores.shape = (encoded.shape[0], beam_size)
    helper.append_op(type='rnn_search_beam_decode', inputs=inputs,
                     outputs={'SentenceIds': [ids],
                              'SentenceScores': [scores]},
                     attrs={'max_out_len': max_out_len,
                            'beam_size': beam_size, 'bos_id': bos_id,
                            'eos_id': eos_id})
    return ids, scores, ['src_word', 'src_len']


def make_fake_batch(batch, src_seq, trg_seq, src_vocab, trg_vocab,
                    seed=0):
    """Synthetic copy-ish task feed (zero-egress environment)."""
    rng = np.random.RandomState(seed)
    src = rng.randint(2, src_vocab, (batch, src_seq)).astype('int64')
    lbl = (src[:, :trg_seq] % (trg_vocab - 2) + 2).astype('int64')
    trg = np.concatenate([np.ones((batch, 1), 'int64'),  # <s> = 1
                          lbl[:, :-1]], axis=1)
    return {'src_word': src,
            'src_len': np.full((batch,), src_seq, 'int32'),
            'trg_word': trg, 'lbl_word': lbl,
            'lbl_mask': np.ones((batch, trg_seq), 'float32')}
