"""word2vec N-gram language model (reference: the word2vec book chapter on
the imikolov dataset): 4 context words -> shared embedding -> concat ->
hidden -> softmax over vocab."""

from .. import layers
from ..param_attr import ParamAttr

EMBED_SIZE = 32
HIDDEN_SIZE = 256
N = 5  # n-gram window


def inference_program(words, dict_size, embed_size=EMBED_SIZE,
                      hidden_size=HIDDEN_SIZE, is_sparse=False):
    """words: list of 4 int64 context-word Variables."""
    embs = []
    for i, w in enumerate(words):
        embs.append(layers.embedding(
            input=w, size=[dict_size, embed_size], dtype='float32',
            is_sparse=is_sparse,
            param_attr=ParamAttr(name='shared_w')))
    concat_embed = layers.concat(input=embs, axis=-1)
    hidden1 = layers.fc(input=concat_embed, size=hidden_size, act='sigmoid')
    predict_word = layers.fc(input=hidden1, size=dict_size, act='softmax')
    return predict_word


def train_program(dict_size, is_sparse=False):
    """Builds data vars + loss. Returns (avg_cost, feed_names)."""
    first = layers.data(name='firstw', shape=[1], dtype='int64')
    second = layers.data(name='secondw', shape=[1], dtype='int64')
    third = layers.data(name='thirdw', shape=[1], dtype='int64')
    fourth = layers.data(name='fourthw', shape=[1], dtype='int64')
    next_word = layers.data(name='nextw', shape=[1], dtype='int64')
    predict = inference_program([first, second, third, fourth], dict_size,
                                is_sparse=is_sparse)
    cost = layers.cross_entropy(input=predict, label=next_word)
    avg_cost = layers.mean(cost)
    return avg_cost, ['firstw', 'secondw', 'thirdw', 'fourthw', 'nextw']
