"""AlexNet (reference: benchmark/paddle/image/alexnet.py — the v1
trainer-config benchmark net: five convs with cross-channel LRN after
the first two, three max-pools, two dropout-regularized 4096-wide fc
layers).

TPU notes: identical layer math, built on the fluid IR so the whole
step compiles to one XLA program. The global average pool used by the
other image models is deliberately NOT substituted — AlexNet's
identity is the 6x6x256 flatten into fc4096 (the MXU-friendliest part
of the net), so the input must be 224x224 (or any size whose conv
stack lands on >=1 spatial cell).
"""

from .. import layers


def alexnet(input, class_dim=1000, is_test=False):
    """benchmark/paddle/image/alexnet.py topology (conv1 11x11/4 ...
    fc8), LRN with the benchmark's size-5 window."""
    conv1 = layers.conv2d(input, num_filters=96, filter_size=11, stride=4,
                          padding=1, act='relu')
    norm1 = layers.lrn(conv1, n=5, k=2.0, alpha=1e-4, beta=0.75)
    pool1 = layers.pool2d(norm1, pool_size=3, pool_stride=2)

    conv2 = layers.conv2d(pool1, num_filters=256, filter_size=5, padding=2,
                          groups=1, act='relu')
    norm2 = layers.lrn(conv2, n=5, k=2.0, alpha=1e-4, beta=0.75)
    pool2 = layers.pool2d(norm2, pool_size=3, pool_stride=2)

    conv3 = layers.conv2d(pool2, num_filters=384, filter_size=3, padding=1,
                          act='relu')
    conv4 = layers.conv2d(conv3, num_filters=384, filter_size=3, padding=1,
                          act='relu')
    conv5 = layers.conv2d(conv4, num_filters=256, filter_size=3, padding=1,
                          act='relu')
    pool3 = layers.pool2d(conv5, pool_size=3, pool_stride=2)

    fc6 = layers.fc(input=pool3, size=4096, act='relu')
    drop6 = layers.dropout(fc6, dropout_prob=0.5, is_test=is_test)
    fc7 = layers.fc(input=drop6, size=4096, act='relu')
    drop7 = layers.dropout(fc7, dropout_prob=0.5, is_test=is_test)
    return layers.fc(input=drop7, size=class_dim, act='softmax')
