"""ResNet family (reference: benchmark/paddle image classification
resnet.py — ResNet-50/101/152 bottleneck nets, plus the cifar resnet of the
image_classification book chapter).

TPU notes: data_format='NHWC' keeps every activation channels-last IN THE
IR — zero layout transposes between ops (one transpose of the NCHW input
feed at the stem); filters stay OIHW so checkpoints are layout-free.
bf16 casting is applied by the bench/entry harness via Program.amp, not
baked into the model.
"""

from .. import layers


def conv_bn_layer(input, ch_out, filter_size, stride, padding, act='relu',
                  is_test=False, data_format='NCHW'):
    conv = layers.conv2d(input=input, num_filters=ch_out,
                         filter_size=filter_size, stride=stride,
                         padding=padding, act=None, bias_attr=False,
                         data_format=data_format)
    return layers.batch_norm(input=conv, act=act, is_test=is_test,
                             data_layout=data_format)


def shortcut(input, ch_out, stride, is_test=False, data_format='NCHW'):
    ch_in = input.shape[3] if data_format == 'NHWC' else input.shape[1]
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, act=None,
                             is_test=is_test, data_format=data_format)
    return input


def basicblock(input, ch_out, stride, is_test=False, data_format='NCHW'):
    short = shortcut(input, ch_out, stride, is_test, data_format)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, is_test=is_test,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, act=None, is_test=is_test,
                          data_format=data_format)
    return layers.elementwise_add(x=short, y=conv2, act='relu')


def bottleneck(input, ch_out, stride, is_test=False, data_format='NCHW'):
    short = shortcut(input, ch_out * 4, stride, is_test, data_format)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, is_test=is_test,
                          data_format=data_format)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, is_test=is_test,
                          data_format=data_format)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, act=None,
                          is_test=is_test, data_format=data_format)
    return layers.elementwise_add(x=short, y=conv3, act='relu')


def layer_warp(block_func, input, ch_out, count, stride, is_test=False,
               data_format='NCHW'):
    res_out = block_func(input, ch_out, stride, is_test, data_format)
    for _ in range(1, count):
        res_out = block_func(res_out, ch_out, 1, is_test, data_format)
    return res_out


def resnet_imagenet(input, class_dim=1000, depth=50, is_test=False,
                    data_format='NCHW'):
    """ResNet-{50,101,152} bottleneck net for 224x224 ImageNet.

    `input` is always the NCHW feed; data_format='NHWC' transposes it
    ONCE here and the rest of the network is transpose-free.
    """
    cfg = {50: ([3, 4, 6, 3], bottleneck),
           101: ([3, 4, 23, 3], bottleneck),
           152: ([3, 8, 36, 3], bottleneck)}
    stages, block_func = cfg[depth]
    if data_format == 'NHWC':
        input = layers.transpose(input, [0, 2, 3, 1])
    conv1 = conv_bn_layer(input, ch_out=64, filter_size=7, stride=2,
                          padding=3, is_test=is_test,
                          data_format=data_format)
    pool1 = layers.pool2d(input=conv1, pool_type='max', pool_size=3,
                          pool_stride=2, pool_padding=1,
                          data_format=data_format)
    res1 = layer_warp(block_func, pool1, 64, stages[0], 1, is_test,
                      data_format)
    res2 = layer_warp(block_func, res1, 128, stages[1], 2, is_test,
                      data_format)
    res3 = layer_warp(block_func, res2, 256, stages[2], 2, is_test,
                      data_format)
    res4 = layer_warp(block_func, res3, 512, stages[3], 2, is_test,
                      data_format)
    pool2 = layers.pool2d(input=res4, pool_size=7, pool_type='avg',
                          global_pooling=True, data_format=data_format)
    out = layers.fc(input=pool2, size=class_dim, act='softmax')
    return out


def resnet_cifar10(input, depth=32, class_dim=10, is_test=False):
    """The book chapter's CIFAR resnet: 6n+2 layers of basic blocks."""
    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, ch_out=16, filter_size=3, stride=1,
                          padding=1, is_test=is_test)
    res1 = layer_warp(basicblock, conv1, 16, n, 1, is_test)
    res2 = layer_warp(basicblock, res1, 32, n, 2, is_test)
    res3 = layer_warp(basicblock, res2, 64, n, 2, is_test)
    pool = layers.pool2d(input=res3, pool_size=8, pool_type='avg',
                         global_pooling=True)
    predict = layers.fc(input=pool, size=class_dim, act='softmax')
    return predict


def resnet50_with_loss(input=None, label=None, class_dim=1000,
                       image_shape=(3, 224, 224), is_test=False,
                       data_format=None):
    """data_format=None reads PADDLE_TPU_RESNET_LAYOUT (default NHWC on
    TPU — the transpose-free channels-last network; NCHW elsewhere).
    The feed is NCHW either way."""
    if data_format is None:
        import os
        data_format = os.environ.get('PADDLE_TPU_RESNET_LAYOUT', '').upper()
        if not data_format:
            from ..core.platform_boot import is_tpu_backend
            data_format = 'NHWC' if is_tpu_backend() else 'NCHW'
    if input is None:
        input = layers.data(name='image', shape=list(image_shape),
                            dtype='float32')
    if label is None:
        label = layers.data(name='label', shape=[1], dtype='int64')
    predict = resnet_imagenet(input, class_dim=class_dim, is_test=is_test,
                              data_format=data_format)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return predict, avg_cost, acc
