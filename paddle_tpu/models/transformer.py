"""Transformer NMT (reference: the benchmark Transformer "base" en-de
config — 6-layer encoder/decoder, d_model 512, 8 heads, label smoothing).

TPU-first differences from the reference build:
- attention is the fused `fused_attention` IR op (Pallas flash kernel on
  TPU) instead of a chain of reshape/matmul/softmax ops, and padding
  masks derive in-graph from a per-example `length` vector — the
  reference feeds precomputed [B, H, T, T] bias tensors from the host.
- positional encodings are a non-trainable device-resident table sliced
  per step, not host-fed.
- the whole train step (fwd + bwd + Adam + label smoothing) compiles to
  one XLA program; bf16-friendly (all matmuls hit the MXU).
"""

import numpy as np

from .. import layers
from ..initializer import Normal, NumpyArrayInitializer
from ..param_attr import ParamAttr


def position_encoding_table(max_length, d_model):
    """Sinusoidal position table [max_length, d_model] (host-computed once,
    lives in HBM as a frozen parameter)."""
    pos = np.arange(max_length)[:, None].astype('float64')
    dim = np.arange(0, d_model, 2).astype('float64')
    inv = 1.0 / np.power(10000.0, dim / d_model)
    angles = pos * inv[None, :]
    table = np.zeros((max_length, d_model), dtype='float32')
    table[:, 0::2] = np.sin(angles)
    table[:, 1::2] = np.cos(angles)
    return table


def _multi_head_attention(queries, keys, values, d_key, d_value, d_model,
                          n_head, dropout_rate, causal=False,
                          key_length=None, name='attn'):
    q = layers.fc(input=queries, size=d_key * n_head, num_flatten_dims=2,
                  bias_attr=False,
                  param_attr=ParamAttr(name=name + '_q.w'))
    k = layers.fc(input=keys, size=d_key * n_head, num_flatten_dims=2,
                  bias_attr=False,
                  param_attr=ParamAttr(name=name + '_k.w'))
    v = layers.fc(input=values, size=d_value * n_head, num_flatten_dims=2,
                  bias_attr=False,
                  param_attr=ParamAttr(name=name + '_v.w'))

    from ..layers.helper import LayerHelper
    helper = LayerHelper('fused_attention', name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    if q.shape is not None:
        out.shape = (q.shape[0], q.shape[1], d_value * n_head)
    inputs = {'Q': [q], 'K': [k], 'V': [v]}
    if key_length is not None:
        inputs['KeyLength'] = [key_length]
    helper.append_op(type='fused_attention', inputs=inputs,
                     outputs={'Out': [out]},
                     attrs={'n_head': n_head, 'causal': causal,
                            'dropout_rate': dropout_rate})
    proj = layers.fc(input=out, size=d_model, num_flatten_dims=2,
                     bias_attr=False,
                     param_attr=ParamAttr(name=name + '_out.w'))
    return proj


def _ffn(x, d_inner, d_model, dropout_rate, name='ffn'):
    hidden = layers.fc(input=x, size=d_inner, num_flatten_dims=2,
                       act='relu', param_attr=ParamAttr(name=name + '_1.w'))
    if dropout_rate:
        hidden = layers.dropout(hidden, dropout_prob=dropout_rate)
    return layers.fc(input=hidden, size=d_model, num_flatten_dims=2,
                     param_attr=ParamAttr(name=name + '_2.w'))


def _post_process(prev, out, dropout_rate):
    """residual add + layer_norm (+ dropout), the reference's "dan" chain."""
    if dropout_rate:
        out = layers.dropout(out, dropout_prob=dropout_rate)
    added = layers.elementwise_add(x=out, y=prev)
    return layers.layer_norm(added, begin_norm_axis=len(added.shape) - 1)


def _prepare_input(word_ids, vocab_size, d_model, max_length, dropout_rate,
                   emb_name, pos_table):
    emb = layers.embedding(
        input=word_ids, size=[vocab_size, d_model], dtype='float32',
        param_attr=ParamAttr(name=emb_name,
                             initializer=Normal(0., d_model ** -0.5)))
    emb = layers.scale(x=emb, scale=d_model ** 0.5)
    seq_len = word_ids.shape[1]
    pos_enc = layers.create_parameter(
        shape=[max_length, d_model], dtype='float32',
        name=emb_name + '_pos_enc',
        attr=ParamAttr(name=emb_name + '_pos_enc',
                       initializer=NumpyArrayInitializer(pos_table),
                       trainable=False))
    pos_slice = layers.slice(pos_enc, axes=[0], starts=[0], ends=[seq_len])
    pos_slice = layers.reshape(x=pos_slice, shape=[1, seq_len, d_model])
    out = layers.elementwise_add(x=emb, y=pos_slice)
    if dropout_rate:
        out = layers.dropout(out, dropout_prob=dropout_rate)
    return out


def encoder_layer(x, n_head, d_key, d_value, d_model, d_inner, dropout_rate,
                  src_length=None, name='enc'):
    attn = _multi_head_attention(x, x, x, d_key, d_value, d_model, n_head,
                                 dropout_rate, key_length=src_length,
                                 name=name + '_slf')
    x = _post_process(x, attn, dropout_rate)
    ffn = _ffn(x, d_inner, d_model, dropout_rate, name=name + '_ffn')
    return _post_process(x, ffn, dropout_rate)


def decoder_layer(x, enc_out, n_head, d_key, d_value, d_model, d_inner,
                  dropout_rate, src_length=None, name='dec'):
    slf = _multi_head_attention(x, x, x, d_key, d_value, d_model, n_head,
                                dropout_rate, causal=True,
                                name=name + '_slf')
    x = _post_process(x, slf, dropout_rate)
    cross = _multi_head_attention(x, enc_out, enc_out, d_key, d_value,
                                  d_model, n_head, dropout_rate,
                                  key_length=src_length,
                                  name=name + '_cross')
    x = _post_process(x, cross, dropout_rate)
    ffn = _ffn(x, d_inner, d_model, dropout_rate, name=name + '_ffn')
    return _post_process(x, ffn, dropout_rate)


def transformer(src_vocab_size, trg_vocab_size, max_length=256,
                n_layer=6, n_head=8, d_key=64, d_value=64, d_model=512,
                d_inner=2048, dropout_rate=0.1, label_smooth_eps=0.1,
                src_seq_len=None, trg_seq_len=None, batch_size=None,
                weight_sharing=False):
    """Build the full training graph. Feeds: src_word [B,S] int64,
    src_length [B] int64, trg_word [B,T] int64 (decoder input),
    lbl_word [B,T] int64 (shifted target), lbl_weight [B,T] float32
    (1 for real tokens, 0 for pads). Returns (avg_cost, logits)."""
    src_word = layers.data(name='src_word', shape=[src_seq_len],
                           dtype='int64')
    src_length = layers.data(name='src_length', shape=[], dtype='int64')
    trg_word = layers.data(name='trg_word', shape=[trg_seq_len],
                           dtype='int64')
    lbl_word = layers.data(name='lbl_word', shape=[trg_seq_len],
                           dtype='int64')
    lbl_weight = layers.data(name='lbl_weight', shape=[trg_seq_len],
                             dtype='float32')

    pos_table = position_encoding_table(max_length, d_model)

    enc_in = _prepare_input(src_word, src_vocab_size, d_model, max_length,
                            dropout_rate, 'src_emb', pos_table)
    x = enc_in
    for i in range(n_layer):
        x = encoder_layer(x, n_head, d_key, d_value, d_model, d_inner,
                          dropout_rate, src_length=src_length,
                          name='enc_%d' % i)
    enc_out = x

    dec_emb_name = 'src_emb' if weight_sharing else 'trg_emb'
    dec_in = _prepare_input(trg_word, trg_vocab_size, d_model, max_length,
                            dropout_rate, dec_emb_name, pos_table)
    y = dec_in
    for i in range(n_layer):
        y = decoder_layer(y, enc_out, n_head, d_key, d_value, d_model,
                          d_inner, dropout_rate, src_length=src_length,
                          name='dec_%d' % i)

    logits = layers.fc(input=y, size=trg_vocab_size, num_flatten_dims=2,
                       bias_attr=False,
                       param_attr=ParamAttr(name='out_proj.w'))

    # label smoothing + softmax cross entropy, weighted by non-pad mask
    if label_smooth_eps:
        smooth = layers.label_smooth(
            label=layers.one_hot(lbl_word, depth=trg_vocab_size),
            epsilon=label_smooth_eps)
        cost = layers.softmax_with_cross_entropy(
            logits=logits, label=smooth, soft_label=True)
    else:
        lbl3 = layers.unsqueeze(lbl_word, axes=[2])
        cost = layers.softmax_with_cross_entropy(logits=logits, label=lbl3)
    cost = layers.reshape(x=cost, shape=list(lbl_weight.shape))
    weighted = layers.elementwise_mul(x=cost, y=lbl_weight)
    sum_cost = layers.reduce_sum(weighted)
    token_count = layers.reduce_sum(lbl_weight)
    avg_cost = layers.elementwise_div(x=sum_cost, y=token_count)
    return avg_cost, logits


def transformer_base(src_vocab_size=32000, trg_vocab_size=32000,
                     src_seq_len=64, trg_seq_len=64, **overrides):
    """The reference "base" configuration."""
    cfg = dict(n_layer=6, n_head=8, d_key=64, d_value=64, d_model=512,
               d_inner=2048, dropout_rate=0.1, label_smooth_eps=0.1,
               src_seq_len=src_seq_len, trg_seq_len=trg_seq_len)
    cfg.update(overrides)
    return transformer(src_vocab_size, trg_vocab_size, **cfg)


FEED_NAMES = ['src_word', 'src_length', 'trg_word', 'lbl_word', 'lbl_weight']


def make_fake_batch(batch_size, src_seq_len, trg_seq_len, src_vocab_size,
                    trg_vocab_size, seed=0):
    """Synthetic feed dict for tests/bench (zero-egress environment)."""
    rng = np.random.RandomState(seed)
    return {
        'src_word': rng.randint(1, src_vocab_size,
                                (batch_size, src_seq_len)).astype('int64'),
        'src_length': np.full((batch_size,), src_seq_len, dtype='int64'),
        'trg_word': rng.randint(1, trg_vocab_size,
                                (batch_size, trg_seq_len)).astype('int64'),
        'lbl_word': rng.randint(1, trg_vocab_size,
                                (batch_size, trg_seq_len)).astype('int64'),
        'lbl_weight': np.ones((batch_size, trg_seq_len), dtype='float32'),
    }
