"""Transformer NMT (reference: the benchmark Transformer "base" en-de
config — 6-layer encoder/decoder, d_model 512, 8 heads, label smoothing).

TPU-first differences from the reference build:
- attention is the fused `fused_attention` IR op (Pallas flash kernel on
  TPU) instead of a chain of reshape/matmul/softmax ops, and padding
  masks derive in-graph from a per-example `length` vector — the
  reference feeds precomputed [B, H, T, T] bias tensors from the host.
- positional encodings are a non-trainable device-resident table sliced
  per step, not host-fed.
- the whole train step (fwd + bwd + Adam + label smoothing) compiles to
  one XLA program; bf16-friendly (all matmuls hit the MXU).
"""

import re

import numpy as np

from .. import layers
from ..initializer import Normal, NumpyArrayInitializer
from ..param_attr import ParamAttr


def position_encoding_table(max_length, d_model):
    """Sinusoidal position table [max_length, d_model] (host-computed once,
    lives in HBM as a frozen parameter)."""
    pos = np.arange(max_length)[:, None].astype('float64')
    dim = np.arange(0, d_model, 2).astype('float64')
    inv = 1.0 / np.power(10000.0, dim / d_model)
    angles = pos * inv[None, :]
    table = np.zeros((max_length, d_model), dtype='float32')
    table[:, 0::2] = np.sin(angles)
    table[:, 1::2] = np.cos(angles)
    return table


def _multi_head_attention(queries, keys, values, d_key, d_value, d_model,
                          n_head, dropout_rate, causal=False,
                          key_length=None, name='attn'):
    q = layers.fc(input=queries, size=d_key * n_head, num_flatten_dims=2,
                  bias_attr=False,
                  param_attr=ParamAttr(name=name + '_q.w'))
    k = layers.fc(input=keys, size=d_key * n_head, num_flatten_dims=2,
                  bias_attr=False,
                  param_attr=ParamAttr(name=name + '_k.w'))
    v = layers.fc(input=values, size=d_value * n_head, num_flatten_dims=2,
                  bias_attr=False,
                  param_attr=ParamAttr(name=name + '_v.w'))

    from ..layers.helper import LayerHelper
    helper = LayerHelper('fused_attention', name=name)
    out = helper.create_variable_for_type_inference(q.dtype)
    if q.shape is not None:
        out.shape = (q.shape[0], q.shape[1], d_value * n_head)
    inputs = {'Q': [q], 'K': [k], 'V': [v]}
    if key_length is not None:
        inputs['KeyLength'] = [key_length]
    helper.append_op(type='fused_attention', inputs=inputs,
                     outputs={'Out': [out]},
                     attrs={'n_head': n_head, 'causal': causal,
                            'dropout_rate': dropout_rate})
    proj = layers.fc(input=out, size=d_model, num_flatten_dims=2,
                     bias_attr=False,
                     param_attr=ParamAttr(name=name + '_out.w'))
    return proj


def _ffn(x, d_inner, d_model, dropout_rate, name='ffn'):
    hidden = layers.fc(input=x, size=d_inner, num_flatten_dims=2,
                       act='relu', param_attr=ParamAttr(name=name + '_1.w'),
                       bias_attr=ParamAttr(name=name + '_1.b'))
    if dropout_rate:
        hidden = layers.dropout(hidden, dropout_prob=dropout_rate)
    return layers.fc(input=hidden, size=d_model, num_flatten_dims=2,
                     param_attr=ParamAttr(name=name + '_2.w'),
                     bias_attr=ParamAttr(name=name + '_2.b'))


def _post_process(prev, out, dropout_rate, name='pp'):
    """residual add + layer_norm (+ dropout), the reference's "dan" chain.
    Every parameter is explicitly named so inference graphs (including
    the unrolled decode, which re-runs these layers per step) share the
    trained weights."""
    if dropout_rate:
        out = layers.dropout(out, dropout_prob=dropout_rate)
    added = layers.elementwise_add(x=out, y=prev)
    return layers.layer_norm(added, begin_norm_axis=len(added.shape) - 1,
                             param_attr=ParamAttr(name=name + '_ln.w'),
                             bias_attr=ParamAttr(name=name + '_ln.b'))


def _prepare_input(word_ids, vocab_size, d_model, max_length, dropout_rate,
                   emb_name, pos_table):
    emb = layers.embedding(
        input=word_ids, size=[vocab_size, d_model], dtype='float32',
        param_attr=ParamAttr(name=emb_name,
                             initializer=Normal(0., d_model ** -0.5)))
    if len(emb.shape) == 2:
        # embedding squeezes a trailing dim of 1 (the fluid [B, 1]
        # id-column convention); a length-1 decode prefix must stay 3-D
        # or the step-1 graph would declare wrongly-shaped fc weights.
        emb = layers.reshape(x=emb, shape=[0, 1, d_model])
    emb = layers.scale(x=emb, scale=d_model ** 0.5)
    seq_len = word_ids.shape[1]
    pos_enc = layers.create_parameter(
        shape=[max_length, d_model], dtype='float32',
        name=emb_name + '_pos_enc',
        attr=ParamAttr(name=emb_name + '_pos_enc',
                       initializer=NumpyArrayInitializer(pos_table),
                       trainable=False))
    pos_slice = layers.slice(pos_enc, axes=[0], starts=[0], ends=[seq_len])
    pos_slice = layers.reshape(x=pos_slice, shape=[1, seq_len, d_model])
    out = layers.elementwise_add(x=emb, y=pos_slice)
    if dropout_rate:
        out = layers.dropout(out, dropout_prob=dropout_rate)
    return out


def encoder_layer(x, n_head, d_key, d_value, d_model, d_inner, dropout_rate,
                  src_length=None, name='enc'):
    attn = _multi_head_attention(x, x, x, d_key, d_value, d_model, n_head,
                                 dropout_rate, key_length=src_length,
                                 name=name + '_slf')
    x = _post_process(x, attn, dropout_rate, name=name + '_pp1')
    ffn = _ffn(x, d_inner, d_model, dropout_rate, name=name + '_ffn')
    return _post_process(x, ffn, dropout_rate, name=name + '_pp2')


def decoder_layer(x, enc_out, n_head, d_key, d_value, d_model, d_inner,
                  dropout_rate, src_length=None, name='dec'):
    slf = _multi_head_attention(x, x, x, d_key, d_value, d_model, n_head,
                                dropout_rate, causal=True,
                                name=name + '_slf')
    x = _post_process(x, slf, dropout_rate, name=name + '_pp1')
    cross = _multi_head_attention(x, enc_out, enc_out, d_key, d_value,
                                  d_model, n_head, dropout_rate,
                                  key_length=src_length,
                                  name=name + '_cross')
    x = _post_process(x, cross, dropout_rate, name=name + '_pp2')
    ffn = _ffn(x, d_inner, d_model, dropout_rate, name=name + '_ffn')
    return _post_process(x, ffn, dropout_rate, name=name + '_pp3')


def _stack_param(name, shape, fan_in, fan_out, constant=None):
    """[n_layer, ...] stacked parameter. Xavier fans are passed explicitly
    (the leading layer axis must not enter the fan computation)."""
    from ..initializer import Constant, Xavier
    init = Constant(constant) if constant is not None else \
        Xavier(uniform=True, fan_in=fan_in, fan_out=fan_out)
    return layers.create_parameter(
        shape=shape, dtype='float32', name=name,
        attr=ParamAttr(name=name, initializer=init))


def _stacked_layer_params(prefix, n_layer, n_head, d_key, d_value, d_model,
                          d_inner, decoder=False):
    """The transformer_layer_stack op's weight pytree, stacked on a
    leading [n_layer] axis (ops/transformer_ops.py slot layout)."""
    L = n_layer
    p = {}

    def attn(pre):
        p[pre + '_q'] = _stack_param('%s_%s_q.w' % (prefix, pre),
                                     [L, d_model, d_key * n_head],
                                     d_model, d_key * n_head)
        p[pre + '_k'] = _stack_param('%s_%s_k.w' % (prefix, pre),
                                     [L, d_model, d_key * n_head],
                                     d_model, d_key * n_head)
        p[pre + '_v'] = _stack_param('%s_%s_v.w' % (prefix, pre),
                                     [L, d_model, d_value * n_head],
                                     d_model, d_value * n_head)
        p[pre + '_o'] = _stack_param('%s_%s_o.w' % (prefix, pre),
                                     [L, d_value * n_head, d_model],
                                     d_value * n_head, d_model)

    def ln(slot):
        p[slot + '_w'] = _stack_param('%s_%s.w' % (prefix, slot),
                                      [L, d_model], 0, 0, constant=1.0)
        p[slot + '_b'] = _stack_param('%s_%s.b' % (prefix, slot),
                                      [L, d_model], 0, 0, constant=0.0)

    attn('slf')
    ln('ln1')
    if decoder:
        attn('cross')
        ln('ln2')
    p['ffn_w1'] = _stack_param('%s_ffn_1.w' % prefix,
                               [L, d_model, d_inner], d_model, d_inner)
    p['ffn_b1'] = _stack_param('%s_ffn_1.b' % prefix, [L, d_inner],
                               0, 0, constant=0.0)
    p['ffn_w2'] = _stack_param('%s_ffn_2.w' % prefix,
                               [L, d_inner, d_model], d_inner, d_model)
    p['ffn_b2'] = _stack_param('%s_ffn_2.b' % prefix, [L, d_model],
                               0, 0, constant=0.0)
    ln('ln3' if decoder else 'ln2')
    return p


def _layer_stack(x, params, n_head, dropout_rate, enc_out=None,
                 src_length=None, name='stack'):
    from ..layers.helper import LayerHelper
    from ..ops.transformer_ops import _slot_to_input
    helper = LayerHelper('transformer_layer_stack', name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    inputs = {'X': [x]}
    if enc_out is not None:
        inputs['EncOut'] = [enc_out]
    if src_length is not None:
        inputs['SrcLength'] = [src_length]
    for slot, param in params.items():
        inputs[_slot_to_input(slot)] = [param]
    helper.append_op(type='transformer_layer_stack', inputs=inputs,
                     outputs={'Out': [out]},
                     attrs={'n_head': n_head,
                            'dropout_rate': dropout_rate})
    return out


def transformer(src_vocab_size, trg_vocab_size, max_length=256,
                n_layer=6, n_head=8, d_key=64, d_value=64, d_model=512,
                d_inner=2048, dropout_rate=0.1, label_smooth_eps=0.1,
                src_seq_len=None, trg_seq_len=None, batch_size=None,
                weight_sharing=False, scan_layers=None):
    """Build the full training graph. Feeds: src_word [B,S] int64,
    src_length [B] int64, trg_word [B,T] int64 (decoder input),
    lbl_word [B,T] int64 (shifted target), lbl_weight [B,T] float32
    (1 for real tokens, 0 for pads). Returns (avg_cost, logits).

    scan_layers: None reads PADDLE_TPU_SCAN_LAYERS (default off). When
    on, the n_layer encoder/decoder stacks become ONE
    transformer_layer_stack op each (lax.scan over [n_layer, ...]
    stacked weights) — XLA compiles the layer body once, so compile
    time stays flat as stacks deepen."""
    import os
    if scan_layers is None:
        scan_layers = os.environ.get('PADDLE_TPU_SCAN_LAYERS') == '1'
    src_word = layers.data(name='src_word', shape=[src_seq_len],
                           dtype='int64')
    src_length = layers.data(name='src_length', shape=[], dtype='int64')
    trg_word = layers.data(name='trg_word', shape=[trg_seq_len],
                           dtype='int64')
    lbl_word = layers.data(name='lbl_word', shape=[trg_seq_len],
                           dtype='int64')
    lbl_weight = layers.data(name='lbl_weight', shape=[trg_seq_len],
                             dtype='float32')

    pos_table = position_encoding_table(max_length, d_model)

    enc_in = _prepare_input(src_word, src_vocab_size, d_model, max_length,
                            dropout_rate, 'src_emb', pos_table)
    x = enc_in
    if scan_layers:
        enc_params = _stacked_layer_params(
            'enc_stack', n_layer, n_head, d_key, d_value, d_model, d_inner)
        x = _layer_stack(x, enc_params, n_head, dropout_rate,
                         src_length=src_length, name='enc_stack')
    else:
        for i in range(n_layer):
            x = encoder_layer(x, n_head, d_key, d_value, d_model, d_inner,
                              dropout_rate, src_length=src_length,
                              name='enc_%d' % i)
    enc_out = x

    dec_emb_name = 'src_emb' if weight_sharing else 'trg_emb'
    dec_in = _prepare_input(trg_word, trg_vocab_size, d_model, max_length,
                            dropout_rate, dec_emb_name, pos_table)
    y = dec_in
    if scan_layers:
        dec_params = _stacked_layer_params(
            'dec_stack', n_layer, n_head, d_key, d_value, d_model, d_inner,
            decoder=True)
        y = _layer_stack(y, dec_params, n_head, dropout_rate,
                         enc_out=enc_out, src_length=src_length,
                         name='dec_stack')
    else:
        for i in range(n_layer):
            y = decoder_layer(y, enc_out, n_head, d_key, d_value, d_model,
                              d_inner, dropout_rate, src_length=src_length,
                              name='dec_%d' % i)

    logits = layers.fc(input=y, size=trg_vocab_size, num_flatten_dims=2,
                       bias_attr=False,
                       param_attr=ParamAttr(name='out_proj.w'))

    # label smoothing + softmax cross entropy, weighted by non-pad mask
    if label_smooth_eps:
        # fused: never materializes the [B, T, V] smoothed one-hot
        cost = layers.label_smoothed_cross_entropy(
            logits=logits, label=lbl_word, epsilon=label_smooth_eps)
    else:
        lbl3 = layers.unsqueeze(lbl_word, axes=[2])
        cost = layers.softmax_with_cross_entropy(logits=logits, label=lbl3)
    cost = layers.reshape(x=cost, shape=list(lbl_weight.shape))
    weighted = layers.elementwise_mul(x=cost, y=lbl_weight)
    sum_cost = layers.reduce_sum(weighted)
    token_count = layers.reduce_sum(lbl_weight)
    avg_cost = layers.elementwise_div(x=sum_cost, y=token_count)
    return avg_cost, logits


def transformer_base(src_vocab_size=32000, trg_vocab_size=32000,
                     src_seq_len=64, trg_seq_len=64, **overrides):
    """The reference "base" configuration."""
    cfg = dict(n_layer=6, n_head=8, d_key=64, d_value=64, d_model=512,
               d_inner=2048, dropout_rate=0.1, label_smooth_eps=0.1,
               src_seq_len=src_seq_len, trg_seq_len=trg_seq_len)
    cfg.update(overrides)
    return transformer(src_vocab_size, trg_vocab_size, **cfg)


def transformer_big(src_vocab_size=32000, trg_vocab_size=32000,
                    src_seq_len=64, trg_seq_len=64, **overrides):
    """The reference "big" configuration (benchmark NMT suite:
    d_model 1024, 16 heads, d_inner 4096, dropout 0.3)."""
    cfg = dict(n_layer=6, n_head=16, d_key=64, d_value=64, d_model=1024,
               d_inner=4096, dropout_rate=0.3, label_smooth_eps=0.1,
               src_seq_len=src_seq_len, trg_seq_len=trg_seq_len)
    cfg.update(overrides)
    return transformer(src_vocab_size, trg_vocab_size, **cfg)


FEED_NAMES = ['src_word', 'src_length', 'trg_word', 'lbl_word', 'lbl_weight']


def make_fake_batch(batch_size, src_seq_len, trg_seq_len, src_vocab_size,
                    trg_vocab_size, seed=0):
    """Synthetic feed dict for tests/bench (zero-egress environment)."""
    rng = np.random.RandomState(seed)
    return {
        'src_word': rng.randint(1, src_vocab_size,
                                (batch_size, src_seq_len)).astype('int64'),
        'src_length': np.full((batch_size,), src_seq_len, dtype='int64'),
        'trg_word': rng.randint(1, trg_vocab_size,
                                (batch_size, trg_seq_len)).astype('int64'),
        'lbl_word': rng.randint(1, trg_vocab_size,
                                (batch_size, trg_seq_len)).astype('int64'),
        'lbl_weight': np.ones((batch_size, trg_seq_len), dtype='float32'),
    }


_UNROLLED_PARAM_RE = re.compile(
    r'^(enc|dec)_(\d+)_(slf|cross)_(q|k|v|out)\.w$|'
    r'^(enc|dec)_(\d+)_pp(\d)_ln\.(w|b)$|'
    r'^(enc|dec)_(\d+)_ffn_(1|2)\.(w|b)$')


def _unrolled_to_stacked_name(name):
    """Map an unrolled per-layer param name ('enc_0_slf_q.w',
    'dec_3_pp1_ln.w', 'enc_1_ffn_2.b') to its stacked equivalent
    ('enc_stack_slf_q.w', layer index). Returns (None, None) for
    non-layer params (embeddings, pos tables, out_proj)."""
    m = _UNROLLED_PARAM_RE.match(name)
    if not m:
        return None, None
    if m.group(1):
        side, i = m.group(1), int(m.group(2))
        slot = '%s_%s.w' % (m.group(3),
                            'o' if m.group(4) == 'out' else m.group(4))
    elif m.group(5):
        side, i = m.group(5), int(m.group(6))
        slot = 'ln%s.%s' % (m.group(7), m.group(8))
    else:
        side, i = m.group(9), int(m.group(10))
        slot = 'ffn_%s.%s' % (m.group(11), m.group(12))
    return '%s_stack_%s' % (side, slot), i


def stack_trained_weights(scope, n_layer):
    """Convert an unrolled-trained scope in place: np.stack every
    per-layer parameter onto the stacked '[enc|dec]_stack_*' names the
    scan/incremental paths read. Non-layer params (embeddings, pos
    tables, out_proj) already share names. Returns the stacked names."""
    stacks = {}
    for name in scope.keys():
        val = scope.find(name)
        if val is None:
            continue
        sname, i = _unrolled_to_stacked_name(name)
        if sname is not None:
            if i >= n_layer:
                raise ValueError(
                    'stack_trained_weights: %r has layer index %d but '
                    'n_layer=%d' % (name, i, n_layer))
            stacks.setdefault(sname, [None] * n_layer)[i] = np.asarray(val)
    for sname, parts in stacks.items():
        missing = [i for i, p in enumerate(parts) if p is None]
        if missing:
            raise ValueError('stack_trained_weights: %r missing layers %s'
                             % (sname, missing))
        scope.set(sname, np.stack(parts, axis=0))
    return sorted(stacks)


# ---------------------------------------------------------------- inference
def _decode_prefix(prefix_ids, enc_out, src_length, cfg):
    """Run the decoder stack over a [B*, t] prefix; returns last-position
    logits [B*, V]. Parameter names match the training graph (including
    the stacked 'dec_stack_*' names when cfg['scan_layers'] is on), so a
    trained scope decodes directly."""
    dec_in = _prepare_input(prefix_ids, cfg['trg_vocab_size'],
                            cfg['d_model'], cfg['max_length'], 0.0,
                            cfg['dec_emb_name'], cfg['pos_table'])
    y = dec_in
    if cfg['scan_layers']:
        dec_params = _stacked_layer_params(
            'dec_stack', cfg['n_layer'], cfg['n_head'], cfg['d_key'],
            cfg['d_value'], cfg['d_model'], cfg['d_inner'], decoder=True)
        y = _layer_stack(y, dec_params, cfg['n_head'], 0.0,
                         enc_out=enc_out, src_length=src_length,
                         name='dec_stack')
    else:
        for i in range(cfg['n_layer']):
            y = decoder_layer(y, enc_out, cfg['n_head'], cfg['d_key'],
                              cfg['d_value'], cfg['d_model'],
                              cfg['d_inner'], 0.0, src_length=src_length,
                              name='dec_%d' % i)
    logits = layers.fc(input=y, size=cfg['trg_vocab_size'],
                       num_flatten_dims=2, bias_attr=False,
                       param_attr=ParamAttr(name='out_proj.w'))
    t = prefix_ids.shape[1]
    last = layers.slice(logits, axes=[1], starts=[t - 1], ends=[t])
    return layers.reshape(x=last, shape=[0, cfg['trg_vocab_size']])


def _infer_cfg(src_vocab_size, trg_vocab_size, max_length, n_layer, n_head,
               d_key, d_value, d_model, d_inner, weight_sharing,
               scan_layers=None):
    import os
    if scan_layers is None:
        scan_layers = os.environ.get('PADDLE_TPU_SCAN_LAYERS') == '1'
    return dict(trg_vocab_size=trg_vocab_size, d_model=d_model,
                max_length=max_length, n_layer=n_layer, n_head=n_head,
                d_key=d_key, d_value=d_value, d_inner=d_inner,
                dec_emb_name='src_emb' if weight_sharing else 'trg_emb',
                pos_table=position_encoding_table(max_length, d_model),
                scan_layers=scan_layers)


def _build_encoder(src_word, src_length, src_vocab_size, cfg):
    enc_in = _prepare_input(src_word, src_vocab_size, cfg['d_model'],
                            cfg['max_length'], 0.0, 'src_emb',
                            cfg['pos_table'])
    x = enc_in
    if cfg['scan_layers']:
        enc_params = _stacked_layer_params(
            'enc_stack', cfg['n_layer'], cfg['n_head'], cfg['d_key'],
            cfg['d_value'], cfg['d_model'], cfg['d_inner'])
        x = _layer_stack(x, enc_params, cfg['n_head'], 0.0,
                         src_length=src_length, name='enc_stack')
    else:
        for i in range(cfg['n_layer']):
            x = encoder_layer(x, cfg['n_head'], cfg['d_key'],
                              cfg['d_value'], cfg['d_model'],
                              cfg['d_inner'], 0.0,
                              src_length=src_length, name='enc_%d' % i)
    return x


def _incremental_decode_inputs(enc_out, src_length, cfg):
    """Shared inputs dict for the KV-cached decode ops: stacked decoder
    params ('dec_stack_*' — natively present for scan_layers-trained
    scopes; stack_trained_weights converts unrolled-trained ones) plus
    embedding / position / output-projection params under the training
    graph's names."""
    from ..ops.transformer_ops import _slot_to_input

    dec_params = _stacked_layer_params(
        'dec_stack', cfg['n_layer'], cfg['n_head'], cfg['d_key'],
        cfg['d_value'], cfg['d_model'], cfg['d_inner'], decoder=True)
    emb = layers.create_parameter(
        shape=[cfg['trg_vocab_size'], cfg['d_model']], dtype='float32',
        name=cfg['dec_emb_name'],
        attr=ParamAttr(name=cfg['dec_emb_name'],
                       initializer=Normal(0., cfg['d_model'] ** -0.5)))
    pos_enc = layers.create_parameter(
        shape=[cfg['max_length'], cfg['d_model']], dtype='float32',
        name=cfg['dec_emb_name'] + '_pos_enc',
        attr=ParamAttr(name=cfg['dec_emb_name'] + '_pos_enc',
                       initializer=NumpyArrayInitializer(cfg['pos_table']),
                       trainable=False))
    wout = layers.create_parameter(
        shape=[cfg['d_model'], cfg['trg_vocab_size']], dtype='float32',
        name='out_proj.w', attr=ParamAttr(name='out_proj.w'))
    inputs = {'EncOut': [enc_out], 'Emb': [emb], 'PosEnc': [pos_enc],
              'OutProj': [wout]}
    if src_length is not None:
        inputs['SrcLength'] = [src_length]
    for slot, param in dec_params.items():
        inputs[_slot_to_input(slot)] = [param]
    return inputs


def _incremental_greedy(enc_out, src_length, cfg, max_out_len, bos_id,
                        eos_id):
    """Emit the KV-cached transformer_greedy_decode op: one lax.scan
    over positions instead of max_out_len prefix re-runs."""
    from ..layers.helper import LayerHelper
    inputs = _incremental_decode_inputs(enc_out, src_length, cfg)
    helper = LayerHelper('transformer_greedy_decode', name='greedy_decode')
    out = helper.create_variable_for_type_inference('int64')
    out.shape = (enc_out.shape[0], max_out_len)
    helper.append_op(type='transformer_greedy_decode', inputs=inputs,
                     outputs={'Out': [out]},
                     attrs={'n_head': cfg['n_head'],
                            'max_out_len': max_out_len,
                            'bos_id': bos_id, 'eos_id': eos_id})
    return out


def _incremental_beam(enc_out, src_length, cfg, beam_size, max_out_len,
                      bos_id, eos_id):
    """Emit the KV-cached transformer_beam_decode op (one lax.scan;
    caches reordered by parent index each step)."""
    from ..layers.helper import LayerHelper
    inputs = _incremental_decode_inputs(enc_out, src_length, cfg)
    helper = LayerHelper('transformer_beam_decode', name='beam_decode')
    sent = helper.create_variable_for_type_inference('int64')
    sent.shape = (enc_out.shape[0], beam_size, max_out_len - 1)
    scores = helper.create_variable_for_type_inference('float32')
    scores.shape = (enc_out.shape[0], beam_size)
    helper.append_op(type='transformer_beam_decode', inputs=inputs,
                     outputs={'SentenceIds': [sent],
                              'SentenceScores': [scores]},
                     attrs={'n_head': cfg['n_head'],
                            'max_out_len': max_out_len,
                            'beam_size': beam_size,
                            'bos_id': bos_id, 'eos_id': eos_id})
    return sent, scores


def transformer_greedy_infer(src_vocab_size, trg_vocab_size,
                             max_out_len=16, bos_id=0, eos_id=1,
                             src_seq_len=16, max_length=256, n_layer=6,
                             n_head=8, d_key=64, d_value=64, d_model=512,
                             d_inner=2048, weight_sharing=False,
                             scan_layers=None, incremental=False):
    """Greedy decode. incremental=True (TPU-native default path for long
    outputs) uses the KV-cached transformer_greedy_decode op — one
    lax.scan over positions, O(T) compute, flat compile time; decoder
    weights are read in the stacked layout (stack_trained_weights
    converts an unrolled-trained scope). incremental=False unrolls one
    decoder re-run per position (static shapes per step, one XLA
    program; the shape the reference's While-based infer program takes).
    Feeds: src_word [B, S], src_length [B]. Returns out_ids [B, T]."""
    cfg = _infer_cfg(src_vocab_size, trg_vocab_size, max_length, n_layer,
                     n_head, d_key, d_value, d_model, d_inner,
                     weight_sharing, scan_layers)
    src_word = layers.data(name='src_word', shape=[src_seq_len],
                           dtype='int64')
    src_length = layers.data(name='src_length', shape=[], dtype='int64')
    enc_out = _build_encoder(src_word, src_length, src_vocab_size, cfg)
    if incremental:
        ids = _incremental_greedy(enc_out, src_length, cfg, max_out_len,
                                  bos_id, eos_id)
        return ids, ['src_word', 'src_length']

    bos = layers.fill_constant_batch_size_like(
        src_word, shape=[1, 1], dtype='int64', value=bos_id)
    ids = bos
    for _t in range(1, max_out_len):
        logits = _decode_prefix(ids, enc_out, src_length, cfg)
        nxt = layers.argmax(logits, axis=-1)
        nxt = layers.reshape(x=nxt, shape=[0, 1])
        ids = layers.concat([ids, layers.cast(nxt, 'int64')], axis=1)
    # freeze everything after the first EOS to EOS (the beam path gets
    # this from beam_search_decode; greedy does it arithmetically)
    eos = layers.fill_constant_batch_size_like(
        ids, shape=[1, max_out_len], dtype='int64', value=eos_id)
    is_eos = layers.cast(layers.equal(x=ids, y=eos), 'int64')
    before = layers.elementwise_sub(
        x=layers.cumsum(is_eos, axis=1), y=is_eos)   # eos count before t
    zeros = layers.fill_constant_batch_size_like(
        ids, shape=[1, max_out_len], dtype='int64', value=0)
    after = layers.cast(layers.less_than(x=zeros, y=before), 'int64')
    keep = layers.elementwise_sub(
        x=layers.fill_constant_batch_size_like(
            ids, shape=[1, max_out_len], dtype='int64', value=1),
        y=after)
    ids = layers.elementwise_add(
        x=layers.elementwise_mul(x=ids, y=keep),
        y=layers.elementwise_mul(x=eos, y=after))
    return ids, ['src_word', 'src_length']


def transformer_beam_infer(src_vocab_size, trg_vocab_size, beam_size=4,
                           max_out_len=16, bos_id=0, eos_id=1,
                           src_seq_len=16, max_length=256, n_layer=6,
                           n_head=8, d_key=64, d_value=64, d_model=512,
                           d_inner=2048, weight_sharing=False,
                           scan_layers=None, incremental=False):
    """Beam-search decode. incremental=False unrolls one decoder re-run
    per position over the beam_search/beam_gather/beam_search_decode
    ops; incremental=True emits the KV-cached transformer_beam_decode
    op (one lax.scan, caches reordered by parent — same sequences, O(T)
    compute). Returns (sentence_ids [B, beam, T], sentence_scores
    [B, beam])."""
    cfg = _infer_cfg(src_vocab_size, trg_vocab_size, max_length, n_layer,
                     n_head, d_key, d_value, d_model, d_inner,
                     weight_sharing, scan_layers)
    src_word = layers.data(name='src_word', shape=[src_seq_len],
                           dtype='int64')
    src_length = layers.data(name='src_length', shape=[], dtype='int64')
    enc_out = _build_encoder(src_word, src_length, src_vocab_size, cfg)
    if incremental:
        out = _incremental_beam(enc_out, src_length, cfg, beam_size,
                                max_out_len, bos_id, eos_id)
        return out, ['src_word', 'src_length']

    # tile encoder state over the beam: [B, S, D] -> [B*beam, S, D]
    enc_beam = layers.expand(layers.unsqueeze(enc_out, axes=[1]),
                             expand_times=[1, beam_size, 1, 1])
    enc_beam = layers.reshape(x=enc_beam, shape=[-1] +
                              [enc_out.shape[1], enc_out.shape[2]])
    len_beam = layers.expand(layers.unsqueeze(src_length, axes=[1]),
                             expand_times=[1, beam_size])
    len_beam = layers.reshape(x=len_beam, shape=[-1])

    bos = layers.fill_constant_batch_size_like(
        enc_beam, shape=[1, 1], dtype='int64', value=bos_id)
    prefix = bos                                   # [B*beam, t]
    pre_ids = layers.fill_constant_batch_size_like(
        src_word, shape=[1, beam_size], dtype='int64', value=bos_id)
    # only slot 0 live at t=0 (all beams identical otherwise): bias is
    # (one_hot(0) - 1) * 1e9 = [0, -1e9, ...] broadcast over the batch
    slot0 = layers.fill_constant(shape=[1, 1], dtype='int64', value=0)
    oh = layers.reshape(x=layers.one_hot(slot0, depth=beam_size),
                        shape=[1, beam_size])
    init_bias = layers.scale(oh, scale=1e9, bias=-1e9)
    ones = layers.fill_constant_batch_size_like(
        src_word, shape=[1, beam_size], dtype='float32', value=1.0)
    pre_scores = layers.elementwise_mul(x=ones, y=init_bias, axis=-1)

    step_ids, step_parents = [], []
    for _t in range(1, max_out_len):
        logits = _decode_prefix(prefix, enc_beam, len_beam, cfg)
        logp = layers.log_softmax(logits)          # [B*beam, V]
        top_scores, top_ids = layers.topk(logp, k=beam_size)
        cand_ids = layers.reshape(x=layers.cast(top_ids, 'int64'),
                                  shape=[-1, beam_size, beam_size])
        cand_scores = layers.reshape(x=top_scores,
                                     shape=[-1, beam_size, beam_size])
        sel_ids, sel_scores, parent = layers.beam_search(
            pre_ids, pre_scores, cand_ids, cand_scores,
            beam_size=beam_size, end_id=eos_id)
        # realign prefixes to the selected parents and append new token
        prefix_b = layers.reshape(x=prefix, shape=[-1, beam_size,
                                                   prefix.shape[1]])
        prefix_b = layers.beam_gather(prefix_b, parent)
        prefix = layers.reshape(x=prefix_b,
                                shape=[-1, prefix.shape[1]])
        nxt = layers.reshape(x=sel_ids, shape=[-1, 1])
        prefix = layers.concat([prefix, nxt], axis=1)
        pre_ids, pre_scores = sel_ids, sel_scores
        step_ids.append(sel_ids)
        step_parents.append(parent)

    stacked_ids = layers.stack(step_ids, axis=0)       # [T-1, B, beam]
    stacked_parents = layers.stack(step_parents, axis=0)
    sent, sent_scores = layers.beam_search_decode(
        stacked_ids, stacked_parents, final_scores=pre_scores,
        end_id=eos_id)
    return (sent, sent_scores), ['src_word', 'src_length']
