"""Personalized recommendation: dual-tower embedding model on MovieLens.

Reference: the recommender_system book chapter — user tower (id, gender,
age, job embeddings -> fc) and movie tower (id, category, title
embeddings -> fc), cosine similarity scaled to the rating range,
regressed against the observed score. Feeds the dataset schema of
paddle_tpu.dataset.movielens. TPU-first: every tower is dense
embedding-gather + fc (MXU), one fused step.
"""

from .. import layers


def _tower(ids_and_sizes, emb_dim, out_dim, name):
    feats = []
    for i, (var, vocab) in enumerate(ids_and_sizes):
        feats.append(layers.embedding(
            input=var, size=[vocab, emb_dim], dtype='float32',
            param_attr='%s_emb_%d' % (name, i)))
    hidden = layers.fc(input=layers.concat(feats, axis=1)
                       if len(feats) > 1 else feats[0],
                       size=out_dim, act='tanh',
                       param_attr='%s_fc.w' % name)
    return hidden


def recommender(user_vocab=944, gender_vocab=2, age_vocab=7,
                job_vocab=21, movie_vocab=1683, category_vocab=19,
                emb_dim=32, fc_dim=200, max_rating=5.0):
    """Returns (predicted_score, avg_cost). Feeds (all [B, 1] int64
    except score): uid, gender, age, job, mov_id, category, score
    [B, 1] float32."""
    uid = layers.data(name='uid', shape=[1], dtype='int64')
    gender = layers.data(name='gender', shape=[1], dtype='int64')
    age = layers.data(name='age', shape=[1], dtype='int64')
    job = layers.data(name='job', shape=[1], dtype='int64')
    mov_id = layers.data(name='mov_id', shape=[1], dtype='int64')
    category = layers.data(name='category', shape=[1], dtype='int64')
    score = layers.data(name='score', shape=[1], dtype='float32')

    usr = _tower([(uid, user_vocab), (gender, gender_vocab),
                  (age, age_vocab), (job, job_vocab)],
                 emb_dim, fc_dim, 'usr')
    mov = _tower([(mov_id, movie_vocab), (category, category_vocab)],
                 emb_dim, fc_dim, 'mov')

    sim = layers.cos_sim(X=usr, Y=mov)
    pred = layers.scale(sim, scale=max_rating)
    cost = layers.square_error_cost(input=pred, label=score)
    avg_cost = layers.mean(cost)
    return pred, avg_cost
