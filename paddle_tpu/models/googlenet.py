"""GoogLeNet / Inception-v1 (reference: benchmark/paddle/image/
googlenet.py — the v1 trainer-config net: stem, nine inception modules
across three stages, global average pool, single 1000-way classifier;
the benchmark config disables the paper's two auxiliary heads, and so
does this build).

TPU notes: each inception module is four parallel conv towers
concatenated on the channel axis — all four are independent MXU work
XLA schedules from one fused graph. Math and topology match the
reference config (filter counts straight from the benchmark file).
"""

from .. import layers


def inception(input, filter1, filter3r, filter3, filter5r, filter5, proj,
              name=None):
    """One inception module (googlenet.py inception2): 1x1, 1x1->3x3,
    1x1->5x5, and 3x3maxpool->1x1proj towers, channel-concatenated."""
    tower1 = layers.conv2d(input, num_filters=filter1, filter_size=1,
                           act='relu')
    tower3r = layers.conv2d(input, num_filters=filter3r, filter_size=1,
                            act='relu')
    tower3 = layers.conv2d(tower3r, num_filters=filter3, filter_size=3,
                           padding=1, act='relu')
    tower5r = layers.conv2d(input, num_filters=filter5r, filter_size=1,
                            act='relu')
    tower5 = layers.conv2d(tower5r, num_filters=filter5, filter_size=5,
                           padding=2, act='relu')
    towerp = layers.pool2d(input, pool_size=3, pool_stride=1,
                           pool_padding=1)
    towerproj = layers.conv2d(towerp, num_filters=proj, filter_size=1,
                              act='relu')
    return layers.concat([tower1, tower3, tower5, towerproj], axis=1)


def googlenet(input, class_dim=1000, is_test=False):
    """benchmark/paddle/image/googlenet.py topology; aux heads off."""
    # stem: conv7/2 - pool - conv1 - conv3 - pool
    conv1 = layers.conv2d(input, num_filters=64, filter_size=7, stride=2,
                          padding=3, act='relu')
    pool1 = layers.pool2d(conv1, pool_size=3, pool_stride=2)
    conv2r = layers.conv2d(pool1, num_filters=64, filter_size=1, act='relu')
    conv2 = layers.conv2d(conv2r, num_filters=192, filter_size=3, padding=1,
                          act='relu')
    pool2 = layers.pool2d(conv2, pool_size=3, pool_stride=2)

    ince3a = inception(pool2, 64, 96, 128, 16, 32, 32)
    ince3b = inception(ince3a, 128, 128, 192, 32, 96, 64)
    pool3 = layers.pool2d(ince3b, pool_size=3, pool_stride=2)

    ince4a = inception(pool3, 192, 96, 208, 16, 48, 64)
    ince4b = inception(ince4a, 160, 112, 224, 24, 64, 64)
    ince4c = inception(ince4b, 128, 128, 256, 24, 64, 64)
    ince4d = inception(ince4c, 112, 144, 288, 32, 64, 64)
    ince4e = inception(ince4d, 256, 160, 320, 32, 128, 128)
    pool4 = layers.pool2d(ince4e, pool_size=3, pool_stride=2)

    ince5a = inception(pool4, 256, 160, 320, 32, 128, 128)
    ince5b = inception(ince5a, 384, 192, 384, 48, 128, 128)

    pool5 = layers.pool2d(ince5b, pool_type='avg', global_pooling=True)
    drop = layers.dropout(pool5, dropout_prob=0.4, is_test=is_test)
    return layers.fc(input=drop, size=class_dim, act='softmax')
