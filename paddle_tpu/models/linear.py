"""fit_a_line: linear regression on UCI housing (the reference's first book
chapter and smallest end-to-end config)."""

from .. import layers


def fit_a_line(x=None, y=None, feature_dim=13):
    """Build y_hat = xW + b with MSE loss. Returns (prediction, avg_loss)."""
    if x is None:
        x = layers.data(name='x', shape=[feature_dim], dtype='float32')
    if y is None:
        y = layers.data(name='y', shape=[1], dtype='float32')
    y_predict = layers.fc(input=x, size=1, act=None)
    cost = layers.square_error_cost(input=y_predict, label=y)
    avg_cost = layers.mean(cost)
    return y_predict, avg_cost
