"""Switch-Transformer language model: causal self-attention blocks with
mixture-of-experts FFNs (layers.switch_moe).

No reference analog (the reference predates MoE); this is the flagship
exercise of the mesh's expert-parallel 'ep' axis — expert weights shard
E/ep per chip and the router's dispatch/combine einsums ride ICI. Pair
with parallel.transpile on a mesh with ep > 1 (tests/test_moe.py;
__graft_entry__.dryrun_multichip runs one ep-sharded step).
"""

import re

import numpy as np

from .. import layers
from ..initializer import Normal, NumpyArrayInitializer
from ..param_attr import ParamAttr
from .transformer import _multi_head_attention, position_encoding_table

_UNROLLED_MOE_RE = re.compile(
    r'^moe_(\d+)_(slf_(?:q|k|v)|slf_out)\.w$|'
    r'^moe_(\d+)_ln(\d)\.(w|b)$|'
    r'^moe_(\d+)_exp_(gate\.w|1\.w|1\.b|2\.w|2\.b)$')


def _unrolled_to_moe_stacked_name(name):
    """Map an unrolled MoE-block param name ('moe_0_slf_q.w',
    'moe_1_exp_1.w', ...) to (stacked 'moe_stack_*' name, layer index);
    (None, None) for non-layer params (embeddings, pos table, out)."""
    m = _UNROLLED_MOE_RE.match(name)
    if not m:
        return None, None
    if m.group(1):
        slot = m.group(2).replace('slf_out', 'slf_o') + '.w'
        return 'moe_stack_%s' % slot, int(m.group(1))
    if m.group(3):
        return 'moe_stack_ln%s.%s' % (m.group(4), m.group(5)), \
            int(m.group(3))
    return 'moe_stack_%s' % m.group(7), int(m.group(6))


def stack_moe_trained_weights(scope, n_layer):
    """Convert an unrolled-trained switch_transformer_lm scope in place
    to the stacked 'moe_stack_*' layout the scan_layers=True graph
    reads (the MoE analog of transformer.stack_trained_weights).
    Returns the stacked names.

    To CONTINUE TRAINING under the scan graph (not just infer): build
    the scan program, run its startup (fresh stacked params + optimizer
    accumulators), restore the trained shared-name weights, then call
    this — optimizer state restarts cold for the migrated layout."""
    stacks = {}
    for name in scope.keys():
        val = scope.find(name)
        if val is None:
            continue
        sname, i = _unrolled_to_moe_stacked_name(name)
        if sname is not None:
            if i >= n_layer:
                raise ValueError(
                    'stack_moe_trained_weights: %r has layer index %d '
                    'but n_layer=%d' % (name, i, n_layer))
            stacks.setdefault(sname, [None] * n_layer)[i] = \
                np.asarray(val)
    for sname, parts in stacks.items():
        missing = [i for i, p in enumerate(parts) if p is None]
        if missing:
            raise ValueError('stack_moe_trained_weights: %r missing '
                             'layers %s' % (sname, missing))
        scope.set(sname, np.stack(parts, axis=0))
    return sorted(stacks)


def _stacked_moe_params(n_layer, n_head, d_model, d_inner, num_experts):
    """[n_layer, ...] stacked weights for the moe_layer_stack op
    (ops/transformer_ops.py MOE_SLOTS layout); expert weights stack
    [n_layer, E, ...] and mark expert_shard_axis=1 so the transpiler
    shards the EXPERT axis (not the layer axis) over 'ep'."""
    from .transformer import _stack_param
    L, E = n_layer, num_experts
    hd = (d_model // n_head) * n_head  # == unrolled d_head * n_head
    p = {
        'slf_q': _stack_param('moe_stack_slf_q.w', [L, d_model, hd],
                              d_model, hd),
        'slf_k': _stack_param('moe_stack_slf_k.w', [L, d_model, hd],
                              d_model, hd),
        'slf_v': _stack_param('moe_stack_slf_v.w', [L, d_model, hd],
                              d_model, hd),
        'slf_o': _stack_param('moe_stack_slf_o.w', [L, hd, d_model],
                              hd, d_model),
        'ln1_w': _stack_param('moe_stack_ln1.w', [L, d_model], 0, 0,
                              constant=1.0),
        'ln1_b': _stack_param('moe_stack_ln1.b', [L, d_model], 0, 0,
                              constant=0.0),
        'gate_w': _stack_param('moe_stack_gate.w',
                               [L, d_model, E], d_model, E),
        'moe_w1': _stack_param('moe_stack_1.w',
                               [L, E, d_model, d_inner], d_model,
                               d_inner),
        'moe_b1': _stack_param('moe_stack_1.b', [L, E, d_inner], 0, 0,
                               constant=0.0),
        'moe_w2': _stack_param('moe_stack_2.w',
                               [L, E, d_inner, d_model], d_inner,
                               d_model),
        'moe_b2': _stack_param('moe_stack_2.b', [L, E, d_model], 0, 0,
                               constant=0.0),
        'ln2_w': _stack_param('moe_stack_ln2.w', [L, d_model], 0, 0,
                              constant=1.0),
        'ln2_b': _stack_param('moe_stack_ln2.b', [L, d_model], 0, 0,
                              constant=0.0),
    }
    for slot in ('moe_w1', 'moe_b1', 'moe_w2', 'moe_b2'):
        p[slot].expert_shard = True
        p[slot].expert_shard_axis = 1
    return p


def _moe_stack(x, params, n_head, dropout_rate, capacity_factor, top_k):
    from ..layers.helper import LayerHelper
    from ..ops.transformer_ops import _slot_to_input
    helper = LayerHelper('moe_layer_stack', name='moe_stack')
    out = helper.create_variable_for_type_inference(x.dtype)
    out.shape = x.shape
    aux = helper.create_variable_for_type_inference('float32')
    aux.shape = ()
    inputs = {'X': [x]}
    for slot, param in params.items():
        inputs[_slot_to_input(slot)] = [param]
    helper.append_op(type='moe_layer_stack', inputs=inputs,
                     outputs={'Out': [out], 'AuxLoss': [aux]},
                     attrs={'n_head': n_head,
                            'dropout_rate': dropout_rate,
                            'capacity_factor': capacity_factor,
                            'top_k': top_k})
    return out, aux


def switch_transformer_lm(vocab_size, seq_len, n_layer=2, n_head=4,
                          d_model=64, d_inner=128, num_experts=4,
                          capacity_factor=1.25, top_k=1, aux_weight=1e-2,
                          dropout_rate=0.0, max_length=512,
                          scan_layers=False):
    """Causal LM: feeds word [B, T] int64 and label [B, T] int64;
    returns (avg_cost, logits). Every block: causal fused attention ->
    residual+LN -> Switch-MoE FFN -> residual+LN; the MoE aux losses are
    added to the CE at `aux_weight` (Switch Transformer's 1e-2).
    scan_layers=True compiles the n_layer blocks as ONE lax.scan over
    stacked weights (moe_layer_stack op) — flat compile time over
    depth, expert sharding intact."""
    if not 1 <= top_k <= num_experts:
        raise ValueError('switch_transformer_lm: top_k=%d must be in '
                         '[1, num_experts=%d]' % (top_k, num_experts))
    word = layers.data(name='word', shape=[seq_len], dtype='int64')
    label = layers.data(name='label', shape=[seq_len], dtype='int64')

    emb = layers.embedding(
        input=word, size=[vocab_size, d_model], dtype='float32',
        param_attr=ParamAttr(name='moe_emb',
                             initializer=Normal(0., d_model ** -0.5)))
    pos = layers.create_parameter(
        shape=[max_length, d_model], dtype='float32', name='moe_pos_enc',
        attr=ParamAttr(name='moe_pos_enc',
                       initializer=NumpyArrayInitializer(
                           position_encoding_table(max_length, d_model)),
                       trainable=False))
    pos_slice = layers.reshape(
        x=layers.slice(pos, axes=[0], starts=[0], ends=[seq_len]),
        shape=[1, seq_len, d_model])
    x = layers.elementwise_add(x=emb, y=pos_slice)

    aux_losses = []
    if scan_layers:
        params = _stacked_moe_params(n_layer, n_head, d_model, d_inner,
                                     num_experts)
        x, aux = _moe_stack(x, params, n_head, dropout_rate,
                            capacity_factor, top_k)
        aux_losses.append(aux)
    for i in range(0 if scan_layers else n_layer):
        d_head = d_model // n_head
        proj = _multi_head_attention(
            x, x, x, d_head, d_head, d_model, n_head, dropout_rate,
            causal=True, name='moe_%d_slf' % i)
        x = layers.layer_norm(
            layers.elementwise_add(x=x, y=proj),
            begin_norm_axis=2,
            param_attr=ParamAttr(name='moe_%d_ln1.w' % i),
            bias_attr=ParamAttr(name='moe_%d_ln1.b' % i))
        ffn, aux = layers.switch_moe(
            x, num_experts=num_experts, d_inner=d_inner,
            capacity_factor=capacity_factor, top_k=top_k,
            param_attr=ParamAttr(name='moe_%d_exp' % i))
        aux_losses.append(aux)
        x = layers.layer_norm(
            layers.elementwise_add(x=x, y=ffn),
            begin_norm_axis=2,
            param_attr=ParamAttr(name='moe_%d_ln2.w' % i),
            bias_attr=ParamAttr(name='moe_%d_ln2.b' % i))

    logits = layers.fc(input=x, size=vocab_size, num_flatten_dims=2,
                       bias_attr=False,
                       param_attr=ParamAttr(name='moe_out.w'))
    lbl3 = layers.unsqueeze(label, axes=[2])
    ce = layers.softmax_with_cross_entropy(logits=logits, label=lbl3)
    avg_cost = layers.mean(ce)
    for aux in aux_losses:
        avg_cost = layers.elementwise_add(
            x=avg_cost, y=layers.scale(aux, scale=aux_weight))
    return avg_cost, logits
