"""Sequence models (reference: understand_sentiment + label_semantic_roles
book chapters): conv sentiment net, stacked bi-LSTM sentiment net, and a
stacked-GRU sequence tagger skeleton.

Sequences are padded [B, T] int64 id arrays with a `length` Variable for
mask-aware pooling/recurrence (the TPU replacement for LoD)."""

from .. import layers, nets


def convolution_net(data, label, input_dim, class_dim=2, emb_dim=32,
                    hid_dim=32, length=None):
    """Sentiment conv net: embedding -> two sequence_conv_pools -> softmax."""
    emb = layers.embedding(input=data, size=[input_dim, emb_dim],
                           dtype='float32')
    conv_3 = nets.sequence_conv_pool(input=emb, num_filters=hid_dim,
                                     filter_size=3, act='tanh',
                                     pool_type='sqrt', length=length)
    conv_4 = nets.sequence_conv_pool(input=emb, num_filters=hid_dim,
                                     filter_size=4, act='tanh',
                                     pool_type='sqrt', length=length)
    prediction = layers.fc(input=[conv_3, conv_4], size=class_dim,
                           act='softmax')
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=prediction, label=label)
    return prediction, avg_cost, acc


def stacked_lstm_net(data, label, input_dim, class_dim=2, emb_dim=128,
                     hid_dim=512, stacked_num=3, length=None):
    """Stacked alternating-direction LSTM sentiment net (book chapter 06)."""
    assert stacked_num % 2 == 1
    emb = layers.embedding(input=data, size=[input_dim, emb_dim],
                           dtype='float32')
    fc1 = layers.fc(input=emb, size=hid_dim, num_flatten_dims=2)
    lstm1, cell1 = layers.dynamic_lstm(input=fc1, size=hid_dim,
                                       length=length)
    inputs = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = layers.fc(input=inputs, size=hid_dim, num_flatten_dims=2)
        lstm, cell = layers.dynamic_lstm(input=fc, size=hid_dim,
                                         is_reverse=(i % 2) == 0,
                                         length=length)
        inputs = [fc, lstm]
    fc_last = layers.sequence_pool(input=inputs[0], pool_type='max',
                                   length=length)
    lstm_last = layers.sequence_pool(input=inputs[1], pool_type='max',
                                     length=length)
    prediction = layers.fc(input=[fc_last, lstm_last], size=class_dim,
                           act='softmax')
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=prediction, label=label)
    return prediction, avg_cost, acc


def stacked_gru_tagger(word, target, word_dict_len, label_dict_len,
                       emb_dim=32, hidden_dim=128, depth=2, length=None):
    """Simplified SRL-style tagger: embedding -> stacked bi-GRU -> per-step
    softmax over labels (reference label_semantic_roles chapter uses an
    8-feature crf net; the CRF decode layer lives in layers/decode.py)."""
    emb = layers.embedding(input=word, size=[word_dict_len, emb_dim],
                           dtype='float32')
    hidden = layers.fc(input=emb, size=hidden_dim * 3, num_flatten_dims=2)
    for i in range(depth):
        gru = layers.dynamic_gru(input=hidden, size=hidden_dim,
                                 is_reverse=(i % 2) == 1, length=length)
        hidden = layers.fc(input=gru, size=hidden_dim * 3,
                           num_flatten_dims=2)
    feature = layers.fc(input=hidden, size=label_dict_len,
                        num_flatten_dims=2, act=None)
    # per-step cross entropy over the padded grid, masked by length
    probs = layers.softmax(feature)
    cost = layers.cross_entropy(input=probs, label=target)
    avg_cost = layers.mean(cost)
    return feature, avg_cost
