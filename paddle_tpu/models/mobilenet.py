"""MobileNet v1 (reference: the image-classification model suite's
depthwise-separable net). Depthwise convs lower to
`lax.conv_general_dilated(feature_group_count=C)`, which XLA maps to TPU
depthwise convolutions directly."""

from .. import layers


def conv_bn(input, filter_size, num_filters, stride, padding, num_groups=1,
            act='relu', is_test=False, data_format='NCHW'):
    conv = layers.conv2d(input=input, num_filters=num_filters,
                         filter_size=filter_size, stride=stride,
                         padding=padding, groups=num_groups, act=None,
                         bias_attr=False, data_format=data_format)
    return layers.batch_norm(input=conv, act=act, is_test=is_test,
                             data_layout=data_format)


def depthwise_separable(input, num_filters1, num_filters2, num_groups,
                        stride, scale, is_test=False, data_format='NCHW'):
    depthwise = conv_bn(input=input, filter_size=3,
                        num_filters=int(num_filters1 * scale), stride=stride,
                        padding=1, num_groups=int(num_groups * scale),
                        is_test=is_test, data_format=data_format)
    pointwise = conv_bn(input=depthwise, filter_size=1,
                        num_filters=int(num_filters2 * scale), stride=1,
                        padding=0, is_test=is_test, data_format=data_format)
    return pointwise


def mobile_net(img, class_dim=1000, scale=1.0, is_test=False,
               data_format='NCHW'):
    if data_format == 'NHWC':
        img = layers.transpose(img, [0, 2, 3, 1])
    # conv1: 3x3 s2
    tmp = conv_bn(img, 3, int(32 * scale), 2, 1, is_test=is_test,
                  data_format=data_format)
    # (in, out, groups, stride) per depthwise-separable stage
    cfg = [(32, 64, 32, 1), (64, 128, 64, 2), (128, 128, 128, 1),
           (128, 256, 128, 2), (256, 256, 256, 1), (256, 512, 256, 2),
           (512, 512, 512, 1), (512, 512, 512, 1), (512, 512, 512, 1),
           (512, 512, 512, 1), (512, 512, 512, 1), (512, 1024, 512, 2),
           (1024, 1024, 1024, 1)]
    for f1, f2, g, s in cfg:
        tmp = depthwise_separable(tmp, f1, f2, g, s, scale, is_test=is_test,
                                  data_format=data_format)
    pool = layers.pool2d(input=tmp, pool_type='avg', global_pooling=True,
                         data_format=data_format)
    out = layers.fc(input=pool, size=class_dim, act='softmax')
    return out


def mobilenet_with_loss(input=None, label=None, class_dim=1000,
                        image_shape=(3, 224, 224), is_test=False,
                        data_format='NCHW'):
    if input is None:
        input = layers.data(name='image', shape=list(image_shape),
                            dtype='float32')
    if label is None:
        label = layers.data(name='label', shape=[1], dtype='int64')
    predict = mobile_net(input, class_dim=class_dim, is_test=is_test,
                         data_format=data_format)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return predict, avg_cost, acc
