"""Wide&Deep CTR model (reference: the ctr demo — wide logistic part over
sparse crosses + deep MLP over embeddings; BASELINE config 5).

TPU-native: the reference trains this against a parameter server with
sparse row updates (paddle/pserver). Here the is_sparse tables get both
halves of that role: capacity — the table row-shards over the mesh when
transpiled (lookup partitioned by GSPMD) — and update cost — under
SGD/Adagrad the gradient is the O(batch x dim) row stack scattered in
place (core/backward.py sparse_grads), never an O(vocab) dense grad.
The whole step is one XLA program; the dp-axis grad psum plays the
pserver's role (SURVEY.md §2.4).
"""

from .. import layers
from ..param_attr import ParamAttr


def wide_deep_net(sparse_ids, dense_feat, label, vocab_sizes,
                  embed_size=16, hidden_sizes=(64, 32), is_test=False):
    """sparse_ids: list of int64 id Variables (one per slot);
    dense_feat: float dense features [B, D]; label: int64 [B, 1]."""
    # ---- deep part: per-slot embeddings -> MLP
    embs = []
    for i, (ids, vocab) in enumerate(zip(sparse_ids, vocab_sizes)):
        embs.append(layers.embedding(
            input=ids, size=[vocab, embed_size], dtype='float32',
            is_sparse=True,  # CTR-scale: row-shard the table over the mesh
            param_attr=ParamAttr(name='emb_slot_%d' % i)))
    deep = layers.concat(input=embs + [dense_feat], axis=-1)
    for i, h in enumerate(hidden_sizes):
        deep = layers.fc(input=deep, size=h, act='relu')

    # ---- wide part: one weight per id (linear over the sparse slots)
    wides = []
    for i, (ids, vocab) in enumerate(zip(sparse_ids, vocab_sizes)):
        wides.append(layers.embedding(
            input=ids, size=[vocab, 1], dtype='float32', is_sparse=True,
            param_attr=ParamAttr(name='wide_slot_%d' % i)))
    wide = layers.concat(input=wides + [dense_feat], axis=-1)

    merged = layers.concat(input=[wide, deep], axis=-1)
    predict = layers.fc(input=merged, size=2, act='softmax')
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return predict, avg_cost, acc


def build(num_slots=8, vocab_size=1000, dense_dim=13, embed_size=16):
    """Standard CTR layout: `num_slots` sparse slots + dense features."""
    sparse_ids = [layers.data(name='C%d' % i, shape=[1], dtype='int64')
                  for i in range(num_slots)]
    dense = layers.data(name='dense', shape=[dense_dim], dtype='float32')
    label = layers.data(name='label', shape=[1], dtype='int64')
    vocab_sizes = [vocab_size] * num_slots
    predict, avg_cost, acc = wide_deep_net(sparse_ids, dense, label,
                                           vocab_sizes, embed_size)
    feeds = ['C%d' % i for i in range(num_slots)] + ['dense', 'label']
    return predict, avg_cost, acc, feeds
