"""Model zoo matching the reference's benchmark configs and book chapters
(SURVEY.md §2.6): benchmark/paddle image classification suite
(ResNet/VGG/SE-ResNeXt/MobileNet), recognize_digits LeNet, fit_a_line,
Transformer NMT, Wide&Deep CTR, word2vec, LSTM sentiment models.

Every builder is pure front-end: it appends ops to the default (or given)
Program; the Executor compiles the whole model — forward, backward,
optimizer — into one XLA computation.
"""

from . import linear  # noqa: F401
from . import lenet  # noqa: F401
from . import vgg  # noqa: F401
from . import alexnet  # noqa: F401
from . import googlenet  # noqa: F401
from . import resnet  # noqa: F401
from . import mobilenet  # noqa: F401
from . import resnext  # noqa: F401
from . import word2vec  # noqa: F401
from . import wide_deep  # noqa: F401
from . import seq_models  # noqa: F401
from . import rnn_search  # noqa: F401
from . import transformer  # noqa: F401
