"""MNIST digit recognition nets (reference: recognize_digits book chapter:
softmax regression, MLP, LeNet-5-style convnet)."""

from .. import layers, nets


def softmax_regression(img=None, label=None):
    if img is None:
        img = layers.data(name='img', shape=[1, 28, 28], dtype='float32')
    if label is None:
        label = layers.data(name='label', shape=[1], dtype='int64')
    predict = layers.fc(input=img, size=10, act='softmax',
                        num_flatten_dims=1)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return predict, avg_cost, acc


def multilayer_perceptron(img=None, label=None):
    if img is None:
        img = layers.data(name='img', shape=[1, 28, 28], dtype='float32')
    if label is None:
        label = layers.data(name='label', shape=[1], dtype='int64')
    hidden = layers.fc(input=img, size=128, act='relu')
    hidden = layers.fc(input=hidden, size=64, act='relu')
    predict = layers.fc(input=hidden, size=10, act='softmax')
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return predict, avg_cost, acc


def convolutional_neural_network(img=None, label=None):
    """LeNet-5 style conv-pool x2 + fc, as in the reference chapter."""
    if img is None:
        img = layers.data(name='img', shape=[1, 28, 28], dtype='float32')
    if label is None:
        label = layers.data(name='label', shape=[1], dtype='int64')
    conv_pool_1 = nets.simple_img_conv_pool(
        input=img, filter_size=5, num_filters=20, pool_size=2,
        pool_stride=2, act='relu')
    conv_pool_2 = nets.simple_img_conv_pool(
        input=conv_pool_1, filter_size=5, num_filters=50, pool_size=2,
        pool_stride=2, act='relu')
    predict = layers.fc(input=conv_pool_2, size=10, act='softmax')
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return predict, avg_cost, acc


lenet = convolutional_neural_network
