"""SSD object detector (reference: the SSD config built on
fluid/layers/detection.py — MobileNet-SSD style, shrunk feature pyramid).

The backbone is a small conv stack; heads come from
layers.multi_box_head; training uses layers.ssd_loss (bipartite match +
hard negative mining); inference uses layers.detection_output
(decode + multiclass NMS) — all static-shape TPU ops.
"""

from .. import layers


def _conv_block(x, filters, stride):
    c = layers.conv2d(input=x, num_filters=filters, filter_size=3,
                      stride=stride, padding=1, act=None, bias_attr=False)
    return layers.batch_norm(input=c, act='relu')


def ssd_net(image, num_classes=21, image_shape=(3, 128, 128)):
    """Builds the backbone + multibox head. Returns
    (locs [B,N,4], confs [B,N,C], prior_boxes [N,4], prior_vars [N,4])."""
    f = _conv_block(image, 16, 2)      # /2
    f = _conv_block(f, 32, 2)          # /4
    f1 = _conv_block(f, 64, 2)         # /8
    f2 = _conv_block(f1, 128, 2)       # /16
    f3 = _conv_block(f2, 128, 2)       # /32
    s = image_shape[1]
    locs, confs, boxes, vars_ = layers.multi_box_head(
        inputs=[f1, f2, f3], image=image, num_classes=num_classes,
        min_sizes=[s * 0.1, s * 0.3, s * 0.6],
        max_sizes=[s * 0.3, s * 0.6, s * 0.9],
        aspect_ratios=[[1.0, 2.0], [1.0, 2.0], [1.0, 2.0]],
        flip=True, clip=True, kernel_size=3, pad=1)
    return locs, confs, boxes, vars_


def ssd_train(num_classes=21, image_shape=(3, 128, 128), max_gt=8):
    """Training graph: feeds image, gt_box [B,M,4], gt_label [B,M].
    Returns (avg_loss, feeds)."""
    image = layers.data(name='image', shape=list(image_shape),
                        dtype='float32')
    gt_box = layers.data(name='gt_box', shape=[max_gt, 4],
                         dtype='float32')
    gt_label = layers.data(name='gt_label', shape=[max_gt], dtype='int64')
    locs, confs, boxes, vars_ = ssd_net(image, num_classes, image_shape)
    loss = layers.ssd_loss(locs, confs, gt_box, gt_label, boxes, vars_)
    avg = layers.mean(loss)
    return avg, ['image', 'gt_box', 'gt_label']


def ssd_infer(num_classes=21, image_shape=(3, 128, 128), keep_top_k=16):
    """Inference graph: image -> [B, keep_top_k, 6] detections."""
    image = layers.data(name='image', shape=list(image_shape),
                        dtype='float32')
    locs, confs, boxes, vars_ = ssd_net(image, num_classes, image_shape)
    probs = layers.softmax(confs)
    out = layers.detection_output(locs, probs, boxes, vars_,
                                  keep_top_k=keep_top_k)
    return out, ['image']
