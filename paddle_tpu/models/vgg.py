"""VGG nets (reference: benchmark/paddle image classification vgg config and
the image_classification book chapter's vgg_bn_drop)."""

from .. import layers, nets
from ..param_attr import ParamAttr
from ..initializer import Normal


def vgg_bn_drop(input, class_dim=10):
    """CIFAR VGG with batch-norm + dropout conv groups (book chapter 03)."""

    def conv_block(ipt, num_filter, groups, dropouts):
        return nets.img_conv_group(
            input=ipt, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups, conv_filter_size=3,
            conv_act='relu', conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts, pool_type='max')

    conv1 = conv_block(input, 64, 2, [0.3, 0])
    conv2 = conv_block(conv1, 128, 2, [0.4, 0])
    conv3 = conv_block(conv2, 256, 3, [0.4, 0.4, 0])
    conv4 = conv_block(conv3, 512, 3, [0.4, 0.4, 0])
    conv5 = conv_block(conv4, 512, 3, [0.4, 0.4, 0])

    drop = layers.dropout(x=conv5, dropout_prob=0.5)
    fc1 = layers.fc(input=drop, size=512, act=None)
    bn = layers.batch_norm(input=fc1, act='relu')
    drop2 = layers.dropout(x=bn, dropout_prob=0.5)
    fc2 = layers.fc(input=drop2, size=512, act=None)
    predict = layers.fc(input=fc2, size=class_dim, act='softmax')
    return predict


def vgg16(input, class_dim=1000):
    """Plain VGG-16 (benchmark/paddle vgg.py shape): 13 conv + 3 fc."""
    cfg = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
    tmp = input
    for num_filter, groups in cfg:
        tmp = nets.img_conv_group(
            input=tmp, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups, conv_filter_size=3,
            conv_act='relu', conv_with_batchnorm=False, pool_type='max')
    fc6 = layers.fc(input=tmp, size=4096, act='relu',
                    param_attr=ParamAttr(initializer=Normal(0.0, 0.01)))
    drop6 = layers.dropout(x=fc6, dropout_prob=0.5)
    fc7 = layers.fc(input=drop6, size=4096, act='relu',
                    param_attr=ParamAttr(initializer=Normal(0.0, 0.01)))
    drop7 = layers.dropout(x=fc7, dropout_prob=0.5)
    predict = layers.fc(input=drop7, size=class_dim, act='softmax')
    return predict


def vgg16_with_loss(input=None, label=None, class_dim=1000,
                    image_shape=(3, 224, 224)):
    if input is None:
        input = layers.data(name='image', shape=list(image_shape),
                            dtype='float32')
    if label is None:
        label = layers.data(name='label', shape=[1], dtype='int64')
    predict = vgg16(input, class_dim)
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    acc = layers.accuracy(input=predict, label=label)
    return predict, avg_cost, acc
