"""Semantic role labeling: stacked bi-GRU emissions + linear-chain CRF.

Reference: the label_semantic_roles book chapter (an 8-feature stacked
bidirectional LSTM feeding linear_chain_crf / crf_decoding over conll05).
TPU-first shape: padded [B, T] grids with a per-example length vector —
the CRF loss and Viterbi decode are the log-domain lax.scan lowerings in
ops/decode_ops.py, so train and decode both compile into the step.
"""

from .. import layers
from ..param_attr import ParamAttr


def srl_tagger(word, mark, target, word_dict_len, label_dict_len,
               mark_dict_len=2, emb_dim=32, hidden_dim=64, depth=2,
               length=None):
    """Returns (emission, crf_cost, avg_cost). Feeds: word [B, T] int64,
    mark [B, T] int64 (predicate-position feature, the chapter's
    mark_dict role), target [B, T] int64, plus `length` [B] for padding.
    """
    word_emb = layers.embedding(input=word,
                                size=[word_dict_len, emb_dim],
                                dtype='float32')
    mark_emb = layers.embedding(input=mark,
                                size=[mark_dict_len, emb_dim // 2],
                                dtype='float32')
    hidden = layers.concat([word_emb, mark_emb], axis=2)
    for i in range(depth):
        # dynamic_gru consumes a 3h pre-projection of its input
        proj = layers.fc(input=hidden, size=hidden_dim * 3,
                         num_flatten_dims=2)
        hidden = layers.dynamic_gru(input=proj, size=hidden_dim,
                                    is_reverse=(i % 2) == 1,
                                    length=length)
    emission = layers.fc(input=hidden, size=label_dict_len,
                         num_flatten_dims=2,
                         param_attr=ParamAttr(name='srl_emission.w'))
    crf_cost = layers.linear_chain_crf(
        input=emission, label=target, length=length,
        param_attr=ParamAttr(name='srl_crf.w'))
    avg_cost = layers.mean(crf_cost)
    return emission, crf_cost, avg_cost


def srl_decode(emission, length=None):
    """Viterbi decode sharing the trained transition ('srl_crf.w')."""
    return layers.crf_decoding(
        input=emission, length=length,
        param_attr=ParamAttr(name='srl_crf.w'))
