"""Evaluators accumulating metrics across minibatches
(reference: python/paddle/fluid/evaluator.py)."""

import numpy as np

from . import layers

__all__ = ['Accuracy', 'ChunkEvaluator', 'Evaluator']


class Evaluator(object):
    def __init__(self, name=None):
        self._name = name

    def reset(self, executor=None):
        raise NotImplementedError

    def eval(self, executor=None):
        raise NotImplementedError


class Accuracy(Evaluator):
    """Streaming accuracy. Per-batch correct/total come from the graph; the
    running sums live host-side (the reference keeps them as scope vars)."""

    def __init__(self, input, label, k=1, **kwargs):
        super(Accuracy, self).__init__(**kwargs)
        helper_out = layers.accuracy(input=input, label=label, k=k)
        self.metrics = [helper_out]
        self._correct_total = 0
        self._num_total = 0
        self._batch_acc = helper_out

    def reset(self, executor=None):
        self._correct_total = 0
        self._num_total = 0

    def update(self, batch_acc, batch_size):
        self._correct_total += float(np.asarray(batch_acc).reshape(-1)[0]) \
            * batch_size
        self._num_total += batch_size

    def eval(self, executor=None):
        if self._num_total == 0:
            return 0.0
        return self._correct_total / self._num_total


class ChunkEvaluator(Evaluator):
    """Chunk (IOB/IOE/IOBES) precision/recall/F1, computed host-side
    (reference: evaluator.py ChunkEvaluator + chunk_eval_op.cc)."""

    def __init__(self, input=None, label=None, chunk_scheme='IOB',
                 num_chunk_types=None, excluded_chunk_types=None, **kwargs):
        super(ChunkEvaluator, self).__init__(**kwargs)
        self.chunk_scheme = chunk_scheme
        self.num_chunk_types = num_chunk_types
        self.excluded = set(excluded_chunk_types or [])
        self.reset()

    def reset(self, executor=None):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def _extract_chunks(self, tags, seq_len):
        """Decode chunks from tag ids under the configured scheme."""
        scheme = self.chunk_scheme
        n_types = self.num_chunk_types
        chunks = []
        start = None
        cur_type = None
        if scheme == 'IOB':
            tag_kinds = 2  # B, I
        elif scheme == 'IOE':
            tag_kinds = 2  # I, E
        elif scheme == 'IOBES':
            tag_kinds = 4  # B, I, E, S
        else:  # 'plain'
            tag_kinds = 1
        for i in range(seq_len):
            tag = int(tags[i])
            outside = tag == n_types * tag_kinds
            if outside:
                if start is not None:
                    chunks.append((start, i - 1, cur_type))
                    start = None
                continue
            ttype = tag // tag_kinds
            kind = tag % tag_kinds
            if scheme == 'IOB':
                is_begin = kind == 0
                if is_begin or ttype != cur_type:
                    if start is not None:
                        chunks.append((start, i - 1, cur_type))
                    start, cur_type = i, ttype
            elif scheme == 'IOE':
                is_end = kind == 1
                if start is None or ttype != cur_type:
                    if start is not None:
                        chunks.append((start, i - 1, cur_type))
                    start, cur_type = i, ttype
                if is_end:
                    chunks.append((start, i, cur_type))
                    start = None
            elif scheme == 'IOBES':
                if kind == 3:  # S
                    if start is not None:
                        chunks.append((start, i - 1, cur_type))
                        start = None
                    chunks.append((i, i, ttype))
                elif kind == 0:  # B
                    if start is not None:
                        chunks.append((start, i - 1, cur_type))
                    start, cur_type = i, ttype
                elif kind == 2:  # E
                    if start is not None:
                        chunks.append((start, i, cur_type))
                        start = None
            else:
                if cur_type != ttype:
                    if start is not None:
                        chunks.append((start, i - 1, cur_type))
                    start, cur_type = i, ttype
        if start is not None:
            chunks.append((start, seq_len - 1, cur_type))
        return set(c for c in chunks if c[2] not in self.excluded)

    def update(self, infer_tags, label_tags, lengths):
        infer_tags = np.asarray(infer_tags)
        label_tags = np.asarray(label_tags)
        lengths = np.asarray(lengths).reshape(-1)
        for b in range(infer_tags.shape[0]):
            n = int(lengths[b])
            infer = self._extract_chunks(infer_tags[b], n)
            label = self._extract_chunks(label_tags[b], n)
            self.num_infer_chunks += len(infer)
            self.num_label_chunks += len(label)
            self.num_correct_chunks += len(infer & label)

    def eval(self, executor=None):
        precision = self.num_correct_chunks / self.num_infer_chunks \
            if self.num_infer_chunks else 0.0
        recall = self.num_correct_chunks / self.num_label_chunks \
            if self.num_label_chunks else 0.0
        f1 = 2 * precision * recall / (precision + recall) \
            if precision + recall else 0.0
        return precision, recall, f1
