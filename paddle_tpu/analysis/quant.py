"""Pass 6 — quantization dtype/scale contracts.

The PTQ rewrite (quant/ptq.py) and the quantized KV arenas
(serving/decode/model.py) both pair low-precision storage with fp32
scale vars; accumulation stays fp32. A quantized weight that loses its
scale (or pairs with a wrong-shaped one) doesn't crash — it silently
produces garbage logits, the worst failure mode. This pass locks the
pairing statically:

- every ``quant_mul`` / ``quant_matmul`` / ``quant_lookup_table``
  weight must be int8, its ``Scale`` input present, fp32, persistable
  like the weight, and shaped exactly ``[weight.shape[quant_axis]]``;
  ``accum_dtype`` must be 'float32' (these ops upcast to fp32 at the
  use site — anything else breaks the weight-only contract).
- every paged decode op (``paged_prefill`` / ``paged_decode_step`` /
  ``paged_spec_verify``) whose K/V arena is int8 or fp8 must carry
  ``KScale``/``VScale`` arenas of dtype fp32 shaped ``[L, NB, H, bs]``
  (one scale per stored row) — and must both be written back
  (``KScaleOut``/``VScaleOut``), or the donation contract silently
  drops the scales of every new token.
"""

from .base import analysis_pass

# op type -> (weight slot, default per-channel axis)
_QUANT_OPS = {
    'quant_mul': ('Y', 1),
    'quant_matmul': ('Y', 1),
    'quant_lookup_table': ('W', 0),
}

_PAGED_OPS = ('paged_prefill', 'paged_decode_step', 'paged_spec_verify')
_QUANT_ARENA_DTYPES = ('int8', 'float8_e4m3fn')


@analysis_pass('quant')
def check(ctx):
    for i, op in enumerate(ctx.block.ops):
        if op.type in _QUANT_OPS:
            _check_weight_op(ctx, i, op)
        elif op.type in _PAGED_OPS:
            _check_paged_op(ctx, i, op)


def _check_weight_op(ctx, i, op):
    wslot, default_axis = _QUANT_OPS[op.type]
    wname = op.input(wslot)
    wvar = ctx.find_var(wname) if wname else None
    if wvar is None:
        return   # wellformed reports undefined inputs
    if wvar.dtype != 'int8':
        ctx.error('quant-weight-dtype',
                  'quantized op consumes %r of dtype %s — the %s slot '
                  'of a %s must be int8 (the PTQ rewrite produces the '
                  'int8 copy; do not hand it the fp32 original)'
                  % (wname, wvar.dtype, wslot, op.type),
                  op=op, op_index=i, var=wname)
    sname = op.input('Scale')
    if sname is None:
        ctx.error('quant-missing-scale',
                  'quantized weight %r has no Scale input — int8 '
                  'weights are meaningless without their per-channel '
                  'fp32 scales' % wname,
                  op=op, op_index=i, var=wname)
        return
    svar = ctx.find_var(sname)
    if svar is None:
        return
    if svar.dtype != 'float32':
        ctx.error('quant-scale-dtype',
                  'scale %r has dtype %s; per-channel scales must be '
                  'float32' % (sname, svar.dtype),
                  op=op, op_index=i, var=sname)
    axis = op.attr('quant_axis', default_axis)
    if wvar.shape is not None and svar.shape is not None:
        want = (wvar.shape[axis % len(wvar.shape)],)
        if tuple(svar.shape) != want:
            ctx.error('quant-scale-shape',
                      'scale %r has shape %s; weight %r quantized on '
                      'axis %d needs scales shaped %s'
                      % (sname, list(svar.shape), wname, axis,
                         list(want)),
                      op=op, op_index=i, var=sname)
    if wvar.persistable and not (svar.persistable or svar.is_data):
        ctx.error('quant-scale-transient',
                  'scale %r is a temporary but its weight %r is '
                  'persistable — the pair must live (and serialize) '
                  'together' % (sname, wname),
                  op=op, op_index=i, var=sname)
    if op.attr('accum_dtype', 'float32') != 'float32':
        ctx.error('quant-accum-dtype',
                  '%s declares accum_dtype=%r; weight-only int8 ops '
                  'accumulate in float32' % (op.type,
                                             op.attr('accum_dtype')),
                  op=op, op_index=i)


def _check_paged_op(ctx, i, op):
    for cache_slot, scale_slot in (('KCache', 'KScale'),
                                   ('VCache', 'VScale')):
        cname = op.input(cache_slot)
        cvar = ctx.find_var(cname) if cname else None
        if cvar is None or cvar.dtype not in _QUANT_ARENA_DTYPES:
            continue
        sname = op.input(scale_slot)
        if sname is None:
            ctx.error('kv-missing-scale',
                      '%s arena %r is %s but the op has no %s input — '
                      'quantized pages cannot be dequantized without '
                      'their per-row scales'
                      % (cache_slot, cname, cvar.dtype, scale_slot),
                      op=op, op_index=i, var=cname)
            continue
        svar = ctx.find_var(sname)
        if svar is None:
            continue
        if svar.dtype != 'float32':
            ctx.error('kv-scale-dtype',
                      'scale arena %r has dtype %s; must be float32'
                      % (sname, svar.dtype),
                      op=op, op_index=i, var=sname)
        if cvar.shape is not None and svar.shape is not None and \
                tuple(svar.shape) != tuple(cvar.shape[:4]):
            ctx.error('kv-scale-shape',
                      'scale arena %r has shape %s; arena %r %s needs '
                      'per-row scales shaped %s (one per [L, NB, H, '
                      'bs] slot)'
                      % (sname, list(svar.shape), cname,
                         list(cvar.shape), list(cvar.shape[:4])),
                      op=op, op_index=i, var=sname)
        out_slot = scale_slot + 'Out'
        if op.output(out_slot) is None:
            ctx.error('kv-scale-not-written',
                      "%s is read but %s is missing — new tokens' "
                      'scales would be silently dropped by the '
                      'donated in-place update'
                      % (scale_slot, out_slot),
                      op=op, op_index=i, var=sname)
