"""Pass 4 — donation/aliasing safety.

The executor DONATES every scope input to the jitted step: persistable
buffers (params, optimizer state, the decode KV arenas) alias their
outputs and update in place in HBM. Two ops writing one persistable in
a single step therefore race on a donated buffer (the executor keeps
the last write; whichever the user meant, one update is silently
lost), and an op that reads a param AFTER its in-place optimizer
update observes the post-step value inside the very step whose forward
consumed the pre-step value.
"""

from .base import analysis_pass

_SUBBLOCK_OPS = frozenset(('while', 'if_else', 'static_rnn',
                           'dynamic_rnn'))


@analysis_pass('donation')
def check(ctx):
    block = ctx.block
    writers = {}
    for i, op in enumerate(block.ops):
        if op.type in _SUBBLOCK_OPS:
            continue
        for name in set(op.output_names()):
            v = ctx.find_var(name)
            if v is None or not v.persistable:
                continue
            writers.setdefault(name, []).append((i, op))

    for name, lst in writers.items():
        if len(lst) <= 1:
            continue
        i, op = lst[1]
        ctx.error('double-donation',
                  'persistable %r is written by %d ops in one step '
                  '(first at op#%d %s) — with buffer donation the '
                  'writes race on one aliased buffer and only the '
                  'last survives' % (name, len(lst), lst[0][0],
                                     lst[0][1].type),
                  op=op, op_index=i, var=name)

    # read-after-donate: Param-slot in-place updates (ParamOut == Param)
    # followed by any op that reads the updated var later in the step
    updates = {}
    for i, op in enumerate(block.ops):
        pname = op.input('Param')
        if pname is not None and pname in op.output_names():
            updates.setdefault(pname, (i, op))
    if not updates:
        return
    for j, op in enumerate(block.ops):
        for name in set(op.input_names()):
            at = updates.get(name)
            if at is None or j <= at[0] or op is at[1]:
                continue
            if op.input('Param') == name and name in op.output_names():
                # another in-place updater of the same var: that race is
                # double-donation, already reported above
                continue
            ctx.warning('read-after-donate',
                        'op reads %r after its in-place update at '
                        'op#%d %s — it observes the POST-update value '
                        'within the same step (the forward consumed '
                        'the pre-update value)' % (name, at[0],
                                                   at[1].type),
                        op=op, op_index=j, var=name)
