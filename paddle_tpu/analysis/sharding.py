"""Pass 3 — sharding consistency.

Propagates the logical PartitionSpecs the transpiler attached
(`program.var_shardings`, GSPMD-style) through static checks: a spec
axis that the mesh does not have, a sharded dim the mesh axis cannot
divide, parameters left unannotated on a >1-device mesh, and input
spec conflicts that force XLA to insert an implicit all-gather/
reshard on the hot path. Mesh and specs are duck-typed (``mesh.shape``
mapping, specs iterate as axis entries) so the pass never imports jax.
"""

from .base import analysis_pass

# Ops where inputs meeting with different layouts forces a reshard.
_ALIGNED_OPS = frozenset((
    'elementwise_add', 'elementwise_sub', 'elementwise_mul',
    'elementwise_div', 'elementwise_max', 'elementwise_min'))


def _spec_entries(spec):
    """PartitionSpec -> list of per-dim entries, each None | axis name |
    tuple of axis names."""
    try:
        return list(spec)
    except TypeError:
        return []


def _entry_axes(entry):
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


@analysis_pass('sharding')
def check(ctx):
    program = ctx.program
    mesh = program.mesh
    shardings = program.var_shardings or {}
    if mesh is None and not shardings:
        return
    mesh_shape = {}
    if mesh is not None:
        mesh_shape = dict(mesh.shape)
    n_devices = 1
    for size in mesh_shape.values():
        n_devices *= int(size)

    first_op = ctx.block.ops[0] if ctx.block.ops else None
    for name in sorted(shardings):
        spec = shardings[name]
        entries = _spec_entries(spec)
        if not entries:
            continue
        var = ctx.find_var(name)
        shape = None if var is None or var.shape is None \
            else tuple(var.shape)
        if shape is not None and len(entries) > len(shape):
            ctx.error('spec-rank-mismatch',
                      'sharding spec %s has %d entries but %r has rank '
                      '%d' % (tuple(entries), len(entries), name,
                              len(shape)), var=name)
            continue
        for dim, entry in enumerate(entries):
            extent = 1
            for axis in _entry_axes(entry):
                if mesh is not None and axis not in mesh_shape:
                    ctx.error('unknown-mesh-axis',
                              '%r dim %d is sharded over axis %r, '
                              'which mesh %s does not have'
                              % (name, dim, axis,
                                 dict(mesh_shape)), var=name)
                    continue
                extent *= int(mesh_shape.get(axis, 1))
            if extent <= 1 or shape is None:
                continue
            d = shape[dim]
            if d is not None and d >= 0 and d % extent:
                ctx.error('axis-indivisible',
                          '%r dim %d (=%d) is sharded over %s '
                          '(extent %d) but %d %% %d != 0 — XLA must '
                          'pad or reshard every step'
                          % (name, dim, d, _entry_axes(entry), extent,
                             d, extent), var=name)

    if n_devices > 1:
        for param in program.all_parameters():
            if param.name not in shardings:
                ctx.warning('unannotated-param',
                            'parameter %r has no sharding spec on a '
                            '%d-device mesh — it will be replicated '
                            'by default; run parallel.transpile or '
                            'annotate it' % (param.name, n_devices),
                            var=param.name)

    # spec conflicts at aligned ops: both inputs annotated, same rank,
    # different layouts -> GSPMD inserts a reshard to make them meet
    def sharded_spec(name):
        entries = _spec_entries(shardings.get(name))
        return entries if any(e is not None for e in entries) else None

    for i, op in enumerate(ctx.block.ops):
        if op.type not in _ALIGNED_OPS:
            continue
        xn, yn = op.input('X'), op.input('Y')
        if xn is None or yn is None:
            continue
        xs, ys = sharded_spec(xn), sharded_spec(yn)
        if xs is None or ys is None:
            continue
        xv, yv = ctx.shape_of(xn), ctx.shape_of(yn)
        if xv is None or yv is None or len(xv) != len(yv):
            continue
        if xs != ys:
            ctx.warning('spec-conflict',
                        '%s meets %r sharded %s with %r sharded %s — '
                        'GSPMD will insert an implicit reshard here '
                        'every step' % (op.type, xn, tuple(xs), yn,
                                        tuple(ys)), op=op, op_index=i,
                        var=yn)

    # ZeRO-1 contracts. Optimizer ops are found structurally (any op
    # with a 'Param' input slot — the same rule the transpiler's
    # accumulator loop uses): (a) same-shape accumulators of one update
    # must agree on a layout, else GSPMD reshards state every step;
    # (b) a dp-sharded accumulator wants a dp-sharded (reduce-scattered)
    # gradient — a replicated grad beside sharded state makes XLA
    # materialize the full gradient on every device and slice it,
    # spending the memory ZeRO-1 was meant to save.
    for i, op in enumerate(ctx.block.ops):
        pnames = op.inputs.get('Param')
        if not pnames:
            continue
        pvar = ctx.find_var(pnames[0])
        pshape = None if pvar is None or pvar.shape is None \
            else tuple(pvar.shape)
        if pshape is None:
            continue
        state_specs = {}
        for slot, names in op.inputs.items():
            if slot in ('Param', 'Grad', 'LearningRate'):
                continue
            for n in names:
                v = ctx.find_var(n)
                if v is None or not getattr(v, 'persistable', False) \
                        or v.shape is None or tuple(v.shape) != pshape:
                    continue
                state_specs[n] = tuple(_spec_entries(shardings.get(n)))
        if not state_specs:
            continue
        if len(set(state_specs.values())) > 1:
            ctx.warning('zero-state-spec-mismatch',
                        '%s accumulators for param %r carry differing '
                        'specs %s — GSPMD reshards optimizer state '
                        'every step; re-run parallel.transpile so one '
                        'layout decision covers them all'
                        % (op.type, pnames[0],
                           {n: s for n, s in sorted(state_specs.items())}),
                        op=op, op_index=i, var=pnames[0])
        grads = op.inputs.get('Grad') or []
        gname = grads[0] if grads else None
        dp_state = [n for n, s in sorted(state_specs.items())
                    if any('dp' in _entry_axes(e) for e in s)]
        if dp_state and gname is not None:
            g_dp = any('dp' in _entry_axes(e)
                       for e in _spec_entries(shardings.get(gname)))
            if not g_dp:
                ctx.warning('zero-grad-replicated',
                            '%s state %s for param %r is dp-sharded but '
                            'gradient %r is not — the update all-gathers '
                            'the full gradient on every device each '
                            'step, defeating ZeRO-1\'s reduce-scatter'
                            % (op.type, dp_state, pnames[0], gname),
                            op=op, op_index=i, var=gname)
