"""Pass 3 — sharding consistency.

Propagates the logical PartitionSpecs the transpiler attached
(`program.var_shardings`, GSPMD-style) through static checks: a spec
axis that the mesh does not have, a sharded dim the mesh axis cannot
divide, parameters left unannotated on a >1-device mesh, and input
spec conflicts that force XLA to insert an implicit all-gather/
reshard on the hot path. Mesh and specs are duck-typed (``mesh.shape``
mapping, specs iterate as axis entries) so the pass never imports jax.
"""

from .base import analysis_pass

# Ops where inputs meeting with different layouts forces a reshard.
_ALIGNED_OPS = frozenset((
    'elementwise_add', 'elementwise_sub', 'elementwise_mul',
    'elementwise_div', 'elementwise_max', 'elementwise_min'))


def _spec_entries(spec):
    """PartitionSpec -> list of per-dim entries, each None | axis name |
    tuple of axis names."""
    try:
        return list(spec)
    except TypeError:
        return []


def _entry_axes(entry):
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


@analysis_pass('sharding')
def check(ctx):
    program = ctx.program
    mesh = program.mesh
    shardings = program.var_shardings or {}
    if mesh is None and not shardings:
        return
    mesh_shape = {}
    if mesh is not None:
        mesh_shape = dict(mesh.shape)
    n_devices = 1
    for size in mesh_shape.values():
        n_devices *= int(size)

    first_op = ctx.block.ops[0] if ctx.block.ops else None
    for name in sorted(shardings):
        spec = shardings[name]
        entries = _spec_entries(spec)
        if not entries:
            continue
        var = ctx.find_var(name)
        shape = None if var is None or var.shape is None \
            else tuple(var.shape)
        if shape is not None and len(entries) > len(shape):
            ctx.error('spec-rank-mismatch',
                      'sharding spec %s has %d entries but %r has rank '
                      '%d' % (tuple(entries), len(entries), name,
                              len(shape)), var=name)
            continue
        for dim, entry in enumerate(entries):
            extent = 1
            for axis in _entry_axes(entry):
                if mesh is not None and axis not in mesh_shape:
                    ctx.error('unknown-mesh-axis',
                              '%r dim %d is sharded over axis %r, '
                              'which mesh %s does not have'
                              % (name, dim, axis,
                                 dict(mesh_shape)), var=name)
                    continue
                extent *= int(mesh_shape.get(axis, 1))
            if extent <= 1 or shape is None:
                continue
            d = shape[dim]
            if d is not None and d >= 0 and d % extent:
                ctx.error('axis-indivisible',
                          '%r dim %d (=%d) is sharded over %s '
                          '(extent %d) but %d %% %d != 0 — XLA must '
                          'pad or reshard every step'
                          % (name, dim, d, _entry_axes(entry), extent,
                             d, extent), var=name)

    if n_devices > 1:
        for param in program.all_parameters():
            if param.name not in shardings:
                ctx.warning('unannotated-param',
                            'parameter %r has no sharding spec on a '
                            '%d-device mesh — it will be replicated '
                            'by default; run parallel.transpile or '
                            'annotate it' % (param.name, n_devices),
                            var=param.name)

    # spec conflicts at aligned ops: both inputs annotated, same rank,
    # different layouts -> GSPMD inserts a reshard to make them meet
    def sharded_spec(name):
        entries = _spec_entries(shardings.get(name))
        return entries if any(e is not None for e in entries) else None

    for i, op in enumerate(ctx.block.ops):
        if op.type not in _ALIGNED_OPS:
            continue
        xn, yn = op.input('X'), op.input('Y')
        if xn is None or yn is None:
            continue
        xs, ys = sharded_spec(xn), sharded_spec(yn)
        if xs is None or ys is None:
            continue
        xv, yv = ctx.shape_of(xn), ctx.shape_of(yn)
        if xv is None or yv is None or len(xv) != len(yv):
            continue
        if xs != ys:
            ctx.warning('spec-conflict',
                        '%s meets %r sharded %s with %r sharded %s — '
                        'GSPMD will insert an implicit reshard here '
                        'every step' % (op.type, xn, tuple(xs), yn,
                                        tuple(ys)), op=op, op_index=i,
                        var=yn)
