"""Pass 2 — shape/dtype abstract interpretation.

Per-op contracts over the DECLARED var shapes (batch and other unbound
dims are -1 and treated as wildcards), checked before anything traces:
a mul whose flattened inner dims disagree fails here with the op type
and the Python file:line that appended it, instead of as a jnp
broadcast error three layers into `jit`. Contracts cover the
high-traffic op set — matmul/mul, conv, fused attention, norms,
elementwise, reshape/concat/transpose, and the optimizer update ops —
and are deliberately permissive: any dim that is unknown (-1 or an
undeclared shape) skips the check rather than guessing.
"""

from .base import analysis_pass

_WILD = -1


def _known(*dims):
    return all(d is not None and d >= 0 for d in dims)


def _prod(dims):
    """Product of a dim slice, or None when any dim is unknown."""
    out = 1
    for d in dims:
        if d is None or d < 0:
            return None
        out *= d
    return out


def _dims_eq(a, b):
    return a < 0 or b < 0 or a == b


_FLOATS = ('float16', 'bfloat16', 'float32', 'float64')
_INTS = ('int16', 'int32', 'int64', 'uint8', 'int8', 'bool')

# Optimizer state slots that must be param-shaped.
_STATE_SLOTS = frozenset((
    'Moment', 'Moment1', 'Moment2', 'Velocity', 'InfNorm', 'MeanSquare',
    'MeanGrad', 'AvgSquaredGrad', 'AvgSquaredUpdate',
    'SquaredAccumulator', 'LinearAccumulator'))

_OPTIMIZER_OPS = frozenset((
    'sgd', 'momentum', 'adagrad', 'adam', 'adamax', 'adadelta',
    'rmsprop', 'ftrl', 'decayed_adagrad', 'proximal_gd',
    'proximal_adagrad'))

_ELEMENTWISE_PREFIX = 'elementwise_'


def _sparse_params(block):
    """Param names whose grads flow as sparse rows (shape-exempt)."""
    for op in block.ops:
        if op.type == 'backward_marker':
            return set(op.attrs.get('sparse_grads') or ())
    return set()


@analysis_pass('shapes')
def check(ctx):
    sparse = _sparse_params(ctx.block)
    for i, op in enumerate(ctx.block.ops):
        fn = _CONTRACTS.get(op.type)
        if fn is None and op.type.startswith(_ELEMENTWISE_PREFIX):
            fn = _elementwise
        if fn is None and op.type in _OPTIMIZER_OPS:
            fn = _optimizer
        if fn is not None:
            fn(ctx, op, i, sparse)


# ------------------------------------------------------------- contracts
def _in_shape(ctx, op, slot):
    name = op.input(slot)
    return None if name is None else ctx.shape_of(name)


def _check_float(ctx, op, i, slots):
    for slot in slots:
        name = op.input(slot)
        if name is None:
            continue
        dt = ctx.dtype_of(name)
        if dt in _INTS:
            ctx.error('dtype-not-float',
                      'input %r (slot %s) has dtype %s; %s computes in '
                      'floating point' % (name, slot, dt, op.type),
                      op=op, op_index=i, var=name)


def _mul(ctx, op, i, sparse):
    x, y = _in_shape(ctx, op, 'X'), _in_shape(ctx, op, 'Y')
    _check_float(ctx, op, i, ('X', 'Y'))
    if x is None or y is None:
        return
    xd = op.attr('x_num_col_dims', 1)
    yd = op.attr('y_num_col_dims', 1)
    inner_x = _prod(x[xd:])
    inner_y = _prod(y[:yd])
    if inner_x is not None and inner_y is not None and inner_x != inner_y:
        ctx.error('matmul-mismatch',
                  'mul contracts X%s cols (%d, from dims %s) against '
                  'Y%s rows (%d, from dims %s)'
                  % (list(x), inner_x, list(x[xd:]), list(y), inner_y,
                     list(y[:yd])), op=op, op_index=i,
                  var=op.input('Y'))


def _matmul(ctx, op, i, sparse):
    x, y = _in_shape(ctx, op, 'X'), _in_shape(ctx, op, 'Y')
    _check_float(ctx, op, i, ('X', 'Y'))
    if x is None or y is None or len(x) < 1 or len(y) < 1:
        return
    xc = x[-2] if op.attr('transpose_X', False) and len(x) > 1 else x[-1]
    if len(y) == 1:
        yc = y[0]
    elif op.attr('transpose_Y', False):
        yc = y[-1]
    else:
        yc = y[-2]
    if _known(xc, yc) and xc != yc:
        ctx.error('matmul-mismatch',
                  'matmul contracting dims disagree: X%s gives %d, '
                  'Y%s gives %d' % (list(x), xc, list(y), yc),
                  op=op, op_index=i, var=op.input('Y'))


def _elementwise(ctx, op, i, sparse):
    x, y = _in_shape(ctx, op, 'X'), _in_shape(ctx, op, 'Y')
    xn, yn = op.input('X'), op.input('Y')
    dx, dy = ctx.dtype_of(xn), ctx.dtype_of(yn)
    if dx and dy and (dx in _FLOATS) != (dy in _FLOATS):
        ctx.warning('dtype-mix',
                    '%s mixes %s (%r) with %s (%r); jnp promotion '
                    'decides the result dtype' % (op.type, dx, xn, dy,
                                                  yn),
                    op=op, op_index=i, var=yn)
    if x is None or y is None:
        return
    axis = op.attr('axis', -1)
    if axis in (-1, None):
        axis = len(x) - len(y)
    if axis < 0 or axis + len(y) > len(x):
        ctx.error('broadcast-mismatch',
                  '%s cannot align Y%s into X%s at axis %d'
                  % (op.type, list(y), list(x), axis),
                  op=op, op_index=i, var=yn)
        return
    for j, yd in enumerate(y):
        xd = x[axis + j]
        if _known(xd, yd) and xd != yd and 1 not in (xd, yd):
            ctx.error('broadcast-mismatch',
                      '%s: Y%s dim %d (=%d) does not broadcast against '
                      'X%s dim %d (=%d)' % (op.type, list(y), j, yd,
                                            list(x), axis + j, xd),
                      op=op, op_index=i, var=yn)
            return


def _concat(ctx, op, i, sparse):
    shapes = [(n, ctx.shape_of(n)) for n in op.inputs.get('X', [])]
    shapes = [(n, s) for n, s in shapes if s is not None]
    if len(shapes) < 2:
        return
    axis = op.attr('axis', 0)
    rank = len(shapes[0][1])
    for n, s in shapes[1:]:
        if len(s) != rank:
            ctx.error('rank-mismatch',
                      'concat input %r has rank %d, first input has '
                      'rank %d' % (n, len(s), rank), op=op, op_index=i,
                      var=n)
            return
    ax = axis % rank if rank else 0
    base = shapes[0][1]
    for n, s in shapes[1:]:
        for d in range(rank):
            if d == ax:
                continue
            if not _dims_eq(base[d], s[d]):
                ctx.error('concat-mismatch',
                          'concat along axis %d but input %r dim %d '
                          '(=%d) != first input dim (=%d)'
                          % (ax, n, d, s[d], base[d]), op=op,
                          op_index=i, var=n)
                return


def _reshape(ctx, op, i, sparse):
    x = _in_shape(ctx, op, 'X')
    target = op.attr('shape')
    if x is None or not target:
        return
    target = list(target)
    for j, s in enumerate(target):
        if s == 0:
            target[j] = x[j] if j < len(x) else -1
    n_infer = sum(1 for s in target if s == -1)
    if n_infer > 1:
        ctx.error('reshape-mismatch',
                  'reshape target %s has %d inferred (-1) dims; at '
                  'most one is allowed' % (target, n_infer), op=op,
                  op_index=i, var=op.input('X'))
        return
    src = _prod(x)
    if src is None:
        return
    fixed = _prod([s for s in target if s != -1])
    if fixed is None or fixed == 0:
        return
    if n_infer == 0 and fixed != src:
        ctx.error('reshape-mismatch',
                  'reshape of X%s (%d elements) to %s (%d elements)'
                  % (list(x), src, target, fixed), op=op, op_index=i,
                  var=op.input('X'))
    elif n_infer == 1 and src % fixed:
        ctx.error('reshape-mismatch',
                  'reshape of X%s (%d elements) to %s: %d %% %d != 0, '
                  'the -1 dim cannot be inferred' % (list(x), src,
                                                     target, src, fixed),
                  op=op, op_index=i, var=op.input('X'))


def _transpose(ctx, op, i, sparse):
    x = _in_shape(ctx, op, 'X')
    axis = op.attr('axis')
    if x is None or axis is None:
        return
    if sorted(a % len(x) if len(x) else a for a in axis) != \
            list(range(len(x))):
        ctx.error('transpose-mismatch',
                  'transpose axis %s is not a permutation of rank %d'
                  % (list(axis), len(x)), op=op, op_index=i,
                  var=op.input('X'))


def _conv2d(ctx, op, i, sparse):
    x, w = _in_shape(ctx, op, 'Input'), _in_shape(ctx, op, 'Filter')
    _check_float(ctx, op, i, ('Input', 'Filter'))
    if x is None or w is None or len(x) != 4 or len(w) != 4:
        return
    groups = op.attr('groups', 1) or 1
    cin = x[3] if op.attr('data_format', 'NCHW') == 'NHWC' else x[1]
    if _known(cin, w[1]) and cin != w[1] * groups:
        ctx.error('channel-mismatch',
                  'conv2d input has %d channels but Filter%s expects '
                  '%d (groups=%d)' % (cin, list(w), w[1] * groups,
                                      groups), op=op, op_index=i,
                  var=op.input('Filter'))


def _fused_attention(ctx, op, i, sparse):
    q = _in_shape(ctx, op, 'Q')
    k = _in_shape(ctx, op, 'K')
    v = _in_shape(ctx, op, 'V')
    n_head = op.attr('n_head', 1) or 1
    for slot, s in (('Q', q), ('K', k), ('V', v)):
        if s is not None and _known(s[-1]) and s[-1] % n_head:
            ctx.error('attention-mismatch',
                      '%s feature dim %d is not divisible by n_head=%d'
                      % (slot, s[-1], n_head), op=op, op_index=i,
                      var=op.input(slot))
    if q is not None and k is not None and \
            not _dims_eq(q[-1], k[-1]):
        ctx.error('attention-mismatch',
                  'Q%s and K%s disagree on the key feature dim'
                  % (list(q), list(k)), op=op, op_index=i,
                  var=op.input('K'))
    if k is not None and v is not None and len(k) == len(v) and \
            len(k) >= 2 and not _dims_eq(k[-2], v[-2]):
        ctx.error('attention-mismatch',
                  'K%s and V%s disagree on the source sequence dim'
                  % (list(k), list(v)), op=op, op_index=i,
                  var=op.input('V'))


def _layer_norm(ctx, op, i, sparse):
    x = _in_shape(ctx, op, 'X')
    if x is None:
        return
    begin = op.attr('begin_norm_axis', 1)
    norm = _prod(x[begin:])
    for slot in ('Scale', 'Bias'):
        s = _in_shape(ctx, op, slot)
        if s is None:
            continue
        n = _prod(s)
        if norm is not None and n is not None and n != norm:
            ctx.error('norm-shape-mismatch',
                      'layer_norm %s%s has %d elements but X%s '
                      'normalizes %d (begin_norm_axis=%d)'
                      % (slot, list(s), n, list(x), norm, begin),
                      op=op, op_index=i, var=op.input(slot))


def _batch_norm(ctx, op, i, sparse):
    x = _in_shape(ctx, op, 'X')
    if x is None:
        return
    layout = op.attr('data_layout', 'NCHW')
    c = x[-1] if (layout == 'NHWC' and len(x) == 4) else \
        (x[1] if len(x) >= 2 else None)
    if c is None or c < 0:
        return
    for slot in ('Scale', 'Bias', 'Mean', 'Variance'):
        s = _in_shape(ctx, op, slot)
        if s is None or not s:
            continue
        if _known(s[0]) and s[0] != c:
            ctx.error('norm-shape-mismatch',
                      'batch_norm %s has %d entries but X%s has %d '
                      'channels (%s)' % (slot, s[0], list(x), c,
                                         layout), op=op, op_index=i,
                      var=op.input(slot))


def _optimizer(ctx, op, i, sparse):
    pname = op.input('Param')
    p = None if pname is None else ctx.shape_of(pname)
    if p is None:
        return
    gname = op.input('Grad')
    if gname is not None and pname not in sparse:
        g = ctx.shape_of(gname)
        if g is not None and len(g) == len(p) and \
                not all(_dims_eq(a, b) for a, b in zip(p, g)):
            ctx.error('update-shape-mismatch',
                      '%s: Grad%s does not match Param %r %s'
                      % (op.type, list(g), pname, list(p)), op=op,
                      op_index=i, var=gname)
    for slot, names in op.inputs.items():
        if slot not in _STATE_SLOTS:
            continue
        for n in names:
            s = ctx.shape_of(n)
            if s is not None and (len(s) != len(p) or not all(
                    _dims_eq(a, b) for a, b in zip(p, s))):
                ctx.error('update-shape-mismatch',
                          '%s: state %s=%r %s does not match Param %r '
                          '%s' % (op.type, slot, n, list(s), pname,
                                  list(p)), op=op, op_index=i, var=n)


def _lookup_table(ctx, op, i, sparse):
    ids = op.input('Ids')
    if ids is not None:
        dt = ctx.dtype_of(ids)
        if dt is not None and dt not in _INTS:
            ctx.error('dtype-not-int',
                      'lookup_table Ids %r has dtype %s; embedding '
                      'indices must be integral' % (ids, dt), op=op,
                      op_index=i, var=ids)


def _cross_entropy(ctx, op, i, sparse):
    if op.attr('soft_label', False):
        return
    label = op.input('Label')
    if label is not None:
        dt = ctx.dtype_of(label)
        if dt is not None and dt in _FLOATS:
            ctx.error('dtype-not-int',
                      '%s Label %r has dtype %s; hard labels are '
                      'integral class ids (or set soft_label=True)'
                      % (op.type, label, dt), op=op, op_index=i,
                      var=label)


_CONTRACTS = {
    'mul': _mul,
    'matmul': _matmul,
    'concat': _concat,
    'reshape': _reshape,
    'transpose': _transpose,
    'conv2d': _conv2d,
    'fused_attention': _fused_attention,
    'layer_norm': _layer_norm,
    'batch_norm': _batch_norm,
    'lookup_table': _lookup_table,
    'cross_entropy': _cross_entropy,
    'softmax_with_cross_entropy': _cross_entropy,
}
