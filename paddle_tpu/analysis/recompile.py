"""Pass 5 — recompile-hazard lint.

The executor caches compiled steps per (program content, feed
signature); the AOT disk cache additionally fingerprints the
SERIALIZED program. Two bug classes silently defeat both: attrs that
embed per-process Python values (an object repr carries a memory
address, a callable can't round-trip through serialization at all), so
the 'same' program fingerprints differently every build; and feed vars
with unbound non-batch dims, where every distinct length arriving from
live traffic mints a fresh XLA signature — the signature-churn class
the serving engines bound with BucketLadder and fixed decode shapes.
"""

from .base import analysis_pass

_SCALARS = (bool, int, float, str, bytes, type(None))


def _attr_hazard(value, depth=0):
    """None, or (code, severity, detail) for the worst hazard in an
    attr value tree."""
    if isinstance(value, _SCALARS):
        return None
    if depth > 6:
        return None
    if isinstance(value, (list, tuple)):
        for v in value:
            h = _attr_hazard(v, depth + 1)
            if h is not None:
                return h
        return None
    if isinstance(value, dict):
        for k, v in value.items():
            if not isinstance(k, _SCALARS):
                return ('attr-object-id', 'error',
                        'dict key %r is not a serializable scalar' % (k,))
            h = _attr_hazard(v, depth + 1)
            if h is not None:
                return h
        return None
    if isinstance(value, (set, frozenset)):
        return ('attr-unordered', 'warning',
                'set value %r has no stable iteration order — its '
                'serialization (and so the AOT cache fingerprint) can '
                'differ between processes' % (sorted(map(repr, value)),))
    if callable(value):
        return ('attr-callable', 'error',
                'callable %r cannot be serialized; its identity (a '
                'per-process pointer) leaks into the program '
                'fingerprint' % getattr(value, '__name__', value))
    tname = type(value).__name__
    if tname == 'ndarray':
        return ('attr-ndarray', 'warning',
                'numpy array of shape %s embedded in attrs — prefer a '
                'list (arrays are rebuilt per call and defeat '
                'fingerprint stability)' % (getattr(value, 'shape',
                                                    '?'),))
    r = repr(value)
    if ' object at 0x' in r or ' at 0x' in r:
        return ('attr-object-id', 'error',
                'attr holds %s whose repr embeds a memory address — '
                'the program fingerprint (and any cache keyed on it) '
                'churns every process' % type(value).__name__)
    return ('attr-object', 'warning',
            'attr holds a %s instance, which JSON serialization of '
            'the program cannot represent' % type(value).__name__)


@analysis_pass('recompile')
def check(ctx):
    for i, op in enumerate(ctx.block.ops):
        for attr_name, value in op.attrs.items():
            h = _attr_hazard(value)
            if h is None:
                continue
            code, severity, detail = h
            msg = 'attr %r of %s: %s' % (attr_name, op.type, detail)
            if severity == 'error':
                ctx.error(code, msg, op=op, op_index=i)
            else:
                ctx.warning(code, msg, op=op, op_index=i)

    for v in ctx.block.vars.values():
        if not v.is_data or v.shape is None:
            continue
        unbound = [d for d in range(1, len(v.shape)) if v.shape[d] == -1]
        if unbound:
            ctx.warning('dynamic-feed-dim',
                        'data var %r has unbound non-batch dims %s — '
                        'every distinct length fed at run time mints a '
                        'new executor signature (compile + cache '
                        'entry); bucket or pad it '
                        '(serving.BucketLadder)' % (v.name, unbound),
                        var=v.name)
