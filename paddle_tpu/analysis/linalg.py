"""Pass 7 — blocked-layout contracts for the distributed linalg tier.

The `sharding` pass checks generic PartitionSpec consistency; this one
knows the linalg ops' LAYOUT CONTRACTS (ops/linalg_ops.py) and
verifies them before anything traces:

- **block divisibility vs mesh axes** (`block-indivisible`, error):
  SUMMA needs N, K divisible by dp and K, M divisible by tp;
  Cholesky/QR/power iteration need N divisible by dp. An indivisible
  shape can't be blocked without padding — XLA would reshard every
  step.
- **panel-spec consistency** (`panel-misaligned`, warning): an
  explicit `panel`/`block` attr that doesn't divide the legal extents
  is rounded DOWN by the lowering; the diagnostic names the size that
  will actually run so a tuned table entry can't silently drift.
- **no implicit full-gather resharding** (`layout-not-blocked` /
  `implicit-full-gather`, error): on a >1-device grid every linalg
  operand must carry its blocked PartitionSpec. A missing spec means
  GSPMD replicates the operand — a FULL matrix per shard, the exact
  failure the O(N^2/P) memory contract exists to prevent; a wrong
  spec makes GSPMD insert a whole-matrix reshard in front of the
  shard_map.

Duck-typed like the sharding pass (mesh is a `.shape` mapping, specs
iterate as entries) — never imports jax, so `tools/program_lint.py`
runs it on a bastion host.
"""

from .base import analysis_pass

LINALG_OPS = ('summa_matmul', 'blocked_cholesky', 'blocked_qr',
              'power_iter_step')


def _gcd(a, b):
    while b:
        a, b = b, a % b
    return a


def _entries(spec):
    try:
        return tuple(spec)
    except TypeError:
        return ()


def _norm(entries):
    """Strip trailing replicated dims so P('dp') == P('dp', None)."""
    out = list(entries)
    while out and out[-1] is None:
        out.pop()
    return tuple(out)


def _check_layout(ctx, op, i, shardings, want):
    """Every (var, expected entries) pair must be annotated exactly."""
    for name, expect in want.items():
        if name is None:
            continue
        if name not in shardings:
            ctx.error('layout-not-blocked',
                      '%s operand %r has no sharding spec on a '
                      'multi-device grid — GSPMD replicates it (a '
                      'FULL matrix per shard), breaking the O(N^2/P) '
                      'memory contract; annotate it %s'
                      % (op.type, name, expect), op=op, op_index=i,
                      var=name)
            continue
        got = _norm(_entries(shardings[name]))
        if got != _norm(expect):
            ctx.error('implicit-full-gather',
                      '%s operand %r is annotated %s but the blocked '
                      'layout is %s — GSPMD must insert a whole-'
                      'matrix reshard before the shard_map every step'
                      % (op.type, name, got, _norm(expect)), op=op,
                      op_index=i, var=name)


@analysis_pass('linalg')
def check(ctx):
    program = ctx.program
    mesh_shape = {}
    if program.mesh is not None:
        mesh_shape = {str(a): int(s)
                      for a, s in dict(program.mesh.shape).items()}
    shardings = program.var_shardings or {}

    for i, op in enumerate(ctx.block.ops):
        if op.type not in LINALG_OPS:
            continue
        row = op.attrs.get('row_axis', op.attrs.get('axis', 'dp'))
        col = op.attrs.get('col_axis', 'tp')
        n_dp = int(mesh_shape.get(row, 1))
        n_tp = int(mesh_shape.get(col, 1))
        on_grid = n_dp * n_tp > 1

        def dim(name, d):
            shape = ctx.shape_of(name)
            if shape is None or d >= len(shape):
                return None
            v = shape[d]
            return int(v) if v is not None and v >= 0 else None

        if op.type == 'summa_matmul':
            xn, yn, on = op.input('X'), op.input('Y'), op.output('Out')
            n, k = dim(xn, 0), dim(xn, 1)
            m = dim(yn, 1)
            for label, size, ax, extent in (
                    ('N', n, row, n_dp), ('K', k, row, n_dp),
                    ('K', k, col, n_tp), ('M', m, col, n_tp)):
                if size is not None and extent > 1 and size % extent:
                    ctx.error('block-indivisible',
                              'summa_matmul dim %s=%d is not divisible '
                              'by mesh axis %r (size %d) — the operand '
                              'cannot be blocked without padding'
                              % (label, size, ax, extent), op=op,
                              op_index=i, var=xn)
            panel = int(op.attrs.get('panel', 0) or 0)
            if panel > 0 and k is not None and not (k % n_dp or
                                                    k % n_tp):
                g = _gcd(k // n_tp, k // n_dp)
                if g % panel:
                    legal = max(d for d in range(1, panel + 1)
                                if g % d == 0)
                    ctx.warning('panel-misaligned',
                                'summa_matmul panel=%d does not divide '
                                'gcd(K/%s, K/%s)=%d; the lowering '
                                'rounds it down to %d'
                                % (panel, col, row, g, legal), op=op,
                                op_index=i, var=xn)
            if on_grid:
                _check_layout(ctx, op, i, shardings,
                              {xn: (row, col), yn: (row, col),
                               on: (row, col)})

        elif op.type in ('blocked_cholesky', 'blocked_qr'):
            xn = op.input('X')
            n = dim(xn, 0)
            m = dim(xn, 1)
            if n is not None and n_dp > 1 and n % n_dp:
                ctx.error('block-indivisible',
                          '%s N=%d is not divisible by mesh axis %r '
                          '(size %d)' % (op.type, n, row, n_dp), op=op,
                          op_index=i, var=xn)
            block = int(op.attrs.get('block', 0) or 0)
            if block > 0:
                extent = None
                if op.type == 'blocked_cholesky' and n is not None \
                        and n_dp >= 1 and not (n % max(n_dp, 1)):
                    extent, what = n // max(n_dp, 1), 'N/dp'
                elif op.type == 'blocked_qr' and m is not None:
                    extent, what = m, 'M'
                if extent is not None and extent % block:
                    legal = max(d for d in range(1, block + 1)
                                if extent % d == 0)
                    ctx.warning('panel-misaligned',
                                '%s block=%d does not divide %s=%d; '
                                'the lowering rounds it down to %d'
                                % (op.type, block, what, extent,
                                   legal), op=op, op_index=i, var=xn)
            if n_dp > 1:
                want = {xn: (row,)}
                if op.type == 'blocked_cholesky':
                    want[op.output('Out')] = (row,)
                else:
                    want[op.output('Q')] = (row,)
                    want[op.output('R')] = ()
                _check_layout(ctx, op, i, shardings, want)

        elif op.type == 'power_iter_step':
            xn, vn = op.input('X'), op.input('V')
            n = dim(xn, 0)
            if n is not None and n_dp > 1 and n % n_dp:
                ctx.error('block-indivisible',
                          'power_iter_step N=%d is not divisible by '
                          'mesh axis %r (size %d)' % (n, row, n_dp),
                          op=op, op_index=i, var=xn)
            if n_dp > 1:
                _check_layout(ctx, op, i, shardings,
                              {xn: (None, row), vn: (),
                               op.output('VOut'): (),
                               op.output('Eigval'): ()})
