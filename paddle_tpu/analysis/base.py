"""Pass framework for static analysis over Program IR.

A pass is a function ``fn(ctx)`` registered under a name with
``@analysis_pass('name')``; it inspects ``ctx.program`` and reports
findings through ``ctx.error / ctx.warning / ctx.info``, each of which
appends a structured :class:`Diagnostic` (severity, pass name, op
index, variable, and the op's construction provenance ``file:line``).
Passes NEVER mutate the program and never raise for findings — raising
is the caller's policy (``analysis.verify`` in strict mode).

The framework is deliberately jax-free at module level so
``tools/program_lint.py`` can lint a serialized program without
touching an accelerator runtime.
"""

SEVERITY_ERROR = 'error'
SEVERITY_WARNING = 'warning'
SEVERITY_INFO = 'info'
SEVERITIES = (SEVERITY_ERROR, SEVERITY_WARNING, SEVERITY_INFO)


class Diagnostic(object):
    """One finding: where (op index / var / provenance), what (pass,
    code, message), how bad (severity)."""

    __slots__ = ('pass_name', 'code', 'severity', 'message', 'op_index',
                 'op_type', 'block_idx', 'var', 'provenance')

    def __init__(self, pass_name, code, severity, message, op_index=None,
                 op_type=None, block_idx=0, var=None, provenance=None):
        if severity not in SEVERITIES:
            raise ValueError('unknown severity %r' % (severity,))
        self.pass_name = pass_name
        self.code = code
        self.severity = severity
        self.message = message
        self.op_index = op_index
        self.op_type = op_type
        self.block_idx = block_idx
        self.var = var
        self.provenance = provenance

    def to_dict(self):
        return {'pass': self.pass_name, 'code': self.code,
                'severity': self.severity, 'message': self.message,
                'op_index': self.op_index, 'op_type': self.op_type,
                'block': self.block_idx, 'var': self.var,
                'provenance': self.provenance}

    def format(self):
        loc = []
        if self.op_index is not None:
            loc.append('op#%d' % self.op_index)
        if self.op_type:
            loc.append(self.op_type)
        if self.var:
            loc.append('var %r' % self.var)
        where = ' ' + ' '.join(loc) if loc else ''
        built = ' (built at %s)' % self.provenance if self.provenance \
            else ''
        return '%s[%s/%s]%s: %s%s' % (self.severity, self.pass_name,
                                      self.code, where, self.message,
                                      built)

    def __repr__(self):
        return 'Diagnostic(%s)' % self.format()


class ProgramVerifyError(RuntimeError):
    """Strict-mode verification failure. `.diagnostics` holds EVERY
    finding from the run (warnings/infos included); the message lists
    the errors that made it raise."""

    def __init__(self, diagnostics, context=None):
        self.diagnostics = list(diagnostics)
        errors = [d for d in self.diagnostics
                  if d.severity == SEVERITY_ERROR]
        head = ('program verification failed%s: %d error(s), '
                '%d diagnostic(s) total'
                % (' [%s]' % context if context else '', len(errors),
                   len(self.diagnostics)))
        lines = [head] + ['  ' + d.format() for d in errors[:20]]
        if len(errors) > 20:
            lines.append('  ... and %d more errors' % (len(errors) - 20))
        super(ProgramVerifyError, self).__init__('\n'.join(lines))


# Registered passes in definition order (the order they run).
PASSES = {}


def analysis_pass(name):
    """Register a pass under `name`. The function receives an
    AnalysisContext and reports via ctx.error/warning/info."""
    def deco(fn):
        if name in PASSES:
            raise ValueError('duplicate analysis pass %r' % name)
        PASSES[name] = fn
        return fn
    return deco


class AnalysisContext(object):
    """What a pass sees: the program, optional feed/fetch context, and
    the diagnostics sink."""

    def __init__(self, program, feed_names=None, fetch_names=None):
        self.program = program
        self.block = program.global_block()
        self.feed_names = set(feed_names or ())
        self.fetch_names = [getattr(f, 'name', f)
                            for f in (fetch_names or ())]
        self.diagnostics = []
        self._pass = None

    # ------------------------------------------------------------ report
    def _report(self, severity, code, message, op=None, op_index=None,
                var=None):
        self.diagnostics.append(Diagnostic(
            self._pass, code, severity, message, op_index=op_index,
            op_type=getattr(op, 'type', None),
            block_idx=getattr(getattr(op, 'block', None), 'idx', 0),
            var=var, provenance=getattr(op, 'provenance', None)))

    def error(self, code, message, op=None, op_index=None, var=None):
        self._report(SEVERITY_ERROR, code, message, op, op_index, var)

    def warning(self, code, message, op=None, op_index=None, var=None):
        self._report(SEVERITY_WARNING, code, message, op, op_index, var)

    def info(self, code, message, op=None, op_index=None, var=None):
        self._report(SEVERITY_INFO, code, message, op, op_index, var)

    # ----------------------------------------------------------- helpers
    def find_var(self, name):
        return self.block._find_var_recursive(name)

    def shape_of(self, name):
        """Declared shape tuple (with -1 wildcards) or None."""
        v = self.find_var(name)
        if v is None or v.shape is None:
            return None
        return tuple(v.shape)

    def dtype_of(self, name):
        v = self.find_var(name)
        return v.dtype if v is not None else None


def _ensure_passes_loaded():
    # importing the modules registers their passes
    from . import wellformed, shapes, sharding, donation, \
        recompile, quant, linalg  # noqa: F401


def run_passes(program, feed_names=None, fetch_names=None, passes=None):
    """Run the analysis passes over `program`; returns the list of
    Diagnostics in pass order. `passes` selects a subset by name
    (default: every registered pass). A pass that crashes becomes a
    'pass-crashed' warning instead of masking the program under
    analysis — the verifier must never be the thing that takes a
    training run down."""
    _ensure_passes_loaded()
    ctx = AnalysisContext(program, feed_names=feed_names,
                          fetch_names=fetch_names)
    for name in (list(PASSES) if passes is None else passes):
        if name not in PASSES:
            raise ValueError('unknown analysis pass %r (have: %s)'
                             % (name, ', '.join(PASSES)))
        ctx._pass = name
        try:
            PASSES[name](ctx)
        except Exception as e:
            ctx.warning('pass-crashed',
                        'analysis pass %r crashed: %s: %s'
                        % (name, type(e).__name__, e))
    return ctx.diagnostics
