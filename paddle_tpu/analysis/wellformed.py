"""Pass 1 — graph well-formedness.

Catches the whole-program-compilation failure modes that otherwise
surface as opaque KeyErrors deep inside the tracer: reads of names no
block defines, reads that happen before their producer in block order,
two ops racing on one temporary, and ops no fetch target can reach
(which the executor silently prunes — dead weight in the builder).
"""

from .base import analysis_pass

# Op types that legitimately rewrite an existing var (loop counters,
# tensor-array cells, explicit copies); duplicate writes through them
# are control flow, not races.
_REWRITE_OPS = frozenset(('array_write', 'assign', 'increment', 'while',
                          'if_else', 'static_rnn', 'dynamic_rnn',
                          'beam_search', 'scatter'))

# Pruning survivors that exist for their side effect, not a fetch.
_EFFECT_OPS = frozenset(('print', 'backward_marker'))


def _injected_names(program):
    """Names that op LOWERINGS inject into sub-block envs at trace time
    rather than any op producing them: recurrent memories
    (memory_names pre entries), per-step scan slices
    (step_input_names), and the generation-decode feedback token
    (id_pre_name). The executor treats reads of declared-nowhere names
    the same way (core/executor.py _compile feeds them through), so
    they are convention, not breakage."""
    injected = set()
    for b in program.blocks:
        for op in b.ops:
            for pre, _cur in op.attrs.get('memory_names') or ():
                injected.add(pre)
            injected.update(op.attrs.get('step_input_names') or ())
            id_pre = op.attrs.get('id_pre_name')
            if id_pre:
                injected.add(id_pre)
    return injected


@analysis_pass('wellformed')
def check(ctx):
    from ..core.executor import _op_reads, _prune_ops
    program, block = ctx.program, ctx.block
    reads_cache = {}

    defined = set(ctx.feed_names)
    for b in program.blocks:
        for name, v in b.vars.items():
            if v.persistable or v.is_data:
                defined.add(name)

    all_written = set()
    for b in program.blocks:
        for op in b.ops:
            all_written.update(op.output_names())
            if op.type == 'backward_marker':
                all_written.update(op.attrs.get('grad_names', ()))

    defined |= _injected_names(program)

    producers = {}
    for i, op in enumerate(block.ops):
        if op.type == 'backward_marker':
            defined.update(op.attrs.get('grad_names', ()))
            continue
        direct = set(op.input_names())
        for name in _op_reads(op, program, reads_cache):
            if name in defined:
                continue
            defined.add(name)   # report each name once
            if ctx.find_var(name) is None:
                if name in direct:
                    ctx.error('undefined-input',
                              'op reads %r, which no block declares '
                              'and no op produces' % name, op=op,
                              op_index=i, var=name)
                else:
                    # a sub-block read of a declared-nowhere name: the
                    # executor assumes a lowering injects it; flag it,
                    # but not fatally
                    ctx.warning('undefined-subblock-input',
                                'sub-block of op reads %r, which no '
                                'block declares and no op produces — '
                                'the lowering must inject it at trace '
                                'time' % name, op=op, op_index=i,
                                var=name)
            elif name in all_written:
                ctx.error('use-before-def',
                          'op reads %r before any producer in block '
                          'order (first written by a later op)' % name,
                          op=op, op_index=i, var=name)
            else:
                ctx.error('uninitialized-input',
                          'op reads %r, which is neither fed, '
                          'persistable, nor produced by any op — the '
                          'executor will fail to gather it from scope'
                          % name, op=op, op_index=i, var=name)
        for name in op.output_names():
            defined.add(name)
            producers.setdefault(name, []).append((i, op))

    for name, writers in producers.items():
        if len(writers) <= 1:
            continue
        v = ctx.find_var(name)
        if v is not None and v.persistable:
            continue   # in-place persistable updates: donation pass
        if any(op.type in _REWRITE_OPS for _, op in writers):
            continue
        i, op = writers[1]
        ctx.warning('duplicate-writer',
                    '%r is written by %d ops (first at op#%d %s) — '
                    'later writes shadow earlier ones in one trace'
                    % (name, len(writers), writers[0][0],
                       writers[0][1].type), op=op, op_index=i, var=name)

    if ctx.fetch_names:
        kept = set(id(op) for op in _prune_ops(
            block, list(block.ops), ctx.fetch_names, reads_cache))
        for i, op in enumerate(block.ops):
            if id(op) in kept or op.type in _EFFECT_OPS:
                continue
            ctx.info('dead-op',
                     'op reaches no fetch target and writes no '
                     'persistable state; the executor prunes it',
                     op=op, op_index=i)
