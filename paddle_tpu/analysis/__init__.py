"""paddle_tpu.analysis — the Program IR verifier.

Whole-program XLA compilation means graph bugs otherwise surface as
opaque tracer exceptions (or silent recompiles) deep inside `jit`, far
from the user code that appended the op. This package runs BEFORE any
trace: seven static-analysis passes over Program/Block/Operator IR,
each emitting structured diagnostics with severity, op index, and the
op's construction provenance (`file.py:line`, captured at append_op).

Passes (see docs/static_analysis.md for the full catalog):

- ``wellformed`` — undefined inputs, use-before-def in block order,
  duplicate writers of one temporary, fetch-unreachable dead ops,
- ``shapes``     — shape/dtype contracts for the high-traffic op set
  (matmul/mul, conv, fused attention, norms, elementwise, reshape/
  concat/transpose, optimizer updates),
- ``sharding``   — PartitionSpec consistency: unknown mesh axes,
  axis-indivisible dims, unannotated params on a >1 mesh, spec
  conflicts that force implicit resharding,
- ``donation``   — double-donation and read-after-donate of in-place
  persistable state (params, optimizer accumulators, KV arenas),
- ``recompile``  — attrs embedding per-process values/object ids and
  unbound feed dims: the executor-cache signature-churn class.
- ``quant``      — quantization dtype/scale contracts: int8 PTQ
  weights must pair with fp32 per-channel scale vars (fp32
  accumulation), quantized KV arenas with per-row scale arenas.
- ``linalg``     — blocked-layout contracts for the distributed
  linalg tier: block divisibility vs mesh axes, panel-spec
  consistency, and no implicit full-gather resharding (a missing or
  wrong blocked spec would hand GSPMD a full matrix per shard).

Three ways in:

- ``PADDLE_TPU_VERIFY=off|warn|strict`` on the Executor: each program
  key is verified ONCE at first compile. ``strict`` raises
  :class:`ProgramVerifyError` before anything traces; ``warn`` records
  ``program_verify`` flight events plus
  ``analysis.diagnostics_total{severity,pass}`` counters and carries
  on.
- The trainer and both serving engines call :func:`startup_verify` at
  startup (default mode ``warn`` when the env is unset).
- ``python tools/program_lint.py model_dir/`` lints a serialized
  program offline (``--json`` for machines).
"""

import os
import time

from .base import (SEVERITIES, SEVERITY_ERROR, SEVERITY_INFO,  # noqa: F401
                   SEVERITY_WARNING, AnalysisContext, Diagnostic,
                   PASSES, ProgramVerifyError, analysis_pass,
                   run_passes)

__all__ = ['Diagnostic', 'ProgramVerifyError', 'analysis_pass',
           'run_passes', 'verify', 'startup_verify', 'verify_mode',
           'summarize', 'PASSES', 'SEVERITIES']

_MODES = ('off', 'warn', 'strict')


def verify_mode(default='off'):
    """The PADDLE_TPU_VERIFY mode ('off' | 'warn' | 'strict'), read per
    call so tests and long-lived processes can flip it; `default`
    applies when the variable is unset."""
    raw = os.environ.get('PADDLE_TPU_VERIFY', '').strip().lower()
    if not raw:
        return default
    if raw not in _MODES:
        raise ValueError('PADDLE_TPU_VERIFY=%r (expected one of %s)'
                         % (raw, '|'.join(_MODES)))
    return raw


def summarize(diagnostics):
    """{severity: count} over a diagnostics list (all keys present)."""
    counts = dict.fromkeys(SEVERITIES, 0)
    for d in diagnostics:
        counts[d.severity] += 1
    return counts


def verify(program, feed_names=None, fetch_names=None, mode='strict',
           label='program'):
    """Run every pass over `program` and apply `mode`: 'off' skips
    entirely (returns []), 'warn' publishes telemetry and returns the
    diagnostics, 'strict' additionally raises ProgramVerifyError when
    any error-severity diagnostic exists. `label` tags the telemetry
    (trainer / serving / decode / executor kind)."""
    if mode == 'off':
        return []
    if mode not in _MODES:
        raise ValueError('verify mode %r (expected one of %s)'
                         % (mode, '|'.join(_MODES)))
    t0 = time.perf_counter()
    diags = run_passes(program, feed_names=feed_names,
                       fetch_names=fetch_names)
    dt = time.perf_counter() - t0
    _publish(label, diags, dt)
    if mode == 'strict':
        counts = summarize(diags)
        if counts[SEVERITY_ERROR]:
            raise ProgramVerifyError(diags, context=label)
    return diags


def startup_verify(program, feed_names=None, fetch_names=None,
                   label='startup'):
    """Entry point for the trainer and serving engines: one verification
    at startup, honoring PADDLE_TPU_VERIFY but defaulting to 'warn'
    when unset (the check is one pure-Python walk over the ops — noise
    next to the XLA compile it precedes)."""
    return verify(program, feed_names=feed_names,
                  fetch_names=fetch_names,
                  mode=verify_mode(default='warn'), label=label)


def _publish(label, diags, seconds):
    from .. import observe as _obs
    counts = summarize(diags)
    if _obs.enabled():
        _obs.inc('analysis.programs_verified_total', label=label)
        _obs.record('analysis.verify_seconds', seconds, label=label)
        for d in diags:
            _obs.inc('analysis.diagnostics_total',
                     **{'severity': d.severity, 'pass': d.pass_name})
    first_error = next((d.format() for d in diags
                        if d.severity == SEVERITY_ERROR), None)
    event = {'label': label, 'seconds': round(seconds, 6),
             'errors': counts[SEVERITY_ERROR],
             'warnings': counts[SEVERITY_WARNING],
             'infos': counts[SEVERITY_INFO]}
    if first_error:
        event['first_error'] = first_error[:300]
    _obs.flight_event('program_verify', **event)
