"""Optimizers (reference: python/paddle/fluid/optimizer.py).

Same class surface as the reference (SGD/Momentum/Adagrad/Adam/Adamax/
DecayedAdagrad + Adadelta/RMSProp/Ftrl). minimize() appends backward +
clip + regularization + update ops; the Executor fuses everything into the
single jitted train step with parameter buffers donated in HBM.
"""

from .clip import append_gradient_clip_ops
from .core.backward import append_backward
from .core.program import Variable, default_main_program
from .initializer import Constant
from .layers.helper import LayerHelper
from .regularizer import append_regularization_ops

__all__ = ['SGD', 'Momentum', 'Adagrad', 'Adam', 'Adamax', 'DecayedAdagrad',
           'Adadelta', 'RMSProp', 'Ftrl', 'SGDOptimizer',
           'MomentumOptimizer', 'AdagradOptimizer', 'AdamOptimizer',
           'AdamaxOptimizer', 'DecayedAdagradOptimizer',
           'AdadeltaOptimizer', 'RMSPropOptimizer', 'FtrlOptimizer',
           'ProximalAdagrad', 'ProximalAdagradOptimizer',
           'Optimizer', 'GradientAccumulator']


class Optimizer(object):
    # True on optimizers whose update op can consume row-sparse embedding
    # gradients (scatter rows in place of a dense [vocab, dim] grad —
    # the reference's SelectedRows path, lookup_table_op.cc:119-127).
    # SGD and Adagrad support it exactly, like the reference pserver;
    # moment-decay optimizers (Adam & co.) decay EVERY row every step,
    # so they take the dense path for exactness.
    _supports_sparse_update = False

    def __init__(self, learning_rate, regularization=None, name=None):
        if not isinstance(learning_rate, (float, Variable)):
            raise TypeError('learning_rate must be float or Variable')
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._name = name
        self._accumulators = {}
        self._learning_rate_var = None
        self.helper = None

    # ---------------------------------------------------------------- lr
    def _create_global_learning_rate(self):
        if self._learning_rate_var is not None:
            return
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_var = self._learning_rate
            return
        from .core import unique_name
        helper = LayerHelper('learning_rate')
        name = unique_name.generate('learning_rate')
        var = helper.main_program.global_block().create_var(
            name=name, shape=(1,), dtype='float32', persistable=True)
        var.stop_gradient = True
        Constant(float(self._learning_rate))(var)
        self._learning_rate_var = var

    def _global_learning_rate(self):
        return self._learning_rate_var

    def _create_param_lr(self, param_and_grad):
        param = param_and_grad[0]
        mult = getattr(param, 'optimize_attr', {}).get('learning_rate', 1.0)
        if mult == 1.0:
            return self._learning_rate_var
        helper = LayerHelper('param_lr')
        out = helper.create_variable_for_type_inference('float32')
        out.shape = (1,)
        out.stop_gradient = True
        helper.append_op(type='scale',
                         inputs={'X': [self._learning_rate_var]},
                         outputs={'Out': [out]}, attrs={'scale': mult})
        return out

    # ------------------------------------------------------- accumulators
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if (name, param.name) in self._accumulators:
            raise ValueError('accumulator %s for %s exists' %
                             (name, param.name))
        block = default_main_program().global_block()
        var = block.create_var(
            name='%s_%s_acc' % (param.name, name),
            shape=tuple(shape) if shape is not None else param.shape,
            dtype=dtype or param.dtype, persistable=True)
        var.stop_gradient = True
        Constant(float(fill_value))(var)
        self._accumulators[(name, param.name)] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[(name, param.name)]

    # ----------------------------------------------------------- hooks
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block):
        pass

    # ----------------------------------------------------------- driver
    def _create_optimization_pass(self, parameters_and_grads, loss,
                                  startup_program=None):
        block = loss.block.program.global_block()
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        self._create_accumulators(block,
                                  [p for p, _ in parameters_and_grads])
        optimize_ops = []
        for param_and_grad in parameters_and_grads:
            if param_and_grad[1] is None or not param_and_grad[0].trainable:
                continue
            optimize_ops.append(
                self._append_optimize_op(block, param_and_grad))
        self._finish_update(block)
        return optimize_ops

    def _minimize_prologue(self, loss, startup_program, parameter_list,
                           no_grad_set):
        """Shared front half of minimize: resolve programs, append
        backward + clip + regularization. Returns (main_program,
        startup_program, params_grads); the caller appends its update
        ops under program_guard(main, startup)."""
        from .core.program import default_startup_program
        main_program = loss.block.program
        if startup_program is None:
            startup_program = main_program._startup_ref or \
                default_startup_program()
        from .core.program import program_guard
        with program_guard(main_program, startup_program):
            # optimizer-level regularization applies to EVERY param and
            # is written against the dense grad shape — disable sparse
            params_grads = append_backward(
                loss, parameter_list, no_grad_set,
                sparse_supported=(self._supports_sparse_update and
                                  self.regularization is None))
            params_grads = append_gradient_clip_ops(params_grads)
            params_grads = append_regularization_ops(params_grads,
                                                     self.regularization)
        return main_program, startup_program, params_grads

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        # All helper ops (lr var, accumulators, clip, regularizer) must land
        # in the LOSS's program, not whatever default is current — guard it
        # (the reference wraps the same way via program_guard).
        from .core.program import program_guard
        main_program, startup_program, params_grads = \
            self._minimize_prologue(loss, startup_program, parameter_list,
                                    no_grad_set)
        with program_guard(main_program, startup_program):
            optimize_ops = self._create_optimization_pass(
                params_grads, loss, startup_program)
        return optimize_ops, params_grads


class SGDOptimizer(Optimizer):
    _supports_sparse_update = True

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            type='sgd',
            inputs={'Param': [param], 'Grad': [grad],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param]})


class MomentumOptimizer(Optimizer):
    _velocity_acc_str = 'velocity'

    def __init__(self, learning_rate, momentum, use_nesterov=False,
                 lazy_mode=False, **kwargs):
        """lazy_mode=True (opt-in, r5): row-sparse embedding gradients
        update param AND velocity only on rows touched this step —
        untouched rows skip the mu-decay dense momentum applies every
        step. A documented divergence traded for never materializing
        the O(vocab) grad (see AdamOptimizer.lazy_mode for the measured
        dense cost)."""
        super(MomentumOptimizer, self).__init__(learning_rate, **kwargs)
        self._momentum = momentum
        self._use_nesterov = use_nesterov
        if lazy_mode:
            self._supports_sparse_update = True

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._velocity_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator(self._velocity_acc_str, param)
        return block.append_op(
            type='momentum',
            inputs={'Param': [param], 'Grad': [grad],
                    'Velocity': [velocity],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param], 'VelocityOut': [velocity]},
            attrs={'mu': self._momentum,
                   'use_nesterov': self._use_nesterov})


class AdagradOptimizer(Optimizer):
    _supports_sparse_update = True
    _moment_acc_str = 'moment'

    def __init__(self, learning_rate, epsilon=1e-6, **kwargs):
        super(AdagradOptimizer, self).__init__(learning_rate, **kwargs)
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, param)
        return block.append_op(
            type='adagrad',
            inputs={'Param': [param], 'Grad': [grad], 'Moment': [moment],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param], 'MomentOut': [moment]},
            attrs={'epsilon': self._epsilon})


class AdamOptimizer(Optimizer):
    _moment1_acc_str = 'moment1'
    _moment2_acc_str = 'moment2'

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kwargs):
        """lazy_mode=True (opt-in, r5 — VERDICT r4 next-#7): row-sparse
        embedding gradients take the lazy-Adam path — moments decay and
        the param moves only on rows touched this step (the standard
        CTR-scale answer; reference sparse-row protocol
        lookup_table_op.cc:119-127). DIVERGENCE from dense Adam, which
        decays every row's moments every step; exactness-sensitive
        configs keep the default dense fallback. Why the default stays
        dense-off but the flag exists: at a 1e6-row x 64 table, batch
        256 x 16 ids, the dense fallback materializes three
        [1e6, 64] vocab-sized tensors per step (grad + two moment
        updates) where lazy touches [4096, 64] rows — a ~250x per-step
        memory-traffic gap on the embedding update."""
        super(AdamOptimizer, self).__init__(learning_rate, **kwargs)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._beta1_pow = None
        self._beta2_pow = None
        if lazy_mode:
            self._supports_sparse_update = True

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment1_acc_str, p)
            self._add_accumulator(self._moment2_acc_str, p)
        main = default_main_program().global_block()
        from .core import unique_name
        self._beta1_pow = main.create_var(
            name=unique_name.generate('beta1_pow_acc'), shape=(1,),
            dtype='float32', persistable=True)
        self._beta1_pow.stop_gradient = True
        Constant(self._beta1)(self._beta1_pow)
        self._beta2_pow = main.create_var(
            name=unique_name.generate('beta2_pow_acc'), shape=(1,),
            dtype='float32', persistable=True)
        self._beta2_pow.stop_gradient = True
        Constant(self._beta2)(self._beta2_pow)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment1 = self._get_accumulator(self._moment1_acc_str, param)
        moment2 = self._get_accumulator(self._moment2_acc_str, param)
        return block.append_op(
            type='adam',
            inputs={'Param': [param], 'Grad': [grad],
                    'Moment1': [moment1], 'Moment2': [moment2],
                    'Beta1Pow': [self._beta1_pow],
                    'Beta2Pow': [self._beta2_pow],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param], 'Moment1Out': [moment1],
                     'Moment2Out': [moment2]},
            attrs={'beta1': self._beta1, 'beta2': self._beta2,
                   'epsilon': self._epsilon})

    def _finish_update(self, block):
        block.append_op(
            type='adam_beta_pow_update',
            inputs={'Beta1Pow': [self._beta1_pow],
                    'Beta2Pow': [self._beta2_pow]},
            outputs={'Beta1PowOut': [self._beta1_pow],
                     'Beta2PowOut': [self._beta2_pow]},
            attrs={'beta1': self._beta1, 'beta2': self._beta2})


class AdamaxOptimizer(Optimizer):
    _moment_acc_str = 'moment'
    _inf_norm_acc_str = 'inf_norm'

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super(AdamaxOptimizer, self).__init__(learning_rate, **kwargs)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._beta1_pow = None

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)
            self._add_accumulator(self._inf_norm_acc_str, p)
        from .core import unique_name
        main = default_main_program().global_block()
        self._beta1_pow = main.create_var(
            name=unique_name.generate('beta1_pow_acc'), shape=(1,),
            dtype='float32', persistable=True)
        self._beta1_pow.stop_gradient = True
        Constant(self._beta1)(self._beta1_pow)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, param)
        inf_norm = self._get_accumulator(self._inf_norm_acc_str, param)
        return block.append_op(
            type='adamax',
            inputs={'Param': [param], 'Grad': [grad], 'Moment': [moment],
                    'InfNorm': [inf_norm],
                    'Beta1Pow': [self._beta1_pow],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param], 'MomentOut': [moment],
                     'InfNormOut': [inf_norm]},
            attrs={'beta1': self._beta1, 'beta2': self._beta2,
                   'epsilon': self._epsilon})

    def _finish_update(self, block):
        block.append_op(type='beta_pow_update',
                        inputs={'BetaPow': [self._beta1_pow]},
                        outputs={'BetaPowOut': [self._beta1_pow]},
                        attrs={'beta': self._beta1})


class DecayedAdagradOptimizer(Optimizer):
    _moment_acc_str = 'moment'

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kwargs):
        super(DecayedAdagradOptimizer, self).__init__(learning_rate,
                                                      **kwargs)
        self._decay = decay
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, param)
        return block.append_op(
            type='decayed_adagrad',
            inputs={'Param': [param], 'Grad': [grad], 'Moment': [moment],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param], 'MomentOut': [moment]},
            attrs={'decay': self._decay, 'epsilon': self._epsilon})


class AdadeltaOptimizer(Optimizer):
    _avg_squared_grad_acc_str = '_avg_squared_grad'
    _avg_squared_update_acc_str = '_avg_squared_update'

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kwargs):
        super(AdadeltaOptimizer, self).__init__(learning_rate, **kwargs)
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._avg_squared_grad_acc_str, p)
            self._add_accumulator(self._avg_squared_update_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        asg = self._get_accumulator(self._avg_squared_grad_acc_str, param)
        asu = self._get_accumulator(self._avg_squared_update_acc_str, param)
        return block.append_op(
            type='adadelta',
            inputs={'Param': [param], 'Grad': [grad],
                    'AvgSquaredGrad': [asg], 'AvgSquaredUpdate': [asu]},
            outputs={'ParamOut': [param], 'AvgSquaredGradOut': [asg],
                     'AvgSquaredUpdateOut': [asu]},
            attrs={'epsilon': self._epsilon, 'rho': self._rho})


class RMSPropOptimizer(Optimizer):
    _momentum_acc_str = 'momentum'
    _mean_square_acc_str = 'mean_square'

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 **kwargs):
        super(RMSPropOptimizer, self).__init__(learning_rate, **kwargs)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._momentum_acc_str, p)
            self._add_accumulator(self._mean_square_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        momentum = self._get_accumulator(self._momentum_acc_str, param)
        mean_square = self._get_accumulator(self._mean_square_acc_str, param)
        return block.append_op(
            type='rmsprop',
            inputs={'Param': [param], 'Grad': [grad],
                    'Moment': [momentum], 'MeanSquare': [mean_square],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param], 'MomentOut': [momentum],
                     'MeanSquareOut': [mean_square]},
            attrs={'epsilon': self._epsilon, 'decay': self._rho,
                   'momentum': self._momentum})


class FtrlOptimizer(Optimizer):
    _squared_acc_str = 'squared'
    _linear_acc_str = 'linear'

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 **kwargs):
        super(FtrlOptimizer, self).__init__(learning_rate, **kwargs)
        self._l1 = l1
        self._l2 = l2
        self._lr_power = lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._squared_acc_str, p)
            self._add_accumulator(self._linear_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        sq = self._get_accumulator(self._squared_acc_str, param)
        lin = self._get_accumulator(self._linear_acc_str, param)
        return block.append_op(
            type='ftrl',
            inputs={'Param': [param], 'Grad': [grad],
                    'SquaredAccumulator': [sq], 'LinearAccumulator': [lin],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param], 'SquaredAccumOut': [sq],
                     'LinearAccumOut': [lin]},
            attrs={'l1': self._l1, 'l2': self._l2,
                   'lr_power': self._lr_power})


class ProximalAdagradOptimizer(Optimizer):
    """Adagrad with the proximal l1/l2 operator
    (proximal_adagrad_op.{cc,h})."""
    _moment_acc_str = 'moment'

    def __init__(self, learning_rate, l1=0.0, l2=0.0, **kwargs):
        super(ProximalAdagradOptimizer, self).__init__(learning_rate,
                                                       **kwargs)
        self._l1 = l1
        self._l2 = l2

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator(self._moment_acc_str, p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator(self._moment_acc_str, param)
        return block.append_op(
            type='proximal_adagrad',
            inputs={'Param': [param], 'Grad': [grad], 'Moment': [moment],
                    'LearningRate': [self._create_param_lr(param_and_grad)]},
            outputs={'ParamOut': [param], 'MomentOut': [moment]},
            attrs={'l1': self._l1, 'l2': self._l2})


SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
ProximalAdagrad = ProximalAdagradOptimizer


class GradientAccumulator(object):
    """Gradient accumulation: wrap any optimizer so the parameter update
    applies every `accum_steps` executor steps with the MEAN of the
    accumulated gradients — effective batch = accum_steps x micro-batch
    without the memory of the large batch.

    No reference analog (the pserver era predates it); TPU-native
    design: no Python branching — every step runs the same XLA program.
    Per (param, grad): acc += grad and the inner update consumes acc /
    accum_steps; every persistable var the inner update writes (params,
    moments, beta pows) is snapshotted before the update ops and
    blended back with select arithmetic `snap + (new - snap) * flag`,
    where flag = [phase == accum_steps - 1]; acc and the phase counter
    reset on apply steps. Composes with Executor.run_steps (state
    chains through the scan carry).

    Caveats: gradient clip / regularization (the inner optimizer's
    config) apply to each MICRO gradient before accumulation. The two
    step clocks differ by design: @LR_DECAY_COUNTER@ (created by the lr
    schedule before this wrapper's gated region) advances every MICRO
    step, while a user-supplied `global_step` counter is written inside
    the inner optimization pass and therefore gated — it counts APPLIED
    updates, advancing once per accum_steps micro steps."""

    def __init__(self, optimizer, accum_steps):
        if int(accum_steps) != accum_steps or accum_steps < 1:
            raise ValueError('accum_steps must be a positive integer, '
                             'got %r' % (accum_steps,))
        self._inner = optimizer
        self._k = int(accum_steps)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .core import unique_name
        from .core.program import program_guard
        from . import layers as _layers
        inner = self._inner
        k = self._k
        if k == 1:
            return inner.minimize(loss, startup_program, parameter_list,
                                  no_grad_set)
        # row-sparse embedding grads cannot accumulate across micro steps
        # (each step's [n_ids, dim] rows index different ids) — force the
        # exact dense path for the gated region. Save/restore any
        # instance-level value (lazy_mode sets one) instead of popping.
        had = '_supports_sparse_update' in inner.__dict__
        saved = inner.__dict__.get('_supports_sparse_update')
        inner.__dict__['_supports_sparse_update'] = False
        try:
            main_program, startup_program, params_grads = \
                inner._minimize_prologue(loss, startup_program,
                                         parameter_list, no_grad_set)
        finally:
            if had:
                inner.__dict__['_supports_sparse_update'] = saved
            else:
                inner.__dict__.pop('_supports_sparse_update', None)
        block = main_program.global_block()
        with program_guard(main_program, startup_program):
            helper = LayerHelper('grad_accum')
            phase = block.create_var(name=unique_name.generate(
                'grad_accum_phase'), shape=(1,), dtype='float32',
                persistable=True)
            phase.stop_gradient = True
            Constant(0.0)(phase)
            boundary = _layers.fill_constant(shape=[1], dtype='float32',
                                             value=float(k - 1))
            flag = _layers.cast(_layers.equal(x=phase, y=boundary),
                                'float32')            # 1.0 on apply steps
            keep = _layers.scale(flag, scale=-1.0, bias=1.0)

            # acc += grad; the inner update consumes the mean grad
            accs = []
            for p, g in params_grads:
                acc = block.create_var(
                    name=unique_name.generate(p.name + '_grad_acc'),
                    shape=p.shape, dtype=p.dtype, persistable=True)
                acc.stop_gradient = True
                Constant(0.0)(acc)
                helper.append_op(type='elementwise_add',
                                 inputs={'X': [acc], 'Y': [g]},
                                 outputs={'Out': [acc]})
                helper.append_op(type='scale', inputs={'X': [acc]},
                                 outputs={'Out': [g]},
                                 attrs={'scale': 1.0 / k})
                accs.append(acc)

            mark = len(block.ops)
            optimize_ops = inner._create_optimization_pass(
                params_grads, loss, startup_program)

            # every persistable var the inner update wrote gets
            # snapshot-before / select-after treatment
            written = []
            seen = set()
            for op in block.ops[mark:]:
                for n in op.output_names():
                    v = block._find_var_recursive(n)
                    if v is not None and v.persistable and n not in seen:
                        seen.add(n)
                        written.append(v)
            insert_at = mark
            snaps = {}
            for v in written:
                snap = helper.create_variable_for_type_inference(v.dtype)
                snap.shape = v.shape
                helper.append_op(type='assign', inputs={'X': [v]},
                                 outputs={'Out': [snap]})
                block.ops.insert(insert_at, block.ops.pop())
                insert_at += 1
                snaps[v.name] = snap
            for v in written:
                snap = snaps[v.name]
                delta = _layers.elementwise_sub(x=v, y=snap)
                gated = _layers.elementwise_mul(x=delta, y=flag)
                helper.append_op(type='elementwise_add',
                                 inputs={'X': [snap], 'Y': [gated]},
                                 outputs={'Out': [v]})
            for acc in accs:  # reset on apply steps
                helper.append_op(type='elementwise_mul',
                                 inputs={'X': [acc], 'Y': [keep]},
                                 outputs={'Out': [acc]})
            bumped = _layers.scale(phase, scale=1.0, bias=1.0)
            gated_phase = _layers.elementwise_mul(x=bumped, y=keep)
            helper.append_op(type='assign',
                             inputs={'X': [gated_phase]},
                             outputs={'Out': [phase]})
        return optimize_ops, params_grads
