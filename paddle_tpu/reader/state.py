"""Checkpointable reader state: mid-epoch resume.

Reference analog: go/master/service.go:165-213 — the data master
persists its task queue to etcd and recovers mid-epoch on failover, so
resumed training sees exactly the untrained remainder. TPU-native /
masterless design: instead of storing a task queue, the wrapper makes
the epoch stream DETERMINISTIC (per-epoch shuffle seed chained from a
base seed) and records only (epoch, offset); resume replays the same
epoch order and skips the consumed prefix — recompute-over-store, the
same trade the executor makes with rematerialization.

Pairs with io.save_checkpoint(..., reader=...) / load_checkpoint(...,
reader=...). Under multihost positional sharding every process consumes
the same NUMBER of items per step, so the single-writer checkpoint's
(epoch, offset) applies to every host's shard reader.
"""

import random

__all__ = ['checkpointable', 'CheckpointableReader']


class CheckpointableReader(object):
    """Wrap a reader factory with resumable position state.

    reader: nullary callable yielding one epoch of items.
    shuffle_buf: optional buffered shuffle INSIDE the wrapper (use this
        instead of reader.shuffle — the global-RNG decorator is not
        replayable) with a per-epoch rng seeded (seed, epoch), so epoch
        k's order is identical on replay.
    seed: base seed for the per-epoch shuffle chain.

    Each __call__ yields the remainder of the current epoch (all of it
    when offset == 0) and advances (epoch, offset) as items are
    consumed; a generator abandoned mid-epoch leaves offset at the
    consumed count, which is exactly what state_dict() then captures.
    """

    def __init__(self, reader, shuffle_buf=0, seed=0):
        self._base = reader
        self._buf = int(shuffle_buf)
        self._seed = int(seed)
        self.epoch = 0
        self.offset = 0
        # Positional-shard width (parallel.multihost.shard_reader sets
        # this to the host count when it wraps us). The shard wrapper
        # sits OUTSIDE, so `offset` always counts GLOBAL stream items —
        # width items advance here per one per-host yield. The Trainer's
        # pending ledger counts PER-HOST yields; state_dict converts
        # with this width, which is what keeps a checkpointed position
        # valid when the run resumes at a different host count.
        self.shard_width = 1

    def _epoch_stream(self):
        if not self._buf:
            for e in self._base():
                yield e
            return
        rng = random.Random((self._seed * 1000003) ^ self.epoch)
        buf = []
        for e in self._base():
            buf.append(e)
            if len(buf) >= self._buf:
                rng.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            rng.shuffle(buf)
            for b in buf:
                yield b

    def __call__(self):
        skip = self.offset
        for i, e in enumerate(self._epoch_stream()):
            if i < skip:
                continue    # replayed prefix: already trained on
            self.offset = i + 1
            yield e
        self.epoch += 1
        self.offset = 0

    # ------------------------------------------------------------ state
    def state_dict(self, pending=0):
        """pending: items already PULLED from the stream but not yet
        trained on (the Trainer's partially-filled dispatch window) —
        subtracted from offset so resume replays them. Callers must not
        pass a pending that spans an epoch boundary (offset resets to 0
        there; the Trainer defers the save instead).

        pending is in PER-HOST yield units while offset is in GLOBAL
        stream units: under positional sharding one per-host yield
        advances the underlying stream by shard_width items, so pending
        is scaled before subtracting. The recorded offset is therefore
        topology-neutral — a resume at any other host count replays
        exactly the untrained global remainder. `hosts` records the
        writing width for tooling/postmortems."""
        width = max(1, int(self.shard_width))
        pending = int(pending) * width
        if pending < 0 or pending > self.offset:
            raise ValueError(
                'state_dict: pending=%d global items (pending x '
                'shard_width=%d) not in [0, offset=%d] — pulled-but-'
                'untrained items cannot span an epoch boundary'
                % (pending, width, self.offset))
        return {'epoch': int(self.epoch),
                'offset': int(self.offset) - pending,
                'seed': self._seed, 'shuffle_buf': self._buf,
                'hosts': width}

    def load_state_dict(self, state):
        if int(state.get('seed', self._seed)) != self._seed or \
                int(state.get('shuffle_buf', self._buf)) != self._buf:
            raise ValueError(
                'reader state was saved with seed=%s shuffle_buf=%s but '
                'this reader has seed=%s shuffle_buf=%s — the replayed '
                'epoch order would differ from the trained one'
                % (state.get('seed'), state.get('shuffle_buf'),
                   self._seed, self._buf))
        # offset is global-stream units — no remap needed across a
        # changed dp width (state['hosts'] is the WRITING width, kept
        # for inspection; this reader's own shard_width is whatever the
        # restoring topology set)
        self.epoch = int(state['epoch'])
        self.offset = int(state['offset'])


def checkpointable(reader, shuffle_buf=0, seed=0):
    """Decorator form: reader.checkpointable(r, shuffle_buf=1024)."""
    return CheckpointableReader(reader, shuffle_buf=shuffle_buf, seed=seed)
