"""Reader creators (reference: python/paddle/v2/reader/creator.py:19-90
np_array / text_file / recordio; cloud_reader's etcd master is replaced
by reader.shard — see parallel.multihost.shard_reader)."""

__all__ = ['np_array', 'text_file', 'recordio']


def np_array(x):
    """Yield rows of an ndarray."""
    import numpy as np
    arr = np.asarray(x)

    def reader():
        for row in arr:
            yield row
    return reader


def text_file(path):
    """Yield lines of a text file (newline stripped)."""
    def reader():
        with open(path, 'r') as f:
            for line in f:
                yield line.rstrip('\n')
    return reader


def recordio(paths, buf_size=100):
    """Yield raw records from recordio file(s) via the native reader
    (paddle_tpu/native/recordio.cpp)."""
    from .recordio import recordio_reader
    if isinstance(paths, str):
        paths = paths.split(',')

    def reader():
        for rec in recordio_reader(list(paths), prefetch=buf_size,
                                   raw=True)():
            yield rec
    return reader
