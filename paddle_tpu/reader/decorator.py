"""Reader decorators (reference: python/paddle/v2/reader/decorator.py)."""

import itertools
import random
from queue import Queue
from threading import Thread

import numpy as np

from .. import observe as _obs

__all__ = ['map_readers', 'buffered', 'compose', 'chain', 'shuffle',
           'prefetch_to_device',
           'firstn', 'xmap_readers', 'cache', 'batch', 'shard', 'retry']


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)
    return reader


def shuffle(reader, buf_size):
    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random.shuffle(buf)
            for b in buf:
                yield b
    return data_reader


def chain(*readers):
    def reader():
        for r in readers:
            for e in r():
                yield e
    return reader


def compose(*readers, **kwargs):
    check_alignment = kwargs.pop('check_alignment', True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                for o in outputs:
                    if o is None:
                        raise RuntimeError('readers have different lengths')
                yield sum(list(map(make_tuple, outputs)), ())
    return reader


def buffered(reader, size):
    class EndSignal(object):
        pass
    end = EndSignal()

    def data_reader():
        from queue import Full
        from threading import Event
        r = reader()
        q = Queue(maxsize=size)
        closed = Event()

        def put(item):
            # close-aware put: a consumer that stopped pulling
            # (break / GeneratorExit) leaves the queue full forever —
            # a bare q.put would pin this thread for the process
            # lifetime, one leaked thread per abandoned epoch
            while not closed.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except Full:
                    pass
            return False

        def read_worker():
            for d in r:
                if not put(d):
                    return
            put(end)

        t = Thread(target=read_worker,
                   name='paddle_tpu_buffered_reader')
        t.daemon = True
        t.start()
        try:
            e = q.get()
            while e is not end:
                if _obs.enabled():
                    # occupancy AFTER the pop: 0 means the consumer is
                    # starved (the producer is the bottleneck)
                    _obs.set_gauge('reader.buffered_queue_depth',
                                   q.qsize())
                yield e
                e = q.get()
        finally:
            closed.set()
    return data_reader


def firstn(reader, n):
    def data_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item
    return data_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with worker threads."""
    end = object()

    def data_reader():
        in_q = Queue(buffer_size)
        out_q = Queue(buffer_size)

        def feed():
            for i, sample in enumerate(reader()):
                in_q.put((i, sample))
            for _ in range(process_num):
                in_q.put(end)

        def work():
            while True:
                item = in_q.get()
                if item is end:
                    out_q.put(end)
                    break
                i, sample = item
                out_q.put((i, mapper(sample)))

        feeder = Thread(target=feed)
        feeder.daemon = True
        feeder.start()
        workers = []
        for _ in range(process_num):
            w = Thread(target=work)
            w.daemon = True
            w.start()
            workers.append(w)

        finished = 0
        pending = {}
        next_idx = 0
        while finished < process_num:
            item = out_q.get()
            if item is end:
                finished += 1
                continue
            if not order:
                yield item[1]
            else:
                pending[item[0]] = item[1]
                while next_idx in pending:
                    yield pending.pop(next_idx)
                    next_idx += 1
        for idx in sorted(pending):
            yield pending[idx]
    return data_reader


def cache(reader):
    all_data = []

    def data_reader():
        if not all_data:
            all_data.extend(reader())
        for d in all_data:
            yield d
    return data_reader


def batch(reader, batch_size, drop_last=True):
    """Group examples into lists of batch_size (reference: paddle.batch).
    drop_last defaults True: static shapes avoid XLA recompilation."""
    def batch_reader():
        b = []
        for item in reader():
            b.append(item)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


def retry(reader, tries=3, backoff=0.1, exceptions=(OSError,)):
    """Transient-input-error tolerance: when the underlying reader raises
    one of `exceptions`, the stream is rebuilt and the already-yielded
    prefix is SKIPPED on replay (readers here are deterministic — the
    same contract CheckpointableReader's mid-epoch resume leans on), so
    consumers see each item at most once, in order.

    `tries` counts consecutive failed attempts: the tries-th consecutive
    failure re-raises; any successfully yielded item resets the counter.
    `backoff` seconds before each retry, doubling per consecutive
    failure (0 disables sleeping).
    """
    import time
    if tries < 1:
        raise ValueError('retry: tries must be >= 1, got %r' % (tries,))

    def data_reader():
        yielded = 0
        failures = 0
        while True:
            try:
                for i, item in enumerate(reader()):
                    if i < yielded:
                        continue    # replayed prefix: already delivered
                    yield item
                    yielded += 1
                    failures = 0
                return
            except exceptions:
                failures += 1
                _obs.inc('reader.retry_total')
                if failures >= tries:
                    _obs.inc('reader.retry_exhausted_total')
                    raise
                if backoff:
                    time.sleep(backoff * (2 ** (failures - 1)))
    return data_reader


def resolve_device(place):
    """paddle place / jax device / None -> jax device (None = default)."""
    import jax
    if place is None:
        return None
    if hasattr(place, 'device_id'):  # a paddle_tpu Place
        return jax.devices()[place.device_id]
    return place


def feed_normalizer(first, feed_names):
    """Returns item -> feed-dict fn for readers yielding dicts or tuples."""
    if feed_names is not None and not isinstance(first, dict):
        return lambda item: dict(zip(feed_names, item))
    return lambda item: item


def prefetch_to_device(reader, feed_names=None, buffer_size=2, place=None):
    """Overlap host->HBM transfer with compute: device_put the next
    batch(es) while the current one trains (the flax prefetch pattern —
    the TPU analog of the reference's pinned-memory double buffering).

    reader yields dicts (or tuples zipped with feed_names); yields dicts
    of device arrays. `place` (a paddle place or jax device) selects the
    target device; default is jax's default device.

    Mutation safety: a reader that reuses its output buffers (recordio
    slots, a preallocated decode array) is safe to prefetch — on hosts
    where XLA:CPU zero-copies aligned arrays the batch is copied before
    device_put (staging.host_alias_safe, the same invariant as the
    staging ring), so the producer overwriting its slot cannot corrupt
    an in-flight prefetched batch.
    """
    import jax

    from .staging import host_alias_safe

    device = resolve_device(place)

    def device_reader():
        import collections
        queue = collections.deque()
        norm = [None]
        target = device if device is not None else jax.devices()[0]

        def put(item):
            if norm[0] is None:
                norm[0] = feed_normalizer(item, feed_names)
            item = norm[0](item)
            queue.append({k: jax.device_put(
                host_alias_safe(np.asarray(v) if not hasattr(v, 'devices')
                                else v, target), device)
                          for k, v in item.items()})

        it = iter(reader())
        try:
            for _ in range(buffer_size):
                put(next(it))
        except StopIteration:
            pass
        for item in it:
            out = queue.popleft()
            put(item)  # transfer of the NEXT batch is now in flight
            yield out
        while queue:
            yield queue.popleft()

    return device_reader


def shard(reader, num_shards, shard_id, drop_uneven=True):
    """Deterministic round-robin shard of a reader stream: shard i yields
    samples i, i+n, i+2n, ... Every host must construct the SAME base
    reader (same seed/order); the shards are then disjoint and together
    cover the stream — the role go/master/service.go:1-510 plays with its
    task queue, done as a pure function of position so there is no
    master to run or lose.

    drop_uneven=True drops the ragged tail so all shards yield the SAME
    number of samples — required under SPMD, where every host must step
    the same number of times or the collectives deadlock.
    """
    if not 0 <= shard_id < num_shards:
        raise ValueError('shard_id %d not in [0, %d)' % (shard_id,
                                                         num_shards))

    def impl():
        buf = []
        for i, item in enumerate(reader()):
            if i % num_shards == shard_id:
                buf.append(item)
            if len(buf) and (i + 1) % num_shards == 0:
                yield buf.pop()
        if buf and not drop_uneven:
            yield buf.pop()
    return impl
