"""Host staging feeder: native aligned-buffer ring + superbatch packing.

Reference analog: the pinned-memory double buffering of the reference's
DataProvider (paddle/fluid/memory pinned allocations). TPU-native shape:
a background thread packs `steps` consecutive batches CONTIGUOUSLY into
one page-aligned C++ staging buffer (native/staging.cpp) while the
current window trains; the consumer wraps the buffer zero-copy with
np.frombuffer and issues ONE jax.device_put per feed per window. Pairs
with Executor.run_steps(stacked_feed=True): one dispatch and one h2d
transfer per `steps` training steps.
"""

import ctypes

import numpy as np

from .. import observe as _obs

__all__ = ['staged_superbatch', 'fields_to_device', 'host_alias_safe']


def _load():
    from ..native import load_staging
    return load_staging()


def host_alias_safe(arr, target):
    """Return `arr` safe to device_put onto `target` while the caller
    keeps mutating its buffer: XLA:CPU zero-copies aligned host arrays,
    so the 'device' array would alias the source slot — copy there.
    Real accelerators DMA a fresh HBM buffer; no copy needed. The one
    home of the invariant, shared by fields_to_device (staging ring
    slots) and reader.prefetch_to_device (readers that reuse their
    output buffers, e.g. recordio slots)."""
    if getattr(target, 'platform', None) == 'cpu' and \
            isinstance(arr, np.ndarray):
        return arr.copy()
    return arr


def fields_to_device(fields, target):
    """fields: name -> numpy view ALIASING a reusable staging slot.
    Copies on host-aliasing platforms (host_alias_safe), device_puts,
    and blocks until the h2d transfer completes so the caller may
    release and reuse the slot."""
    import jax
    window = {}
    for name, arr in fields.items():
        window[name] = jax.device_put(host_alias_safe(arr, target),
                                      target)
    for v in window.values():
        v.block_until_ready()
    return window


def staged_superbatch(reader, steps, feed_names=None, n_buffers=3,
                      place=None):
    """Wrap `reader` (yielding per-step feed dicts, or tuples zipped with
    feed_names) into a generator of device-resident superbatch dicts:
    every yielded value maps name -> jax.Array of shape [steps, *batch]
    for Executor.run_steps(steps, feed=..., stacked_feed=True).
    Trailing batches that do not fill a window are dropped (static
    shapes; same stance as reader.batch(drop_last=True))."""
    import jax
    import queue as _q
    import threading

    from .decorator import feed_normalizer, resolve_device

    device = resolve_device(place)

    def gen():
        lib = _load()
        it = iter(reader())
        try:
            first = next(it)
        except StopIteration:
            return
        norm = feed_normalizer(first, feed_names)
        first = norm(first)
        names = sorted(first)
        specs = {n: (np.asarray(first[n]).shape,
                     np.asarray(first[n]).dtype) for n in names}
        sizes = {n: int(np.prod(specs[n][0])) * specs[n][1].itemsize
                 for n in names}
        # each field's region starts page-aligned within the slot so
        # every per-field h2d copy stays on the aligned-DMA path
        align = 4096
        offs, total = {}, 0
        for n in names:
            offs[n] = total
            total += -(-(sizes[n] * steps) // align) * align

        ring = lib.staging_open(total, n_buffers)
        if not ring:
            raise MemoryError('staging_open failed (%d bytes x %d)'
                              % (total, n_buffers))
        err = _q.Queue()
        state = {'produced': 0, 'consumed': 0}

        def _ring_gauges():
            # occupancy: committed windows not yet consumed, 0..n_buffers
            # (pinned at n_buffers-ish = reader ahead of the device; at 0
            # = the input pipeline is the bottleneck)
            _obs.set_gauge('reader.staging_ring_occupancy',
                           state['produced'] - state['consumed'])
            _obs.set_gauge('reader.staging_ring_slots', n_buffers)

        def produce():
            try:
                import itertools
                batches = []
                # first already normalized; route it through the same
                # flush path so a steps=1 window packs exactly one batch
                stream = itertools.chain([first], map(norm, it))
                for item in stream:
                    batches.append(item)
                    if len(batches) < steps:
                        continue
                    buf = lib.staging_acquire_fill(ring)
                    if not buf:
                        return  # consumer closed the ring early
                    for n in names:
                        shape, dtype = specs[n]
                        for i, b in enumerate(batches):
                            arr = np.ascontiguousarray(
                                np.asarray(b[n], dtype=dtype))
                            if arr.shape != shape:
                                raise ValueError(
                                    'staged_superbatch: feed %r shape %s '
                                    '!= first batch %s' %
                                    (n, arr.shape, shape))
                            ctypes.memmove(buf + offs[n] + i * sizes[n],
                                           arr.ctypes.data, sizes[n])
                    if lib.staging_commit(ring, total):
                        raise RuntimeError('staging_commit failed')
                    state['produced'] += 1
                    if _obs.enabled():
                        _obs.inc('reader.staging_windows_produced_total')
                        _ring_gauges()
                    batches = []
            except Exception as e:  # surfaced on the consumer side
                err.put(e)
            finally:
                lib.staging_close_ring(ring)

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                out_len = ctypes.c_uint64()
                buf = lib.staging_acquire_read(ring,
                                               ctypes.byref(out_len))
                if not buf:
                    if not err.empty():
                        raise err.get()
                    return
                raw = ctypes.cast(
                    ctypes.c_void_p(buf),
                    ctypes.POINTER(ctypes.c_uint8 * out_len.value))
                target = device if device is not None else jax.devices()[0]
                fields = {}
                for n in names:
                    shape, dtype = specs[n]
                    flat = np.frombuffer(
                        raw.contents, dtype=dtype,
                        count=steps * int(np.prod(shape)),
                        offset=offs[n])
                    fields[n] = flat.reshape((steps,) + shape)
                window = fields_to_device(fields, target)
                if lib.staging_release(ring):
                    raise RuntimeError('staging_release failed')
                state['consumed'] += 1
                if _obs.enabled():
                    _obs.inc('reader.staging_windows_consumed_total')
                    _ring_gauges()
                yield window
        finally:
            lib.staging_close_ring(ring)
            t.join(timeout=5.0)
            if t.is_alive():
                # producer is stuck inside the user reader; freeing now
                # would hand it a dangling ring -> leak the ring instead
                import warnings
                warnings.warn('staged_superbatch: producer thread did not '
                              'exit; leaking one staging ring')
            else:
                lib.staging_free(ring)

    return gen
