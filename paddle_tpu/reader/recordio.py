"""Python interface to the native recordio pipeline.

Reference: the reference trains from recordio files through C++
DataProviders with background decode threads; same architecture here
(paddle_tpu/native/recordio.cpp) with a reader()-decorator-compatible
surface: records are pickled Python items, decode/shuffle/prefetch run
off the main thread in C++.
"""

import ctypes
import pickle

from ..native import load_library

__all__ = ['RecordIOWriter', 'write_recordio', 'recordio_reader',
           'example_dtype', 'write_example_recordio',
           'recordio_superbatch']


class RecordIOWriter(object):
    def __init__(self, path):
        self._lib = load_library()
        self._h = self._lib.recordio_writer_open(path.encode())
        if not self._h:
            raise IOError('cannot open %s for writing' % path)

    def write(self, obj):
        self.write_raw(pickle.dumps(obj,
                                    protocol=pickle.HIGHEST_PROTOCOL))

    def write_raw(self, data):
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        if self._lib.recordio_writer_write(self._h, buf, len(data)) != 0:
            raise IOError('recordio write failed')

    def close(self):
        if self._h:
            self._lib.recordio_writer_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_recordio(path, items):
    """Serialize an iterable of picklable items to a recordio file."""
    with RecordIOWriter(path) as w:
        n = 0
        for item in items:
            w.write(item)
            n += 1
    return n


def recordio_reader(paths, shuffle_buf=0, seed=0, prefetch=256, raw=False):
    """Returns a v2-style reader() generator factory over recordio files.
    Decode + shuffle + prefetch happen in the native worker thread.
    raw=True yields the undecoded record bytes (reader.creator.recordio
    parity with the reference's raw-record creator)."""
    if isinstance(paths, str):
        paths = [paths]
    joined = '\n'.join(paths).encode()

    def reader():
        lib = load_library()
        h = lib.recordio_reader_open(joined, shuffle_buf, seed, prefetch)
        if not h:
            raise IOError('cannot open recordio reader')
        try:
            out = ctypes.POINTER(ctypes.c_uint8)()
            while True:
                n = lib.recordio_reader_next(h, ctypes.byref(out))
                if n == 0:
                    break
                if n < 0:
                    raise IOError(lib.recordio_reader_error(h).decode())
                data = ctypes.string_at(out, n)
                yield data if raw else pickle.loads(data)
        finally:
            lib.recordio_reader_close(h)

    return reader


def example_dtype(specs):
    """Structured numpy dtype for one fixed-shape example: `specs` is an
    ordered mapping name -> (shape, dtype). Packed in field order with
    no padding — exactly the byte layout write_example_recordio emits
    and the C++ pipeline window parser assumes."""
    import numpy as np
    return np.dtype([(n, np.dtype(dt), tuple(shape))
                     for n, (shape, dt) in specs.items()])


def write_example_recordio(path, examples, specs):
    """Serialize fixed-shape example dicts as raw records (one example =
    one record of example_dtype(specs).itemsize bytes) for the C++
    superbatch pipeline. Returns the number of records written."""
    import numpy as np
    rec_dtype = example_dtype(specs)
    n = 0
    with RecordIOWriter(path) as w:
        for ex in examples:
            row = np.zeros((), dtype=rec_dtype)
            for name, (shape, dt) in specs.items():
                arr = np.asarray(ex[name], dtype=dt)
                if tuple(arr.shape) != tuple(shape):
                    raise ValueError(
                        'example field %r shape %s != spec %s'
                        % (name, arr.shape, tuple(shape)))
                row[name] = arr
            w.write_raw(row.tobytes())
            n += 1
    return n


def recordio_superbatch(paths, specs, steps, batch, shuffle_buf=0,
                        seed=0, n_buffers=3, place=None):
    """C++-to-C++ feed path: the native pipeline (native/pipeline.cpp)
    drains recordio files and packs steps*batch fixed-size example
    records per page-aligned staging window with no Python in the
    per-record loop; this generator parses each window with ONE
    np.frombuffer (structured dtype) and yields
    {name: jax.Array [steps, batch, *shape]} dicts for
    Executor.run_steps(stacked_feed=True). Trailing records that do not
    fill a window are dropped (static shapes)."""
    import numpy as np
    from ..native import load_pipeline
    from .decorator import resolve_device
    from .staging import fields_to_device

    rec_dtype = example_dtype(specs)
    device = resolve_device(place)
    if isinstance(paths, str):
        paths = [paths]

    def gen():
        import jax
        lib = load_pipeline()
        per_window = steps * batch
        h = lib.pipeline_start('\n'.join(paths).encode(), shuffle_buf,
                               seed, rec_dtype.itemsize, per_window,
                               n_buffers)
        if not h:
            raise IOError('pipeline_start failed')
        try:
            target = device if device is not None else jax.devices()[0]
            while True:
                out_len = ctypes.c_uint64()
                buf = lib.pipeline_next_window(h, ctypes.byref(out_len))
                if not buf:
                    err = lib.pipeline_error(h)
                    if err:
                        raise IOError('recordio pipeline: %s'
                                      % err.decode())
                    return
                raw = ctypes.cast(
                    ctypes.c_void_p(buf),
                    ctypes.POINTER(ctypes.c_uint8 * out_len.value))
                recs = np.frombuffer(raw.contents, dtype=rec_dtype,
                                     count=per_window)
                fields = {
                    name: recs[name].reshape((steps, batch) +
                                             tuple(shape))
                    for name, (shape, _dt) in specs.items()}
                window = fields_to_device(fields, target)
                if lib.pipeline_release(h):
                    raise RuntimeError('pipeline_release failed')
                yield window
        finally:
            lib.pipeline_stop(h)

    return gen
