"""Python interface to the native recordio pipeline.

Reference: the reference trains from recordio files through C++
DataProviders with background decode threads; same architecture here
(paddle_tpu/native/recordio.cpp) with a reader()-decorator-compatible
surface: records are pickled Python items, decode/shuffle/prefetch run
off the main thread in C++.
"""

import ctypes
import pickle

from ..native import load_library

__all__ = ['RecordIOWriter', 'write_recordio', 'recordio_reader']


class RecordIOWriter(object):
    def __init__(self, path):
        self._lib = load_library()
        self._h = self._lib.recordio_writer_open(path.encode())
        if not self._h:
            raise IOError('cannot open %s for writing' % path)

    def write(self, obj):
        data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data)
        if self._lib.recordio_writer_write(self._h, buf, len(data)) != 0:
            raise IOError('recordio write failed')

    def close(self):
        if self._h:
            self._lib.recordio_writer_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_recordio(path, items):
    """Serialize an iterable of picklable items to a recordio file."""
    with RecordIOWriter(path) as w:
        n = 0
        for item in items:
            w.write(item)
            n += 1
    return n


def recordio_reader(paths, shuffle_buf=0, seed=0, prefetch=256, raw=False):
    """Returns a v2-style reader() generator factory over recordio files.
    Decode + shuffle + prefetch happen in the native worker thread.
    raw=True yields the undecoded record bytes (reader.creator.recordio
    parity with the reference's raw-record creator)."""
    if isinstance(paths, str):
        paths = [paths]
    joined = '\n'.join(paths).encode()

    def reader():
        lib = load_library()
        h = lib.recordio_reader_open(joined, shuffle_buf, seed, prefetch)
        if not h:
            raise IOError('cannot open recordio reader')
        try:
            out = ctypes.POINTER(ctypes.c_uint8)()
            while True:
                n = lib.recordio_reader_next(h, ctypes.byref(out))
                if n == 0:
                    break
                if n < 0:
                    raise IOError(lib.recordio_reader_error(h).decode())
                data = ctypes.string_at(out, n)
                yield data if raw else pickle.loads(data)
        finally:
            lib.recordio_reader_close(h)

    return reader
