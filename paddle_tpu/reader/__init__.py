"""Reader framework (reference: python/paddle/v2/reader).

A reader is a nullary callable returning an iterator of examples; the
decorators compose readers. The native C++ shuffle buffer / recordio reader
plug in via paddle_tpu.reader.recordio when built.
"""

from .decorator import (batch, buffered, cache, chain, compose,  # noqa
                        firstn, map_readers, retry, shard, shuffle,
                        xmap_readers)
from .decorator import prefetch_to_device  # noqa: F401
from .staging import staged_superbatch  # noqa: F401
from .state import CheckpointableReader, checkpointable  # noqa: F401
from .recordio import (example_dtype, recordio_superbatch,  # noqa: F401
                       write_example_recordio)
from . import creator  # noqa: F401
