"""Program debugging/visualization (reference: python/paddle/fluid/
debuger.py — pprint + graphviz export of a ProgramDesc).

draw_block_graphviz writes a .dot file (render offline); print_program /
program_to_code give a readable op listing with shapes and attrs.
"""

from .core.program import Variable, default_main_program

__all__ = ['program_to_code', 'print_program', 'draw_block_graphviz']


def _fmt_var(block, name):
    var = block._find_var_recursive(name)
    if var is None:
        return name
    shape = 'x'.join('?' if s is None else str(s)
                     for s in (var.shape or ()))
    return '%s[%s:%s]' % (name, var.dtype, shape)


def program_to_code(program=None, skip_attrs=('op_role',)):
    program = program or default_main_program()
    lines = []
    for block in program.blocks:
        lines.append('// block %d (parent %d)' % (block.idx,
                                                  block.parent_idx))
        for op in block.ops:
            ins = ', '.join(
                '%s=%s' % (slot, [_fmt_var(block, n) for n in names])
                for slot, names in sorted(op.inputs.items()))
            outs = ', '.join(
                '%s=%s' % (slot, [_fmt_var(block, n) for n in names])
                for slot, names in sorted(op.outputs.items()))
            attrs = ', '.join(
                '%s=%r' % (k, v) for k, v in sorted(op.attrs.items())
                if k not in skip_attrs)
            lines.append('  %s(%s) -> %s  {%s}' % (op.type, ins, outs,
                                                   attrs))
    return '\n'.join(lines)


def print_program(program=None):
    print(program_to_code(program))


def draw_block_graphviz(block, path='program.dot', highlights=None):
    """Emit a graphviz dot of the op/var dataflow graph."""
    highlights = set(highlights or [])
    lines = ['digraph G {', '  rankdir=TB;']
    for i, op in enumerate(block.ops):
        color = 'lightcoral' if op.type in highlights else 'lightblue'
        lines.append('  op_%d [label="%s" shape=box style=filled '
                     'fillcolor=%s];' % (i, op.type, color))
    producers = {}
    for i, op in enumerate(block.ops):
        for name in op.output_names():
            producers[name] = i
    for i, op in enumerate(block.ops):
        for name in op.input_names():
            j = producers.get(name)
            if j is not None and j != i:
                lines.append('  op_%d -> op_%d [label="%s"];'
                             % (j, i, name))
    lines.append('}')
    with open(path, 'w') as f:
        f.write('\n'.join(lines))
    return path
