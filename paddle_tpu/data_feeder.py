"""DataFeeder (reference: python/paddle/fluid/data_feeder.py).

Converts reader minibatches (list of example tuples) into the dense feed
dict the Executor expects. LoD (ragged) slots are padded to the batch max
length with an auxiliary '<name>_len' int32 vector — the TPU-native ragged
representation (SURVEY.md §6).
"""

import numpy as np

from .core.dtypes import canonical_dtype
from .core.program import Variable, default_main_program


class DataFeeder(object):
    def __init__(self, feed_list, place=None, program=None):
        self.program = program if program is not None else \
            default_main_program()
        self.feed_vars = []
        for v in feed_list:
            if isinstance(v, str):
                v = self.program.global_block().var(v)
            if not isinstance(v, Variable):
                raise TypeError('feed_list items must be Variable or name')
            self.feed_vars.append(v)
        self.place = place

    def feed(self, iterable):
        rows = list(iterable)
        if not rows:
            raise ValueError('empty minibatch')
        feed = {}
        for i, var in enumerate(self.feed_vars):
            cols = [row[i] for row in rows]
            dtype = canonical_dtype(var.dtype)
            v2_type = getattr(var, '_v2_type', None)
            if v2_type is not None and getattr(v2_type, 'kind', None) in \
                    ('sparse_binary', 'sparse_float'):
                # v2 sparse slots: samples are index lists (binary) or
                # (index, value) pairs (float) — densify to multi-hot
                # (reference readers yield these for sparse_binary_vector /
                # sparse_float_vector; the TPU path has no sparse tensor).
                batch = np.zeros((len(cols), v2_type.dim), dtype=dtype)
                for j, c in enumerate(cols):
                    if v2_type.kind == 'sparse_binary':
                        idx = np.asarray(c, dtype='int64').reshape(-1)
                        batch[j, idx] = 1.0
                    else:
                        for idx, val in c:
                            batch[j, int(idx)] = val
                feed[var.name] = batch
                continue
            if var.lod_level and var.lod_level > 0:
                arrs = [np.asarray(c) for c in cols]
                max_len = max(a.shape[0] for a in arrs)
                tail = arrs[0].shape[1:]
                batch = np.zeros((len(arrs), max_len) + tail, dtype=dtype)
                lengths = np.zeros((len(arrs),), dtype='int32')
                for j, a in enumerate(arrs):
                    batch[j, :a.shape[0]] = a
                    lengths[j] = a.shape[0]
                feed[var.name] = batch
                feed[var.name + '_len'] = lengths
            else:
                arr = np.asarray(cols)
                shape = var.shape
                if shape is not None:
                    want = [s for s in shape]
                    # align trailing dims, e.g. label [-1, 1] from scalars
                    if len(arr.shape) < len(want) and want[-1] == 1:
                        arr = arr.reshape(arr.shape + (1,))
                feed[var.name] = arr.astype(dtype)
        return feed
