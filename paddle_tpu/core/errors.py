"""Error checking (reference: paddle/fluid/platform/enforce.h
PADDLE_ENFORCE / EnforceNotMet).

The reference throws EnforceNotMet with a captured stack; here enforce()
raises EnforceError at graph-build time (shape inference, attr checks) —
runtime numerics live inside XLA, so most misuse is caught before
compile.
"""

__all__ = ['EnforceError', 'enforce', 'enforce_eq', 'enforce_shape_match']


class EnforceError(RuntimeError):
    """Raised when a framework invariant is violated (EnforceNotMet)."""


def enforce(condition, message, *fmt_args):
    if not condition:
        raise EnforceError(message % fmt_args if fmt_args else message)


def enforce_eq(a, b, message=None):
    if a != b:
        raise EnforceError(message or 'enforce_eq failed: %r != %r' % (a, b))


def enforce_shape_match(shape_a, shape_b, message=None):
    """None dims (unknown batch) match anything."""
    ok = len(shape_a) == len(shape_b) and all(
        x is None or y is None or x == y or x == -1 or y == -1
        for x, y in zip(shape_a, shape_b))
    if not ok:
        raise EnforceError(
            message or 'shape mismatch: %s vs %s' % (shape_a, shape_b))
