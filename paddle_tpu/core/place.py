"""Device places (reference: paddle/fluid/platform/place.h CPUPlace/CUDAPlace).

TPU-native: TPUPlace maps onto a jax TPU device; CPUPlace onto the host
platform. A place resolves lazily so that importing paddle_tpu never forces
jax backend initialization.
"""


class Place(object):
    device_kind = None

    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    def __repr__(self):
        return '%s(%d)' % (type(self).__name__, self.device_id)

    def __eq__(self, other):
        return type(self) is type(other) and self.device_id == other.device_id

    def __hash__(self):
        return hash((type(self).__name__, self.device_id))

    def jax_device(self):
        """Resolve to a concrete jax device, or None to use the default."""
        import jax
        kind = self.device_kind
        devs = [d for d in jax.devices() if d.platform == kind]
        if not devs:
            if kind == 'tpu':
                # Fall back to whatever the default backend offers (e.g. the
                # 8-virtual-device CPU mesh used in tests).
                devs = jax.devices()
            else:
                devs = jax.devices('cpu')
        return devs[self.device_id % len(devs)]


class CPUPlace(Place):
    device_kind = 'cpu'


class TPUPlace(Place):
    """The TPU analog of the reference's CUDAPlace (platform/place.h:60)."""
    device_kind = 'tpu'


# Alias kept for scripts written against the reference's naming.
CUDAPlace = TPUPlace
