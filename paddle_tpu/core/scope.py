"""Scope: runtime variable store (reference: paddle/fluid/framework/scope.{h,cc}).

Holds name -> array (numpy or jax.Array). Persistable program variables
(parameters, optimizer accumulators, learning rate, batch-norm statistics)
live here between Executor.run calls. Values stay on device as jax.Arrays to
avoid host<->HBM round trips; only fetched vars are pulled to host.
"""

import numpy as np


class Scope(object):
    def __init__(self, parent=None):
        self._vars = {}
        self._parent = parent

    def var(self, name):
        """Get-or-create slot for name (mirrors Scope::Var)."""
        if name not in self._vars and (self._parent is None or
                                       self._parent.find(name) is None):
            self._vars[name] = None
        return name

    def find(self, name):
        if name in self._vars:
            return self._vars[name]
        if self._parent is not None:
            return self._parent.find(name)
        return None

    def has(self, name):
        return name in self._vars or (self._parent is not None and
                                      self._parent.has(name))

    def set(self, name, value):
        self._vars[name] = value

    def get(self, name):
        value = self.find(name)
        if value is None:
            raise KeyError('Variable %r has no value in scope (did you run '
                           'the startup program?)' % name)
        return value

    def erase(self, name):
        self._vars.pop(name, None)

    def new_scope(self):
        return Scope(parent=self)

    def keys(self):
        return list(self._vars.keys())

    def numpy(self, name):
        return np.asarray(self.get(name))

    def clear(self):
        self._vars.clear()


_global_scope = Scope()


def global_scope():
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        global _global_scope
        old = _global_scope
        _global_scope = scope
        try:
            yield
        finally:
            _global_scope = old

    return _guard()
