"""Scope: runtime variable store (reference: paddle/fluid/framework/scope.{h,cc}).

Holds name -> array (numpy or jax.Array). Persistable program variables
(parameters, optimizer accumulators, learning rate, batch-norm statistics)
live here between Executor.run calls. Values stay on device as jax.Arrays to
avoid host<->HBM round trips; only fetched vars are pulled to host.
"""

import numpy as np


class Scope(object):
    def __init__(self, parent=None):
        self._vars = {}
        self._parent = parent

    def var(self, name):
        """Get-or-create slot for name (mirrors Scope::Var)."""
        if name not in self._vars and (self._parent is None or
                                       self._parent.find(name) is None):
            self._vars[name] = None
        return name

    def find(self, name):
        if name in self._vars:
            return self._vars[name]
        if self._parent is not None:
            return self._parent.find(name)
        return None

    def has(self, name):
        return name in self._vars or (self._parent is not None and
                                      self._parent.has(name))

    def set(self, name, value):
        self._vars[name] = value

    def get(self, name):
        value = self.find(name)
        if value is None:
            raise KeyError('Variable %r has no value in scope (did you run '
                           'the startup program?)' % name)
        return value

    def erase(self, name):
        self._vars.pop(name, None)

    def new_scope(self):
        return Scope(parent=self)

    def keys(self):
        return list(self._vars.keys())

    def numpy(self, name):
        return np.asarray(self.get(name))

    def clear(self):
        self._vars.clear()


_global_scope = Scope()

# scope_guard overrides are per-THREAD: concurrent embedded-ABI clients
# (native/capi.cpp — two predictors loading models on two pthreads) must
# not see each other's guarded scopes, or loads write parameters into
# the wrong predictor's store. Single-thread semantics are unchanged:
# with no active guard on this thread, global_scope() is process-global.
import threading as _threading

_tls = _threading.local()


def global_scope():
    stack = getattr(_tls, 'scope_stack', None)
    if stack:
        return stack[-1]
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        stack = getattr(_tls, 'scope_stack', None)
        if stack is None:
            stack = _tls.scope_stack = []
        stack.append(scope)
        try:
            yield
        finally:
            stack.pop()

    return _guard()
