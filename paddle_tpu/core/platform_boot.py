"""Force the host-CPU jax platform despite the hosted-TPU sitecustomize.

The hosted environment pins jax_platforms to 'axon,cpu' at interpreter boot
(overriding the JAX_PLATFORMS env var), and the first device query then
blocks initializing the axon relay when it is down. The one reliable force
is jax.config.update BEFORE any device query. This helper is the single
home for that dance — bench.py, __graft_entry__.py, and tests/conftest.py
all use it so the next backend quirk is fixed in one place.
"""

import os


def force_host_cpu(n_devices=None):
    """Pin jax to the host CPU platform; optionally request n_devices
    virtual devices (only effective if the backend is not yet initialized).

    Safe to call after `import jax` but must run before any device query
    (jax.devices(), first jit execution, ...).
    """
    if n_devices is not None:
        flags = os.environ.get('XLA_FLAGS', '')
        if '--xla_force_host_platform_device_count' not in flags:
            os.environ['XLA_FLAGS'] = (
                flags + ' --xla_force_host_platform_device_count=%d'
                % n_devices).strip()
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    jax.config.update('jax_platforms', 'cpu')


_cache_armed = False


def arm_compile_cache():
    """Arm jax's persistent XLA *module* cache (idempotent; called at
    Executor construction). Re-runs of any program — across processes
    and across driver rounds — skip the HLO->binary compile; on the
    tunneled relay that also shields against mid-compile hangs on
    re-runs. Default dir is stable per machine;
    JAX_COMPILATION_CACHE_DIR overrides, PADDLE_TPU_COMPILE_CACHE=0
    disables. On this jax build the env var alone does not arm the
    cache — the explicit config call does (bench.py verified entries
    appear).

    This is ONE of THREE distinct cache layers — do not conflate them
    when debugging cold-start behavior:

    1. **XLA module cache** (this function; ``JAX_COMPILATION_CACHE_DIR``
       / ``PADDLE_TPU_COMPILE_CACHE``): jax-internal, keyed by HLO.
       Skips the XLA backend compile but the process still pays the
       full Python/jax TRACE of every program before the cache is even
       consulted.
    2. **AOT executable cache** (``core/aot_cache.py``;
       ``PADDLE_TPU_AOT_CACHE`` / ``PADDLE_TPU_AOT_CACHE_DIR``): the
       Executor serializes the fully-compiled step executable keyed by
       program CONTENT + feed signature + backend fingerprint. A warm
       process skips trace AND compile — zero trace/compile events on
       its hot keys (docs/performance.md "Autotuning and AOT warm
       start").
    3. **Kernel tuning table** (``paddle_tpu/tuning``;
       ``PADDLE_TPU_AUTOTUNE`` / ``PADDLE_TPU_TUNING_TABLE``): which
       kernel VARIANT (XLA vs Pallas, block sizes) each (op, shape,
       dtype) dispatches — affects what gets compiled, not whether
       compilation happens. Inspect with ``tools/tuning_inspect.py``.
    """
    global _cache_armed
    if _cache_armed:
        return
    from .flags import get_flag
    mode = get_flag('compile_cache')  # 'auto' | explicit on | off
    if mode in (False, '0', 'false', 'no', 'off'):
        return
    explicit_on = mode in (True, '1', 'true', 'yes', 'on')
    # 'auto': TPU backends only. XLA:CPU persists AOT results whose
    # recorded machine features can mismatch the loader's host
    # detection (observed on this box: '+prefer-no-scatter ... could
    # lead to SIGILL', then a mid-suite 'Fatal Python error: Aborted'
    # materializing an array from a cache-loaded executable). An
    # explicit PADDLE_TPU_COMPILE_CACHE=1 / set_flag('compile_cache',
    # True) opts CPU in anyway.
    if not explicit_on and not is_tpu_backend():
        return
    _cache_armed = True
    import getpass
    import tempfile
    # per-user default: a fixed shared-tmp name would break (or poison)
    # across users on a shared machine
    try:
        user = getpass.getuser()
    except Exception:
        user = str(os.getuid()) if hasattr(os, 'getuid') else 'default'
    cache_dir = os.environ.get(
        'JAX_COMPILATION_CACHE_DIR',
        os.path.join(tempfile.gettempdir(),
                     'paddle_tpu_xla_cache_%s' % user))
    try:
        import jax
        jax.config.update('jax_compilation_cache_dir', cache_dir)
        # compile times on the relay are tens of seconds; cache even
        # fast compiles so CPU test reruns benefit too
        jax.config.update('jax_persistent_cache_min_compile_time_secs', 0)
    except Exception:
        pass  # older jax without the knobs: cache is an optimization


def is_tpu_backend():
    """True when the default jax backend is real TPU hardware — the
    'tpu' platform, or the hosted 'axon' relay in case a jax version
    reports the relay's own platform name. Shared by the
    backend-dependent defaults (executor._default_prng dropout RNG,
    conv_ops._conv_layout) so the detection policy lives in one place."""
    try:
        import jax
        return jax.default_backend() in ('tpu', 'axon')
    except Exception:
        return False
