"""Force the host-CPU jax platform despite the hosted-TPU sitecustomize.

The hosted environment pins jax_platforms to 'axon,cpu' at interpreter boot
(overriding the JAX_PLATFORMS env var), and the first device query then
blocks initializing the axon relay when it is down. The one reliable force
is jax.config.update BEFORE any device query. This helper is the single
home for that dance — bench.py, __graft_entry__.py, and tests/conftest.py
all use it so the next backend quirk is fixed in one place.
"""

import os


def force_host_cpu(n_devices=None):
    """Pin jax to the host CPU platform; optionally request n_devices
    virtual devices (only effective if the backend is not yet initialized).

    Safe to call after `import jax` but must run before any device query
    (jax.devices(), first jit execution, ...).
    """
    if n_devices is not None:
        flags = os.environ.get('XLA_FLAGS', '')
        if '--xla_force_host_platform_device_count' not in flags:
            os.environ['XLA_FLAGS'] = (
                flags + ' --xla_force_host_platform_device_count=%d'
                % n_devices).strip()
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    jax.config.update('jax_platforms', 'cpu')


def is_tpu_backend():
    """True when the default jax backend is real TPU hardware — the
    'tpu' platform, or the hosted 'axon' relay in case a jax version
    reports the relay's own platform name. Shared by the
    backend-dependent defaults (executor._default_prng dropout RNG,
    conv_ops._conv_layout) so the detection policy lives in one place."""
    try:
        import jax
        return jax.default_backend() in ('tpu', 'axon')
    except Exception:
        return False
