"""Force the host-CPU jax platform despite the hosted-TPU sitecustomize.

The hosted environment pins jax_platforms to 'axon,cpu' at interpreter boot
(overriding the JAX_PLATFORMS env var), and the first device query then
blocks initializing the axon relay when it is down. The one reliable force
is jax.config.update BEFORE any device query. This helper is the single
home for that dance — bench.py, __graft_entry__.py, and tests/conftest.py
all use it so the next backend quirk is fixed in one place.
"""

import os


def force_host_cpu(n_devices=None):
    """Pin jax to the host CPU platform; optionally request n_devices
    virtual devices (only effective if the backend is not yet initialized).

    Safe to call after `import jax` but must run before any device query
    (jax.devices(), first jit execution, ...).
    """
    if n_devices is not None:
        flags = os.environ.get('XLA_FLAGS', '')
        if '--xla_force_host_platform_device_count' not in flags:
            os.environ['XLA_FLAGS'] = (
                flags + ' --xla_force_host_platform_device_count=%d'
                % n_devices).strip()
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    jax.config.update('jax_platforms', 'cpu')
