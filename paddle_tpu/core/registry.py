"""Op lowering registry.

Reference analog: paddle/fluid/framework/op_registry.h (REGISTER_OP_KERNEL).
Instead of per-device C++ kernels, each op type registers ONE lowering
function that maps traced jax values -> traced jax values; the Executor
composes lowerings for a whole Program and jits the result, so XLA performs
fusion/placement (there is no per-op dispatch at run time).
"""

OP_LOWERINGS = {}


def register(op_type):
    def deco(fn):
        if op_type in OP_LOWERINGS:
            raise ValueError('duplicate lowering for op %r' % op_type)
        OP_LOWERINGS[op_type] = fn
        return fn
    return deco


def get_lowering(op_type):
    fn = OP_LOWERINGS.get(op_type)
    if fn is None:
        raise NotImplementedError(
            'No TPU lowering registered for op type %r. Known ops: %s' %
            (op_type, ', '.join(sorted(OP_LOWERINGS))))
    return fn


# AMP 'bf16' dtype policy: whitelist ops compute in bfloat16 (MXU),
# blacklist ops are numerically sensitive and force fp32; all others run
# in whatever dtype arrives (jnp promotion resolves mixes).
AMP_WHITELIST = {
    'mul', 'matmul', 'conv2d', 'conv2d_transpose', 'fused_attention',
    'sequence_conv', 'row_conv',
    # recurrences: the per-step h @ W rides the MXU; uniform bf16
    # inputs also keep the lax.scan carry dtype stable (a fp32 weight
    # against a bf16 pre-projection would promote h to fp32 mid-scan)
    'lstm', 'lstmp', 'gru', 'simple_rnn', 'gru_unit', 'lstm_unit',
}
AMP_BLACKLIST = {
    'softmax', 'softmax_with_cross_entropy', 'cross_entropy',
    'layer_norm', 'batch_norm', 'mean', 'reduce_sum', 'reduce_mean',
    'exp', 'log', 'square_error_cost', 'l2_normalize', 'cos_sim',
    'clip_by_norm', 'linear_chain_crf', 'nce',
}

# Normalization ops compute their statistics in fp32 (blacklist above)
# but hand the ACTIVATION back to the bf16 stream: without this, every
# conv->bn->conv boundary round-trips fp32 activations through HBM —
# measured +18% ResNet-50 img/s on chip (1,926 vs 1,631). Maps op type
# -> the activation output slots to re-cast; statistics outputs
# (MeanOut/VarianceOut/...) stay fp32.
AMP_BF16_OUT_SLOTS = {
    'batch_norm': ('Y',),
    'layer_norm': ('Y',),
    'group_norm': ('Y',),
}


class LoweringContext(object):
    """Execution context handed to each op lowering.

    env      : dict var name -> traced jax value
    op       : the Operator being lowered
    block    : Block for var metadata lookups
    rng      : per-op PRNG key factory (stable across steps given base key)
    amp      : None or 'bf16' — input() autocasts per the policy above
    """

    def __init__(self, env, op, block, op_index, base_key, is_test=False,
                 amp=None):
        self.env = env
        self.op = op
        self.block = block
        self.op_index = op_index
        self._base_key = base_key
        self.is_test = is_test
        self.amp = amp

    def _autocast(self, value):
        if self.amp != 'bf16' or value is None:
            return value
        import jax.numpy as jnp
        dtype = getattr(value, 'dtype', None)
        if self.op.type in AMP_WHITELIST and dtype == jnp.float32:
            return value.astype(jnp.bfloat16)
        if self.op.type in AMP_BLACKLIST and dtype == jnp.bfloat16:
            return value.astype(jnp.float32)
        return value

    # ---- inputs / outputs ----
    def input(self, slot):
        name = self.op.input(slot)
        if name is None:
            return None
        return self._autocast(self.env[name])

    def input_list(self, slot):
        return [self._autocast(self.env[n])
                for n in self.op.inputs.get(slot, [])]

    def has_input(self, slot):
        names = self.op.inputs.get(slot, [])
        return bool(names) and names[0] in self.env

    def set_output(self, slot, value):
        name = self.op.output(slot)
        if name is None:
            return
        var = self.block._find_var_recursive(name)
        if var is not None and var.stop_gradient:
            import jax
            value = jax.lax.stop_gradient(value)
        self.env[name] = value

    def set_output_list(self, slot, values):
        names = self.op.outputs.get(slot, [])
        for name, value in zip(names, values):
            var = self.block._find_var_recursive(name)
            if var is not None and var.stop_gradient:
                import jax
                value = jax.lax.stop_gradient(value)
            self.env[name] = value

    def attr(self, name, default=None):
        return self.op.attrs.get(name, default)

    def out_var(self, slot):
        name = self.op.output(slot)
        return self.block._find_var_recursive(name) if name else None

    def in_var(self, slot):
        name = self.op.input(slot)
        return self.block._find_var_recursive(name) if name else None

    # ---- randomness ----
    def rng_key(self):
        """A PRNG key unique to this op instance, folded from the step key."""
        import jax
        return jax.random.fold_in(self._base_key, self.op_index)

    def out_dtype(self, slot, default='float32'):
        var = self.out_var(slot)
        from .dtypes import to_jnp_dtype
        return to_jnp_dtype(var.dtype if var is not None else default)
