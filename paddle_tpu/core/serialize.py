"""Program <-> dict serialization (reference: ProgramDesc protobuf in
proto/framework.proto; JSON here — human-readable, no codegen step)."""

from .program import Block, Parameter, Program, Variable


def _var_to_dict(v):
    return {
        'name': v.name,
        'shape': list(v.shape) if v.shape is not None else None,
        'dtype': v.dtype,
        'lod_level': v.lod_level,
        'persistable': v.persistable,
        'stop_gradient': v.stop_gradient,
        'is_data': v.is_data,
        'is_parameter': isinstance(v, Parameter),
        'trainable': v.trainable,
    }


def program_to_dict(program):
    blocks = []
    for b in program.blocks:
        blocks.append({
            'idx': b.idx,
            'parent_idx': b.parent_idx,
            'vars': [_var_to_dict(v) for v in b.vars.values()],
            'ops': [{'type': op.type, 'inputs': op.inputs,
                     'outputs': op.outputs, 'attrs': op.attrs,
                     'provenance': op.provenance}
                    for op in b.ops],
        })
    return {'blocks': blocks, 'random_seed': program.random_seed}


def program_from_dict(data):
    p = Program()
    p.random_seed = data.get('random_seed')
    for i, bd in enumerate(data['blocks']):
        if i == 0:
            b = p.global_block()
        else:
            b = Block(p, i, bd['parent_idx'])
            p.blocks.append(b)
        for vd in bd['vars']:
            shape = tuple(vd['shape']) if vd['shape'] is not None else None
            if vd['is_parameter']:
                v = Parameter(b, vd['name'], shape, vd['dtype'],
                              trainable=vd['trainable'])
            else:
                v = Variable(b, vd['name'], shape=shape, dtype=vd['dtype'],
                             lod_level=vd['lod_level'],
                             persistable=vd['persistable'],
                             is_data=vd['is_data'])
            v.stop_gradient = vd['stop_gradient']
            b.vars[vd['name']] = v
        for od in bd['ops']:
            # restore the recorded construction site (absent in pre-
            # provenance serializations; the deserialize call site would
            # be a lie)
            b.append_op(od['type'], od['inputs'], od['outputs'],
                        od['attrs']).provenance = od.get('provenance')
    p.current_block_idx = 0
    return p
