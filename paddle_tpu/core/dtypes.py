"""Dtype handling for the paddle_tpu IR.

The IR stores dtypes as canonical strings; lowering converts to jnp dtypes.
Mirrors the reference's ``paddle/fluid/framework/data_type.h`` enum
(FP16/FP32/FP64/INT16/INT32/INT64/BOOL/UINT8) with bfloat16 added as the
TPU-preferred half precision.
"""

import numpy as np

_CANONICAL = {
    'float16': 'float16',
    'fp16': 'float16',
    'bfloat16': 'bfloat16',
    'bf16': 'bfloat16',
    'float32': 'float32',
    'fp32': 'float32',
    'float': 'float32',
    'float64': 'float64',
    'fp64': 'float64',
    'double': 'float64',
    'int8': 'int8',
    'uint8': 'uint8',
    # fp8 (serving KV arenas; gated on jax support — see to_jnp_dtype)
    'fp8': 'float8_e4m3fn',
    'float8': 'float8_e4m3fn',
    'float8_e4m3fn': 'float8_e4m3fn',
    'int16': 'int16',
    'int32': 'int32',
    'int': 'int32',
    'int64': 'int64',
    'long': 'int64',
    'bool': 'bool',
}


def canonical_dtype(dtype):
    """Normalize a user-provided dtype (string / numpy dtype) to a canonical string."""
    if dtype is None:
        return 'float32'
    if isinstance(dtype, str):
        key = dtype.lower()
    else:
        try:
            key = np.dtype(dtype).name
        except TypeError:
            key = str(dtype)
    if key not in _CANONICAL:
        raise ValueError('Unsupported dtype: %r' % (dtype,))
    return _CANONICAL[key]


def to_jnp_dtype(dtype):
    """Canonical string -> the dtype JAX will actually use on device.

    Runs through jax.dtypes.canonicalize_dtype so 64-bit declarations map
    to their 32-bit device dtypes under the default x64-disabled mode —
    comparing/casting against the uncanonicalized dtype would re-cast (and
    warn) on every executor step without ever matching.
    """
    import jax
    import jax.numpy as jnp
    name = canonical_dtype(dtype)
    if name == 'bfloat16':
        return jnp.bfloat16
    if name == 'float8_e4m3fn':
        if not hasattr(jnp, 'float8_e4m3fn'):
            raise ValueError('dtype float8_e4m3fn is not supported by '
                             'this jax build')
        return jnp.float8_e4m3fn
    return jax.dtypes.canonicalize_dtype(np.dtype(name))


def is_float_dtype(dtype):
    return canonical_dtype(dtype) in ('float16', 'bfloat16', 'float32',
                                      'float64', 'float8_e4m3fn')


def canonical_int():
    """Platform int for in-graph index/count outputs: int64 when x64 is
    enabled, int32 otherwise. jnp.int64 under the default x64-off
    config fires a truncation UserWarning on every trace; this
    canonicalizes silently (reference ops declare int64, the TPU jit
    reality is int32)."""
    import jax
    import jax.numpy as jnp
    return jax.dtypes.canonicalize_dtype(jnp.int64)
