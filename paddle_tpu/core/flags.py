"""Global flags (reference: python/paddle/fluid/__init__.py
__bootstrap__'s gflags — fraction_of_gpu_memory_to_use etc.).

TPU-native flags control the XLA/executor path instead of CUDA knobs.
Values are read from the environment (PADDLE_TPU_<NAME>) at first access,
overridable via init_flags / set_flag.
"""

import os

__all__ = ['init_flags', 'set_flag', 'get_flag', 'FLAGS']

_DEFAULTS = {
    # executor
    'benchmark': False,            # sync + time every executor step
    'use_bf16': False,             # default Program.amp for new programs
    # 'auto' = persistent XLA cache on TPU backends only (XLA:CPU AOT
    # cache entries can abort on feature-mismatched hosts); explicit
    # true/1 arms it everywhere, false/0 never
    'compile_cache': 'auto',
    # data pipeline
    'reader_prefetch': 256,
    # logging
    'v': 0,                        # verbosity (GLOG_v analog)
}

FLAGS = {}


def _coerce(default, raw):
    if isinstance(default, bool):
        return raw.lower() in ('1', 'true', 'yes', 'on')
    return type(default)(raw)


def init_flags(overrides=None):
    """(Re)load flags from defaults + environment + overrides."""
    FLAGS.clear()
    for name, default in _DEFAULTS.items():
        env = os.environ.get('PADDLE_TPU_' + name.upper())
        FLAGS[name] = _coerce(default, env) if env is not None else default
    for name, value in (overrides or {}).items():
        set_flag(name, value)
    return dict(FLAGS)


def set_flag(name, value):
    if name not in _DEFAULTS:
        raise KeyError('unknown flag %r (known: %s)'
                       % (name, sorted(_DEFAULTS)))
    if not FLAGS:
        init_flags()
    FLAGS[name] = value


def get_flag(name):
    if not FLAGS:
        init_flags()
    return FLAGS[name]
