"""Unique name generator (reference: python/paddle/fluid/unique_name.py)."""

import collections
import contextlib

_generator_counters = collections.defaultdict(int)


def generate(key):
    _generator_counters[key] += 1
    return '%s_%d' % (key, _generator_counters[key] - 1)


def reset():
    _generator_counters.clear()


@contextlib.contextmanager
def guard(new_counters=None):
    global _generator_counters
    old = _generator_counters
    _generator_counters = new_counters if new_counters is not None \
        else collections.defaultdict(int)
    try:
        yield
    finally:
        _generator_counters = old
