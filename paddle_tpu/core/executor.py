"""Executor: compiles a Program into ONE jitted XLA computation.

Reference: paddle/fluid/framework/executor.{h,cc} + python/fluid/executor.py.
The reference interprets ops one-by-one through per-device OpKernels; here a
whole block — forward, autodiff'd backward, optimizer updates — is traced
through the registered JAX lowerings and compiled once per
(program version, feed signature). Persistable state (params, optimizer
accumulators, BN statistics, learning rate) flows through the jitted step as
a donated dict argument, so parameter updates are in-place in HBM and steps
run with zero host round-trips beyond feed/fetch.
"""

import os
import threading
import time

import numpy as np

from .. import observe as _obs
from .dtypes import to_jnp_dtype
from .place import CPUPlace, TPUPlace
from .program import Variable, default_main_program
from .registry import LoweringContext, get_lowering
from .scope import global_scope


def _ensure_ops_imported():
    from .. import ops as _ops  # noqa: F401  (registers lowerings)


def collect_error_clips(block, ops):
    """{var name: (lo, hi)} for every op output carrying an error_clip
    (validated once, at compile/trace start — not per op per trace).
    Only ErrorClipByValue maps onto the cotangent-clamp lowering."""
    from ..clip import ErrorClipByValue
    clips = {}
    for op in ops:
        for n in op.output_names():
            if n in clips:
                continue
            v = block._find_var_recursive(n)
            ec = getattr(v, 'error_clip', None) if v is not None else None
            if ec is None:
                continue
            if not isinstance(ec, ErrorClipByValue):
                raise NotImplementedError(
                    'error_clip on %r: only ErrorClipByValue is '
                    'supported by the cotangent-clamp lowering (got %s)'
                    % (n, type(ec).__name__))
            clips[n] = (float(ec.min), float(ec.max))
    return clips


_ERROR_CLIP_FN = None


def _error_clip_grad(x, lo, hi):
    """Identity forward; clamps the cotangent to [lo, hi] on the way
    back (the reference's error clip semantics, fluid/clip.py
    ErrorClipByValue applied through backward.py callbacks). The
    custom_vjp is built once (module cache) — lo/hi ride as nondiff
    args, so one primitive serves every clipped var."""
    global _ERROR_CLIP_FN
    if _ERROR_CLIP_FN is None:
        import functools
        import jax
        import jax.numpy as jnp

        @functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
        def f(x, lo, hi):
            return x

        def fwd(x, lo, hi):
            return x, None

        def bwd(lo, hi, _res, g):
            return (jnp.clip(g, lo, hi),)

        f.defvjp(fwd, bwd)
        _ERROR_CLIP_FN = f
    return _ERROR_CLIP_FN(x, lo, hi)


def _default_prng():
    """Dropout-mask PRNG implementation. On TPU the hardware
    RngBitGenerator ('rbg') is the default: measured +62% transformer
    tok/s over threefry (174.8k vs 108.2k, bench r3 rehearsal) — the
    counter-based threefry mask generation was the single largest
    non-matmul cost of the step. rbg is deterministic for a fixed
    (seed, step) on a given backend/version; threefry remains the
    default off-TPU and the cross-backend-reproducible choice
    (PADDLE_TPU_PRNG=threefry2x32|rbg overrides)."""
    import os
    env = os.environ.get('PADDLE_TPU_PRNG')
    if env:
        return env
    from .platform_boot import is_tpu_backend
    return 'rbg' if is_tpu_backend() else 'threefry2x32'


def _remat_policy(name):
    import jax
    if name in ('full', 'nothing_saveable'):
        return jax.checkpoint_policies.nothing_saveable
    if name == 'dots_saveable':
        return jax.checkpoint_policies.dots_saveable
    raise ValueError('unknown remat policy %r' % name)


class StepHandle(object):
    """One dispatched-but-unresolved step (or run_steps window).

    JAX dispatch is asynchronous: ``run(..., return_handle=True)``
    returns as soon as the computation is enqueued, with the fetches
    still device futures. ``resolve()`` blocks on them (np.asarray —
    the only true sync on a tunneled relay) and returns the numpy
    metrics; ``ready()`` peeks without blocking. ``dispatched_at``
    timestamps the enqueue so the pipelined trainer can attribute
    host-blocked vs device-blocked wall time."""

    __slots__ = ('fetches', 'steps', 'dispatched_at', 'cache_miss',
                 '_resolved')

    def __init__(self, fetches, steps=1, cache_miss=False):
        self.fetches = fetches
        self.steps = int(steps)
        self.cache_miss = bool(cache_miss)
        self.dispatched_at = time.perf_counter()
        self._resolved = None

    def ready(self):
        """True when every fetch has landed (non-blocking peek)."""
        if self._resolved is not None:
            return True
        try:
            return all(bool(v.is_ready()) for v in self.fetches)
        except AttributeError:
            return True   # plain numpy values: nothing in flight

    def resolve(self):
        """Block until the dispatch completes; returns numpy metrics.
        Idempotent — the device references are dropped on first call."""
        if self._resolved is None:
            self._resolved = [np.asarray(v) for v in self.fetches]
            self.fetches = self._resolved
        return self._resolved


class _Compiled(object):
    __slots__ = ('fn', 'raw_fn', 'scope_in_names', 'scope_out_names',
                 'feed_names', 'fetch_names', 'flops', 'aot_fp',
                 'aot_state')

    def __init__(self, fn, raw_fn, scope_in_names, scope_out_names,
                 feed_names, fetch_names):
        self.fn = fn
        self.raw_fn = raw_fn  # un-jitted step function (jittable, no donation)
        self.scope_in_names = scope_in_names
        self.scope_out_names = scope_out_names
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self.flops = None  # per-step XLA cost-analysis FLOPs (observe)
        self.aot_fp = None      # aot_cache fingerprint, when cacheable
        self.aot_state = None   # None | 'save' (serialize at dispatch)
                                # | 'warm' (fn deserialized from disk)


_SUB_BLOCK_ATTRS = ('sub_block', 'true_block', 'false_block')


def _op_reads(op, program, cache=None):
    """All names *op* reads, including external reads made inside its
    sub-blocks (while/rnn bodies, if_else branches). A name defined by an
    earlier op within the same sub-block is internal and excluded, so the
    result is exactly the set of values the op needs from its surroundings.
    Pass a dict as *cache* to amortize the sub-block walk across passes."""
    if cache is not None and id(op) in cache:
        return cache[id(op)]
    reads = list(op.input_names())
    if program is not None:
        for attr in _SUB_BLOCK_ATTRS:
            idx = op.attrs.get(attr)
            if idx is not None:
                defined = set()
                for sub_op in program.block(idx).ops:
                    for n in _op_reads(sub_op, program, cache):
                        if n not in defined:
                            reads.append(n)
                    defined.update(sub_op.output_names())
    if cache is not None:
        cache[id(op)] = reads
    return reads


def _analyze(block, ops, feed_names, reads_cache=None):
    """Determine scope inputs (persistable/state vars read before defined)
    and scope outputs (persistable vars written)."""
    defined = set(feed_names)
    scope_in, scope_out = [], []
    for op in ops:
        if op.type == 'backward_marker':
            defined.update(op.attrs['grad_names'])
            continue
        for name in _op_reads(op, block.program, reads_cache):
            if name in defined or name in scope_in:
                continue
            scope_in.append(name)
        for name in op.output_names():
            defined.add(name)
            var = block._find_var_recursive(name)
            if var is not None and var.persistable and name not in scope_out:
                scope_out.append(name)
    return scope_in, scope_out


def _prune_ops(block, ops, fetch_names, reads_cache=None):
    """Keep ops contributing to fetches or to persistable state updates.

    Liveness walks into sub-blocks via _op_reads: a var read only inside a
    while/if_else body still keeps its producer alive (reference analog:
    Prune in paddle/fluid/framework/prune.cc descends into sub-block descs).
    """
    needed = set(fetch_names)
    kept = []
    for op in reversed(ops):
        writes_state = any(
            (lambda v: v is not None and v.persistable)(
                block._find_var_recursive(n))
            for n in op.output_names())
        if op.type == 'backward_marker' or writes_state or \
                (set(op.output_names()) & needed):
            kept.append(op)
            needed.update(_op_reads(op, block.program, reads_cache))
            if op.type == 'backward_marker':
                needed.add(op.attrs['loss_name'])
    kept.reverse()
    return kept


class Executor(object):
    def __init__(self, place=None):
        self.place = place if place is not None else TPUPlace(0)
        self._cache = {}
        # Serving runs this executor from concurrent threads: _lock
        # guards the compile cache, the per-key compile locks, and the
        # global step counter; last_cache_miss is per-thread so one
        # thread's hit can't mask another thread's miss.
        self._lock = threading.Lock()
        self._compile_locks = {}
        # Program keys already checked by the static verifier
        # (PADDLE_TPU_VERIFY): verification runs once per key, at first
        # compile, BEFORE anything traces.
        self._verified = set()
        # The step fn DONATES its scope inputs (param buffers alias
        # outputs); two concurrent dispatches on one scope would hand
        # the second a deleted buffer. Dispatch + scope write-back is
        # therefore one critical section; traces/compiles of distinct
        # keys still run concurrently.
        self._dispatch_lock = threading.Lock()
        self._tls = threading.local()
        self._step = 0
        # AOT serialized-executable cache ledger (core/aot_cache.py):
        # warm-start hits/misses and load seconds, read by warmup()
        # wiring in serving/decode engines and the trainer. Mutated
        # under self._lock.
        self.aot_stats = {'hits': 0, 'misses': 0, 'saves': 0,
                          'load_failures': 0, 'load_seconds': 0.0}
        from .platform_boot import arm_compile_cache
        arm_compile_cache()

    @property
    def last_cache_miss(self):
        """Whether THIS thread's most recent run()/run_steps() call
        missed the compile cache (thread-local: concurrent serving
        threads each see their own answer)."""
        return getattr(self._tls, 'last_cache_miss', False)

    @last_cache_miss.setter
    def last_cache_miss(self, value):
        self._tls.last_cache_miss = value

    @property
    def last_warm_from_disk(self):
        """Whether THIS thread's most recent run()/run_steps() call
        installed its executable from the AOT disk cache instead of
        tracing+compiling (thread-local, like last_cache_miss)."""
        return getattr(self._tls, 'last_warm_from_disk', False)

    @last_warm_from_disk.setter
    def last_warm_from_disk(self, value):
        self._tls.last_warm_from_disk = value

    def _next_steps(self, n):
        """Atomically claim n global step indices (dropout keys fold
        the step index; two threads must never share one)."""
        with self._lock:
            step0 = self._step
            self._step += n
        return np.int32(step0)

    def _maybe_verify(self, kind, key, program, feed_vals, fetch_names):
        """PADDLE_TPU_VERIFY=off|warn|strict: run the static verifier
        (paddle_tpu.analysis) over the program ONCE per cache key, at
        the first sight of that key and BEFORE any trace — strict mode
        raises ProgramVerifyError while the op that broke the graph is
        still one `file:line` away; warn mode records program_verify
        flight events + analysis.* counters and proceeds. 'off' (the
        default) costs one set lookup per run."""
        if key in self._verified:
            return
        from ..analysis import verify, verify_mode
        mode = verify_mode()
        if mode != 'off':
            verify(program, feed_names=sorted(feed_vals),
                   fetch_names=fetch_names, mode=mode, label=kind)
        self._verified.add(key)

    def _lookup_or_compile(self, kind, key, use_cache, compile_fn,
                           program=None, aot_parts=None):
        """Compile-cache access, safe under concurrent serving threads:
        a hit is one locked dict read; a miss takes a per-key lock so
        two threads racing on the same (program, shapes) signature
        compile ONCE — the loser blocks, then reads the winner's entry
        as a hit. Distinct keys still compile concurrently. Returns
        (compiled, missed).

        On a miss, the AOT serialized-executable cache is consulted
        first (core/aot_cache.py): a disk hit installs the deserialized
        executable — zero trace, zero XLA compile, none of the
        cache_miss/trace/compile events — and a disk miss marks the
        entry for serialization at its first dispatch (when the
        concrete input avals exist)."""
        if not use_cache:
            return self._observed_compile(kind, key, compile_fn), True
        with self._lock:
            compiled = self._cache.get(key)
            if compiled is not None:
                return compiled, False
            key_lock = self._compile_locks.setdefault(key,
                                                      threading.Lock())
        with key_lock:
            with self._lock:
                compiled = self._cache.get(key)
            if compiled is not None:
                return compiled, False
            compiled, fp = None, None
            if program is not None and aot_parts is not None and \
                    program.mesh is None:
                from . import aot_cache as _aot
                if _aot.enabled():
                    fp = _aot.fingerprint(program, aot_parts)
                    compiled = self._try_warm_start(kind, key, fp,
                                                    compile_fn)
            if compiled is None:
                compiled = self._observed_compile(kind, key, compile_fn)
                if fp is not None:
                    compiled.aot_fp = fp
                    compiled.aot_state = 'save'
            with self._lock:
                self._cache[key] = compiled
        return compiled, True

    @staticmethod
    def _donation_safe(loaded):
        """Wrap a DESERIALIZED executable so its donation cannot
        corrupt live state. jax-level donated-buffer bookkeeping does
        not fully survive serialize/deserialize: the executable's
        baked-in input/output aliasing still writes outputs (and
        scratch) into the donated input buffers, but the caller-side
        deleted-array marking that normally fences those buffers off
        is not re-established — so a buffer the scope (or another
        in-flight key) still references gets silently overwritten.
        Observed as replica-weight corruption under concurrent serving
        with PADDLE_TPU_AOT_CACHE=1; the fleet router's hedge
        bit-identity check (router.hedge_mismatch_total) is what
        caught it. Handing the executable a private copy of the
        donated scope argument makes its in-place writes land in
        memory nothing else references; the aliased outputs the
        executor writes back to the scope then own those buffers
        outright. Costs one params-sized device copy per dispatch on
        warm keys only — correctness over the last ounce of warm-path
        throughput."""
        import jax.numpy as jnp

        def call(scope_vals, *rest):
            scope_vals = {k: jnp.array(v, copy=True)
                          for k, v in scope_vals.items()}
            return loaded(scope_vals, *rest)
        return call

    def _try_warm_start(self, kind, key, fp, compile_fn):
        """Install a disk-cached executable for this key, or None. The
        Python lowering walk (compile_fn) still runs — it supplies the
        scope/feed name metadata — but jax never traces and XLA never
        compiles, and none of the miss/trace/compile telemetry fires;
        the warm path emits aot_hit/aot_load_seconds instead."""
        from . import aot_cache as _aot
        t0 = time.perf_counter()
        loaded, status = _aot.load(fp)
        if loaded is None:
            with self._lock:
                self.aot_stats['misses'] += 1
                if status != 'absent':
                    self.aot_stats['load_failures'] += 1
            return None
        compiled = compile_fn()
        compiled.fn = self._donation_safe(loaded)
        compiled.aot_fp = fp
        compiled.aot_state = 'warm'
        # the cost probe would compile — the one thing a warm start
        # exists to avoid; MFU for this key is forfeited, not bought
        compiled.flops = 0.0
        dt = time.perf_counter() - t0
        with self._lock:
            self.aot_stats['hits'] += 1
            self.aot_stats['load_seconds'] += dt
        self.last_warm_from_disk = True
        kid = _obs.key_id(key)
        if _obs.enabled():
            _obs.inc('executor.aot_hit_total', kind=kind, key=kid)
            _obs.record('executor.aot_load_seconds', dt, kind=kind,
                        key=kid)
        _obs.flight_event('aot_load', kind=kind, key=kid,
                          fingerprint=fp[:12],
                          load_seconds=round(dt, 6))
        return compiled

    def _aot_save(self, kind, key, compiled, scope_vals, feed_vals):
        """First dispatch of a disk-missed key: AOT-compile the step at
        the live avals, serialize it for the next process, and install
        the compiled executable as this entry's fn (so the jit wrapper
        never compiles a second copy). Failures leave the jit path
        intact — the cache is an optimization, never a dependency."""
        from . import aot_cache as _aot
        compiled.aot_state = None
        kid = _obs.key_id(key)
        try:
            t0 = time.perf_counter()
            with _obs.span('executor.xla_compile', key=kid):
                exe = compiled.fn.lower(scope_vals, feed_vals,
                                        np.int32(0)).compile()
            dt = time.perf_counter() - t0
            if _obs.enabled():
                _obs.record('executor.compile_seconds', dt, key=kid)
                _obs.overhead('compile', dt)
                if compiled.flops is None:
                    compiled.flops = _obs.cost_analysis_flops(exe) or 0.0
                    if compiled.flops:
                        _obs.set_gauge('executor.step_flops',
                                       compiled.flops)
                        _obs.set_gauge('executor.step_flops_by_key',
                                       compiled.flops, key=kid)
        except Exception as e:
            _obs.flight_event('aot_save_failed', kind=kind, key=kid,
                              error='%s: %s' % (type(e).__name__, e))
            return
        if _aot.save(compiled.aot_fp, exe) is not None:
            with self._lock:
                self.aot_stats['saves'] += 1
            _obs.flight_event('aot_save', kind=kind, key=kid,
                              fingerprint=compiled.aot_fp[:12],
                              compile_seconds=round(dt, 6))
        compiled.fn = exe

    # ------------------------------------------------------------------ run
    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True,
            return_handle=False):
        import jax

        _ensure_ops_imported()
        program = program if program is not None else default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope if scope is not None else global_scope()
        block = program.global_block()

        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in fetch_list]

        feed_vals = self._normalize_feed(block, feed)
        feed_sig = tuple(sorted((n, v.shape, str(v.dtype))
                                for n, v in feed_vals.items()))
        # read per call and folded into the cache key: flipping the
        # PADDLE_TPU_QUANT_ALLREDUCE knob mid-process recompiles
        # instead of silently reusing the other mode's executable
        from ..parallel.collective import grad_bucket_policy
        from ..quant.core import grad_allreduce_policy
        qpolicy = grad_allreduce_policy(program)
        bpolicy = grad_bucket_policy(program)
        key = (id(program), program._version, program.amp,
               program.remat_policy, qpolicy, bpolicy, feed_sig,
               tuple(fetch_names))
        self._maybe_verify('single', key, program, feed_vals,
                           fetch_names)
        self.last_warm_from_disk = False
        compiled, missed = self._lookup_or_compile(
            'single', key, use_program_cache,
            lambda: self._compile(program, sorted(feed_vals),
                                  fetch_names, quant_allreduce=qpolicy,
                                  grad_bucket=bpolicy),
            program=program,
            aot_parts=('single', program.amp, program.remat_policy,
                       qpolicy, bpolicy, feed_sig, tuple(fetch_names)))
        self.last_cache_miss = missed
        if not missed and _obs.enabled():
            _obs.inc('executor.cache_hit_total', kind='single',
                     key=_obs.key_id(key))

        with self._dispatch_lock:
            scope_vals, feed_vals = self._prepare_inputs(
                'Executor.run', program, compiled, scope, feed_vals)
            if compiled.aot_state == 'save':
                self._aot_save('single', key, compiled, scope_vals,
                               feed_vals)
            if _obs.enabled() and compiled.flops is None:
                self._cost_account(compiled, key, scope_vals, feed_vals)

            step_i = self._next_steps(1)
            if _obs.enabled() and self.last_cache_miss:
                # first dispatch of this key = XLA compile + one step; a
                # near-free compile-time signal even when the AOT cost
                # probe is off (PADDLE_TPU_OBSERVE_COST=0)
                t0 = time.perf_counter()
                fetches, new_scope = compiled.fn(scope_vals, feed_vals,
                                                 step_i)
                _obs.record('executor.first_dispatch_seconds',
                            time.perf_counter() - t0, kind='single',
                            key=_obs.key_id(key))
            else:
                fetches, new_scope = compiled.fn(scope_vals, feed_vals,
                                                 step_i)

            for name, value in new_scope.items():
                scope.set(name, value)

        if return_handle:
            return StepHandle(list(fetches), steps=1,
                              cache_miss=self.last_cache_miss)
        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return list(fetches)

    # ---------------------------------------------------------- multi-step
    def run_steps(self, steps, program=None, feed=None, fetch_list=None,
                  scope=None, return_numpy=True, stacked_feed=False,
                  return_handle=False):
        """Run `steps` training steps as ONE XLA execution: the compiled
        step function is wrapped in a lax.scan, so per-dispatch overhead
        (host->device feed, dispatch latency — ~5 ms through a tunneled
        backend) is paid once per `steps` instead of per step. State
        (params, optimizer accumulators, BN stats) chains through the
        scan carry exactly as it chains through the scope across
        Executor.run calls; the per-op PRNG keys fold the true global
        step index, so dropout masks differ per step exactly as they do
        in the one-step path.

        feed values are constant across steps by default (microbench /
        full-batch training); with stacked_feed=True every feed array
        carries a leading [steps, ...] axis (a prefetched superbatch —
        reader.prefetch_to_device pairs with this). Fetches come back
        stacked over the steps axis.

        Reference analog: the trainer's inner batch loop
        (python/paddle/v2/trainer.py:1 train loop); TPU-first, the loop
        itself compiles into the program."""
        import jax
        import jax.numpy as jnp

        _ensure_ops_imported()
        program = program if program is not None else default_main_program()
        fetch_list = fetch_list or []
        scope = scope if scope is not None else global_scope()
        block = program.global_block()
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in fetch_list]

        feed_vals = self._normalize_feed(block, feed or {})
        if stacked_feed:
            for name, arr in feed_vals.items():
                if arr.shape[0] != steps:
                    raise ValueError(
                        'run_steps(stacked_feed=True): feed %r leading '
                        'dim %d != steps %d' % (name, arr.shape[0], steps))

        sig_shape = {n: (v.shape[1:] if stacked_feed else v.shape)
                     for n, v in feed_vals.items()}
        feed_sig = tuple(sorted((n, sig_shape[n], str(v.dtype))
                                for n, v in feed_vals.items()))
        from ..parallel.collective import grad_bucket_policy
        from ..quant.core import grad_allreduce_policy
        qpolicy = grad_allreduce_policy(program)
        bpolicy = grad_bucket_policy(program)
        key = ('multi', id(program), program._version, program.amp,
               program.remat_policy, qpolicy, bpolicy, feed_sig,
               tuple(fetch_names), steps, stacked_feed)
        self._maybe_verify('multi', key, program, feed_vals, fetch_names)

        def _build_multi():
            base = self._compile(program, sorted(feed_vals), fetch_names,
                                 quant_allreduce=qpolicy,
                                 grad_bucket=bpolicy)

            # state that is read each step chains through the scan carry;
            # written-only persistables (no reader) are ALSO carried —
            # seeded with zeros of their traced shape and overwritten
            # every step — so only their final value occupies memory
            # (stacking them in the ys would cost steps x size).
            written_only = [n for n in base.scope_out_names
                            if n not in set(base.scope_in_names)]

            def multi_fn(scope_vals, feeds, step0):
                f0 = {n: v[0] for n, v in feeds.items()} \
                    if stacked_feed else feeds
                _, ns_shapes = jax.eval_shape(base.raw_fn, scope_vals,
                                              f0, step0)
                wo0 = {n: jnp.zeros(ns_shapes[n].shape,
                                    ns_shapes[n].dtype)
                       for n in written_only if n in ns_shapes}

                def body(carry, t):
                    sc, wo = carry
                    f = {n: v[t] for n, v in feeds.items()} \
                        if stacked_feed else feeds
                    fetches, new_scope = base.raw_fn(sc, f, step0 + t)
                    return ({n: new_scope[n] for n in sc},
                            {n: new_scope[n] for n in wo}), fetches

                (final_sc, final_wo), stacked = jax.lax.scan(
                    body, (scope_vals, wo0),
                    jnp.arange(steps, dtype=jnp.int32))
                final_scope = dict(final_sc)
                final_scope.update(final_wo)
                return stacked, final_scope

            jit_multi = jax.jit(multi_fn, donate_argnums=(0,))
            return _Compiled(jit_multi, base.raw_fn,
                             base.scope_in_names, base.scope_out_names,
                             base.feed_names, base.fetch_names)

        self.last_warm_from_disk = False
        compiled, missed = self._lookup_or_compile(
            'multi', key, True, _build_multi,
            program=program,
            aot_parts=('multi', program.amp, program.remat_policy,
                       qpolicy, bpolicy, feed_sig, tuple(fetch_names),
                       steps, stacked_feed))
        self.last_cache_miss = missed
        if not missed and _obs.enabled():
            _obs.inc('executor.cache_hit_total', kind='multi',
                     key=_obs.key_id(key))

        with self._dispatch_lock:
            scope_vals, feed_vals = self._prepare_inputs(
                'Executor.run_steps', program, compiled, scope, feed_vals,
                feed_stack_axis=stacked_feed)
            if compiled.aot_state == 'save':
                self._aot_save('multi', key, compiled, scope_vals,
                               feed_vals)
            if _obs.enabled() and compiled.flops is None:
                one_feed = {n: v[0] for n, v in feed_vals.items()} \
                    if stacked_feed else feed_vals
                self._cost_account(compiled, key, scope_vals, one_feed)
            step0 = self._next_steps(steps)
            if _obs.enabled() and self.last_cache_miss:
                t0 = time.perf_counter()
                fetches, new_scope = compiled.fn(scope_vals, feed_vals,
                                                 step0)
                _obs.record('executor.first_dispatch_seconds',
                            time.perf_counter() - t0, kind='multi',
                            key=_obs.key_id(key))
            else:
                fetches, new_scope = compiled.fn(scope_vals, feed_vals,
                                                 step0)
            for name, value in new_scope.items():
                scope.set(name, value)
        if return_handle:
            return StepHandle(list(fetches), steps=steps,
                              cache_miss=self.last_cache_miss)
        if return_numpy:
            return [np.asarray(v) for v in fetches]
        return list(fetches)

    # -------------------------------------------------------------- helpers
    def _observed_compile(self, kind, key, compile_fn):
        """Trace/prune/compile with telemetry: cache-miss counter, a
        span, and per-key trace seconds. The XLA compile itself happens
        lazily at the first dispatch (and is separately accounted by
        _cost_account's AOT probe when observability is on)."""
        if not _obs.enabled():
            return compile_fn()
        kid = _obs.key_id(key)
        _obs.inc('executor.cache_miss_total', kind=kind, key=kid)
        t0 = time.perf_counter()
        with _obs.span('executor.trace', kind=kind, key=kid):
            compiled = compile_fn()
        dt = time.perf_counter() - t0
        _obs.record('executor.trace_seconds', dt, kind=kind, key=kid)
        # a mid-run compile is exactly the kind of last-seconds context a
        # postmortem needs (shape churn right before death)
        _obs.flight_event('compile', kind=kind, key=kid,
                          trace_seconds=round(dt, 6))
        return compiled

    def _cost_account(self, compiled, key, scope_vals, feed_vals):
        """Best-effort per-step FLOPs via an AOT compile of the un-donated
        step fn + XLA cost_analysis (observe-enabled runs only; one extra
        compile per cache miss — PADDLE_TPU_OBSERVE_COST=0 opts out).
        Also the honest 'executor.compile_seconds' measurement: whole-
        program XLA compile time per (program, shapes) key."""
        if os.environ.get('PADDLE_TPU_OBSERVE_COST') == '0':
            compiled.flops = 0.0
            return
        import jax
        kid = _obs.key_id(key)
        try:
            t0 = time.perf_counter()
            with _obs.span('executor.xla_compile', key=kid):
                exe = jax.jit(compiled.raw_fn).lower(
                    scope_vals, feed_vals, np.int32(0)).compile()
            dt = time.perf_counter() - t0
            _obs.record('executor.compile_seconds', dt, key=kid)
            _obs.overhead('compile', dt)
            compiled.flops = _obs.cost_analysis_flops(exe) or 0.0
        except Exception:
            compiled.flops = 0.0   # tried; never retry per key
        if compiled.flops:
            _obs.set_gauge('executor.step_flops', compiled.flops)
            _obs.set_gauge('executor.step_flops_by_key', compiled.flops,
                           key=kid)

    def _normalize_feed(self, block, feed):
        """Normalize feed values to arrays with the declared
        (canonicalized) dtype. Values already on device (jax Arrays) are
        passed through untouched — np.asarray would round-trip them
        through host memory."""
        import jax
        feed_vals = {}
        for name, value in feed.items():
            var = block._find_var_recursive(name)
            dtype = to_jnp_dtype(var.dtype) if var is not None else None
            arr = value if isinstance(value, jax.Array) \
                else np.asarray(value)
            if dtype is not None and arr.dtype != dtype:
                arr = arr.astype(dtype)
            feed_vals[name] = arr
        return feed_vals

    def _prepare_inputs(self, who, program, compiled, scope, feed_vals,
                        feed_stack_axis=False):
        """Missing-feed check, scope gather, and mesh sharding shared by
        run / run_steps / compile_step."""
        missing = [n for n in compiled.feed_names if n not in feed_vals]
        if missing:
            raise ValueError('%s: missing feed for data vars %s'
                             % (who, missing))
        scope_vals = {}
        for name in compiled.scope_in_names:
            value = scope.find(name)
            if value is None:
                raise RuntimeError(
                    'Variable %r is not initialized in scope. Run the '
                    'startup program first.' % name)
            scope_vals[name] = value
        mesh = program.mesh
        if mesh is not None:
            scope_vals = self._shard_values(program, mesh, scope_vals)
            feed_vals = self._shard_values(program, mesh, feed_vals,
                                           stack_axis=feed_stack_axis)
        return scope_vals, feed_vals

    def _shard_values(self, program, mesh, vals, stack_axis=False):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        out = {}
        for name, value in vals.items():
            spec = program.var_shardings.get(name)
            if spec is None:
                spec = PartitionSpec()
            elif stack_axis:
                # stacked_feed superbatch: the var's spec describes the
                # per-step array; the leading [steps] axis is replicated
                spec = PartitionSpec(None, *spec)
            sharding = NamedSharding(mesh, spec)
            already = getattr(value, 'sharding', None)
            if already == sharding:
                out[name] = value
            else:
                out[name] = jax.device_put(value, sharding)
        return out

    def _compile(self, program, feed_names, fetch_names,
                 quant_allreduce=None, grad_bucket=None):
        import jax

        block = program.global_block()
        all_ops = list(block.ops)
        reads_cache = {}  # amortizes the sub-block walk across the 3 passes
        ops = _prune_ops(block, all_ops, fetch_names, reads_cache)
        if _obs.enabled():
            _obs.inc('executor.ops_pruned_total', len(all_ops) - len(ops))
            _obs.inc('executor.ops_lowered_total', len(ops))

        # Data vars actually consumed must be fed.
        consumed = set()
        for op in ops:
            consumed.update(_op_reads(op, program, reads_cache))
        needed_feeds = sorted(
            n for n in consumed
            if (lambda v: v is not None and v.is_data)(
                block._find_var_recursive(n)))

        scope_in, scope_out = _analyze(block, ops, set(feed_names) | set(
            n for n in consumed if block._find_var_recursive(n) is None),
            reads_cache)
        # Drop anything that's actually a fed data var.
        scope_in = [n for n in scope_in if n not in set(feed_names)]
        # Donation-friendly: every scope input is also returned (pass-through
        # if not updated), so donated buffers alias outputs.
        scope_out_all = list(dict.fromkeys(scope_in + scope_out))

        marker_idxs = [i for i, op in enumerate(ops)
                       if op.type == 'backward_marker']
        if len(marker_idxs) > 1:
            raise NotImplementedError(
                'Program has %d backward sections (multiple '
                'optimizer.minimize / append_backward calls). Build each '
                'loss in its own Program (the reference GAN examples do the '
                'same) — interleaved update/grad semantics in one program '
                'are ambiguous.' % len(marker_idxs))
        marker_idx = marker_idxs[0] if marker_idxs else None
        seed = program.random_seed if program.random_seed is not None else 0
        mesh = program.mesh
        shardings = program.var_shardings
        amp = program.amp
        error_clips = collect_error_clips(block, ops)

        # Quantized dp gradient aggregation (EQuARX wire format): under
        # GSPMD the dp allreduce is inserted by XLA inside the grad
        # contraction, so the compressed schedule is modeled by passing
        # each dense dp-reduced gradient through the int8 per-block
        # quantize/dequantize with stochastic rounding (quant/core.qdq
        # — the requantized-shard leg; the explicit two-leg schedule is
        # collective.quantized_all_reduce, proven against psum in
        # tests/test_quant.py). Active only where the compressed
        # collective would exist: a training step on a dp>1 mesh.
        quant_grads = None
        if quant_allreduce is not None and marker_idx is not None and \
                mesh is not None and dict(mesh.shape).get('dp', 1) > 1:
            quant_grads = {'block': int(quant_allreduce[1])}
            if _obs.enabled():
                from ..quant import core as _quant
                n_dp = dict(mesh.shape).get('dp', 1)
                marker = ops[marker_idx]
                n_elems = 0
                for pn in marker.attrs['param_names']:
                    v = block._find_var_recursive(pn)
                    if v is not None and v.shape:
                        sz = 1
                        for d in v.shape:
                            sz *= int(d)
                        n_elems += sz
                fp32_b = _quant.allreduce_wire_bytes(n_elems, n_dp)
                q_b = _quant.quantized_allreduce_wire_bytes(
                    n_elems, n_dp, quant_grads['block'])
                _obs.set_gauge('quant.allreduce_grad_elements', n_elems)
                _obs.set_gauge('quant.allreduce_bytes_fp32', fp32_b)
                _obs.set_gauge('quant.allreduce_bytes_quant', q_b)
                _obs.set_gauge('quant.allreduce_compression',
                               fp32_b / max(q_b, 1.0))
                _obs.inc('quant.allreduce_compiles_total')

        # Bucketed asynchronous gradient allreduce (the EQuARX overlap
        # leg): instead of leaving the dp reduction as one fused
        # collective after the whole backward, dense gradients are
        # partitioned into size-targeted buckets in reverse production
        # order (assignment is static — computed here from the declared
        # shapes, so trace and re-trace agree) and each bucket gets its
        # own sharding-constraint round trip in step_fn. XLA then emits
        # one reduce-scatter/all-gather pair per bucket with dataflow
        # deps only on that bucket's gradients, which the latency-hiding
        # scheduler overlaps against the remaining backward compute.
        # Same gating as quant_grads: a training step on a dp>1 mesh.
        grad_buckets = None
        if grad_bucket is not None and marker_idx is not None and \
                mesh is not None and dict(mesh.shape).get('dp', 1) > 1:
            from ..parallel.collective import assign_grad_buckets
            marker = ops[marker_idx]
            sparse_names = set(marker.attrs.get('sparse_grads') or {})
            dense_pairs = [
                (pn, gn) for pn, gn in zip(marker.attrs['param_names'],
                                           marker.attrs['grad_names'])
                if pn not in sparse_names]
            items = []
            for pn, _ in dense_pairs:
                v = block._find_var_recursive(pn)
                shape = v.shape if v is not None and v.shape else (1,)
                numel = 1
                for d in shape:
                    numel *= int(d)
                dt = np.dtype(to_jnp_dtype(v.dtype)) if v is not None \
                    else np.dtype('float32')
                items.append((numel * dt.itemsize, str(dt)))
            target = int(grad_bucket[1] * 1024 * 1024)
            buckets = assign_grad_buckets(items, target)
            grad_buckets = {'pairs': dense_pairs, 'buckets': buckets}
            if _obs.enabled():
                per_bucket = [sum(items[i][0] for i in b)
                              for b in buckets]
                _obs.set_gauge('trainer.grad_bucket_count', len(buckets))
                _obs.set_gauge('trainer.grad_bucket_target_bytes', target)
                _obs.set_gauge('trainer.grad_bucket_max_bytes',
                               max(per_bucket) if per_bucket else 0)
                _obs.inc('trainer.grad_bucket_compiles_total')

        def run_ops(op_list, env, base_key, start_index=0):
            import jax as _jax
            import jax.numpy as _jnp
            from jax.sharding import NamedSharding, PartitionSpec
            from .registry import AMP_BF16_OUT_SLOTS
            for i, op in enumerate(op_list):
                ctx = LoweringContext(env, op, block, start_index + i,
                                      base_key,
                                      is_test=bool(op.attrs.get('is_test',
                                                                False)),
                                      amp=amp)
                try:
                    get_lowering(op.type)(ctx)
                except KeyError as e:
                    raise RuntimeError(
                        'While lowering op %r: missing input %s. '
                        'Feed it or run producers first.' % (op.type, e))
                if amp == 'bf16' and op.type in AMP_BF16_OUT_SLOTS:
                    # fp32-stat ops hand activations back to the bf16
                    # stream (see registry.AMP_BF16_OUT_SLOTS)
                    for slot in AMP_BF16_OUT_SLOTS[op.type]:
                        name = op.output(slot)
                        if name in env and env[name].dtype == _jnp.float32:
                            env[name] = env[name].astype(_jnp.bfloat16)
                if error_clips:
                    # reference error_clip: clamp the gradient flowing
                    # BACK through this var (fluid/clip.py ErrorClip +
                    # backward.py error_clip_callback); TPU-native, the
                    # clamp rides the var's cotangent via custom_vjp
                    for name in op.output_names():
                        if name in error_clips and name in env:
                            lo, hi = error_clips[name]
                            env[name] = _error_clip_grad(env[name],
                                                         lo, hi)
                if mesh is not None:
                    for name in op.output_names():
                        spec = shardings.get(name)
                        if spec is not None and name in env:
                            env[name] = _jax.lax.with_sharding_constraint(
                                env[name], NamedSharding(mesh, spec))
            return env

        prng_impl = _default_prng()

        def step_fn(scope_vals, feed_vals, step_i):
            # PADDLE_TPU_PRNG=rbg swaps in the TPU hardware RNG for
            # dropout-mask generation (threefry is counter-based and
            # costs real MXU-adjacent cycles per element; rbg trades
            # strict reproducibility-across-backends for speed).
            base_key = jax.random.fold_in(
                jax.random.key(seed, impl=prng_impl), step_i)
            env = {}
            env.update(feed_vals)
            env.update(scope_vals)

            if marker_idx is not None:
                import jax.numpy as _jnp
                from .backward import SPARSE_SEED_PREFIX
                pre = ops[:marker_idx]
                marker = ops[marker_idx]
                post = ops[marker_idx + 1:]
                param_names = marker.attrs['param_names']
                grad_names = marker.attrs['grad_names']
                loss_name = marker.attrs['loss_name']
                sparse_info = marker.attrs.get('sparse_grads') or {}

                # sparse-grad tables are NOT differentiated (they stay
                # in base_env; the lookup lowering detaches them) — a
                # zero row seed shaped like the lookup OUTPUT becomes
                # the leaf instead, so its grad is O(batch x dim) rows,
                # never an O(vocab) dense table grad
                dense_names = [n for n in param_names
                               if n not in sparse_info]
                base_env = {k: v for k, v in env.items()
                            if k not in set(dense_names)}
                params = {n: env[n] for n in dense_names}
                for pname, info in sparse_info.items():
                    ids = env[info['ids']]
                    ids_shape = ids.shape[:-1] \
                        if ids.ndim >= 2 and ids.shape[-1] == 1 \
                        else ids.shape
                    params[SPARSE_SEED_PREFIX + info['out']] = _jnp.zeros(
                        ids_shape + (env[pname].shape[-1],),
                        env[pname].dtype)

                # Only values consumed after the backward boundary may
                # escape the forward — anything else would be saved as a
                # checkpoint output and defeat rematerialization.
                needed_after = set(fetch_names) | set(scope_out_all)
                needed_after.add(loss_name)

                for op in post:
                    needed_after.update(_op_reads(op, program, reads_cache))

                def fwd(p):
                    e = dict(base_env)
                    e.update(p)
                    e = run_ops(pre, e, base_key)
                    loss = e[loss_name].sum()
                    keep = {k: v for k, v in e.items()
                            if k in needed_after}
                    return loss, keep

                if program.remat_policy:
                    fwd = jax.checkpoint(
                        fwd, policy=_remat_policy(program.remat_policy))

                (_, kept), grads = jax.value_and_grad(
                    fwd, has_aux=True)(params)
                env.update(kept)

                # Bucketed allreduce: each bucket is concatenated,
                # padded to a dp multiple, and pushed through a
                # P('dp') -> [optional qdq] -> P() sharding-constraint
                # round trip. The constraint pair is the per-bucket
                # collective boundary — XLA lowers it to a
                # reduce-scatter/all-gather over just this bucket's
                # gradients, with dataflow deps only on them, so the
                # scheduler overlaps it with the rest of the backward.
                # Exact path is a pure relayout (bit-identical to
                # unbucketed); the quantized path compresses per bucket
                # (key namespace 0x6b31, distinct from per-grad 0x5172).
                bucket_vals = {}
                if grad_buckets is not None:
                    from jax.sharding import NamedSharding as _NS
                    from jax.sharding import PartitionSpec as _P
                    n_dp = dict(mesh.shape)['dp']
                    pairs = grad_buckets['pairs']
                    for bi, bucket in enumerate(grad_buckets['buckets']):
                        names = [pairs[i][0] for i in bucket]
                        flats = [grads[n].reshape(-1) for n in names]
                        cat = _jnp.concatenate(flats) \
                            if len(flats) > 1 else flats[0]
                        numel = cat.shape[0]
                        pad = (-numel) % n_dp
                        if pad:
                            cat = _jnp.pad(cat, (0, pad))
                        cat = jax.lax.with_sharding_constraint(
                            cat, _NS(mesh, _P('dp')))
                        if quant_grads is not None:
                            from ..quant.core import qdq as _bqdq
                            bkey = jax.random.fold_in(
                                jax.random.fold_in(base_key, 0x6b31),
                                bi)
                            cat = _bqdq(cat,
                                        block=quant_grads['block'],
                                        key=bkey)
                        cat = jax.lax.with_sharding_constraint(
                            cat, _NS(mesh, _P()))
                        if pad:
                            cat = cat[:numel]
                        off = 0
                        for n in names:
                            g = grads[n]
                            sz = int(g.size)
                            bucket_vals[n] = cat[off:off + sz] \
                                .reshape(g.shape).astype(g.dtype)
                            off += sz

                for pi, (pn, gn) in enumerate(zip(param_names,
                                                  grad_names)):
                    if pn in sparse_info:
                        # sparse row grads scatter in place; they never
                        # ride the dense allreduce, so no wire format
                        rows = grads[SPARSE_SEED_PREFIX +
                                     sparse_info[pn]['out']]
                        env[gn] = rows.reshape(-1, rows.shape[-1])
                    elif pn in bucket_vals:
                        env[gn] = bucket_vals[pn]
                    elif quant_grads is not None:
                        from ..quant.core import qdq as _qdq
                        gkey = jax.random.fold_in(
                            jax.random.fold_in(base_key, 0x5172), pi)
                        env[gn] = _qdq(grads[pn],
                                       block=quant_grads['block'],
                                       key=gkey)
                    else:
                        env[gn] = grads[pn]
                if mesh is not None:
                    # grads are assigned here, not as op outputs, so the
                    # run_ops constraint pass never sees them; ZeRO-1's
                    # reduce-scatter (transpiler dp-extends the grad
                    # spec when shard_optimizer_states is on) is applied
                    # at the assignment boundary instead.
                    from jax.sharding import NamedSharding as _NS
                    for gn in grad_names:
                        gspec = shardings.get(gn)
                        if gspec is not None and gn in env:
                            env[gn] = jax.lax.with_sharding_constraint(
                                env[gn], _NS(mesh, gspec))
                env = run_ops(post, env, base_key,
                              start_index=marker_idx + 1)
            else:
                env = run_ops(ops, env, base_key)

            fetches = []
            for name in fetch_names:
                if name not in env:
                    raise KeyError(
                        'fetch target %r was not computed by this program'
                        % name)
                fetches.append(env[name])
            new_scope = {n: env[n] for n in scope_out_all if n in env}
            return fetches, new_scope

        jit_fn = jax.jit(step_fn, donate_argnums=(0,))
        return _Compiled(jit_fn, step_fn, scope_in, scope_out_all,
                         needed_feeds, fetch_names)

    def compile_step(self, program=None, feed=None, fetch_list=None,
                     scope=None):
        """AOT path: compile a (program, feed-spec) pair and return
        ``(step_fn, scope_vals, feed_vals)`` where ``step_fn(scope_vals,
        feed_vals, step_i)`` is a pure jittable function returning
        ``(fetches, new_scope)``. Used by bench/__graft_entry__ and the
        inference predictor; ``Executor.run`` callers never need this."""
        _ensure_ops_imported()
        program = program if program is not None else default_main_program()
        scope = scope if scope is not None else global_scope()
        block = program.global_block()
        fetch_names = [f.name if isinstance(f, Variable) else f
                       for f in (fetch_list or [])]
        feed_vals = self._normalize_feed(block, feed or {})
        from ..parallel.collective import grad_bucket_policy
        from ..quant.core import grad_allreduce_policy
        compiled = self._compile(
            program, sorted(feed_vals), fetch_names,
            quant_allreduce=grad_allreduce_policy(program),
            grad_bucket=grad_bucket_policy(program))
        scope_vals, feed_vals = self._prepare_inputs(
            'Executor.compile_step', program, compiled, scope, feed_vals)
        return compiled.raw_fn, scope_vals, feed_vals
