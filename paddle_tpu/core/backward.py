"""append_backward (reference: python/paddle/fluid/backward.py).

The reference appends explicit grad ops per forward op (backward.cc
transpiles OpDesc -> grad OpDesc). TPU-native design: autodiff is delegated
to jax.value_and_grad over the traced forward section, which XLA then fuses
with the forward. append_backward therefore records a single
``backward_marker`` op carrying (loss, params, grad var names); the Executor
splits the op list there, differentiates the prefix, and seeds ``p@GRAD``
variables for the suffix (regularizers, clips, optimizer update ops) to
consume — identical dataflow to the reference, one XLA computation.
"""

from .program import Parameter

GRAD_SUFFIX = '@GRAD'

# env key of the zero "row seed" added to a sparse-grad lookup's output:
# differentiating w.r.t. the seed yields the O(batch x dim) row gradient
# (the reference's SelectedRows, lookup_table_op.cc:119-127) without ever
# materializing an O(vocab) dense table gradient.
SPARSE_SEED_PREFIX = '~sparse_seed~'


def grad_var_name(name):
    return name + GRAD_SUFFIX


def _sparse_grad_lookups(block, params):
    """{param name: {'ids', 'out'}} for every parameter eligible for
    row-sparse gradients: flagged by layers.embedding(is_sparse=True),
    read by exactly ONE lookup_table op — counted across ALL blocks, so
    a second use inside a while/rnn sub-block disqualifies rather than
    silently dropping its grad contribution — whose Ids are available at
    step start (fed data or persistable state), with no regularizer or
    clip anywhere in scope (per-param attrs here; optimizer-level
    regularization and program-level set_gradient_clip are checked by
    the caller — both rewrite grads against the dense shape).
    Ineligible tables silently take the exact dense path."""
    eligible = {}
    program = block.program
    program_clip = getattr(program, '_gradient_clip_attr', None)
    flagged = {p.name for p in params if getattr(p, 'sparse_grad', False)
               and p.regularizer is None and program_clip is None
               and getattr(p, 'gradient_clip_attr', None) is None}
    if not flagged:
        return eligible
    uses = {}
    for b in program.blocks:
        for op in b.ops:
            for n in op.input_names():
                if n in flagged:
                    uses.setdefault(n, []).append(op)
    for name, ops in uses.items():
        if len(ops) != 1 or ops[0].type != 'lookup_table':
            continue
        op = ops[0]
        ids_name = op.inputs['Ids'][0]
        ids_var = block._find_var_recursive(ids_name)
        if ids_var is None or not (ids_var.is_data or ids_var.persistable):
            continue
        eligible[name] = {'ids': ids_name, 'out': op.outputs['Out'][0]}
    return eligible


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, sparse_supported=False):
    """Append the backward section for ``loss``.

    sparse_supported: the calling optimizer's update op can consume
    row-sparse gradients (SGD/Adagrad scatter rows in place); eligible
    embedding tables then get [n_ids, dim] row grads instead of dense
    [vocab, dim] — the SelectedRows role of lookup_table_grad
    (reference lookup_table_op.cc:119-127) under whole-program jit.

    Returns list of (param_var, grad_var) like the reference.
    """
    program = loss.block.program
    block = program.global_block()
    no_grad_set = set(no_grad_set or [])
    no_grad_names = set(v if isinstance(v, str) else v.name
                        for v in no_grad_set)

    if parameter_list is not None:
        names = [p if isinstance(p, str) else p.name for p in parameter_list]
        params = [block.var(n) for n in names]
    else:
        params = program.all_parameters()
    params = [p for p in params
              if isinstance(p, Parameter) and p.trainable
              and not p.stop_gradient and p.name not in no_grad_names]
    if not params:
        raise ValueError('append_backward: no trainable parameters found')

    sparse = _sparse_grad_lookups(block, params) if sparse_supported else {}

    params_and_grads = []
    for p in params:
        if p.name in sparse:
            # runtime shape is [n_ids, dim] (batch-dependent)
            g = block.create_var(name=grad_var_name(p.name),
                                 shape=(-1, p.shape[-1]), dtype=p.dtype)
            g.sparse_ids = sparse[p.name]['ids']
        else:
            g = block.create_var(name=grad_var_name(p.name), shape=p.shape,
                                 dtype=p.dtype)
        g.stop_gradient = True
        params_and_grads.append((p, g))

    block.append_op(
        type='backward_marker',
        inputs={'Loss': [loss.name]},
        outputs={'Grads': [g.name for _, g in params_and_grads]},
        attrs={'param_names': [p.name for p, _ in params_and_grads],
               'grad_names': [g.name for _, g in params_and_grads],
               'loss_name': loss.name,
               'sparse_grads': sparse})
    return params_and_grads
