"""append_backward (reference: python/paddle/fluid/backward.py).

The reference appends explicit grad ops per forward op (backward.cc
transpiles OpDesc -> grad OpDesc). TPU-native design: autodiff is delegated
to jax.value_and_grad over the traced forward section, which XLA then fuses
with the forward. append_backward therefore records a single
``backward_marker`` op carrying (loss, params, grad var names); the Executor
splits the op list there, differentiates the prefix, and seeds ``p@GRAD``
variables for the suffix (regularizers, clips, optimizer update ops) to
consume — identical dataflow to the reference, one XLA computation.
"""

from .program import Parameter

GRAD_SUFFIX = '@GRAD'


def grad_var_name(name):
    return name + GRAD_SUFFIX


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Append the backward section for ``loss``.

    Returns list of (param_var, grad_var) like the reference.
    """
    program = loss.block.program
    block = program.global_block()
    no_grad_set = set(no_grad_set or [])
    no_grad_names = set(v if isinstance(v, str) else v.name
                        for v in no_grad_set)

    if parameter_list is not None:
        names = [p if isinstance(p, str) else p.name for p in parameter_list]
        params = [block.var(n) for n in names]
    else:
        params = program.all_parameters()
    params = [p for p in params
              if isinstance(p, Parameter) and p.trainable
              and not p.stop_gradient and p.name not in no_grad_names]
    if not params:
        raise ValueError('append_backward: no trainable parameters found')

    params_and_grads = []
    for p in params:
        g = block.create_var(name=grad_var_name(p.name), shape=p.shape,
                             dtype=p.dtype)
        g.stop_gradient = True
        params_and_grads.append((p, g))

    block.append_op(
        type='backward_marker',
        inputs={'Loss': [loss.name]},
        outputs={'Grads': [g.name for _, g in params_and_grads]},
        attrs={'param_names': [p.name for p, _ in params_and_grads],
               'grad_names': [g.name for _, g in params_and_grads],
               'loss_name': loss.name})
    return params_and_grads
