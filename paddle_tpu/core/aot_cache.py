"""AOT serialized-executable cache: warm a fresh process from disk.

The third cache layer (see platform_boot.arm_compile_cache's taxonomy).
The persistent XLA *module* cache skips the HLO->binary compile but a
restarted process still pays the full Python trace of every (program,
shapes) key before it can even ASK the module cache; the tuning table
skips re-benchmarking but not compilation. This layer removes both: on
an Executor cache miss the fully-compiled step executable is serialized
(``jax.experimental.serialize_executable`` — PjRT executable bytes +
the call's pytree defs) keyed by a CONTENT fingerprint of the program
plus the feed/fetch signature and a backend fingerprint; the next
process with the same program reaches its first dispatch with ZERO
traces and ZERO XLA compiles — the whole-program-compilation thesis of
PAPERS "Automatic Full Compilation ... to Cloud TPUs" applied to
restart latency (the Gemma-serving fleet scenario: a scaled-up replica
warms in seconds).

Keying: ``fingerprint()`` hashes the serialized program content (the
same dict io.save_inference_model persists), the executor cache-key
parts (kind, amp, remat, feed signature, fetches, steps), and the
backend fingerprint (jax/jaxlib versions, platform, device kind and
count) — NOT ``id(program)``, so two processes (or two Program objects)
with identical content share entries. Any mismatch — different jaxlib,
different chip, corrupted file — falls back to a live compile with an
``aot_fallback`` flight event; the cache can only ever cost a read.

Knobs::

    PADDLE_TPU_AOT_CACHE      auto (default: TPU backends only) | 1 | 0
    PADDLE_TPU_AOT_CACHE_DIR  cache directory (default: per-user tmp)

'auto' mirrors the compile_cache flag's rationale: XLA:CPU AOT
artifacts can embed host-CPU feature sets that SIGILL on a different
machine, so CPU opts in explicitly (tests and single-machine serving
do; the warm-start e2e proves the win on CPU CI).

Only single-device programs are cached (``program.mesh is None``) —
sharded executables embed device assignments that do not relocate.
"""

import hashlib
import json
import os
import pickle
import tempfile

from .. import observe as _obs

FORMAT_VERSION = 1
_SUFFIX = '.jaot'


def enabled(environ=None):
    env = os.environ if environ is None else environ
    raw = (env.get('PADDLE_TPU_AOT_CACHE') or 'auto').strip().lower()
    if raw in ('1', 'true', 'yes', 'on'):
        return True
    if raw in ('0', 'false', 'no', 'off'):
        return False
    from .platform_boot import is_tpu_backend
    return is_tpu_backend()


def cache_dir():
    d = os.environ.get('PADDLE_TPU_AOT_CACHE_DIR')
    if d:
        return d
    try:
        import getpass
        user = getpass.getuser()
    except Exception:
        user = str(os.getuid()) if hasattr(os, 'getuid') else 'default'
    return os.path.join(tempfile.gettempdir(),
                        'paddle_tpu_aot_cache_%s' % user)


def backend_fingerprint():
    """Everything a serialized executable is only valid under."""
    import jax
    try:
        import jaxlib
        jaxlib_ver = jaxlib.__version__
    except Exception:
        jaxlib_ver = 'unknown'
    try:
        devs = jax.devices()
        kind, n = str(devs[0].device_kind), len(devs)
    except Exception:
        kind, n = 'unknown', 0
    return {'format': FORMAT_VERSION, 'jax': jax.__version__,
            'jaxlib': jaxlib_ver, 'platform': jax.default_backend(),
            'device_kind': kind, 'n_devices': n}


def fingerprint(program, parts):
    """Content hash naming the cache entry: program structure (ops,
    vars, attrs — the save_inference_model dict), the executor key
    parts (everything in the in-memory key EXCEPT id(program)), and the
    backend fingerprint. Stable across processes by construction."""
    from .serialize import program_to_dict
    h = hashlib.sha1()
    h.update(json.dumps(program_to_dict(program), sort_keys=True,
                        default=repr).encode())
    h.update(repr(parts).encode())
    h.update(json.dumps(backend_fingerprint(), sort_keys=True).encode())
    return h.hexdigest()


def path_for(fp):
    return os.path.join(cache_dir(), fp + _SUFFIX)


def load(fp):
    """(callable, status): the deserialized-and-loaded executable for
    fingerprint *fp*, or None with status 'absent' | 'mismatch' |
    'error'. Mismatch/corruption is a flight event and a fallback,
    never a raise — a stale cache must not take the process down."""
    path = path_for(fp)
    if not os.path.exists(path):
        return None, 'absent'
    try:
        with open(path, 'rb') as f:
            blob = pickle.load(f)
        meta = blob['meta']
        want = backend_fingerprint()
        if meta != want:
            bad = sorted(k for k in want if meta.get(k) != want.get(k))
            _obs.inc('executor.aot_fallback_total', reason='mismatch')
            _obs.flight_event('aot_fallback', reason='mismatch',
                              fields=','.join(bad), path=path)
            return None, 'mismatch'
        from jax.experimental import serialize_executable as _se
        loaded = _se.deserialize_and_load(blob['payload'],
                                          blob['in_tree'],
                                          blob['out_tree'])
        return loaded, 'loaded'
    except Exception as e:
        _obs.inc('executor.aot_fallback_total', reason='error')
        _obs.flight_event('aot_fallback', reason='error', path=path,
                          error='%s: %s' % (type(e).__name__, e))
        return None, 'error'


def save(fp, compiled_exe):
    """Serialize *compiled_exe* (a jax.stages.Compiled) under *fp*.
    Atomic (unique tmp + os.replace, the io._write_atomic contract) and
    best-effort: serialization failure — e.g. a backend whose PjRT
    executables do not serialize — records a flight event and returns
    None; the in-process executable keeps working regardless."""
    try:
        from jax.experimental import serialize_executable as _se
        payload, in_tree, out_tree = _se.serialize(compiled_exe)
        blob = {'meta': backend_fingerprint(), 'payload': payload,
                'in_tree': in_tree, 'out_tree': out_tree}
        d = cache_dir()
        os.makedirs(d, exist_ok=True)
        path = path_for(fp)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=fp + '.')
        try:
            with os.fdopen(fd, 'wb') as f:
                pickle.dump(blob, f)
            umask = os.umask(0)
            os.umask(umask)
            os.chmod(tmp, 0o666 & ~umask)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path
    except Exception as e:
        _obs.flight_event('aot_save_failed', fingerprint=fp[:12],
                          error='%s: %s' % (type(e).__name__, e))
        return None
