"""LoD (level-of-detail / ragged sequence) translation.

Reference: paddle/fluid/framework/lod_tensor.{h,cc} — variable-length
sequences ride a LoD offset table over a flat tensor. TPU-native design
(SURVEY.md §6): ragged batches become dense [batch, max_len, ...] arrays
plus an int length vector; these helpers convert between the two worlds
(and emulate the reference's create_lod_tensor API for ported scripts).

Bucketing: `bucket_length(n)` rounds max_len up to a small set of
lengths so the executor's compile cache stays warm under varying
sequence lengths (static shapes are an XLA requirement, not a limit).
"""

import numpy as np

__all__ = ['pad_sequences', 'unpad_sequences', 'create_lod_tensor',
           'lod_to_lengths', 'lengths_to_lod', 'bucket_length']

_BUCKETS = (16, 32, 64, 128, 256, 512, 1024, 2048, 4096)


def bucket_length(n, buckets=_BUCKETS):
    for b in buckets:
        if n <= b:
            return b
    return int(n)


def pad_sequences(seqs, pad_value=0, dtype=None, max_len=None,
                  bucketed=False):
    """list of per-example arrays/lists -> (padded [B, T, ...], lengths)."""
    arrs = [np.asarray(s) for s in seqs]
    lengths = np.asarray([a.shape[0] for a in arrs], dtype='int64')
    t = int(lengths.max()) if max_len is None else max_len
    if bucketed:
        t = bucket_length(t)
    tail = arrs[0].shape[1:]
    out_dtype = dtype or arrs[0].dtype
    out = np.full((len(arrs), t) + tail, pad_value, dtype=out_dtype)
    for i, a in enumerate(arrs):
        out[i, :a.shape[0]] = a
    return out, lengths


def unpad_sequences(padded, lengths):
    """Inverse of pad_sequences: -> list of per-example arrays."""
    return [np.asarray(padded[i, :int(n)])
            for i, n in enumerate(np.asarray(lengths))]


def lod_to_lengths(lod):
    """Level-0 LoD offsets [0, 3, 5, ...] -> per-sequence lengths."""
    lod = list(lod)
    return np.asarray([b - a for a, b in zip(lod[:-1], lod[1:])],
                      dtype='int64')


def lengths_to_lod(lengths):
    out = [0]
    for n in np.asarray(lengths).tolist():
        out.append(out[-1] + int(n))
    return out


def create_lod_tensor(data, recursive_seq_lens, place=None):
    """Reference-API shim (fluid.create_lod_tensor): flat data + one
    level of sequence lengths -> (padded, lengths) pair."""
    if len(recursive_seq_lens) != 1:
        raise NotImplementedError(
            'TPU LoD translation supports one ragged level; nest arrays '
            'for deeper structures')
    lengths = recursive_seq_lens[0]
    flat = np.asarray(data)
    seqs, ofs = [], 0
    for n in lengths:
        seqs.append(flat[ofs:ofs + n])
        ofs += n
    return pad_sequences(seqs)
