"""Program IR: Variable / Operator / Block / Program.

Reference: python/paddle/fluid/framework.py (Program/Block/Variable/Operator)
and paddle/fluid/framework/{program_desc,block_desc,op_desc}.{h,cc}.

TPU-native twist: the Program is a pure description. Nothing executes at
build time; the Executor lowers a whole Program (forward + backward + update)
into ONE jitted XLA computation. Mutating a Program bumps its version so
compiled-executable caches invalidate.
"""

import contextlib
import os
import sys

from . import unique_name
from .dtypes import canonical_dtype

# Root of the paddle_tpu package: frames under it are framework
# machinery, frames outside it are the user code an op's construction
# provenance should point at (core/program.py -> core -> paddle_tpu).
_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__))) + os.sep


def _capture_provenance():
    """'file.py:line' of the nearest non-framework frame on the stack —
    the user statement that (transitively) appended this op. Every
    analysis diagnostic and Operator.__repr__ points there, so a shape
    error deep in a 200-op graph names the layers call that built it,
    not the tracer. One short frame walk per append_op; hot
    program-building loops can switch it off with
    PADDLE_TPU_PROVENANCE=0 (None is stored, diagnostics degrade to
    op indices). Returns None when the whole stack is framework frames
    (programs built by clone/serialize keep the ORIGINAL op's
    provenance instead — see Program.clone)."""
    if os.environ.get('PADDLE_TPU_PROVENANCE') == '0':
        return None
    f = sys._getframe(2)   # skip _capture_provenance + append/prepend_op
    depth = 0
    while f is not None and depth < 40:
        filename = f.f_code.co_filename
        if not filename.startswith(_PKG_DIR) and \
                not filename.startswith('<'):
            return '%s:%d' % (filename, f.f_lineno)
        f = f.f_back
        depth += 1
    return None


class Variable(object):
    """A named tensor slot inside a Block.

    shape uses -1 for the (leading) batch dimension of data vars; concrete
    shapes are bound at Executor compile time from the feed.
    """

    def __init__(self, block, name, shape=None, dtype='float32', lod_level=0,
                 persistable=False, stop_gradient=False, is_data=False,
                 trainable=False, **kwargs):
        self.block = block
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = canonical_dtype(dtype)
        self.lod_level = lod_level
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.trainable = trainable
        self._error_clip = kwargs.get('error_clip', None)

    @property
    def error_clip(self):
        return self._error_clip

    @error_clip.setter
    def error_clip(self, value):
        # compile-relevant mutation: a clip set AFTER a run must not be
        # ignored by the executor's warm compile cache
        self._error_clip = value
        if self.block is not None and self.block.program is not None:
            self.block.program._bump_version()

    @property
    def program(self):
        return self.block.program

    def __repr__(self):
        return 'Variable(%s, shape=%s, dtype=%s%s)' % (
            self.name, self.shape, self.dtype,
            ', persistable' if self.persistable else '')

    # Arithmetic sugar (reference: fluid/layers/math_op_patch.py
    # monkey_patch_variable). Implemented via the layers API lazily to avoid
    # an import cycle.
    def _binary(self, other, op, reverse=False):
        from ..layers import ops as _ops
        from ..layers import tensor as _tensor
        if not isinstance(other, Variable):
            other = _tensor.fill_constant(
                shape=[1], dtype=self.dtype, value=float(other))
        a, b = (other, self) if reverse else (self, other)
        return op(a, b)

    def __add__(self, other):
        from ..layers import ops as _ops
        return self._binary(other, _ops.elementwise_add)

    __radd__ = __add__

    def __sub__(self, other):
        from ..layers import ops as _ops
        return self._binary(other, _ops.elementwise_sub)

    def __rsub__(self, other):
        from ..layers import ops as _ops
        return self._binary(other, _ops.elementwise_sub, reverse=True)

    def __mul__(self, other):
        from ..layers import ops as _ops
        return self._binary(other, _ops.elementwise_mul)

    __rmul__ = __mul__

    def __truediv__(self, other):
        from ..layers import ops as _ops
        return self._binary(other, _ops.elementwise_div)

    # NOTE: __eq__/__lt__ are intentionally NOT overloaded (identity
    # semantics stay default, matching the reference) — building compare ops
    # from `==` would corrupt `in`-checks and dict use with silent op
    # side effects. Use layers.equal / layers.less_than.

    def astype(self, dtype):
        from ..layers import tensor as _tensor
        return _tensor.cast(self, dtype)


class Parameter(Variable):
    """A trainable persistable Variable (reference: framework.py Parameter)."""

    def __init__(self, block, name, shape, dtype, **kwargs):
        super(Parameter, self).__init__(
            block, name, shape=shape, dtype=dtype, persistable=True,
            trainable=kwargs.pop('trainable', True), **{
                k: v for k, v in kwargs.items() if k in ('lod_level',)
            })
        self.optimize_attr = kwargs.get('optimize_attr', {'learning_rate': 1.0})
        self.regularizer = kwargs.get('regularizer', None)
        self.gradient_clip_attr = kwargs.get('gradient_clip_attr', None)
        self.do_model_average = kwargs.get('do_model_average', None)
        self.initializer = kwargs.get('initializer', None)


class Operator(object):
    """One op invocation. inputs/outputs map slot name -> list of var names.

    `provenance` is the 'file.py:line' of the user statement that built
    the op (captured by Block.append_op; None with
    PADDLE_TPU_PROVENANCE=0 or for purely framework-built programs).
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None,
                 provenance=None):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        self.provenance = provenance

    def input(self, slot):
        names = self.inputs.get(slot, [])
        return names[0] if names else None

    def output(self, slot):
        names = self.outputs.get(slot, [])
        return names[0] if names else None

    def input_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    def output_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def __repr__(self):
        where = ' @ %s' % self.provenance if self.provenance else ''
        return 'Op(%s, in=%s, out=%s%s)' % (self.type, self.inputs,
                                            self.outputs, where)


def _to_name_list(value):
    """Normalize op input/output values to a list of variable names."""
    if value is None:
        return []
    if isinstance(value, (Variable, str)):
        value = [value]
    return [v.name if isinstance(v, Variable) else v for v in value]


class Block(object):
    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}
        self.ops = []

    @property
    def parent(self):
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    def create_var(self, name=None, **kwargs):
        if name is None:
            name = unique_name.generate('tmp')
        if name in self.vars:
            return self.vars[name]
        var = Variable(self, name, **kwargs)
        self.vars[name] = var
        self.program._bump_version()
        return var

    def create_parameter(self, name, shape, dtype, **kwargs):
        if name in self.vars:
            return self.vars[name]
        param = Parameter(self, name, shape, dtype, **kwargs)
        self.vars[name] = param
        self.program._bump_version()
        return param

    def var(self, name):
        v = self._find_var_recursive(name)
        if v is None:
            raise KeyError('Variable %r not found in block %d' % (name, self.idx))
        return v

    def _find_var_recursive(self, name):
        if name in self.vars:
            return self.vars[name]
        if self.parent is not None:
            return self.parent._find_var_recursive(name)
        return None

    def has_var(self, name):
        return self._find_var_recursive(name) is not None

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        inputs = {k: _to_name_list(v) for k, v in (inputs or {}).items()}
        outputs = {k: _to_name_list(v) for k, v in (outputs or {}).items()}
        op = Operator(self, type, inputs, outputs, attrs,
                      provenance=_capture_provenance())
        self.ops.append(op)
        self.program._bump_version()
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        inputs = {k: _to_name_list(v) for k, v in (inputs or {}).items()}
        outputs = {k: _to_name_list(v) for k, v in (outputs or {}).items()}
        op = Operator(self, type, inputs, outputs, attrs,
                      provenance=_capture_provenance())
        self.ops.insert(0, op)
        self.program._bump_version()
        return op

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    def __repr__(self):
        return 'Block(%d, %d vars, %d ops)' % (self.idx, len(self.vars),
                                               len(self.ops))


class Program(object):
    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self._version = 0
        self._seed = None
        # The startup Program that holds this program's param-init ops
        # (recorded by LayerHelper.create_parameter; used by
        # optimizer.minimize when no startup_program is passed).
        self._startup_ref = None
        # Sharding annotations attached by parallel.transpile:
        # var name -> jax.sharding.PartitionSpec (or None)
        self.var_shardings = {}
        self.mesh = None
        # Pipeline parallelism config attached by parallel.transpile when
        # strategy.pipeline_parallel is set: {'n_micro': int}. Scan-stacked
        # layer ops (transformer_layer_stack) read it and run the GPipe
        # microbatch schedule over the mesh's 'pp' axis.
        self.pipeline = None
        # Mixed precision: None (full fp32) or 'bf16' — matmul/conv-class
        # ops autocast inputs to bfloat16 (MXU-native) while params,
        # grads, optimizer state and loss-class ops stay fp32
        # (master-weight AMP; reference analog: fluid's float16 lists).
        self.amp = None
        # Rematerialization policy set by memory_optimize(): None, 'full',
        # 'dots_saveable', or 'nothing_saveable' (jax.checkpoint).
        self.remat_policy = None
        # Quantized gradient allreduce (EQuARX wire format) over the dp
        # axis, set by ParallelStrategy(quantized_allreduce=True); the
        # per-call PADDLE_TPU_QUANT_ALLREDUCE env knob overrides in
        # either direction (quant/core.grad_allreduce_policy).
        self.quant_allreduce = None

    def _bump_version(self):
        self._version += 1

    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx=None):
        parent_idx = self.current_block_idx if parent_idx is None else parent_idx
        block = Block(self, len(self.blocks), parent_idx)
        self.blocks.append(block)
        self.current_block_idx = block.idx
        self._bump_version()
        return block

    def rollback(self):
        self.current_block_idx = self.current_block().parent_idx
        if self.current_block_idx < 0:
            self.current_block_idx = 0

    def block(self, idx):
        return self.blocks[idx]

    def all_parameters(self):
        params = []
        for b in self.blocks:
            params.extend(b.all_parameters())
        return params

    def list_vars(self):
        for b in self.blocks:
            for v in b.vars.values():
                yield v

    @property
    def random_seed(self):
        return self._seed

    @random_seed.setter
    def random_seed(self, seed):
        self._seed = seed

    def clone(self, for_test=False):
        """Deep-copy the program. for_test=True flips is_test attrs and drops
        backward/optimize ops (reference: framework.py Program.clone +
        inference_optimize)."""
        p = Program()
        p._seed = self._seed
        p.var_shardings = dict(self.var_shardings)
        p.mesh = self.mesh
        p.pipeline = dict(self.pipeline) if self.pipeline else None
        p.quant_allreduce = self.quant_allreduce
        for i, b in enumerate(self.blocks):
            nb = p.blocks[0] if i == 0 else p.create_block(b.parent_idx)
            for name, v in b.vars.items():
                if isinstance(v, Parameter):
                    nv = Parameter(nb, name, v.shape, v.dtype,
                                   trainable=v.trainable,
                                   optimize_attr=dict(v.optimize_attr),
                                   regularizer=v.regularizer,
                                   gradient_clip_attr=v.gradient_clip_attr,
                                   initializer=v.initializer)
                    nv.stop_gradient = v.stop_gradient
                else:
                    nv = Variable(nb, name, shape=v.shape, dtype=v.dtype,
                                  lod_level=v.lod_level,
                                  persistable=v.persistable,
                                  stop_gradient=v.stop_gradient,
                                  is_data=v.is_data, trainable=v.trainable)
                # carry layer-attached annotations (v2 input types,
                # row_shard hints) through the copy
                for extra in ('_v2_type', '_v2_len_var', 'row_shard',
                              'expert_shard', 'expert_shard_axis',
                              '_error_clip', 'sparse_grad', 'sparse_ids'):
                    if hasattr(v, extra):
                        setattr(nv, extra, getattr(v, extra))
                nb.vars[name] = nv
            for op in b.ops:
                if for_test and op.type in ('backward_marker',) :
                    break  # everything after backward is train-only
                attrs = dict(op.attrs)
                if for_test and 'is_test' in attrs:
                    attrs['is_test'] = True
                if for_test and op.type in ('dropout', 'batch_norm'):
                    attrs['is_test'] = True
                # keep the ORIGINAL construction site, not the clone call
                nb.append_op(op.type, op.inputs, op.outputs,
                             attrs).provenance = op.provenance
        p.current_block_idx = 0
        return p

    def prune(self, targets):
        """Return a clone keeping only ops needed for target vars
        (reference: framework/prune.cc). Liveness descends into
        while/if_else sub-blocks, same as the executor's prune."""
        from .executor import _op_reads
        target_names = set(t.name if isinstance(t, Variable) else t
                           for t in targets)
        p = self.clone()
        b = p.global_block()
        needed = set(target_names)
        kept = []
        for op in reversed(b.ops):
            if set(op.output_names()) & needed or op.type == 'backward_marker':
                kept.append(op)
                needed.update(_op_reads(op, p))
        b.ops = list(reversed(kept))
        return p

    def to_string(self, throw_on_error=False):
        lines = []
        for b in self.blocks:
            lines.append('-- block %d (parent %d) --' % (b.idx, b.parent_idx))
            for name, v in b.vars.items():
                lines.append('  var %s : %s %s%s' % (
                    name, v.dtype, v.shape,
                    ' [persistable]' if v.persistable else ''))
            for op in b.ops:
                lines.append('  %r' % (op,))
        return '\n'.join(lines)

    __str__ = to_string


_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


def switch_main_program(program):
    global _main_program
    old = _main_program
    _main_program = program
    return old


def switch_startup_program(program):
    global _startup_program
    old = _startup_program
    _startup_program = program
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


def reset_default_programs():
    global _main_program, _startup_program
    _main_program = Program()
    _startup_program = Program()
    unique_name.reset()
