"""Profiler (reference: python/paddle/fluid/profiler.py +
paddle/fluid/platform/profiler.cc).

TPU-native: wraps jax.profiler (XLA trace -> TensorBoard/perfetto) and adds
host-side per-run wall timing with a sorted summary table, mirroring the
reference's profiler.start_profiler/stop_profiler/profiler context.

One timing substrate: record_event stores into the paddle_tpu.observe
registry (histograms named ``profiler.<event>``), so profiler events
surface in metrics JSONL snapshots alongside the rest of the telemetry
and summarize() is just an aggregate over those histograms. The
``_active`` gate bounds memory: events outside a start/stop_profiler
window are not recorded at all."""

import contextlib
import time

from . import observe as _obs

__all__ = ['cuda_profiler', 'reset_profiler', 'profiler', 'start_profiler',
           'stop_profiler', 'record_event', 'StepTimer']

_EVENT_PREFIX = 'profiler.'
_active = False
_trace_dir = None


def reset_profiler():
    # clears the observe registry (profiler.* histograms included) and
    # recorded spans — the profiler and the telemetry subsystem share
    # one substrate, so they reset together
    _obs.reset()


def start_profiler(state='All', tracer_option=None, trace_dir=None):
    global _active, _trace_dir
    _active = True
    _trace_dir = trace_dir
    if trace_dir:
        import jax
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key='total', profile_path=None):
    global _active
    _active = False
    if _trace_dir:
        import jax
        jax.profiler.stop_trace()
    summary = summarize(sorted_key)
    if profile_path:
        with open(profile_path, 'w') as f:
            f.write(summary)
    else:
        print(summary)


@contextlib.contextmanager
def profiler(state='All', sorted_key='total', profile_path=None,
             trace_dir=None):
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*args, **kwargs):
    # Name kept for reference parity; on TPU this is the XLA trace.
    with profiler():
        yield


@contextlib.contextmanager
def record_event(name):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        # gated on _active: an un-started profiler records nothing
        # (the old `_active or True` leaked every event into a module
        # list forever — unbounded growth in long runs)
        if _active:
            _obs.registry().histogram(_EVENT_PREFIX + name).observe(
                time.perf_counter() - t0)


def summarize(sorted_key='total'):
    rows = []
    for h in _obs.registry().metrics(_EVENT_PREFIX):
        if h.kind != 'histogram':
            continue
        count, total = h.aggregate()
        if count:
            rows.append((h.name[len(_EVENT_PREFIX):], total, count,
                         total / count))
    rows.sort(key=lambda r: -r[1])
    lines = ['%-40s %12s %8s %12s' % ('Event', 'Total(s)', 'Calls',
                                      'Avg(s)')]
    for name, total, count, avg in rows:
        lines.append('%-40s %12.6f %8d %12.6f' % (name, total, count, avg))
    return '\n'.join(lines)


class StepTimer(object):
    """Measures steady-state step time (skips compile/warmup steps)."""

    def __init__(self, skip=2):
        self.skip = skip
        self.times = []
        self._t0 = None
        self._count = 0

    def start(self):
        self._t0 = time.perf_counter()

    def stop(self):
        dt = time.perf_counter() - self._t0
        self._count += 1
        if self._count > self.skip:
            self.times.append(dt)
        return dt

    @property
    def mean(self):
        return sum(self.times) / len(self.times) if self.times else 0.0


def memory_report(exe=None, program=None, feed=None, fetch_list=None):
    """Compile the training/eval step for `program` (default main) and
    return XLA's memory analysis as a dict of byte counts:

        {'temp_bytes', 'argument_bytes', 'output_bytes',
         'alias_bytes', 'generated_code_bytes', 'peak_estimate_bytes'}

    peak_estimate = temp + argument (donated args alias outputs, so
    this upper-bounds live HBM during the step). The reference exposes
    allocator telemetry via its profiler; here memory is XLA's, so the
    compiled executable is the source of truth. Works on any backend
    (CPU included) — useful for sizing remat policies and ZeRO/FSDP
    shardings before touching hardware."""
    import jax
    from .core.executor import Executor
    from .core.place import CPUPlace

    exe = exe or Executor(CPUPlace())
    fn, scope_vals, feed_vals = exe.compile_step(
        program=program, feed=feed or {}, fetch_list=fetch_list or [])
    import numpy as np
    compiled = jax.jit(fn).lower(scope_vals, feed_vals,
                                 np.int32(0)).compile()
    ma = compiled.memory_analysis()
    if ma is None:
        return {}
    out = {}
    for key, attr in (('temp_bytes', 'temp_size_in_bytes'),
                      ('argument_bytes', 'argument_size_in_bytes'),
                      ('output_bytes', 'output_size_in_bytes'),
                      ('alias_bytes', 'alias_size_in_bytes'),
                      ('generated_code_bytes',
                       'generated_code_size_in_bytes')):
        v = getattr(ma, attr, None)
        if v is not None:
            out[key] = int(v)
    if 'temp_bytes' in out and 'argument_bytes' in out:
        out['peak_estimate_bytes'] = (out['temp_bytes'] +
                                      out['argument_bytes'])
    return out
