"""High-level Trainer / Inferencer (reference: the v2 trainer loop
python/paddle/v2/trainer.py SGD.train with event handlers, and the later
fluid.Trainer shape).

A thin, reader-driven loop over the Executor: batches from a v2-style
reader (optionally prefetched to HBM), per-step/epoch events to a
handler, checkpointing via io.save_checkpoint.
"""

import numpy as np

from .core.executor import Executor
from .core.place import TPUPlace
from .core.program import (default_main_program, default_startup_program,
                           program_guard)
from . import io as _io

__all__ = ['BeginEpochEvent', 'EndEpochEvent', 'BeginStepEvent',
           'EndStepEvent', 'Trainer']


class BeginEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent(object):
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id


class EndStepEvent(object):
    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class Trainer(object):
    """train_func builds the graph and returns the fetch vars (loss
    first); optimizer_func returns the optimizer. Mirrors the reference
    trainer's event-handler contract."""

    def __init__(self, train_func, optimizer_func, place=None,
                 checkpoint_config=None, program=None,
                 startup_program=None):
        self.place = place if place is not None else TPUPlace(0)
        self.program = program or default_main_program()
        self.startup = startup_program or default_startup_program()
        # Build into self.program/self.startup even when the caller passed
        # custom Programs (otherwise train_func appends to the defaults and
        # the custom Program trains an empty graph).
        with program_guard(self.program, self.startup):
            self.fetches = train_func()
            if not isinstance(self.fetches, (list, tuple)):
                self.fetches = [self.fetches]
            optimizer_func().minimize(self.fetches[0])
        self.exe = Executor(self.place)
        self.checkpoint_dir = checkpoint_config
        self._step = 0

    def _to_feed(self, data, feeder, feed_order):
        if feeder is not None:
            return feeder.feed(data)
        if isinstance(data, dict):
            return data
        return {name: np.asarray([d[i] for d in data])
                for i, name in enumerate(feed_order)}

    def train(self, num_epochs, event_handler=None, reader=None,
              feed_order=None, feeder=None, steps_per_dispatch=1):
        """Event-driven training loop (reference v2 trainer contract).

        steps_per_dispatch > 1 compiles the loop body into the XLA
        program (Executor.run_steps over stacked feed windows): one
        device dispatch per window, identical trajectory. Event order
        within a window necessarily shifts — the window's
        BeginStepEvents fire before the dispatch and its EndStepEvents
        (with true per-step metrics) after — since the steps execute as
        one program. Trailing batches that do not fill a window run
        per-step."""
        event_handler = event_handler or (lambda e: None)
        if reader is not None:
            # Multihost: each host consumes a disjoint shard of the stream
            # (parallel.multihost.shard_reader; no-op on a single host).
            from .parallel.multihost import shard_reader
            reader = shard_reader(reader)
        self.exe.run(self.startup)
        w = int(steps_per_dispatch)
        for epoch in range(num_epochs):
            event_handler(BeginEpochEvent(epoch))
            step = 0
            window = []
            for data in reader():
                feed = self._to_feed(data, feeder, feed_order)
                if w <= 1:
                    step = self._run_one(epoch, step, feed, event_handler)
                    continue
                if window and self._feed_sig(feed) != \
                        self._feed_sig(window[0]):
                    # shape change mid-window (bucketed readers): the
                    # collected prefix runs per-step, stacking resumes
                    for f in window:
                        step = self._run_one(epoch, step, f,
                                             event_handler)
                    window = []
                window.append(feed)
                if len(window) == w:
                    step = self._run_window(epoch, step, window,
                                            event_handler)
                    window = []
            for feed in window:  # trailing partial window: per-step
                step = self._run_one(epoch, step, feed, event_handler)
            event_handler(EndEpochEvent(epoch))
            if self.checkpoint_dir:
                _io.save_checkpoint(self.exe, self.checkpoint_dir,
                                    main_program=self.program,
                                    step=self._step)

    @staticmethod
    def _feed_sig(feed):
        return {n: np.asarray(v).shape for n, v in feed.items()}

    def _run_one(self, epoch, step, feed, event_handler):
        event_handler(BeginStepEvent(epoch, step))
        metrics = self.exe.run(program=self.program, feed=feed,
                               fetch_list=self.fetches)
        self._step += 1
        event_handler(EndStepEvent(epoch, step, metrics))
        return step + 1

    def _run_window(self, epoch, step0, window, event_handler):
        w = len(window)
        for i in range(w):
            event_handler(BeginStepEvent(epoch, step0 + i))
        stacked = {name: np.stack([f[name] for f in window])
                   for name in window[0]}
        metrics = self.exe.run_steps(w, program=self.program,
                                     feed=stacked,
                                     fetch_list=self.fetches,
                                     stacked_feed=True)
        self._step += w
        for i in range(w):
            event_handler(EndStepEvent(
                epoch, step0 + i, [np.asarray(m[i]) for m in metrics]))
        return step0 + w

    def save_params(self, dirname):
        _io.save_params(self.exe, dirname, main_program=self.program)

    def save_inference_model(self, dirname, feeded_var_names,
                             target_vars):
        _io.save_inference_model(dirname, feeded_var_names, target_vars,
                                 self.exe, main_program=self.program)
