"""High-level Trainer / Inferencer (reference: the v2 trainer loop
python/paddle/v2/trainer.py SGD.train with event handlers, and the later
fluid.Trainer shape).

A thin, reader-driven loop over the Executor: batches from a v2-style
reader (optionally prefetched to HBM), per-step/epoch events to a
handler, checkpointing via the fault.CheckpointManager (periodic
mid-epoch saves, keep-last-K retention, sha1-verified auto-resume) and
bad-step guards (fault.guards) on the fetched loss.
"""

import time

import numpy as np

from .core.executor import Executor
from .core.place import TPUPlace
from .core.program import (default_main_program, default_startup_program,
                           program_guard)
from . import io as _io
from . import observe as _obs
from .fault import CheckpointConfig, CheckpointManager
from .fault import inject as _inject
from .fault.guards import BadStepGuard

__all__ = ['BeginEpochEvent', 'EndEpochEvent', 'BeginStepEvent',
           'EndStepEvent', 'Trainer']


class BeginEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent(object):
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id


class EndStepEvent(object):
    """Step result delivered to the event handler. Beyond the fetched
    `metrics`, carries `wall_time` (this step's host wall seconds —
    windowed steps report wall/window) and, when observability is on,
    `telemetry`: a small dict (steps_per_sec_ema / step_seconds_last /
    mfu / goodput) so handlers can log throughput without re-timing
    steps themselves."""

    def __init__(self, epoch_id, step_id, metrics, wall_time=None,
                 telemetry=None):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics
        self.wall_time = wall_time
        self.telemetry = telemetry


class Trainer(object):
    """train_func builds the graph and returns the fetch vars (loss
    first); optimizer_func returns the optimizer. Mirrors the reference
    trainer's event-handler contract."""

    def __init__(self, train_func, optimizer_func, place=None,
                 checkpoint_config=None, program=None,
                 startup_program=None):
        self.place = place if place is not None else TPUPlace(0)
        self.program = program or default_main_program()
        self.startup = startup_program or default_startup_program()
        # Build into self.program/self.startup even when the caller passed
        # custom Programs (otherwise train_func appends to the defaults and
        # the custom Program trains an empty graph).
        with program_guard(self.program, self.startup):
            self.fetches = train_func()
            if not isinstance(self.fetches, (list, tuple)):
                self.fetches = [self.fetches]
            optimizer_func().minimize(self.fetches[0])
        self.exe = Executor(self.place)
        if isinstance(checkpoint_config, str):
            # legacy contract: a bare dirname = epoch-end saves only,
            # guards off — exactly the pre-fault-subsystem behavior
            checkpoint_config = CheckpointConfig(checkpoint_config,
                                                 nan_policy=None)
        self.checkpoint_config = checkpoint_config
        self._ckpt = (CheckpointManager(checkpoint_config)
                      if checkpoint_config is not None else None)
        self.checkpoint_dir = (checkpoint_config.dirname
                               if checkpoint_config is not None else None)
        self._guard = None
        if checkpoint_config is not None and checkpoint_config.nan_policy:
            self._guard = BadStepGuard(
                checkpoint_config.nan_policy,
                checkpoint_config.max_bad_steps,
                manager=self._ckpt, executor=self.exe,
                program=self.program)
        self._ckpt_reader = None
        self._last_save = time.monotonic()
        self._step = 0
        self._peak_flops = None   # lazy device_peak_flops() (observe)

    def _to_feed(self, data, feeder, feed_order):
        if feeder is not None:
            return feeder.feed(data)
        if isinstance(data, dict):
            return data
        return {name: np.asarray([d[i] for d in data])
                for i, name in enumerate(feed_order)}

    def train(self, num_epochs, event_handler=None, reader=None,
              feed_order=None, feeder=None, steps_per_dispatch=1):
        """Event-driven training loop (reference v2 trainer contract).

        steps_per_dispatch > 1 compiles the loop body into the XLA
        program (Executor.run_steps over stacked feed windows): one
        device dispatch per window, identical trajectory. Event order
        within a window necessarily shifts — the window's
        BeginStepEvents fire before the dispatch and its EndStepEvents
        (with true per-step metrics) after — since the steps execute as
        one program. Trailing batches that do not fill a window run
        per-step."""
        event_handler = event_handler or (lambda e: None)
        _inject.install_from_env()
        _obs.run_begin()
        from .reader.state import CheckpointableReader
        self._ckpt_reader = (reader if isinstance(reader,
                                                  CheckpointableReader)
                             else None)
        if reader is not None:
            # Multihost: each host consumes a disjoint shard of the stream
            # (parallel.multihost.shard_reader; no-op on a single host).
            from .parallel.multihost import shard_reader
            reader = shard_reader(reader)
        self.exe.run(self.startup)
        start_epoch = 0
        resume_step = 0
        if self._ckpt is not None and self.checkpoint_config.resume:
            t_restore = time.monotonic()
            meta = self._ckpt.restore(self.exe, self.program,
                                      reader=self._ckpt_reader)
            if meta is not None:
                # restart recovery is run overhead, not training time
                _obs.overhead('restore', time.monotonic() - t_restore)
                self._step = int(meta.get('step') or 0)
                # RNG stream continuity (dropout masks): the executor's
                # step key counter sits one ahead of the trainer's step
                # (startup consumed key 0)
                self.exe._step = self._step + 1
                tstate = meta.get('trainer') or {}
                start_epoch = int(tstate.get('epoch', 0))
                resume_step = int(tstate.get('epoch_step', 0))
        self._last_save = time.monotonic()
        w = int(steps_per_dispatch)
        for epoch in range(start_epoch, num_epochs):
            event_handler(BeginEpochEvent(epoch))
            # resumed mid-epoch: the CheckpointableReader replays only
            # the untrained remainder; step ids continue where they left
            step = resume_step
            resume_step = 0
            window = []
            self._pending = 0
            for data in reader():
                t_feed = time.perf_counter()
                feed = self._to_feed(data, feeder, feed_order)
                if _obs.enabled():
                    _obs.record('trainer.phase_seconds',
                                time.perf_counter() - t_feed, phase='feed')
                if w <= 1:
                    step = self._run_one(epoch, step, feed, event_handler)
                    continue
                if window and self._feed_sig(feed) != \
                        self._feed_sig(window[0]):
                    # shape change mid-window (bucketed readers): the
                    # collected prefix runs per-step, stacking resumes.
                    # _pending = items PULLED from the reader but not
                    # yet trained (rest of the prefix + the triggering
                    # batch) — a checkpoint here must not record them
                    # as consumed or resume would skip them
                    flush, window = window, []
                    for j, f in enumerate(flush):
                        self._pending = len(flush) - 1 - j + 1
                        step = self._run_one(epoch, step, f,
                                             event_handler)
                    self._pending = 0
                window.append(feed)
                if len(window) == w:
                    step = self._run_window(epoch, step, window,
                                            event_handler)
                    window = []
            for j, feed in enumerate(window):  # trailing window: per-step
                self._pending = len(window) - 1 - j
                step = self._run_one(epoch, step, feed, event_handler)
            self._pending = 0
            event_handler(EndEpochEvent(epoch))
            if self._ckpt is not None and self.checkpoint_config.epoch_end:
                self._save_checkpoint(epoch + 1, 0)
        if self._ckpt is not None:
            # completeness point: LATEST/GC of the last async save landed
            self._ckpt.wait()
        if _obs.enabled():
            _obs.flush()   # end-of-train snapshot (no-op without a sink)

    @staticmethod
    def _feed_sig(feed):
        return {n: np.asarray(v).shape for n, v in feed.items()}

    def _save_checkpoint(self, epoch, epoch_step):
        """Checkpoint NOW, recording where the loop stands: resume
        restarts at (epoch, epoch_step) with the reader replaying the
        untrained remainder of that epoch."""
        t0 = time.monotonic()
        with _obs.span('fault.checkpoint_save', step=self._step):
            self._ckpt.save(self.exe, self.program, step=self._step,
                            reader=self._ckpt_reader,
                            reader_pending=getattr(self, '_pending', 0),
                            trainer_state={'epoch': int(epoch),
                                           'epoch_step': int(epoch_step)})
        _obs.overhead('checkpoint', time.monotonic() - t0)
        self._last_save = time.monotonic()

    def _maybe_checkpoint(self, epoch, epoch_step):
        cfg = self.checkpoint_config
        if self._ckpt is None or (not cfg.save_every_steps and
                                  cfg.save_every_secs is None):
            return
        if self._ckpt_reader is not None and \
                getattr(self, '_pending', 0) > self._ckpt_reader.offset:
            # pulled-but-untrained items span an epoch boundary (offset
            # already reset); their in-epoch positions are unknowable —
            # defer to the next cadence point instead of mis-recording
            return
        due = bool(cfg.save_every_steps) and self._step > 0 and \
            self._step % cfg.save_every_steps == 0
        if not due and cfg.save_every_secs is not None:
            due = time.monotonic() - self._last_save >= cfg.save_every_secs
        if due:
            self._save_checkpoint(epoch, epoch_step)

    def _record_step(self, wall, compute_s, fetch_s, verdict, steps=1):
        """Telemetry for one dispatch: phase histograms, throughput EMA,
        MFU, and the goodput ledger. A dispatch that compiled charges its
        wall time to overhead (goodput counts recompiles against the
        run); bad steps likewise."""
        if not _obs.enabled():
            return
        _obs.record('trainer.phase_seconds', compute_s, phase='compute')
        _obs.record('trainer.phase_seconds', fetch_s, phase='fetch')
        per_step = wall / steps
        _obs.record('trainer.step_seconds', per_step)
        _obs.set_gauge('trainer.step_seconds_last', per_step)
        rate = steps / wall if wall > 0 else 0.0
        prev = _obs.get_gauge('trainer.steps_per_sec_ema')
        _obs.set_gauge('trainer.steps_per_sec_ema',
                       rate if prev is None else 0.9 * prev + 0.1 * rate)
        if getattr(self.exe, 'last_cache_miss', False):
            _obs.overhead('first_dispatch', wall)
        elif verdict == 'ok':
            _obs.step_done(wall, steps)
        else:
            _obs.overhead('bad_step', wall)
        flops = _obs.get_gauge('executor.step_flops')
        if flops:
            if self._peak_flops is None:
                self._peak_flops = _obs.device_peak_flops() or 0.0
            if self._peak_flops:
                _obs.set_gauge('trainer.mfu', min(
                    1.0, steps * flops / wall / self._peak_flops))
        _obs.maybe_flush()

    def _run_one(self, epoch, step, feed, event_handler):
        g = self._guard
        if g is not None and g.needs_snapshot:
            g.snapshot()
        event_handler(BeginStepEvent(epoch, step))
        t0 = time.perf_counter()
        with _obs.span('trainer.step', step=self._step):
            fetched = self.exe.run(program=self.program, feed=feed,
                                   fetch_list=self.fetches,
                                   return_numpy=False)
            t_run = time.perf_counter()
            metrics = [np.asarray(v) for v in fetched]
        t1 = time.perf_counter()
        self._step += 1
        verdict = g.handle(metrics[0], self._step) if g is not None \
            else 'ok'
        if verdict == 'skipped':
            self._step -= 1     # the update was undone; it never counted
        self._record_step(t1 - t0, t_run - t0, t1 - t_run, verdict)
        event_handler(EndStepEvent(
            epoch, step, metrics, wall_time=t1 - t0,
            telemetry=_obs.step_telemetry() if _obs.enabled() else None))
        if verdict == 'ok':
            # never checkpoint a bad step's state; a skipped/rolled-back
            # step saves nothing and the next good one resumes cadence
            self._maybe_checkpoint(epoch, step + 1)
        _inject.fire('step_end', step=self._step)
        return step + 1

    def _run_window(self, epoch, step0, window, event_handler):
        w = len(window)
        g = self._guard
        if g is not None and g.needs_snapshot:
            g.snapshot()
        for i in range(w):
            event_handler(BeginStepEvent(epoch, step0 + i))
        stacked = {name: np.stack([f[name] for f in window])
                   for name in window[0]}
        t0 = time.perf_counter()
        with _obs.span('trainer.window', steps=w, step0=self._step):
            fetched = self.exe.run_steps(w, program=self.program,
                                         feed=stacked,
                                         fetch_list=self.fetches,
                                         stacked_feed=True,
                                         return_numpy=False)
            t_run = time.perf_counter()
            metrics = [np.asarray(v) for v in fetched]
        t1 = time.perf_counter()
        self._step += w
        # a window with ANY bad step is undone as a unit — the steps ran
        # as one device program, so that's also the undo granularity
        verdict = g.handle(metrics[0], self._step) if g is not None \
            else 'ok'
        if verdict == 'skipped':
            self._step -= w
        self._record_step(t1 - t0, t_run - t0, t1 - t_run, verdict,
                          steps=w)
        telemetry = _obs.step_telemetry() if _obs.enabled() else None
        for i in range(w):
            event_handler(EndStepEvent(
                epoch, step0 + i, [np.asarray(m[i]) for m in metrics],
                wall_time=(t1 - t0) / w, telemetry=telemetry))
        if verdict == 'ok':
            self._maybe_checkpoint(epoch, step0 + w)
        _inject.fire('step_end', step=self._step)
        return step0 + w

    def save_params(self, dirname):
        _io.save_params(self.exe, dirname, main_program=self.program)

    def save_inference_model(self, dirname, feeded_var_names,
                             target_vars):
        _io.save_inference_model(dirname, feeded_var_names, target_vars,
                                 self.exe, main_program=self.program)
