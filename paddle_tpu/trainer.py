"""High-level Trainer / Inferencer (reference: the v2 trainer loop
python/paddle/v2/trainer.py SGD.train with event handlers, and the later
fluid.Trainer shape).

A reader-driven loop over the Executor: batches from a v2-style reader
(optionally prefetched to HBM), per-step/epoch events to a handler,
checkpointing via the fault.CheckpointManager (periodic mid-epoch
saves, keep-last-K retention, sha1-verified auto-resume) and bad-step
guards (fault.guards) on the fetched loss.

The loop is a bounded asynchronous pipeline (train(pipeline_depth=D)):
JAX dispatch is async, so each step is ENQUEUED without syncing and a
deque of <= D in-flight StepHandles is resolved oldest-first — the
host prepares and enqueues steps k+1..k+D while step k executes
on-device. D=1 (the default) resolves each dispatch immediately and is
bit-identical to the classic synchronous loop, params and event stream
alike. host_prefetch=N additionally moves reader iteration, _to_feed,
and window stacking onto a worker thread behind a bounded queue.
"""

import collections
import threading
import time

import numpy as np

from .core.executor import Executor
from .core.place import TPUPlace
from .core.program import (default_main_program, default_startup_program,
                           program_guard)
from . import io as _io
from . import observe as _obs
from .fault import CheckpointConfig, CheckpointManager
from .fault import inject as _inject
from .fault.guards import BadStepGuard

__all__ = ['BeginEpochEvent', 'EndEpochEvent', 'BeginStepEvent',
           'EndStepEvent', 'Trainer', 'record_allreduce_overlap']

_PREFETCH_ERR = object()


def record_allreduce_overlap(step_seconds, compute_seconds,
                             comm_seconds):
    """Publish ``trainer.allreduce_overlap_fraction`` — the fraction of
    the gradient-allreduce leg hidden behind backward compute, from
    three wall-clock measurements (the bucketed step, the compute-only
    step, and the collective-only leg; see observe.overlap_fraction).
    Sits alongside ``trainer.pipeline_overlap_fraction``; the bench
    `trainspeed` workload measures the legs and asserts it > 0 on the
    dp mesh. Returns the fraction (or None on degenerate inputs)."""
    frac = _obs.overlap_fraction(step_seconds, compute_seconds,
                                 comm_seconds)
    if frac is not None and _obs.enabled():
        _obs.set_gauge('trainer.allreduce_overlap_fraction', frac)
    return frac


class BeginEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent(object):
    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent(object):
    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id


class EndStepEvent(object):
    """Step result delivered to the event handler. Beyond the fetched
    `metrics`, carries `wall_time` (this step's host wall seconds —
    windowed steps report wall/window; pipelined steps report the wall
    charged to this dispatch, i.e. excluding time overlapped with older
    in-flight steps) and, when observability is on, `telemetry`: a
    small dict (steps_per_sec_ema / step_seconds_last / mfu / goodput)
    so handlers can log throughput without re-timing steps
    themselves."""

    def __init__(self, epoch_id, step_id, metrics, wall_time=None,
                 telemetry=None):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics
        self.wall_time = wall_time
        self.telemetry = telemetry


class _Inflight(object):
    """One dispatched-but-unresolved unit in the trainer's pipeline."""

    __slots__ = ('epoch', 'step0', 'steps', 'items', 'handle', 't0', 't1')

    def __init__(self, epoch, step0, steps, items, handle, t0, t1):
        self.epoch = epoch
        self.step0 = step0
        self.steps = steps
        self.items = items
        self.handle = handle
        self.t0 = t0        # dispatch (enqueue) start
        self.t1 = t1        # dispatch (enqueue) end


class Trainer(object):
    """train_func builds the graph and returns the fetch vars (loss
    first); optimizer_func returns the optimizer. Mirrors the reference
    trainer's event-handler contract."""

    def __init__(self, train_func, optimizer_func, place=None,
                 checkpoint_config=None, program=None,
                 startup_program=None):
        self.place = place if place is not None else TPUPlace(0)
        self.program = program or default_main_program()
        self.startup = startup_program or default_startup_program()
        # Build into self.program/self.startup even when the caller passed
        # custom Programs (otherwise train_func appends to the defaults and
        # the custom Program trains an empty graph).
        with program_guard(self.program, self.startup):
            self.fetches = train_func()
            if not isinstance(self.fetches, (list, tuple)):
                self.fetches = [self.fetches]
            optimizer_func().minimize(self.fetches[0])
        self.exe = Executor(self.place)
        if isinstance(checkpoint_config, str):
            # legacy contract: a bare dirname = epoch-end saves only,
            # guards off — exactly the pre-fault-subsystem behavior
            checkpoint_config = CheckpointConfig(checkpoint_config,
                                                 nan_policy=None)
        self.checkpoint_config = checkpoint_config
        self._ckpt = (CheckpointManager(checkpoint_config)
                      if checkpoint_config is not None else None)
        self.checkpoint_dir = (checkpoint_config.dirname
                               if checkpoint_config is not None else None)
        self._guard = None
        if checkpoint_config is not None and checkpoint_config.nan_policy:
            self._guard = BadStepGuard(
                checkpoint_config.nan_policy,
                checkpoint_config.max_bad_steps,
                manager=self._ckpt, executor=self.exe,
                program=self.program)
        self._ckpt_reader = None
        self._last_save = time.monotonic()
        self._step = 0
        self._t_train_entry = None   # set at train() entry; cleared at
                                     # the first dispatch (startup gauge)
        self._peak_flops = None   # lazy device_peak_flops() (observe)
        # ------------------------------------------- pipeline state
        self._event_handler = lambda e: None
        self._inflight = collections.deque()
        self._group_start_step = 0     # _step at the last pipeline-empty
        self._last_resolve_end = None
        self._idle_since = None        # pipeline-empty timestamp
        self._in_ckpt_drain = False
        # pulled-vs-trained ledger (reader-yield units): _pulled moves
        # with the reader (possibly on a prefetch worker thread),
        # _trained with resolves; _reader_lock keeps a checkpoint's
        # (offset, pending) pair consistent against concurrent pulls
        self._reader_lock = threading.Lock()
        self._pulled = 0
        self._trained = 0
        self._pending = 0
        # ---------------------------------------- co-location yield
        # (serving.tenancy.colocation_yield): request_yield() asks the
        # loop to pause at the next dispatch boundary; the loop drains
        # its in-flight pipeline first — the checkpoint sync point —
        # then parks until resume_from_yield(). Pausing between
        # dispatches never changes the dispatched computation, so the
        # final params are bit-identical to an uninterrupted run at
        # the same step count.
        self._yield_requested = False
        self._yield_gate = threading.Event()
        self._yield_gate.set()
        self._parked = False

    def _to_feed(self, data, feeder, feed_order):
        if feeder is not None:
            return feeder.feed(data)
        if isinstance(data, dict):
            # dicts pass through untouched — including dicts of
            # device-resident jax Arrays from reader.prefetch_to_device
            return data
        return {name: np.asarray([d[i] for d in data])
                for i, name in enumerate(feed_order)}

    def train(self, num_epochs, event_handler=None, reader=None,
              feed_order=None, feeder=None, steps_per_dispatch=1,
              pipeline_depth=1, host_prefetch=0, stacked_windows=False):
        """Event-driven training loop (reference v2 trainer contract).

        steps_per_dispatch > 1 compiles the loop body into the XLA
        program (Executor.run_steps over stacked feed windows): one
        device dispatch per window, identical trajectory. Event order
        within a window necessarily shifts — the window's
        BeginStepEvents fire before the dispatch and its EndStepEvents
        (with true per-step metrics) after — since the steps execute as
        one program. Trailing batches that do not fill a window run
        per-step.

        pipeline_depth=D > 1 keeps up to D dispatches in flight:
        enqueue is async, so the host feeds and enqueues steps
        k+1..k+D while step k computes; fetches resolve oldest-first.
        D=1 (default) is bit-identical to the synchronous loop.
        BeginStepEvent fires at dispatch and EndStepEvent at resolve,
        so with D>1 up to D Begin events may precede a step's End.
        Checkpoint cadence points and skip_step guard snapshots drain
        the pipeline first (a save or an undo must not race in-flight
        updates), so cadence may land up to D-1 steps late and the
        skip_step undo unit widens to the whole drain group (<= D
        steps) — see fault.guards.

        host_prefetch=N > 0 runs reader iteration + _to_feed + window
        stacking on a worker thread behind a queue of <= N prepared
        feeds, overlapping host decode with both dispatch and device
        compute.

        stacked_windows=True declares that the reader yields
        device-resident [steps_per_dispatch, ...] superbatches
        (reader.staged_superbatch / recordio_superbatch): each yield is
        fed straight to Executor.run_steps(stacked_feed=True) with no
        re-normalization or host stacking."""
        event_handler = event_handler or (lambda e: None)
        self._event_handler = event_handler
        _inject.install_from_env()
        # crash forensics: PADDLE_TPU_FLIGHT_DUMP arms the flight
        # recorder (and a SIGTERM postmortem) even with metrics off, so
        # a preempted run leaves its last seconds behind
        _obs.arm_flight_from_env()
        # static IR verification before the first compile: default warn
        # (flight events + counters), PADDLE_TPU_VERIFY=strict raises
        # ProgramVerifyError here — before tracing, pointing at the
        # layers call that built the broken op
        from . import analysis as _analysis
        _analysis.startup_verify(
            self.program,
            fetch_names=[getattr(f, 'name', f) for f in self.fetches],
            label='trainer')
        _obs.run_begin()
        try:
            self._train_impl(num_epochs, event_handler, reader,
                             feed_order, feeder, steps_per_dispatch,
                             pipeline_depth, host_prefetch,
                             stacked_windows)
        except BaseException as e:
            _obs.flight_event('train_exception', error=type(e).__name__,
                              step=self._step)
            _obs.flight_dump('trainer_exception', exc=e)
            raise

    def _train_impl(self, num_epochs, event_handler, reader, feed_order,
                    feeder, steps_per_dispatch, pipeline_depth,
                    host_prefetch, stacked_windows):
        from .reader.state import CheckpointableReader
        self._t_train_entry = time.perf_counter()
        self._ckpt_reader = (reader if isinstance(reader,
                                                  CheckpointableReader)
                             else None)
        if reader is not None:
            # Multihost: each host consumes a disjoint shard of the stream
            # (parallel.multihost.shard_reader; no-op on a single host).
            from .parallel.multihost import shard_reader
            reader = shard_reader(reader)
        self.exe.run(self.startup)
        start_epoch = 0
        resume_step = 0
        if self._ckpt is not None and self.checkpoint_config.resume:
            t_restore = time.monotonic()
            meta = self._ckpt.restore(self.exe, self.program,
                                      reader=self._ckpt_reader)
            if meta is not None:
                # restart recovery is run overhead, not training time
                _obs.overhead('restore', time.monotonic() - t_restore)
                if meta.get('reader') and self._ckpt_reader is None \
                        and reader is not None:
                    import warnings
                    warnings.warn(
                        'resume: the checkpoint records a reader '
                        'position but the passed reader is not a '
                        'CheckpointableReader — the resumed stream '
                        'will REPLAY already-trained items. Wrap it in '
                        'reader.checkpointable(...) to resume '
                        'mid-epoch.')
                self._step = int(meta.get('step') or 0)
                # RNG stream continuity (dropout masks): the executor's
                # step key counter sits one ahead of the trainer's step
                # (startup consumed key 0)
                self.exe._step = self._step + 1
                tstate = meta.get('trainer') or {}
                start_epoch = int(tstate.get('epoch', 0))
                resume_step = int(tstate.get('epoch_step', 0))
        self._last_save = time.monotonic()
        w = int(steps_per_dispatch)
        depth = max(1, int(pipeline_depth))
        self._inflight = collections.deque()
        self._last_resolve_end = None
        # the device is idle until the first dispatch: that lead-in is
        # host-blocked wall, same as any later pipeline-empty gap
        self._idle_since = time.perf_counter()
        self._in_ckpt_drain = False
        self._pulled = 0
        self._trained = 0
        t_train0 = time.perf_counter()
        blocked0 = (self._blocked_seconds() if _obs.enabled() else (0, 0))
        # skip_step undoes via a host snapshot taken at pipeline-empty
        # points; bounding the undo unit to <= depth means draining the
        # whole group before refilling instead of popping one
        sync_groups = self._guard is not None and \
            self._guard.needs_snapshot
        for epoch in range(start_epoch, num_epochs):
            event_handler(BeginEpochEvent(epoch))
            # resumed mid-epoch: the CheckpointableReader replays only
            # the untrained remainder; step ids continue where they left
            step = resume_step
            resume_step = 0
            units = self._feed_units(reader, feeder, feed_order, w,
                                     stacked_windows)
            if host_prefetch and int(host_prefetch) > 0:
                units = self._prefetch_units(units, int(host_prefetch))
            for feed, n_steps, n_items in units:
                if self._yield_requested:
                    self._yield_point()
                self._dispatch(epoch, step, feed, n_steps, n_items)
                step += n_steps
                if len(self._inflight) >= depth:
                    if sync_groups:
                        while self._inflight:
                            self._resolve_oldest()
                    else:
                        self._resolve_oldest()
            while self._inflight:
                self._resolve_oldest()
            event_handler(EndEpochEvent(epoch))
            if self._ckpt is not None and self.checkpoint_config.epoch_end:
                with self._reader_lock:
                    self._pending = self._pulled - self._trained
                    self._save_checkpoint(epoch + 1, 0)
        if self._ckpt is not None:
            # completeness point: LATEST/GC of the last async save landed
            self._ckpt.wait()
        if _obs.enabled():
            wall = time.perf_counter() - t_train0
            hb, db = self._blocked_seconds()
            if wall > 0:
                # 1.0 = feed/fetch fully hidden under device compute;
                # 0.0 = the loop is serial (sync depth-1 behavior)
                _obs.set_gauge(
                    'trainer.pipeline_overlap_fraction',
                    max(0.0, 1.0 - ((hb - blocked0[0]) +
                                    (db - blocked0[1])) / wall))
            # AOT warm-start ledger: how many of this run's keys came
            # off disk instead of trace+compile (core/aot_cache.py)
            st = self.exe.aot_stats
            _obs.set_gauge('trainer.warm_from_disk_keys', st['hits'])
            _obs.set_gauge('trainer.aot_load_seconds',
                           st['load_seconds'])
            _obs.flush()   # end-of-train snapshot (no-op without a sink)

    # ------------------------------------------------------ feed stream
    @staticmethod
    def _feed_sig(feed):
        # .shape is read off device arrays directly — np.asarray here
        # would pull a prefetched batch back through host memory
        return {n: (v.shape if hasattr(v, 'shape')
                    else np.asarray(v).shape)
                for n, v in feed.items()}

    @staticmethod
    def _stack_window(window):
        """Stack w per-step feeds into [w, ...] arrays for
        run_steps(stacked_feed=True). Device-resident feeds
        (reader.prefetch_to_device) stack on-device."""
        out = {}
        for name in window[0]:
            vals = [f[name] for f in window]
            if hasattr(vals[0], 'devices'):
                import jax.numpy as jnp
                out[name] = jnp.stack(vals)
            else:
                out[name] = np.stack(vals)
        return out

    def _feed_units(self, reader, feeder, feed_order, w,
                    stacked_windows):
        """One epoch of prepared dispatch units (feed, n_steps,
        n_items): reader pull + _to_feed + window collection/stacking —
        every host-side cost the dispatch path does not need to pay
        itself, so _prefetch_units can move the whole generator onto a
        worker thread. n_items counts reader yields (the
        CheckpointableReader offset unit) for the pulled-vs-trained
        checkpoint ledger."""
        it = iter(reader())
        window = []
        while True:
            # the lock keeps a concurrent checkpoint's (offset, pending)
            # pair consistent when this generator runs on the prefetch
            # worker; uncontended cost is one atomic acquire per batch
            with self._reader_lock:
                try:
                    data = next(it)
                except StopIteration:
                    break
                self._pulled += 1
            if stacked_windows:
                # already a device-resident [w, ...] superbatch
                # (reader.staged_superbatch / recordio_superbatch):
                # no _to_feed, no re-normalization, no host stack
                yield data, w, 1
                continue
            t_feed = time.perf_counter()
            feed = self._to_feed(data, feeder, feed_order)
            if _obs.enabled():
                _obs.record('trainer.phase_seconds',
                            time.perf_counter() - t_feed, phase='feed')
            if w <= 1:
                yield feed, 1, 1
                continue
            if window and self._feed_sig(feed) != \
                    self._feed_sig(window[0]):
                # shape change mid-window (bucketed readers): the
                # collected prefix runs per-step, stacking resumes at
                # this batch
                for f in window:
                    yield f, 1, 1
                window = []
            window.append(feed)
            if len(window) == w:
                t_stack = time.perf_counter()
                stacked = self._stack_window(window)
                if _obs.enabled():
                    # per-window feed cost carries a steps=w label so
                    # phase percentiles stay comparable across
                    # dispatch modes
                    _obs.record('trainer.phase_seconds',
                                time.perf_counter() - t_stack,
                                phase='feed', steps=w)
                window = []
                yield stacked, w, w
        for f in window:    # trailing window: per-step
            yield f, 1, 1

    def _prefetch_units(self, units, depth):
        """Bounded host prefetch: iterate the _feed_units generator on
        a worker thread behind a Queue(depth). Puts are close-aware
        (timeout loop against a closed Event), so a consumer that exits
        early — break, error, GeneratorExit — never leaves the worker
        blocked on a full queue."""
        from queue import Full, Queue
        q = Queue(maxsize=max(1, int(depth)))
        done = object()
        closed = threading.Event()

        def _put(item):
            while not closed.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except Full:
                    pass
            return False

        def work():
            try:
                for unit in units:
                    if not _put(unit):
                        return
                _put(done)
            except BaseException as e:   # surfaced on the consumer side
                _put((_PREFETCH_ERR, e, None))

        t = threading.Thread(target=work, daemon=True,
                             name='paddle_tpu_trainer_prefetch')
        t.start()
        try:
            while True:
                item = q.get()
                if item is done:
                    return
                if item[0] is _PREFETCH_ERR:
                    raise item[1]
                if _obs.enabled():
                    # occupancy AFTER the pop: 0 = dispatch is starved
                    _obs.set_gauge('trainer.prefetch_queue_depth',
                                   q.qsize())
                yield item
        finally:
            closed.set()

    # ------------------------------------------------ co-location yield
    def request_yield(self):
        """Ask the training loop to pause at its next dispatch
        boundary (serving.tenancy.colocation_yield calls this when the
        co-located serving replica hits SLO pressure). Returns
        immediately; the loop drains its in-flight pipeline — the same
        sync point a due checkpoint uses — then parks with the device
        idle until :meth:`resume_from_yield`. A yield never changes
        what gets dispatched, so params stay bit-identical to an
        uninterrupted run at the same step count."""
        self._yield_gate.clear()
        self._yield_requested = True

    def resume_from_yield(self):
        """Release a :meth:`request_yield` park (idempotent)."""
        self._yield_requested = False
        self._yield_gate.set()

    def yielded(self):
        """True while the training loop is actually parked (drained
        and blocked) — the co-location scenario's observable."""
        return self._parked

    def _yield_point(self):
        # drain: every dispatched step resolves before the pause, so
        # a resume (or a checkpoint during the pause window) sees a
        # consistent param state
        while self._inflight:
            self._resolve_oldest()
        self._parked = True
        t0 = time.perf_counter()
        _obs.set_gauge('trainer.yielded', 1)
        self._yield_gate.wait()
        self._parked = False
        if self._idle_since is not None:
            # the parked window is the tenant's time, not host-blocked
            # wall — restart the idle clock so the overlap fraction
            # only bills real feed-preparation gaps
            self._idle_since = time.perf_counter()
        _obs.set_gauge('trainer.yielded', 0)
        if _obs.enabled():
            _obs.record('trainer.yield_seconds',
                        time.perf_counter() - t0)

    # ------------------------------------------------- dispatch/resolve
    def _dispatch(self, epoch, step0, feed, n_steps, n_items):
        handler = self._event_handler
        g = self._guard
        if not self._inflight:
            if g is not None and g.needs_snapshot:
                # snapshot cadence = pipeline-empty points (<= every
                # depth dispatches under sync_groups); nothing is in
                # flight here, so the device->host readback cannot
                # stall pending work
                g.snapshot()
            self._group_start_step = self._step
            if _obs.enabled() and self._idle_since is not None:
                # the device had nothing queued while the host prepared
                # this feed: that gap is host-blocked wall
                _obs.add_gauge('trainer.host_blocked_seconds',
                               time.perf_counter() - self._idle_since)
        self._idle_since = None
        for i in range(n_steps):
            handler(BeginStepEvent(epoch, step0 + i))
        t0 = time.perf_counter()
        if n_steps == 1:
            with _obs.span('trainer.step', step=self._step):
                h = self.exe.run(program=self.program, feed=feed,
                                 fetch_list=self.fetches,
                                 return_handle=True)
        else:
            with _obs.span('trainer.window', steps=n_steps,
                           step0=self._step):
                h = self.exe.run_steps(n_steps, program=self.program,
                                       feed=feed,
                                       fetch_list=self.fetches,
                                       stacked_feed=True,
                                       return_handle=True)
        t1 = time.perf_counter()
        if self._t_train_entry is not None:
            # cold-vs-warm startup headline: wall from train() entry to
            # the first dispatch ENQUEUED — startup-program run, resume,
            # and the first step's trace+compile (or its AOT warm load)
            # all land in here
            _obs.set_gauge('trainer.time_to_first_dispatch_seconds',
                           t1 - self._t_train_entry)
            self._t_train_entry = None
        self._inflight.append(
            _Inflight(epoch, step0, n_steps, n_items, h, t0, t1))
        _obs.set_gauge('trainer.inflight_depth', len(self._inflight))

    def _resolve_oldest(self):
        """Resolve the oldest in-flight dispatch: sync its fetches,
        run the guard, fire EndStepEvents, count it, checkpoint if due.
        Returns (epoch, next_epoch_step) of the resolved unit."""
        handler = self._event_handler
        ent = self._inflight.popleft()
        _obs.set_gauge('trainer.inflight_depth', len(self._inflight))
        r0 = time.perf_counter()
        was_ready = ent.handle.ready() if _obs.enabled() else True
        with _obs.span('trainer.resolve', step0=ent.step0,
                       steps=ent.steps):
            metrics = ent.handle.resolve()
        r1 = time.perf_counter()
        if _obs.enabled():
            _obs.record('trainer.resolve_seconds', r1 - r0)
            if not was_ready:
                # the host sat here waiting on the device
                _obs.add_gauge('trainer.device_blocked_seconds', r1 - r0)
        self._step += ent.steps
        loss_val = None
        if _obs.enabled():
            # leading indicator: z-score the fetched loss against its
            # EWMA baseline BEFORE the guard's NaN postcondition runs
            try:
                loss_val = float(np.mean(
                    np.asarray(metrics[0], dtype=np.float64)))
            except (TypeError, ValueError):
                pass
            if loss_val is not None:
                _obs.anomaly('loss', loss_val)
        g = self._guard
        verdict = 'ok'
        if g is not None:
            from .fault.guards import is_bad
            undo = ent.steps
            if is_bad(metrics[0]) and self._inflight:
                # pipelined detection: the steps behind this one are
                # already dispatched on poisoned state — drain and
                # discard them BEFORE the guard restores anything
                # (their scope writes happened at dispatch; the
                # restore must win)
                self._drain_discard()
            if g.needs_snapshot:
                # the snapshot predates the whole drain group: undoing
                # it takes the group's earlier good steps with it
                undo = self._step - self._group_start_step
            verdict = g.handle(metrics[0], self._step, steps=undo)
            if verdict == 'skipped':
                self._step = self._group_start_step
        if self._last_resolve_end is not None:
            wall = r1 - max(ent.t0, self._last_resolve_end)
        else:
            wall = r1 - ent.t0
        self._last_resolve_end = r1
        self._record_step(wall, ent.t1 - ent.t0, r1 - r0, verdict,
                          steps=ent.steps,
                          cache_miss=ent.handle.cache_miss)
        if loss_val is not None:
            _obs.flight_event('step_end', step=self._step,
                              epoch=ent.epoch, steps=ent.steps,
                              verdict=verdict, wall=round(wall, 6),
                              loss=loss_val)
        else:
            _obs.flight_event('step_end', step=self._step,
                              epoch=ent.epoch, steps=ent.steps,
                              verdict=verdict, wall=round(wall, 6))
        telemetry = _obs.step_telemetry() if _obs.enabled() else None
        if ent.steps == 1:
            handler(EndStepEvent(ent.epoch, ent.step0, metrics,
                                 wall_time=wall, telemetry=telemetry))
        else:
            for i in range(ent.steps):
                handler(EndStepEvent(
                    ent.epoch, ent.step0 + i,
                    [np.asarray(m[i]) for m in metrics],
                    wall_time=wall / ent.steps, telemetry=telemetry))
        self._trained += ent.items
        if not self._inflight:
            self._idle_since = time.perf_counter()
        if verdict == 'ok':
            # never checkpoint a bad step's state; a skipped/rolled-back
            # step saves nothing and the next good one resumes cadence
            self._maybe_checkpoint(ent.epoch, ent.step0 + ent.steps)
        _inject.fire('step_end', step=self._step)
        return ent.epoch, ent.step0 + ent.steps

    def _drain_discard(self):
        """Bad step detected with younger dispatches in flight: resolve
        them (their updates are about to be overwritten by the guard's
        restore), fire their EndStepEvents, and count their reader
        items as consumed — the data stream continues FORWARD past a
        bad batch — but never count their steps."""
        handler = self._event_handler
        while self._inflight:
            ent = self._inflight.popleft()
            metrics = ent.handle.resolve()
            _obs.inc('trainer.pipeline_drained_steps_total', ent.steps)
            if ent.steps == 1:
                handler(EndStepEvent(ent.epoch, ent.step0, metrics))
            else:
                for i in range(ent.steps):
                    handler(EndStepEvent(
                        ent.epoch, ent.step0 + i,
                        [np.asarray(m[i]) for m in metrics]))
            self._trained += ent.items
        _obs.set_gauge('trainer.inflight_depth', 0)
        self._idle_since = None

    @staticmethod
    def _blocked_seconds():
        return (_obs.get_gauge('trainer.host_blocked_seconds') or 0.0,
                _obs.get_gauge('trainer.device_blocked_seconds') or 0.0)

    # ----------------------------------------------------- checkpoints
    def _save_checkpoint(self, epoch, epoch_step):
        """Checkpoint NOW, recording where the loop stands: resume
        restarts at (epoch, epoch_step) with the reader replaying the
        untrained remainder of that epoch."""
        t0 = time.monotonic()
        with _obs.span('fault.checkpoint_save', step=self._step):
            self._ckpt.save(self.exe, self.program, step=self._step,
                            reader=self._ckpt_reader,
                            reader_pending=getattr(self, '_pending', 0),
                            trainer_state={'epoch': int(epoch),
                                           'epoch_step': int(epoch_step)})
        _obs.overhead('checkpoint', time.monotonic() - t0)
        self._last_save = time.monotonic()

    def _ckpt_cadence_due(self):
        cfg = self.checkpoint_config
        if self._ckpt is None or (not cfg.save_every_steps and
                                  cfg.save_every_secs is None):
            return False
        due = bool(cfg.save_every_steps) and self._step > 0 and \
            self._step % cfg.save_every_steps == 0
        if not due and cfg.save_every_secs is not None:
            due = time.monotonic() - self._last_save >= cfg.save_every_secs
        return due

    def _maybe_checkpoint(self, epoch, epoch_step):
        if self._in_ckpt_drain or not self._ckpt_cadence_due():
            return
        # a due save is a sync point: younger steps are already
        # dispatched (updates applied), so resolve them first — the
        # saved params and the recorded position must agree. Cadence
        # therefore lands up to depth-1 steps late under pipelining.
        self._in_ckpt_drain = True
        try:
            while self._inflight:
                epoch, epoch_step = self._resolve_oldest()
        finally:
            self._in_ckpt_drain = False
        with self._reader_lock:
            self._pending = self._pulled - self._trained
            if self._ckpt_reader is not None and \
                    self._pending > self._ckpt_reader.offset:
                # pulled-but-untrained items span an epoch boundary
                # (offset already reset); their in-epoch positions are
                # unknowable — defer to the next cadence point instead
                # of mis-recording
                return
            self._save_checkpoint(epoch, epoch_step)

    # -------------------------------------------------------- telemetry
    def _record_step(self, wall, compute_s, fetch_s, verdict, steps=1,
                     cache_miss=False):
        """Telemetry for one dispatch: phase histograms, throughput EMA,
        MFU, and the goodput ledger. A dispatch that compiled charges its
        wall time to overhead (goodput counts recompiles against the
        run); bad steps likewise. cache_miss is captured at dispatch —
        under pipelining the executor's last_cache_miss already belongs
        to a younger step by resolve time."""
        if not _obs.enabled():
            return
        if steps > 1:
            # windows record whole-window phase seconds; the steps=w
            # label keeps them out of the per-step percentile streams
            _obs.record('trainer.phase_seconds', compute_s,
                        phase='compute', steps=steps)
            _obs.record('trainer.phase_seconds', fetch_s,
                        phase='fetch', steps=steps)
        else:
            _obs.record('trainer.phase_seconds', compute_s,
                        phase='compute')
            _obs.record('trainer.phase_seconds', fetch_s, phase='fetch')
        per_step = wall / steps
        _obs.inc('trainer.steps_total', steps)
        _obs.record('trainer.step_seconds', per_step)
        _obs.set_gauge('trainer.step_seconds_last', per_step)
        _obs.anomaly('step_time', per_step)
        rate = steps / wall if wall > 0 else 0.0
        prev = _obs.get_gauge('trainer.steps_per_sec_ema')
        _obs.set_gauge('trainer.steps_per_sec_ema',
                       rate if prev is None else 0.9 * prev + 0.1 * rate)
        if cache_miss:
            _obs.overhead('first_dispatch', wall)
        elif verdict == 'ok':
            _obs.step_done(wall, steps)
        else:
            _obs.overhead('bad_step', wall)
        flops = _obs.get_gauge('executor.step_flops')
        if flops:
            if self._peak_flops is None:
                self._peak_flops = _obs.device_peak_flops() or 0.0
            if self._peak_flops:
                _obs.set_gauge('trainer.mfu', min(
                    1.0, steps * flops / wall / self._peak_flops))
        _obs.maybe_flush()

    def save_params(self, dirname):
        _io.save_params(self.exe, dirname, main_program=self.program)

    def save_inference_model(self, dirname, feeded_var_names,
                             target_vars):
        _io.save_inference_model(dirname, feeded_var_names, target_vars,
                                 self.exe, main_program=self.program)
