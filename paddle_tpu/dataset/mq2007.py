"""MQ2007 learning-to-rank (reference: python/paddle/v2/dataset/mq2007.py).
Modes: 'pointwise' (feature, relevance), 'pairwise' (better, worse),
'listwise' (per-query feature list, label list)."""

import numpy as np

from . import common

FEATURE_DIM = 46
_QUERIES = 128
_DOCS_PER_QUERY = 8


def _make_query(r):
    # latent weight vector per split drives consistent relevance
    feats = r.uniform(0, 1, (_DOCS_PER_QUERY, FEATURE_DIM)) \
        .astype('float32')
    scores = feats[:, :5].sum(axis=1)
    rel = np.digitize(scores, np.percentile(scores, [50, 80])) \
        .astype('int64')  # 0/1/2 relevance
    return feats, rel


def _reader(split, format):
    def reader():
        r = common.rng('mq2007', split)
        for _ in range(_QUERIES):
            feats, rel = _make_query(r)
            if format == 'pointwise':
                for f, y in zip(feats, rel):
                    yield f, int(y)
            elif format == 'pairwise':
                for i in range(len(rel)):
                    for j in range(len(rel)):
                        if rel[i] > rel[j]:
                            yield feats[i], feats[j]
            elif format == 'listwise':
                yield feats, rel
            else:
                raise ValueError('unknown format %r' % format)
    return reader


def train(format='pairwise'):
    return _reader('train', format)


def test(format='pairwise'):
    return _reader('test', format)
