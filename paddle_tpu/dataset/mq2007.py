"""MQ2007 learning-to-rank (reference: python/paddle/v2/dataset/
mq2007.py:48-240 — Query parse, QueryList grouping, the
pointwise/pairwise/listwise generators).

Real-data path (round 5): the reference shipped a .rar (rarfile is not
in this environment), so drop the EXTRACTED LETOR fold files
`Fold1/train.txt` / `Fold1/test.txt` under $PADDLE_TPU_DATA/mq2007/
and the readers parse with the reference semantics: each line is
`rel qid:N 1:v ... 46:v # docid ...` (48 space-split parts before the
comment), lines group into per-query lists in file order, and the
three formats yield (feature, score), (better, worse) full-order
pairs, or per-query (features, labels). Malformed lines are skipped
like the reference's None-parse path. Synthetic fallback otherwise."""

import os

import numpy as np

from . import common

FEATURE_DIM = 46
_QUERIES = 128
_DOCS_PER_QUERY = 8

TRAIN_FILE = os.path.join('Fold1', 'train.txt')
TEST_FILE = os.path.join('Fold1', 'test.txt')


def _cached_file(name):
    return common.cached('mq2007', name)


def _parse_line(text):
    """(relevance, query_id, [46 floats]) or None (reference Query
    ._parse_ :83-101)."""
    comment = text.find('#')
    line = (text[:comment] if comment >= 0 else text).strip()
    parts = line.split()
    if len(parts) != 48:
        return None
    try:
        rel = int(parts[0])
        qid = int(parts[1].split(':')[1])
        feats = [float(p.split(':')[1]) for p in parts[2:]]
    except (IndexError, ValueError):
        return None
    return rel, qid, feats


def _load_queries(path):
    """[(qid, feats [n,46], rels [n])] grouped in file order."""
    order = []
    by_qid = {}
    with open(path) as f:
        for text in f:
            parsed = _parse_line(text)
            if parsed is None:
                continue
            rel, qid, feats = parsed
            if qid not in by_qid:
                by_qid[qid] = ([], [])
                order.append(qid)
            by_qid[qid][0].append(feats)
            by_qid[qid][1].append(rel)
    return [(qid,
             np.asarray(by_qid[qid][0], 'float32'),
             np.asarray(by_qid[qid][1], 'int64')) for qid in order]


def _emit(feats, rels, format):
    """One query's docs in the requested format — shared by the real
    and synthetic readers so the two cannot drift."""
    if format == 'pointwise':
        for f, y in zip(feats, rels):
            yield f, int(y)
    elif format == 'pairwise':
        for i in range(len(rels)):
            for j in range(len(rels)):
                if rels[i] > rels[j]:
                    yield feats[i], feats[j]
    elif format == 'listwise':
        yield feats, rels
    else:
        raise ValueError('unknown format %r' % format)


def _file_reader(path, format):
    def reader():
        for _qid, feats, rels in _load_queries(path):
            for item in _emit(feats, rels, format):
                yield item
    return reader


def _make_query(r):
    # latent weight vector per split drives consistent relevance
    feats = r.uniform(0, 1, (_DOCS_PER_QUERY, FEATURE_DIM)) \
        .astype('float32')
    scores = feats[:, :5].sum(axis=1)
    rel = np.digitize(scores, np.percentile(scores, [50, 80])) \
        .astype('int64')  # 0/1/2 relevance
    return feats, rel


def _reader(split, format):
    def reader():
        r = common.rng('mq2007', split)
        for _ in range(_QUERIES):
            feats, rel = _make_query(r)
            for item in _emit(feats, rel, format):
                yield item
    return reader


def train(format='pairwise'):
    f = _cached_file(TRAIN_FILE)
    if f:
        return _file_reader(f, format)
    return _reader('train', format)


def test(format='pairwise'):
    f = _cached_file(TEST_FILE)
    if f:
        return _file_reader(f, format)
    return _reader('test', format)
