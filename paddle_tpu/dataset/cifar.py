"""CIFAR-10/100 (reference: python/paddle/v2/dataset/cifar.py)."""

import numpy as np

from . import common

_TRAIN_N = 4096
_TEST_N = 1024


def _synthetic(name, split, n, num_classes):
    r = common.rng(name, split)
    t = common.rng(name, 'templates').rand(num_classes, 3, 32, 32) \
        .astype('float32')
    labels = r.randint(0, num_classes, size=n)
    imgs = t[labels] + 0.2 * r.randn(n, 3, 32, 32).astype('float32')
    imgs = np.clip(imgs, 0.0, 1.0).astype('float32')
    return imgs.reshape(n, 3 * 32 * 32), labels.astype('int64')


def _reader(name, split, n, num_classes):
    def reader():
        xs, ys = _synthetic(name, split, n, num_classes)
        for i in range(len(xs)):
            yield xs[i], int(ys[i])
    return reader


def train10():
    return _reader('cifar10', 'train', _TRAIN_N, 10)


def test10():
    return _reader('cifar10', 'test', _TEST_N, 10)


def train100():
    return _reader('cifar100', 'train', _TRAIN_N, 100)


def test100():
    return _reader('cifar100', 'test', _TEST_N, 100)
