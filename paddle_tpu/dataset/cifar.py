"""CIFAR-10/100 (reference: python/paddle/v2/dataset/cifar.py:46-64).

Real-data path (round 5): drop `cifar-10-python.tar.gz` /
`cifar-100-python.tar.gz` (the standard pickled batches) under
$PADDLE_TPU_DATA/cifar/ and the readers parse them with the reference
semantics: every tar member whose name contains the sub-name
('data_batch' / 'test_batch' for 10, 'train' / 'test' for 100) is
unpickled, `data` rows scale to [0, 1] float32 (flat [3072]), labels
come from `labels` or `fine_labels`. Synthetic fallback otherwise
(per-class templates + noise, learnable)."""

import os
import pickle
import tarfile

import numpy as np

from . import common

_TRAIN_N = 4096
_TEST_N = 1024

CIFAR10_ARCHIVE = 'cifar-10-python.tar.gz'
CIFAR100_ARCHIVE = 'cifar-100-python.tar.gz'


def _cached(archive):
    return common.cached('cifar', archive)


def reader_creator(filename, sub_name):
    """Reference cifar.py:46 semantics over a local archive."""
    def read_batch(batch):
        data = batch[b'data'] if b'data' in batch else batch['data']
        labels = None
        for key in (b'labels', 'labels', b'fine_labels', 'fine_labels'):
            if key in batch:
                labels = batch[key]
                break
        assert labels is not None, 'batch has neither labels nor fine_labels'
        for sample, label in zip(data, labels):
            yield (np.asarray(sample) / 255.0).astype(np.float32), int(label)

    def reader():
        with tarfile.open(filename, mode='r') as f:
            names = [m.name for m in f
                     if sub_name in m.name and m.isfile()]
            for name in sorted(names):
                batch = pickle.load(f.extractfile(name), encoding='bytes')
                for item in read_batch(batch):
                    yield item

    return reader


def _synthetic(name, split, n, num_classes):
    r = common.rng(name, split)
    t = common.rng(name, 'templates').rand(num_classes, 3, 32, 32) \
        .astype('float32')
    labels = r.randint(0, num_classes, size=n)
    imgs = t[labels] + 0.2 * r.randn(n, 3, 32, 32).astype('float32')
    imgs = np.clip(imgs, 0.0, 1.0).astype('float32')
    return imgs.reshape(n, 3 * 32 * 32), labels.astype('int64')


def _reader(name, split, n, num_classes):
    def reader():
        xs, ys = _synthetic(name, split, n, num_classes)
        for i in range(len(xs)):
            yield xs[i], int(ys[i])
    return reader


def train10():
    tar = _cached(CIFAR10_ARCHIVE)
    if tar:
        return reader_creator(tar, 'data_batch')
    return _reader('cifar10', 'train', _TRAIN_N, 10)


def test10():
    tar = _cached(CIFAR10_ARCHIVE)
    if tar:
        return reader_creator(tar, 'test_batch')
    return _reader('cifar10', 'test', _TEST_N, 10)


def train100():
    tar = _cached(CIFAR100_ARCHIVE)
    if tar:
        return reader_creator(tar, 'train')
    return _reader('cifar100', 'train', _TRAIN_N, 100)


def test100():
    tar = _cached(CIFAR100_ARCHIVE)
    if tar:
        return reader_creator(tar, 'test')
    return _reader('cifar100', 'test', _TEST_N, 100)
