"""MovieLens ratings (reference: v2/dataset/movielens.py)."""

import numpy as np

from . import common

_USERS = 944
_MOVIES = 1683
_TRAIN_N = 8192
_TEST_N = 1024


def max_user_id():
    return _USERS - 1


def max_movie_id():
    return _MOVIES - 1


def max_job_id():
    return 20


def age_table():
    return [1, 18, 25, 35, 45, 50, 56]


def _synthetic(split, n):
    r = common.rng('movielens', split)
    users = r.randint(0, _USERS, size=n)
    movies = r.randint(0, _MOVIES, size=n)
    u_bias = common.rng('movielens', 'ub').randn(_USERS)
    m_bias = common.rng('movielens', 'mb').randn(_MOVIES)
    score = 3.0 + u_bias[users] + m_bias[movies] + 0.3 * r.randn(n)
    score = np.clip(np.round(score), 1, 5)
    return users.astype('int64'), movies.astype('int64'), \
        score.astype('float32')


def _reader(split, n):
    def reader():
        users, movies, scores = _synthetic(split, n)
        for u, m, s in zip(users, movies, scores):
            yield int(u), int(m), float(s)
    return reader


def train():
    return _reader('train', _TRAIN_N)


def test():
    return _reader('test', _TEST_N)
