"""MovieLens ml-1m (reference: python/paddle/v2/dataset/movielens.py
:43-170).

Real-data path (round 5): drop `ml-1m.zip` under
$PADDLE_TPU_DATA/movielens/ and the readers parse with the reference
semantics: movies.dat / users.dat / ratings.dat ('::'-separated),
movie titles split `Title (Year)`, category and title-word
dictionaries built over the whole catalog, a seeded 10% holdout split
on ratings, and each real sample yields the reference record
`user.value() + movie.value() + [[rating]]` with rating rescaled to
[-5, 5] (rating*2-5).

Synthetic fallback (no cached archive) keeps the compact
(uid, movie_id, score-in-[1,5]) triple the recommender model/tests
consume — a deliberate divergence documented here: the real path's
record layout is the reference's richer schema."""

import os
import random
import re
import zipfile

import numpy as np

from . import common

_USERS = 944
_MOVIES = 1683
_TRAIN_N = 8192
_TEST_N = 1024

ARCHIVE = 'ml-1m.zip'

_AGE_TABLE = [1, 18, 25, 35, 45, 50, 56]


def _cached_zip():
    return common.cached('movielens', ARCHIVE)


class MovieInfo(object):
    """Movie id, title words, categories (reference :43-68)."""

    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, title_dict):
        return [self.index,
                [categories_dict[c] for c in self.categories],
                [title_dict[w.lower()] for w in self.title.split()]]


class UserInfo(object):
    """User id, gender, age bucket, job (reference :70-92)."""

    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == 'M'
        self.age = _AGE_TABLE.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age,
                self.job_id]


class _Meta(object):
    """Parsed catalog of a real ml-1m.zip (reference
    __initialize_meta_info__ :102-140)."""

    def __init__(self, zip_path):
        pattern = re.compile(r'^(.*)\((\d+)\)$')
        self.movies = {}
        self.users = {}
        title_words = set()
        categories = set()
        with zipfile.ZipFile(zip_path) as package:
            with package.open('ml-1m/movies.dat') as f:
                for line in f:
                    line = line.decode('latin1').strip()
                    movie_id, title, cats = line.split('::')
                    cats = cats.split('|')
                    categories.update(cats)
                    m = pattern.match(title)
                    title = m.group(1).strip() if m else title
                    self.movies[int(movie_id)] = MovieInfo(
                        index=movie_id, categories=cats, title=title)
                    for w in title.split():
                        title_words.add(w.lower())
            with package.open('ml-1m/users.dat') as f:
                for line in f:
                    uid, gender, age, job, _zip = \
                        line.decode('latin1').strip().split('::')
                    self.users[int(uid)] = UserInfo(
                        index=uid, gender=gender, age=age, job_id=job)
        self.categories_dict = {c: i for i, c in enumerate(sorted(
            categories))}
        self.title_dict = {w: i for i, w in enumerate(sorted(title_words))}


_META = {}


def _meta(zip_path):
    if zip_path not in _META:
        _META[zip_path] = _Meta(zip_path)
    return _META[zip_path]


def _zip_reader(zip_path, is_test, rand_seed=0, test_ratio=0.1):
    def reader():
        meta = _meta(zip_path)
        rand = random.Random(x=rand_seed)
        with zipfile.ZipFile(zip_path) as package:
            with package.open('ml-1m/ratings.dat') as f:
                for line in f:
                    if (rand.random() < test_ratio) != is_test:
                        continue
                    uid, mov_id, rating, _ts = \
                        line.decode('latin1').strip().split('::')
                    usr = meta.users[int(uid)]
                    mov = meta.movies[int(mov_id)]
                    yield (usr.value() +
                           mov.value(meta.categories_dict,
                                     meta.title_dict) +
                           [[float(rating) * 2 - 5.0]])
    return reader


# ------------------------------------------------------------ metadata

def max_user_id():
    z = _cached_zip()
    if z:
        return max(_meta(z).users)
    return _USERS - 1


def max_movie_id():
    z = _cached_zip()
    if z:
        return max(_meta(z).movies)
    return _MOVIES - 1


def max_job_id():
    z = _cached_zip()
    if z:
        return max(u.job_id for u in _meta(z).users.values())
    return 20


def age_table():
    return list(_AGE_TABLE)


def movie_categories():
    z = _cached_zip()
    if z:
        return _meta(z).categories_dict
    return {('cat%d' % i): i for i in range(19)}


def get_movie_title_dict():
    z = _cached_zip()
    if z:
        return _meta(z).title_dict
    return {('t%d' % i): i for i in range(256)}


# ------------------------------------------------------------ synthetic

def _synthetic(split, n):
    r = common.rng('movielens', split)
    users = r.randint(0, _USERS, size=n)
    movies = r.randint(0, _MOVIES, size=n)
    u_bias = common.rng('movielens', 'ub').randn(_USERS)
    m_bias = common.rng('movielens', 'mb').randn(_MOVIES)
    score = 3.0 + u_bias[users] + m_bias[movies] + 0.3 * r.randn(n)
    score = np.clip(np.round(score), 1, 5)
    return users.astype('int64'), movies.astype('int64'), \
        score.astype('float32')


def _reader(split, n):
    def reader():
        users, movies, scores = _synthetic(split, n)
        for u, m, s in zip(users, movies, scores):
            yield int(u), int(m), float(s)
    return reader


def train():
    z = _cached_zip()
    if z:
        return _zip_reader(z, is_test=False)
    return _reader('train', _TRAIN_N)


def test():
    z = _cached_zip()
    if z:
        return _zip_reader(z, is_test=True)
    return _reader('test', _TEST_N)
