"""UCI housing (reference: python/paddle/v2/dataset/uci_housing.py).
13 features -> house price; synthetic fallback keeps the linear structure
so fit_a_line converges the same way."""

import numpy as np

from . import common

feature_names = ['CRIM', 'ZN', 'INDUS', 'CHAS', 'NOX', 'RM', 'AGE', 'DIS',
                 'RAD', 'TAX', 'PTRATIO', 'B', 'LSTAT']

_TRAIN_N = 404
_TEST_N = 102


def _synthetic(split, n):
    r = common.rng('uci_housing', split)
    w = common.rng('uci_housing', 'w').randn(13, 1) * 2.0
    x = r.randn(n, 13).astype('float32')
    y = (x @ w + 3.0 + 0.1 * r.randn(n, 1)).astype('float32')
    return x, y


def _reader(split, n):
    def reader():
        x, y = _synthetic(split, n)
        for i in range(x.shape[0]):
            yield x[i], y[i]
    return reader


def train():
    return _reader('train', _TRAIN_N)


def test():
    return _reader('test', _TEST_N)
