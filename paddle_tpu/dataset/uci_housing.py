"""UCI housing (reference: python/paddle/v2/dataset/uci_housing.py
:59-75 load_data).

Real-data path (round 5): drop `housing.data` (the 506×14 whitespace
float table) under $PADDLE_TPU_DATA/uci_housing/ and the readers parse
with the reference semantics: per-feature normalization
(x - mean) / (max - min) computed over the WHOLE file, then an 80/20
train/test split in file order. Synthetic linear fallback otherwise
(fit_a_line converges the same way)."""

import os

import numpy as np

from . import common

feature_names = ['CRIM', 'ZN', 'INDUS', 'CHAS', 'NOX', 'RM', 'AGE', 'DIS',
                 'RAD', 'TAX', 'PTRATIO', 'B', 'LSTAT']

_TRAIN_N = 404
_TEST_N = 102

DATA_FILE = 'housing.data'


def _cached_file():
    return common.cached('uci_housing', DATA_FILE)


def load_data(filename, feature_num=14, ratio=0.8):
    """(train_rows, test_rows) with the reference normalization."""
    data = np.fromfile(filename, sep=' ')
    data = data.reshape(data.shape[0] // feature_num, feature_num)
    maximums = data.max(axis=0)
    minimums = data.min(axis=0)
    avgs = data.sum(axis=0) / data.shape[0]
    for i in range(feature_num - 1):
        data[:, i] = (data[:, i] - avgs[i]) / (maximums[i] - minimums[i])
    offset = int(data.shape[0] * ratio)
    return data[:offset], data[offset:]


def _file_reader(rows):
    def reader():
        for row in rows:
            yield row[:-1].astype('float32'), \
                row[-1:].astype('float32')
    return reader


def _synthetic(split, n):
    r = common.rng('uci_housing', split)
    w = common.rng('uci_housing', 'w').randn(13, 1) * 2.0
    x = r.randn(n, 13).astype('float32')
    y = (x @ w + 3.0 + 0.1 * r.randn(n, 1)).astype('float32')
    return x, y


def _reader(split, n):
    def reader():
        x, y = _synthetic(split, n)
        for i in range(x.shape[0]):
            yield x[i], y[i]
    return reader


def train():
    f = _cached_file()
    if f:
        return _file_reader(load_data(f)[0])
    return _reader('train', _TRAIN_N)


def test():
    f = _cached_file()
    if f:
        return _file_reader(load_data(f)[1])
    return _reader('test', _TEST_N)
