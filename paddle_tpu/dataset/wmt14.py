"""WMT14 en-de NMT pairs (reference: python/paddle/v2/dataset/wmt14.py
:53-100 tar parsing, :114-167 readers/get_dict).

Real-data path (round 5): drop the reference's `wmt14.tgz` — or any
archive with the same layout: exactly one `*src.dict` and one
`*trg.dict` (one token per line, line number = id), and TSV sentence
files whose names end in `train/train` / `test/test` with
`src-sentence \\t trg-sentence` token lines — under
$PADDLE_TPU_DATA/wmt14/. The readers then parse with the reference
semantics: dicts truncate to the first `dict_size` lines, sentences
tokenize on whitespace, unknown tokens map to <unk>=2, sources are
framed <s> ... <e>, pairs with a side longer than 80 tokens drop, and
targets yield as (<s>+ids, ids+<e>). The zero-egress stance refuses
*downloading* (common.download), not *parsing*.

Synthetic fallback (no cached archive): target = deterministic
per-token mapping of source (+BOS/EOS), so seq2seq/Transformer models
can drive loss to ~0 — a real learnability check, like copy-task
benchmarks."""

import os
import tarfile

import numpy as np

from . import common

_VOCAB = 8000
_TRAIN_N = 4096
_TEST_N = 512
_MAX_LEN = 50

START = '<s>'
END = '<e>'
UNK = '<unk>'
UNK_IDX = 2

# synthetic framing ids (the synthetic vocab puts <s>/<e>/<unk> at 0/1/2)
BOS = 0
EOS = 1

TRAIN_ARCHIVE = 'wmt14.tgz'


def _cached_tar():
    return common.cached('wmt14', TRAIN_ARCHIVE)


def _read_to_dict(tar_path, dict_size):
    """(src_dict, trg_dict): first `dict_size` lines of the archive's
    *src.dict / *trg.dict, token -> line number."""
    def to_dict(fd, size):
        d = {}
        for i, line in enumerate(fd):
            if i >= size:
                break
            d[line.decode('utf-8').strip()] = i
        return d

    with tarfile.open(tar_path, mode='r') as f:
        def one(suffix):
            names = [m.name for m in f if m.name.endswith(suffix)]
            if len(names) != 1:
                raise ValueError(
                    'wmt14 archive %r: expected exactly one *%s, found %d'
                    % (tar_path, suffix, len(names)))
            return to_dict(f.extractfile(names[0]), dict_size)

        return one('src.dict'), one('trg.dict')


def _tar_reader(tar_path, file_name, dict_size):
    def reader():
        src_dict, trg_dict = _read_to_dict(tar_path, dict_size)
        with tarfile.open(tar_path, mode='r') as f:
            names = [m.name for m in f if m.name.endswith(file_name)]
            for name in names:
                for raw in f.extractfile(name):
                    parts = raw.decode('utf-8').strip().split('\t')
                    if len(parts) != 2:
                        continue
                    src_ids = [src_dict.get(w, UNK_IDX)
                               for w in [START] + parts[0].split() + [END]]
                    trg_ids = [trg_dict.get(w, UNK_IDX)
                               for w in parts[1].split()]
                    if len(src_ids) > 80 or len(trg_ids) > 80:
                        continue
                    yield (src_ids, [trg_dict[START]] + trg_ids,
                           trg_ids + [trg_dict[END]])
    return reader


def _map_token(tok):
    return 3 + (tok * 7 + 11) % (_VOCAB - 3)


def _synthetic(split, n):
    r = common.rng('wmt14', split)
    pairs = []
    for _ in range(n):
        length = r.randint(5, _MAX_LEN)
        src = (3 + r.randint(0, _VOCAB - 3, size=length)).astype('int64')
        trg = np.asarray([_map_token(t) for t in src], dtype='int64')
        pairs.append((src, np.concatenate([[BOS], trg]),
                      np.concatenate([trg, [EOS]])))
    return pairs


def _reader(split, n):
    def reader():
        for src, trg_in, trg_out in _synthetic(split, n):
            yield src, trg_in, trg_out
    return reader


def train(dict_size=_VOCAB):
    tar = _cached_tar()
    if tar:
        return _tar_reader(tar, 'train/train', dict_size)
    return _reader('train', _TRAIN_N)


def test(dict_size=_VOCAB):
    tar = _cached_tar()
    if tar:
        return _tar_reader(tar, 'test/test', dict_size)
    return _reader('test', _TEST_N)


def get_dict(dict_size=_VOCAB, reverse=False):
    """(src_dict, trg_dict) — real vocabularies when the archive is
    cached (reference :159-167), the synthetic id vocabulary otherwise.
    reverse=True flips both to id -> token."""
    tar = _cached_tar()
    if tar:
        src_dict, trg_dict = _read_to_dict(tar, dict_size)
    else:
        words = [START, END, UNK] + \
            ['w%d' % i for i in range(3, dict_size)]
        src_dict = {w: i for i, w in enumerate(words[:dict_size])}
        trg_dict = dict(src_dict)
    if reverse:
        src_dict = {v: k for k, v in src_dict.items()}
        trg_dict = {v: k for k, v in trg_dict.items()}
    return src_dict, trg_dict
