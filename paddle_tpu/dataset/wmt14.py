"""WMT14 en-de NMT pairs (reference: v2/dataset/wmt14.py).
Synthetic fallback: target = deterministic per-token mapping of source
(+BOS/EOS), so seq2seq/Transformer models can drive loss to ~0 — a real
learnability check, like copy-task benchmarks."""

import numpy as np

from . import common

_VOCAB = 8000
_TRAIN_N = 4096
_TEST_N = 512
_MAX_LEN = 50

BOS = 0
EOS = 1
UNK = 2


def _map_token(tok):
    return 3 + (tok * 7 + 11) % (_VOCAB - 3)


def _synthetic(split, n):
    r = common.rng('wmt14', split)
    pairs = []
    for _ in range(n):
        length = r.randint(5, _MAX_LEN)
        src = (3 + r.randint(0, _VOCAB - 3, size=length)).astype('int64')
        trg = np.asarray([_map_token(t) for t in src], dtype='int64')
        pairs.append((src, np.concatenate([[BOS], trg]),
                      np.concatenate([trg, [EOS]])))
    return pairs


def _reader(split, n):
    def reader():
        for src, trg_in, trg_out in _synthetic(split, n):
            yield src, trg_in, trg_out
    return reader


def train(dict_size=_VOCAB):
    return _reader('train', _TRAIN_N)


def test(dict_size=_VOCAB):
    return _reader('test', _TEST_N)


def get_dict(dict_size=_VOCAB, reverse=False):
    word_dict = {('w%d' % i): i for i in range(dict_size)}
    if reverse:
        return {v: k for k, v in word_dict.items()}
    return word_dict
