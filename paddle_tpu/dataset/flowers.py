"""Oxford 102 flowers (reference: python/paddle/v2/dataset/flowers.py
:44-120). Schema: (image_float32_flat, label).

Real-data path (round 5): drop `102flowers.tgz`, `imagelabels.mat`,
and `setid.mat` under $PADDLE_TPU_DATA/flowers/. Reference semantics:
setid.mat's index lists pick members `jpg/image_%05d.jpg`, labels come
from imagelabels.mat (1-based → label-1 yielded), the train/test flags
are deliberately SWAPPED ('tstid' is train — the reference's own
readme note, test data outnumbers train), and every image is jpeg-
decoded in the reader (so mappers on BOTH paths receive a decoded HWC
uint8 array) then run through the default mapper: simple_transform
resize 256 / crop 224 (train random-crop+flip, test center-crop) with
the reference BGR mean → flattened float32.

Synthetic fallback: class-colored noise with the same pipeline at
scaled-down sizes (resize 40, crop 32) to keep tests fast.
"""

import functools
import os
import tarfile

import numpy as np

from . import common
from .. import image

CLASS_NUM = 102
_TRAIN_N = 1024
_TEST_N = 256
_RAW_HW = (48, 56)     # synthetic source images (HWC uint8, non-square)
RESIZE_SIZE = 40
CROP_SIZE = 32

DATA_ARCHIVE = '102flowers.tgz'
LABEL_FILE = 'imagelabels.mat'
SETID_FILE = 'setid.mat'
# the reference swaps the official flags: 'tstid' is the TRAIN list
TRAIN_FLAG = 'tstid'
TEST_FLAG = 'trnid'
VALID_FLAG = 'valid'
_REAL_MEAN = [103.94, 116.78, 123.68]


def _cached(name):
    return common.cached('flowers', name)


def _have_real():
    return all(_cached(n) for n in (DATA_ARCHIVE, LABEL_FILE, SETID_FILE))


def _real_mapper(is_train, sample):
    """Reference default_mapper over a DECODED (hwc_uint8, label):
    256/224 transform -> flat float32 (flowers.py:58-66). Decoding
    happens in _tar_reader so user-supplied mappers see the same
    decoded-array contract as the synthetic path."""
    img, label = sample
    img = image.simple_transform(img, 256, 224, is_train,
                                 mean=_REAL_MEAN)
    return img.flatten().astype('float32'), label


def _tar_reader(dataset_name, mapper):
    import scipy.io as scio
    labels = scio.loadmat(_cached(LABEL_FILE))['labels'][0]
    indexes = scio.loadmat(_cached(SETID_FILE))[dataset_name][0]
    img2label = {'jpg/image_%05d.jpg' % i: int(labels[i - 1])
                 for i in indexes}

    def reader():
        # iterate members SEQUENTIALLY: random extractfile access on a
        # gzip tar re-decompresses from the stream start per member
        # (O(n²) over 8k images); sequential next() is one pass.
        # Decode HERE so every mapper — default or user-supplied — gets
        # the same (decoded HWC uint8, label) contract as the synthetic
        # path, not raw jpeg bytes.
        with tarfile.open(_cached(DATA_ARCHIVE)) as tf:
            m = tf.next()
            while m is not None:
                label = img2label.get(m.name)
                if label is not None and m.isfile():
                    img = image.load_image_bytes(tf.extractfile(m).read())
                    yield mapper((img, label - 1))
                m = tf.next()
    return reader


def default_mapper(is_train, sample):
    """The reference's default mapper over (hwc_uint8, label)."""
    img, label = sample
    img = image.simple_transform(img, RESIZE_SIZE, CROP_SIZE, is_train,
                                 mean=[127.5, 127.5, 127.5])
    return img / 127.5, label


def _reader(split, n, mapper, buffered_size=1024):
    is_train = split == 'train'
    if mapper is None:
        mapper = functools.partial(default_mapper, is_train)

    def reader():
        r = common.rng('flowers', split)
        h, w = _RAW_HW
        for _ in range(n):
            label = int(r.randint(0, CLASS_NUM))
            base = np.zeros((h, w, 3), dtype='float32')
            base[..., label % 3] = (label % 10) / 10.0
            img = np.clip(base + r.normal(0, 0.2, (h, w, 3)), 0, 1)
            img = (img * 255).astype('uint8')
            yield mapper((img, label))
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    if _have_real():
        return _tar_reader(TRAIN_FLAG,
                           mapper or functools.partial(_real_mapper, True))
    return _reader('train', _TRAIN_N, mapper, buffered_size)


def test(mapper=None, buffered_size=1024, use_xmap=False):
    if _have_real():
        return _tar_reader(TEST_FLAG,
                           mapper or functools.partial(_real_mapper,
                                                       False))
    return _reader('test', _TEST_N, mapper, buffered_size)


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    if _have_real():
        return _tar_reader(VALID_FLAG,
                           mapper or functools.partial(_real_mapper,
                                                       False))
    return _reader('valid', _TEST_N, mapper, buffered_size)
