"""Oxford 102 flowers (reference: python/paddle/v2/dataset/flowers.py).
Schema: (image_chw_float32, label). Synthetic: class-colored noise."""

import numpy as np

from . import common

CLASS_NUM = 102
_TRAIN_N = 1024
_TEST_N = 256
_SHAPE = (3, 32, 32)  # reference resizes to 224; kept small for tests


def _reader(split, n, mapper=None):
    def reader():
        r = common.rng('flowers', split)
        for _ in range(n):
            label = int(r.randint(0, CLASS_NUM))
            base = np.zeros(_SHAPE, dtype='float32')
            base[label % 3] = (label % 10) / 10.0
            img = np.clip(base + r.normal(0, 0.2, _SHAPE), 0, 1) \
                .astype('float32')
            item = (img, label)
            yield mapper(item) if mapper else item
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader('train', _TRAIN_N, mapper)


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader('test', _TEST_N, mapper)


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader('valid', _TEST_N, mapper)
