"""Oxford 102 flowers (reference: python/paddle/v2/dataset/flowers.py).
Schema: (image_chw_float32, label).

Like the reference, raw HWC images go through the default
image.simple_transform mapper (reference flowers.py wires
v2/image.py:291 simple_transform as default_mapper: resize-short then
train random-crop+flip / test center-crop, then CHW float). Synthetic
class-colored noise stands in for the tarball (zero egress); sizes are
scaled down (resize 40, crop 32 vs the reference's 256/224) to keep
tests fast — the pipeline shape is identical.
"""

import functools

import numpy as np

from . import common
from .. import image

CLASS_NUM = 102
_TRAIN_N = 1024
_TEST_N = 256
_RAW_HW = (48, 56)     # synthetic source images (HWC uint8, non-square)
RESIZE_SIZE = 40
CROP_SIZE = 32


def default_mapper(is_train, sample):
    """The reference's default mapper over (hwc_uint8, label)."""
    img, label = sample
    img = image.simple_transform(img, RESIZE_SIZE, CROP_SIZE, is_train,
                                 mean=[127.5, 127.5, 127.5])
    return img / 127.5, label


def _reader(split, n, mapper, buffered_size=1024):
    is_train = split == 'train'
    if mapper is None:
        mapper = functools.partial(default_mapper, is_train)

    def reader():
        r = common.rng('flowers', split)
        h, w = _RAW_HW
        for _ in range(n):
            label = int(r.randint(0, CLASS_NUM))
            base = np.zeros((h, w, 3), dtype='float32')
            base[..., label % 3] = (label % 10) / 10.0
            img = np.clip(base + r.normal(0, 0.2, (h, w, 3)), 0, 1)
            img = (img * 255).astype('uint8')
            yield mapper((img, label))
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader('train', _TRAIN_N, mapper, buffered_size)


def test(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader('test', _TEST_N, mapper, buffered_size)


def valid(mapper=None, buffered_size=1024, use_xmap=False):
    return _reader('valid', _TEST_N, mapper, buffered_size)
