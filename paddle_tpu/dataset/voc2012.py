"""PASCAL VOC2012 segmentation (reference:
python/paddle/v2/dataset/voc2012.py). Schema: (image_chw, seg_label_hw).
Raw HWC frames go through image.to_chw like the reference's PIL decode
path (v2/image.py:189)."""

import numpy as np

from . import common
from .. import image

CLASS_NUM = 21  # 20 classes + background
_TRAIN_N = 256
_TEST_N = 64
_SHAPE = (3, 32, 32)


def _reader(split, n):
    def reader():
        r = common.rng('voc2012', split)
        h, w = _SHAPE[1], _SHAPE[2]
        for _ in range(n):
            hwc = r.uniform(0, 1, (h, w, 3)).astype('float32')
            img = image.to_chw(hwc)
            # blocky segmentation mask
            seg = np.zeros((h, w), dtype='int32')
            for _k in range(3):
                cls = int(r.randint(1, CLASS_NUM))
                y0, x0 = r.randint(0, h // 2), r.randint(0, w // 2)
                seg[y0:y0 + h // 2, x0:x0 + w // 2] = cls
            yield img, seg
    return reader


def train():
    return _reader('train', _TRAIN_N)


def test():
    return _reader('test', _TEST_N)


def val():
    return _reader('val', _TEST_N)
