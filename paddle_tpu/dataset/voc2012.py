"""PASCAL VOC2012 segmentation (reference:
python/paddle/v2/dataset/voc2012.py:28-80). Schema:
(image_hwc_array, seg_label_hw_array) — raw PIL-decoded numpy arrays,
like the reference.

Real-data path (round 5): drop `VOCtrainval_11-May-2012.tar` under
$PADDLE_TPU_DATA/voc2012/ and the readers parse with the reference
semantics: the ImageSets/Segmentation/{trainval,train,val}.txt lists
select frames, JPEGImages/<id>.jpg and SegmentationClass/<id>.png
decode via PIL (the palette PNG yields the class-index map directly).
Reference quirk preserved: train() reads the 'trainval' list and
test() the 'train' list. Synthetic blocky masks otherwise."""

import io
import os
import tarfile

import numpy as np

from . import common
from .. import image

CLASS_NUM = 21  # 20 classes + background
_TRAIN_N = 256
_TEST_N = 64
_SHAPE = (3, 32, 32)

ARCHIVE = 'VOCtrainval_11-May-2012.tar'
SET_FILE = 'VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt'
DATA_FILE = 'VOCdevkit/VOC2012/JPEGImages/{}.jpg'
LABEL_FILE = 'VOCdevkit/VOC2012/SegmentationClass/{}.png'


def _cached_tar():
    return common.cached('voc2012', ARCHIVE)


def reader_creator(filename, sub_name):
    def reader():
        from PIL import Image
        with tarfile.open(filename) as tarobject:
            sets = tarobject.extractfile(SET_FILE.format(sub_name))
            ids = [ln.decode('utf-8').strip() for ln in sets]
            for frame in ids:
                if not frame:
                    continue
                data = tarobject.extractfile(
                    DATA_FILE.format(frame)).read()
                label = tarobject.extractfile(
                    LABEL_FILE.format(frame)).read()
                yield (np.array(Image.open(io.BytesIO(data))),
                       np.array(Image.open(io.BytesIO(label))))
    return reader


def _reader(split, n):
    def reader():
        r = common.rng('voc2012', split)
        h, w = _SHAPE[1], _SHAPE[2]
        for _ in range(n):
            hwc = r.uniform(0, 1, (h, w, 3)).astype('float32')
            img = image.to_chw(hwc)
            # blocky segmentation mask
            seg = np.zeros((h, w), dtype='int32')
            for _k in range(3):
                cls = int(r.randint(1, CLASS_NUM))
                y0, x0 = r.randint(0, h // 2), r.randint(0, w // 2)
                seg[y0:y0 + h // 2, x0:x0 + w // 2] = cls
            yield img, seg
    return reader


def train():
    tar = _cached_tar()
    if tar:
        return reader_creator(tar, 'trainval')
    return _reader('train', _TRAIN_N)


def test():
    tar = _cached_tar()
    if tar:
        return reader_creator(tar, 'train')
    return _reader('test', _TEST_N)


def val():
    tar = _cached_tar()
    if tar:
        return reader_creator(tar, 'val')
    return _reader('val', _TEST_N)
