"""Datasets (reference: python/paddle/v2/dataset).

Zero-egress environment: each module first looks for cached files under
$PADDLE_TPU_DATA (or ~/.cache/paddle_tpu); when absent it falls back to a
deterministic synthetic generator with the same schema/cardinality so
models, tests, and benchmarks run anywhere.
"""

from . import common  # noqa: F401
from . import uci_housing  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import movielens  # noqa: F401
from . import wmt14  # noqa: F401
from . import ctr  # noqa: F401
from . import conll05  # noqa: F401
from . import sentiment  # noqa: F401
from . import wmt16  # noqa: F401
from . import flowers  # noqa: F401
from . import voc2012  # noqa: F401
from . import mq2007  # noqa: F401
