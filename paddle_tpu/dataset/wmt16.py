"""WMT16 en-de translation (reference: python/paddle/v2/dataset/wmt16.py).
Schema: (src_ids, trg_ids, trg_ids_next) with <s>/<e>/<unk> = 0/1/2."""

import numpy as np

from . import common

_SRC_VOCAB = 10000
_TRG_VOCAB = 10000
_TRAIN_N = 2048
_TEST_N = 256
_MAX_LEN = 50


def get_dict(lang, dict_size, reverse=False):
    d = {('%s_w%d' % (lang, i)): i for i in range(dict_size)}
    return {v: k for k, v in d.items()} if reverse else d


def _reader(split, n, src_dict_size, trg_dict_size):
    def reader():
        r = common.rng('wmt16', split)
        for _ in range(n):
            slen = int(r.randint(3, _MAX_LEN))
            tlen = max(3, int(slen * r.uniform(0.8, 1.2)))
            src = r.randint(3, src_dict_size, slen).astype('int64')
            trg = r.randint(3, trg_dict_size, tlen).astype('int64')
            trg_in = np.concatenate([[0], trg]).astype('int64')   # <s> ...
            trg_next = np.concatenate([trg, [1]]).astype('int64')  # ... <e>
            yield src, trg_in, trg_next
    return reader


def train(src_dict_size=_SRC_VOCAB, trg_dict_size=_TRG_VOCAB,
          src_lang='en'):
    return _reader('train', _TRAIN_N, src_dict_size, trg_dict_size)


def test(src_dict_size=_SRC_VOCAB, trg_dict_size=_TRG_VOCAB,
         src_lang='en'):
    return _reader('test', _TEST_N, src_dict_size, trg_dict_size)


def validation(src_dict_size=_SRC_VOCAB, trg_dict_size=_TRG_VOCAB,
               src_lang='en'):
    return _reader('val', _TEST_N, src_dict_size, trg_dict_size)
