"""WMT16 en-de multimodal-task translation (reference:
python/paddle/v2/dataset/wmt16.py:59-311).
Schema: (src_ids, trg_ids, trg_ids_next) with <s>/<e>/<unk> = 0/1/2.

Real-data path (round 5): drop `wmt16.tar.gz` (members `wmt16/train`,
`wmt16/test`, `wmt16/val` — TSV `en-sentence \\t de-sentence` lines)
under $PADDLE_TPU_DATA/wmt16/. Reference semantics: per-language
dictionaries are BUILT from the train split (frequency-descending,
capped at dict_size including the three markers) and cached as
`<lang>_<size>.dict` beside the archive; sources frame <s> ... <e>,
targets yield as (<s>+ids, ids+<e>); src_lang='de' swaps the columns.
Synthetic fallback otherwise."""

import collections
import os
import tarfile

import numpy as np

from . import common

_SRC_VOCAB = 10000
_TRG_VOCAB = 10000
_TRAIN_N = 2048
_TEST_N = 256
_MAX_LEN = 50

ARCHIVE = 'wmt16.tar.gz'
START_MARK = '<s>'
END_MARK = '<e>'
UNK_MARK = '<unk>'


def _cached_tar():
    return common.cached('wmt16', ARCHIVE)


def _build_dict(tar_path, dict_size, save_path, lang):
    word_dict = collections.defaultdict(int)
    col = 0 if lang == 'en' else 1
    with tarfile.open(tar_path, mode='r') as f:
        for line in f.extractfile('wmt16/train'):
            parts = line.decode('utf-8').strip().split('\t')
            if len(parts) != 2:
                continue
            for w in parts[col].split():
                word_dict[w] += 1
    with open(save_path, 'w') as fout:
        fout.write('%s\n%s\n%s\n' % (START_MARK, END_MARK, UNK_MARK))
        # frequency-descending, word tie-break for determinism
        for idx, (word, _c) in enumerate(sorted(
                word_dict.items(), key=lambda x: (-x[1], x[0]))):
            if idx + 3 == dict_size:
                break
            fout.write('%s\n' % word)


def _load_dict(tar_path, dict_size, lang, reverse=False):
    dict_path = os.path.join(os.path.dirname(tar_path),
                             '%s_%d.dict' % (lang, dict_size))
    if not os.path.exists(dict_path) or \
            len(open(dict_path).readlines()) != dict_size:
        _build_dict(tar_path, dict_size, dict_path, lang)
    word_dict = {}
    with open(dict_path) as fdict:
        for idx, line in enumerate(fdict):
            if reverse:
                word_dict[idx] = line.strip()
            else:
                word_dict[line.strip()] = idx
    return word_dict


def reader_creator(tar_path, file_name, src_dict_size, trg_dict_size,
                   src_lang):
    def reader():
        src_dict = _load_dict(tar_path, src_dict_size, src_lang)
        trg_dict = _load_dict(tar_path, trg_dict_size,
                              'de' if src_lang == 'en' else 'en')
        start_id = src_dict[START_MARK]
        end_id = src_dict[END_MARK]
        unk_id = src_dict[UNK_MARK]
        src_col = 0 if src_lang == 'en' else 1
        trg_col = 1 - src_col
        with tarfile.open(tar_path, mode='r') as f:
            for line in f.extractfile(file_name):
                parts = line.decode('utf-8').strip().split('\t')
                if len(parts) != 2:
                    continue
                src_ids = [start_id] + [src_dict.get(w, unk_id)
                                        for w in parts[src_col].split()] \
                    + [end_id]
                trg_ids = [trg_dict.get(w, unk_id)
                           for w in parts[trg_col].split()]
                yield (src_ids, [start_id] + trg_ids,
                       trg_ids + [end_id])
    return reader


def get_dict(lang, dict_size, reverse=False):
    tar = _cached_tar()
    if tar:
        return _load_dict(tar, dict_size, lang, reverse)
    d = {('%s_w%d' % (lang, i)): i for i in range(dict_size)}
    return {v: k for k, v in d.items()} if reverse else d


def _reader(split, n, src_dict_size, trg_dict_size):
    def reader():
        r = common.rng('wmt16', split)
        for _ in range(n):
            slen = int(r.randint(3, _MAX_LEN))
            tlen = max(3, int(slen * r.uniform(0.8, 1.2)))
            src = r.randint(3, src_dict_size, slen).astype('int64')
            trg = r.randint(3, trg_dict_size, tlen).astype('int64')
            trg_in = np.concatenate([[0], trg]).astype('int64')   # <s> ...
            trg_next = np.concatenate([trg, [1]]).astype('int64')  # ... <e>
            yield src, trg_in, trg_next
    return reader


def train(src_dict_size=_SRC_VOCAB, trg_dict_size=_TRG_VOCAB,
          src_lang='en'):
    tar = _cached_tar()
    if tar:
        return reader_creator(tar, 'wmt16/train', src_dict_size,
                              trg_dict_size, src_lang)
    return _reader('train', _TRAIN_N, src_dict_size, trg_dict_size)


def test(src_dict_size=_SRC_VOCAB, trg_dict_size=_TRG_VOCAB,
         src_lang='en'):
    tar = _cached_tar()
    if tar:
        return reader_creator(tar, 'wmt16/test', src_dict_size,
                              trg_dict_size, src_lang)
    return _reader('test', _TEST_N, src_dict_size, trg_dict_size)


def validation(src_dict_size=_SRC_VOCAB, trg_dict_size=_TRG_VOCAB,
               src_lang='en'):
    tar = _cached_tar()
    if tar:
        return reader_creator(tar, 'wmt16/val', src_dict_size,
                              trg_dict_size, src_lang)
    return _reader('val', _TEST_N, src_dict_size, trg_dict_size)
