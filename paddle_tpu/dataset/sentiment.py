"""Movie review sentiment, NLTK-style (reference:
python/paddle/v2/dataset/sentiment.py:52-120). Schema:
(word_id_list, label) with label 0=neg, 1=pos.

Real-data path (round 5): drop the NLTK corpus archive
`movie_reviews.zip` (members movie_reviews/{neg,pos}/*.txt — the
pre-tokenized corpus) under $PADDLE_TPU_DATA/sentiment/. Reference
semantics: the word dictionary is frequency-sorted over the whole
corpus (no cutoff; ties broken by word here for determinism — the
reference's cmp-sort left them at insertion order), files interleave
neg/pos in sorted order (sort_files), the first 1600 interleaved
samples are train and the rest test. Synthetic class-biased token
distributions otherwise."""

import collections
import os
import zipfile

import numpy as np

from . import common

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000
_VOCAB = 8000
_MAX_LEN = 60

ARCHIVE = 'movie_reviews.zip'


def _cached_zip():
    return common.cached('sentiment', ARCHIVE)


def _doc_words(z, name):
    text = z.read(name).decode('utf-8', errors='replace')
    return [w.lower() for w in text.split()]


def _sorted_files(z):
    """Interleaved neg/pos file list (reference sort_files :73-83)."""
    neg = sorted(n for n in z.namelist()
                 if '/neg/' in n and n.endswith('.txt'))
    pos = sorted(n for n in z.namelist()
                 if '/pos/' in n and n.endswith('.txt'))
    out = []
    for a, b in zip(neg, pos):
        out.extend((a, b))
    return out


def get_word_dict():
    """[(word, id)] frequency-sorted over the whole corpus (reference
    :52-70); synthetic ids otherwise."""
    zp = _cached_zip()
    if zp is None:
        return [('w%d' % i, i) for i in range(_VOCAB)]
    freq = collections.defaultdict(int)
    with zipfile.ZipFile(zp) as z:
        for name in _sorted_files(z):
            for w in _doc_words(z, name):
                freq[w] += 1
    ordered = sorted(freq.items(), key=lambda x: (-x[1], x[0]))
    return [(w, i) for i, (w, _c) in enumerate(ordered)]


_CORPUS_CACHE = {}


def _load_corpus():
    """Parsed corpus, memoized per archive path — iterating a reader
    must not re-run the two full zip scans (dict build + docs) every
    epoch."""
    zp = _cached_zip()
    if zp in _CORPUS_CACHE:
        return _CORPUS_CACHE[zp]
    ids = dict(get_word_dict())
    samples = []
    with zipfile.ZipFile(zp) as z:
        for name in _sorted_files(z):
            label = 0 if '/neg/' in name else 1
            samples.append(
                ([ids[w] for w in _doc_words(z, name)], label))
    _CORPUS_CACHE[zp] = samples
    return samples


def _corpus_reader(lo, hi):
    def reader():
        for doc, label in _load_corpus()[lo:hi]:
            yield doc, label
    return reader


def _reader(split, n):
    def reader():
        r = common.rng('sentiment', split)
        for _ in range(n):
            label = int(r.randint(0, 2))
            length = int(r.randint(8, _MAX_LEN))
            if label:
                toks = np.minimum(r.exponential(_VOCAB / 10, length)
                                  .astype('int64'), _VOCAB - 1)
            else:
                toks = _VOCAB - 1 - np.minimum(
                    r.exponential(_VOCAB / 10, length).astype('int64'),
                    _VOCAB - 1)
            yield toks, label
    return reader


def train():
    if _cached_zip():
        return _corpus_reader(0, NUM_TRAINING_INSTANCES)
    return _reader('train', NUM_TRAINING_INSTANCES)


def test():
    if _cached_zip():
        return _corpus_reader(NUM_TRAINING_INSTANCES, None)
    return _reader('test', NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES)
