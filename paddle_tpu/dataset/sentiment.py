"""Movie review sentiment, NLTK-style (reference:
python/paddle/v2/dataset/sentiment.py). Schema: (word_id_list, label)."""

import numpy as np

from . import common

NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000
_VOCAB = 8000
_MAX_LEN = 60


def get_word_dict():
    return [('w%d' % i, i) for i in range(_VOCAB)]


def _reader(split, n):
    def reader():
        r = common.rng('sentiment', split)
        for _ in range(n):
            label = int(r.randint(0, 2))
            length = int(r.randint(8, _MAX_LEN))
            if label:
                toks = np.minimum(r.exponential(_VOCAB / 10, length)
                                  .astype('int64'), _VOCAB - 1)
            else:
                toks = _VOCAB - 1 - np.minimum(
                    r.exponential(_VOCAB / 10, length).astype('int64'),
                    _VOCAB - 1)
            yield toks, label
    return reader


def train():
    return _reader('train', NUM_TRAINING_INSTANCES)


def test():
    return _reader('test', NUM_TOTAL_INSTANCES - NUM_TRAINING_INSTANCES)
