"""Shared dataset helpers (reference: python/paddle/v2/dataset/common.py)."""

import hashlib
import os

import numpy as np

DATA_HOME = os.environ.get(
    'PADDLE_TPU_DATA',
    os.path.join(os.path.expanduser('~'), '.cache', 'paddle_tpu', 'dataset'))


def cached_path(category, filename):
    return os.path.join(DATA_HOME, category, filename)


def has_cached(category, filename):
    return os.path.exists(cached_path(category, filename))


def md5file(fname):
    h = hashlib.md5()
    with open(fname, 'rb') as f:
        for chunk in iter(lambda: f.read(4096), b''):
            h.update(chunk)
    return h.hexdigest()


def rng(name, split):
    """Deterministic per-(dataset, split) generator for synthetic data."""
    seed = int(hashlib.md5(('%s/%s' % (name, split)).encode()).hexdigest()[:8],
               16)
    return np.random.RandomState(seed)


def download(url, category, md5sum=None):
    raise RuntimeError(
        'Network access is unavailable in this environment. Place the file '
        'for %r under %s, or use the synthetic fallback (automatic).' %
        (category, os.path.join(DATA_HOME, category)))


def cached(category, filename):
    """Path of a user-dropped archive, or None when absent — the gate
    every dataset's real-data path shares."""
    p = cached_path(category, filename)
    return p if os.path.exists(p) else None
