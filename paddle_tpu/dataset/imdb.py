"""IMDB sentiment (reference: python/paddle/v2/dataset/imdb.py:40-126).

Real-data path (round 5): drop `aclImdb_v1.tar.gz` under
$PADDLE_TPU_DATA/imdb/ and the readers parse with the reference
semantics: member files matching aclImdb/{train,test}/{pos,neg}/*.txt
are read sequentially (tarfile.next — the reference's
don't-thrash-the-disk note), lowercased, punctuation-stripped,
whitespace-tokenized; word_dict() builds the frequency-sorted
vocabulary with the reference's cutoff of 150 and a trailing <unk>.
Synthetic fallback otherwise (class-biased token distributions so
sentiment models separate the classes)."""

import collections
import os
import re
import string
import tarfile

import numpy as np

from . import common

_VOCAB = 5000
_TRAIN_N = 2048
_TEST_N = 512
_MAX_LEN = 100

ARCHIVE = 'aclImdb_v1.tar.gz'

_PUNCT_TABLE = str.maketrans('', '', string.punctuation)


def _cached_tar():
    return common.cached('imdb', ARCHIVE)


def tokenize(pattern, tar_path=None):
    """Yield one token list per member file matching `pattern`
    (reference imdb.py:40 — sequential access, lowercase, punctuation
    removed)."""
    tar_path = tar_path or _cached_tar()
    if tar_path is None:
        raise RuntimeError('imdb.tokenize needs the cached archive; see '
                           'module docstring')
    with tarfile.open(tar_path) as tarf:
        tf = tarf.next()
        while tf is not None:
            if tf.isfile() and pattern.match(tf.name):
                text = tarf.extractfile(tf).read().decode(
                    'utf-8', errors='replace')
                yield text.rstrip('\n\r').translate(
                    _PUNCT_TABLE).lower().split()
            tf = tarf.next()


def build_dict(pattern, cutoff, tar_path=None):
    """Frequency-sorted token -> id over files matching `pattern`,
    keeping tokens with count > cutoff, <unk> appended last
    (reference imdb.py:55-74)."""
    word_freq = collections.defaultdict(int)
    for doc in tokenize(pattern, tar_path):
        for word in doc:
            word_freq[word] += 1
    kept = [(w, c) for w, c in word_freq.items() if c > cutoff]
    kept.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx['<unk>'] = len(kept)
    return word_idx


def reader_creator(pos_pattern, neg_pattern, word_idx, tar_path=None):
    unk = word_idx['<unk>']

    def reader():
        # stream at iteration time (the reference materialized INS
        # up-front; two sequential tar passes beat pinning ~25k
        # tokenized docs in RAM for the reader's lifetime)
        for pattern, label in ((pos_pattern, 0), (neg_pattern, 1)):
            for doc in tokenize(pattern, tar_path):
                yield [word_idx.get(w, unk) for w in doc], label
    return reader


def word_dict():
    tar = _cached_tar()
    if tar:
        return build_dict(
            re.compile(r'aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$'),
            150, tar)
    return {('w%d' % i): i for i in range(_VOCAB)}


def _synthetic(split, n):
    r = common.rng('imdb', split)
    labels = r.randint(0, 2, size=n)
    seqs = []
    for i in range(n):
        length = r.randint(10, _MAX_LEN)
        # positive reviews skew to low ids, negative to high ids
        if labels[i] == 1:
            toks = np.minimum(r.exponential(_VOCAB / 8, length).astype(int),
                              _VOCAB - 1)
        else:
            toks = _VOCAB - 1 - np.minimum(
                r.exponential(_VOCAB / 8, length).astype(int), _VOCAB - 1)
        seqs.append(toks.astype('int64'))
    return seqs, labels.astype('int64')


def _reader(split, n):
    def reader():
        seqs, labels = _synthetic(split, n)
        for s, l in zip(seqs, labels):
            yield s, int(l)
    return reader


def train(word_idx=None):
    tar = _cached_tar()
    if tar:
        return reader_creator(
            re.compile(r'aclImdb/train/pos/.*\.txt$'),
            re.compile(r'aclImdb/train/neg/.*\.txt$'),
            word_idx or word_dict(), tar)
    return _reader('train', _TRAIN_N)


def test(word_idx=None):
    tar = _cached_tar()
    if tar:
        return reader_creator(
            re.compile(r'aclImdb/test/pos/.*\.txt$'),
            re.compile(r'aclImdb/test/neg/.*\.txt$'),
            word_idx or word_dict(), tar)
    return _reader('test', _TEST_N)
