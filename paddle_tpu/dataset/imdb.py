"""IMDB sentiment (reference: python/paddle/v2/dataset/imdb.py).
Synthetic fallback: two token distributions (positive/negative vocab bias)
so sentiment models separate the classes."""

import numpy as np

from . import common

_VOCAB = 5000
_TRAIN_N = 2048
_TEST_N = 512
_MAX_LEN = 100


def word_dict():
    return {('w%d' % i): i for i in range(_VOCAB)}


def _synthetic(split, n):
    r = common.rng('imdb', split)
    labels = r.randint(0, 2, size=n)
    seqs = []
    for i in range(n):
        length = r.randint(10, _MAX_LEN)
        # positive reviews skew to low ids, negative to high ids
        if labels[i] == 1:
            toks = np.minimum(r.exponential(_VOCAB / 8, length).astype(int),
                              _VOCAB - 1)
        else:
            toks = _VOCAB - 1 - np.minimum(
                r.exponential(_VOCAB / 8, length).astype(int), _VOCAB - 1)
        seqs.append(toks.astype('int64'))
    return seqs, labels.astype('int64')


def _reader(split, n):
    def reader():
        seqs, labels = _synthetic(split, n)
        for s, l in zip(seqs, labels):
            yield s, int(l)
    return reader


def train(word_idx=None):
    return _reader('train', _TRAIN_N)


def test(word_idx=None):
    return _reader('test', _TEST_N)
