"""CoNLL-2005 semantic role labeling (reference:
python/paddle/v2/dataset/conll05.py). Schema: (word_ids, ctx_n2, ctx_n1,
ctx_0, ctx_p1, ctx_p2, verb_id, mark, label_ids) per sentence.
Synthetic fallback keeps the 9-slot schema and label cardinality."""

import numpy as np

from . import common

WORD_DICT_LEN = 44068
LABEL_DICT_LEN = 59
PRED_DICT_LEN = 3162
_TRAIN_N = 1024
_TEST_N = 256
_MAX_LEN = 30


def get_dict():
    word_dict = {('w%d' % i): i for i in range(WORD_DICT_LEN)}
    verb_dict = {('v%d' % i): i for i in range(PRED_DICT_LEN)}
    label_dict = {('l%d' % i): i for i in range(LABEL_DICT_LEN)}
    return word_dict, verb_dict, label_dict


def _reader(split, n):
    def reader():
        r = common.rng('conll05', split)
        for _ in range(n):
            length = int(r.randint(5, _MAX_LEN))
            words = r.randint(0, WORD_DICT_LEN, length).astype('int64')
            ctxs = [np.roll(words, k) for k in (-2, -1, 0, 1, 2)]
            verb = int(r.randint(0, PRED_DICT_LEN))
            verb_pos = int(r.randint(0, length))
            mark = np.zeros(length, dtype='int64')
            mark[verb_pos] = 1
            labels = r.randint(0, LABEL_DICT_LEN, length).astype('int64')
            yield (words,) + tuple(ctxs) + (
                np.full(length, verb, dtype='int64'), mark, labels)
    return reader


def train():
    return _reader('train', _TRAIN_N)


def test():
    return _reader('test', _TEST_N)
