"""CoNLL-2005 semantic role labeling (reference:
python/paddle/v2/dataset/conll05.py:41-230). Schema: (word_ids, ctx_n2,
ctx_n1, ctx_0, ctx_p1, ctx_p2, verb_id, mark, label_ids) per
(sentence, predicate) pair.

Real-data path (round 5): drop the reference's test split archive
`conll05st-tests.tar.gz` (members conll05st-release/test.wsj/words/
test.wsj.words.gz and .../props/test.wsj.props.gz) plus the three dict
files `wordDict.txt` / `verbDict.txt` / `targetDict.txt` under
$PADDLE_TPU_DATA/conll05st/. Parsing follows the reference: words and
props files zip line-by-line (blank line = sentence end), the props
lemma column names the predicates, per-predicate bracket tags convert
to BIO ('*'→O, '(X*'→B-X opening, '*)'→I-close, '(X*)'→single B-X),
and each (sentence, predicate) pair featurizes into the 9-slot record
with the five predicate-context windows and the ±2 mark vector.
Synthetic fallback keeps the 9-slot schema and label cardinality."""

import gzip
import os
import tarfile

import numpy as np

from . import common

WORD_DICT_LEN = 44068
LABEL_DICT_LEN = 59
PRED_DICT_LEN = 3162
_TRAIN_N = 1024
_TEST_N = 256
_MAX_LEN = 30

UNK_IDX = 0

ARCHIVE = 'conll05st-tests.tar.gz'
WORDS_NAME = 'conll05st-release/test.wsj/words/test.wsj.words.gz'
PROPS_NAME = 'conll05st-release/test.wsj/props/test.wsj.props.gz'
WORD_DICT_FILE = 'wordDict.txt'
VERB_DICT_FILE = 'verbDict.txt'
LABEL_DICT_FILE = 'targetDict.txt'


def _cached(name):
    return common.cached('conll05st', name)


def load_dict(filename):
    d = {}
    with open(filename) as f:
        for i, line in enumerate(f):
            d[line.strip()] = i
    return d


def _bracket_to_bio(tags):
    """One predicate's bracket column -> BIO sequence (reference
    :85-107)."""
    out = []
    cur = 'O'
    in_bracket = False
    for l in tags:
        if l == '*' and not in_bracket:
            out.append('O')
        elif l == '*' and in_bracket:
            out.append('I-' + cur)
        elif l == '*)':
            out.append('I-' + cur)
            in_bracket = False
        elif '(' in l and ')' in l:
            cur = l[1:l.find('*')]
            out.append('B-' + cur)
            in_bracket = False
        elif '(' in l and ')' not in l:
            cur = l[1:l.find('*')]
            out.append('B-' + cur)
            in_bracket = True
        else:
            raise RuntimeError('Unexpected label: %s' % l)
    return out


def corpus_reader(data_path, words_name=WORDS_NAME, props_name=PROPS_NAME):
    """Yields (sentence_words, predicate, bio_labels) per
    (sentence, predicate) pair."""
    def reader():
        with tarfile.open(data_path) as tf:
            wf = tf.extractfile(words_name)
            pf = tf.extractfile(props_name)
            with gzip.GzipFile(fileobj=wf) as words_file, \
                    gzip.GzipFile(fileobj=pf) as props_file:
                sentence = []
                columns = []       # per-token [lemma, tag1, tag2, ...]
                for word, label in zip(words_file, props_file):
                    word = word.decode('utf-8').strip()
                    label = label.decode('utf-8').strip().split()
                    if not label:  # blank line: end of sentence
                        if columns:
                            # transpose: column 0 = lemmas, 1.. = tags
                            cols = [[tok[i] for tok in columns]
                                    for i in range(len(columns[0]))]
                            verbs = [x for x in cols[0] if x != '-']
                            for i, tags in enumerate(cols[1:]):
                                yield (sentence, verbs[i],
                                       _bracket_to_bio(tags))
                        sentence = []
                        columns = []
                    else:
                        sentence.append(word)
                        columns.append(label)
    return reader


def reader_creator(corpus, word_dict, predicate_dict, label_dict):
    """The 9-slot featurization (reference :128-178)."""
    def reader():
        for sentence, predicate, labels in corpus():
            sen_len = len(sentence)
            verb_index = labels.index('B-V')
            mark = [0] * len(labels)

            def ctx(offset, default):
                i = verb_index + offset
                if 0 <= i < sen_len:
                    mark[i] = 1
                    return sentence[i]
                return default

            ctx_n2 = ctx(-2, 'bos')
            ctx_n1 = ctx(-1, 'bos')
            ctx_0 = ctx(0, None)
            ctx_p1 = ctx(1, 'eos')
            ctx_p2 = ctx(2, 'eos')

            word_idx = [word_dict.get(w, UNK_IDX) for w in sentence]

            def rep(w):
                return [word_dict.get(w, UNK_IDX)] * sen_len

            yield (word_idx, rep(ctx_n2), rep(ctx_n1), rep(ctx_0),
                   rep(ctx_p1), rep(ctx_p2),
                   [predicate_dict.get(predicate)] * sen_len, mark,
                   [label_dict.get(w) for w in labels])
    return reader


def get_dict():
    """(word_dict, verb_dict, label_dict) — real files when cached."""
    w, v, l = (_cached(WORD_DICT_FILE), _cached(VERB_DICT_FILE),
               _cached(LABEL_DICT_FILE))
    if w and v and l:
        return load_dict(w), load_dict(v), load_dict(l)
    word_dict = {('w%d' % i): i for i in range(WORD_DICT_LEN)}
    verb_dict = {('v%d' % i): i for i in range(PRED_DICT_LEN)}
    label_dict = {('l%d' % i): i for i in range(LABEL_DICT_LEN)}
    return word_dict, verb_dict, label_dict


def _reader(split, n):
    def reader():
        r = common.rng('conll05', split)
        for _ in range(n):
            length = int(r.randint(5, _MAX_LEN))
            words = r.randint(0, WORD_DICT_LEN, length).astype('int64')
            ctxs = [np.roll(words, k) for k in (-2, -1, 0, 1, 2)]
            verb = int(r.randint(0, PRED_DICT_LEN))
            verb_pos = int(r.randint(0, length))
            mark = np.zeros(length, dtype='int64')
            mark[verb_pos] = 1
            labels = r.randint(0, LABEL_DICT_LEN, length).astype('int64')
            yield (words,) + tuple(ctxs) + (
                np.full(length, verb, dtype='int64'), mark, labels)
    return reader


def train():
    # the reference's public release only ships the test.wsj split; a
    # cached archive therefore serves both creators, like its demo did
    return test() if _cached(ARCHIVE) else _reader('train', _TRAIN_N)


def test():
    tar = _cached(ARCHIVE)
    if tar:
        word_dict, verb_dict, label_dict = get_dict()
        return reader_creator(corpus_reader(tar), word_dict, verb_dict,
                              label_dict)
    return _reader('test', _TEST_N)
