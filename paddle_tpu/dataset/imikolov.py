"""PTB-style language-model n-grams (reference: v2/dataset/imikolov.py)."""

import numpy as np

from . import common

_VOCAB = 2048
_TRAIN_N = 8192
_TEST_N = 1024


def build_dict(min_word_freq=50):
    return {('w%d' % i): i for i in range(_VOCAB)}


def _synthetic(split, n, gram):
    """Markov-ish synthetic n-grams: next word correlates with previous."""
    r = common.rng('imikolov', split)
    first = r.randint(0, _VOCAB, size=n)
    rows = [first]
    for _ in range(gram - 1):
        nxt = (rows[-1] * 31 + 17 + r.randint(0, 64, size=n)) % _VOCAB
        rows.append(nxt)
    return np.stack(rows, axis=1).astype('int64')


def _reader(split, n, gram):
    def reader():
        grams = _synthetic(split, n, gram)
        for row in grams:
            yield tuple(int(v) for v in row)
    return reader


def train(word_idx=None, n=5):
    return _reader('train', _TRAIN_N, n)


def test(word_idx=None, n=5):
    return _reader('test', _TEST_N, n)
