"""PTB-style language-model n-grams (reference:
python/paddle/v2/dataset/imikolov.py:30-100).

Real-data path (round 5): drop `simple-examples.tgz` under
$PADDLE_TPU_DATA/imikolov/ and the readers parse with the reference
semantics: build_dict counts words over ptb.train.txt + ptb.valid.txt
(each line also counts one <s> and one <e>), drops the corpus's own
<unk>, keeps words with count > min_word_freq sorted by (-freq, word),
appends <unk> last; NGRAM mode frames each line <s> ... <e> and yields
every n-gram window, SEQ mode yields (<s>+ids, ids+<e>) pairs skipping
lines longer than n. Synthetic Markov-ish n-grams otherwise."""

import collections
import os
import tarfile

import numpy as np

from . import common

_VOCAB = 2048
_TRAIN_N = 8192
_TEST_N = 1024

ARCHIVE = 'simple-examples.tgz'
TRAIN_FILE = './simple-examples/data/ptb.train.txt'
TEST_FILE = './simple-examples/data/ptb.valid.txt'


class DataType(object):
    NGRAM = 1
    SEQ = 2


def _cached_tar():
    return common.cached('imikolov', ARCHIVE)


def word_count(f, word_freq=None):
    if word_freq is None:
        word_freq = collections.defaultdict(int)
    for line in f:
        for w in line.decode('utf-8').strip().split():
            word_freq[w] += 1
        word_freq['<s>'] += 1
        word_freq['<e>'] += 1
    return word_freq


def _member(tf, name):
    """Find a tar member tolerating a missing leading './' (archives
    differ in whether members carry it)."""
    try:
        return tf.extractfile(name)
    except KeyError:
        return tf.extractfile(name.lstrip('./'))


def build_dict(min_word_freq=50):
    tar = _cached_tar()
    if tar is None:
        return {('w%d' % i): i for i in range(_VOCAB)}
    with tarfile.open(tar) as tf:
        freq = word_count(_member(tf, TEST_FILE),
                          word_count(_member(tf, TRAIN_FILE)))
    freq.pop('<unk>', None)       # re-added as the LAST index below
    kept = [(w, c) for w, c in freq.items() if c > min_word_freq]
    kept.sort(key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx['<unk>'] = len(kept)
    return word_idx


def reader_creator(filename, word_idx, n, data_type):
    def reader():
        tar = _cached_tar()
        with tarfile.open(tar) as tf:
            unk = word_idx['<unk>']
            for raw in _member(tf, filename):
                words = raw.decode('utf-8').strip().split()
                if data_type == DataType.NGRAM:
                    assert n > -1, 'Invalid gram length'
                    framed = ['<s>'] + words + ['<e>']
                    if len(framed) >= n:
                        ids = [word_idx.get(w, unk) for w in framed]
                        for i in range(n, len(ids) + 1):
                            yield tuple(ids[i - n:i])
                elif data_type == DataType.SEQ:
                    ids = [word_idx.get(w, unk) for w in words]
                    src = [word_idx['<s>']] + ids
                    trg = ids + [word_idx['<e>']]
                    if n > 0 and len(src) > n:
                        continue
                    yield src, trg
                else:
                    raise ValueError('Unknown data_type %r' % data_type)
    return reader


def _synthetic(split, n, gram):
    """Markov-ish synthetic n-grams: next word correlates with previous."""
    r = common.rng('imikolov', split)
    first = r.randint(0, _VOCAB, size=n)
    rows = [first]
    for _ in range(gram - 1):
        nxt = (rows[-1] * 31 + 17 + r.randint(0, 64, size=n)) % _VOCAB
        rows.append(nxt)
    return np.stack(rows, axis=1).astype('int64')


def _reader(split, n, gram):
    def reader():
        grams = _synthetic(split, n, gram)
        for row in grams:
            yield tuple(int(v) for v in row)
    return reader


def train(word_idx=None, n=5, data_type=DataType.NGRAM):
    if _cached_tar():
        return reader_creator(TRAIN_FILE, word_idx or build_dict(), n,
                              data_type)
    return _reader('train', _TRAIN_N, n)


def test(word_idx=None, n=5, data_type=DataType.NGRAM):
    if _cached_tar():
        return reader_creator(TEST_FILE, word_idx or build_dict(), n,
                              data_type)
    return _reader('test', _TEST_N, n)
