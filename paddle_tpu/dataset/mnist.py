"""MNIST (reference: python/paddle/v2/dataset/mnist.py).
Real data when cached as mnist.npz; else class-structured synthetic digits
(each class = fixed template + noise) so LeNet actually learns."""

import os

import numpy as np

from . import common

_TRAIN_N = 8192
_TEST_N = 2048


def _load_real(split):
    path = common.cached_path('mnist', 'mnist.npz')
    if not os.path.exists(path):
        return None
    data = np.load(path)
    if split == 'train':
        return data['x_train'], data['y_train']
    return data['x_test'], data['y_test']


def _templates():
    r = common.rng('mnist', 'templates')
    return (r.rand(10, 28, 28) > 0.72).astype('float32')


def _synthetic(split, n):
    r = common.rng('mnist', split)
    t = _templates()
    labels = r.randint(0, 10, size=n)
    imgs = t[labels] + 0.25 * r.randn(n, 28, 28).astype('float32')
    imgs = np.clip(imgs, 0.0, 1.0)
    # normalize to [-1, 1] like the reference reader
    imgs = (imgs * 2.0 - 1.0).astype('float32')
    return imgs.reshape(n, 784), labels.astype('int64')


def _reader(split, n):
    def reader():
        real = _load_real(split)
        if real is not None:
            xs, ys = real
            xs = (xs.reshape(len(xs), 784).astype('float32') / 127.5) - 1.0
            ys = ys.astype('int64')
        else:
            xs, ys = _synthetic(split, n)
        for i in range(len(xs)):
            yield xs[i], int(ys[i])
    return reader


def train():
    return _reader('train', _TRAIN_N)


def test():
    return _reader('test', _TEST_N)
