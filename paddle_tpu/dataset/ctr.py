"""CTR dataset for Wide&Deep (analog of the reference's high-dim sparse
CTR workloads served by paddle/pserver; schema mirrors Criteo: 13 dense
ints + 26 categorical hashes -> click)."""

import numpy as np

from . import common

DENSE_DIM = 13
SPARSE_SLOTS = 26
HASH_DIM = 10 ** 4
_TRAIN_N = 8192
_TEST_N = 1024


def _synthetic(split, n):
    r = common.rng('ctr', split)
    dense = r.rand(n, DENSE_DIM).astype('float32')
    sparse = r.randint(0, HASH_DIM, size=(n, SPARSE_SLOTS)).astype('int64')
    w_d = common.rng('ctr', 'wd').randn(DENSE_DIM) * 0.5
    w_s = common.rng('ctr', 'ws').randn(HASH_DIM) * 0.1
    logit = dense @ w_d + w_s[sparse].sum(axis=1) - 1.0
    click = (1.0 / (1.0 + np.exp(-logit)) > r.rand(n)).astype('int64')
    return dense, sparse, click


def _reader(split, n):
    def reader():
        dense, sparse, click = _synthetic(split, n)
        for i in range(n):
            yield dense[i], sparse[i], int(click[i])
    return reader


def train():
    return _reader('train', _TRAIN_N)


def test():
    return _reader('test', _TEST_N)
