"""Pipeline parallelism: GPipe-style microbatch schedule over the 'pp'
mesh axis.

Reference analog: the pserver-era reference has no pipeline engine; this
is the TPU-native design the transpiler targets (SURVEY.md §2.4): stage
parameters are stacked on a leading stage dim sharded over 'pp', every
device runs the SAME stage_fn (SPMD), and activations hop stage→stage via
`ppermute` while microbatches stream in — the classic bubble schedule
(n_micro + n_stages - 1 ticks). Differentiable end-to-end: ppermute's
transpose is the reverse permute, so jax.grad recovers the usual
backward pipeline.
"""

import jax
import jax.numpy as jnp


def pipeline(stage_fn, stage_params, microbatches, axis_name='pp'):
    """Run inside shard_map over `axis_name`.

    stage_fn(params, x) -> y           one pipeline stage (same shape in/out)
    stage_params: pytree whose leaves are this device's stage params
                  (leading stage dim already stripped by shard_map)
    microbatches: [n_micro, mb, ...]   replicated input microbatches
    Returns [n_micro, mb, ...] final-stage outputs (valid on the LAST
    stage; other stages hold garbage — combine with out_specs that index
    the last shard, or psum-mask as convenient).
    """
    n_stages = jax.lax.axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    total = n_micro + n_stages - 1
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(buf, t):
        # stage 0 ingests microbatch t (clamped; masked later)
        mb = microbatches[jnp.clip(t, 0, n_micro - 1)]
        x = jnp.where(stage == 0, mb, buf)
        y = stage_fn(stage_params, x)
        nxt = jax.lax.ppermute(y, axis_name, fwd_perm)
        return nxt, y

    # mark the carry varying over pp (ppermute outputs are varying; an
    # unvarying init would make the scan carry types mismatch)
    buf0 = jax.lax.pvary(jnp.zeros_like(microbatches[0]), (axis_name,))
    _, ys = jax.lax.scan(tick, buf0, jnp.arange(total))
    # last stage emits microbatch m at tick m + n_stages - 1
    out = jax.lax.dynamic_slice_in_dim(ys, n_stages - 1, n_micro, axis=0)
    return out


def pipelined_apply(stage_fn, stacked_params, x, n_micro, mesh,
                    axis_name='pp'):
    """Host-level convenience: shard_map-wrap `pipeline` over `mesh`.

    stacked_params: pytree with leading dim n_stages (will shard on pp).
    x: [batch, ...] global input; split into n_micro microbatches.
    Returns [batch, ...] output of the whole stage stack.
    """
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    assert batch % n_micro == 0, 'batch must divide into microbatches'
    mb_x = x.reshape((n_micro, batch // n_micro) + x.shape[1:])

    param_specs = jax.tree.map(
        lambda _: P(*((axis_name,) + (None,) * (_.ndim - 1))),
        stacked_params)
    mb_axes = (None,) * (mb_x.ndim)

    def inner(params, mb):
        # shard_map keeps the sharded stage dim as size 1 — strip it
        params = jax.tree.map(lambda p: p[0], params)
        out = pipeline(stage_fn, params, mb, axis_name)
        # emit only the last stage's result; zeros elsewhere so a psum
        # over pp reconstructs the true output on every device.
        is_last = jax.lax.axis_index(axis_name) == \
            jax.lax.axis_size(axis_name) - 1
        out = jnp.where(is_last, out, jnp.zeros_like(out))
        return jax.lax.psum(out, axis_name)

    mapped = jax.shard_map(
        inner, mesh=mesh,
        in_specs=(param_specs, P(*mb_axes)),
        out_specs=P(*mb_axes), check_vma=False)
    out = mapped(jax.tree.map(jnp.asarray, stacked_params), mb_x)
    return out.reshape((batch,) + out.shape[2:])
