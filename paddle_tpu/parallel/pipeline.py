"""Pipeline parallelism: GPipe-style microbatch schedule over the 'pp'
mesh axis.

Reference analog: the pserver-era reference has no pipeline engine; this
is the TPU-native design the transpiler targets (SURVEY.md §2.4): stage
parameters are stacked on a leading stage dim sharded over 'pp', every
device runs the SAME stage_fn (SPMD), and activations hop stage→stage via
`ppermute` while microbatches stream in — the classic bubble schedule
(n_micro + n_stages - 1 ticks). Differentiable end-to-end: ppermute's
transpose is the reverse permute, so jax.grad recovers the usual
backward pipeline.
"""

import jax
import jax.numpy as jnp


def pipeline(stage_fn, stage_params, microbatches, axis_name='pp',
             with_mb_index=False, with_aux=False):
    """Run inside shard_map over `axis_name`.

    stage_fn(params, x) -> y           one pipeline stage (same shape in/out)
    stage_params: pytree whose leaves are this device's stage params
                  (leading stage dim already stripped by shard_map)
    microbatches: [n_micro, mb, ...]   replicated input microbatches
    with_mb_index: call stage_fn(params, x, m) where m is the index of
    the microbatch this stage processes at this tick (t - stage,
    clamped) — lets the stage fold m into dropout PRNG keys so masks
    stay per-microbatch, matching the semantics of one big batch split
    into n_micro pieces.
    with_aux: stage_fn additionally returns a scalar auxiliary loss
    (MoE load-balancing); contributions are summed over this stage's
    VALID ticks only (warm-up/cool-down ticks process clamped garbage
    microbatches and must not pollute the total) and returned as the
    second output — psum over the pipe and divide by n_micro to
    recover the full-batch mean.
    Returns [n_micro, mb, ...] final-stage outputs (valid on the LAST
    stage; other stages hold garbage — combine with out_specs that index
    the last shard, or psum-mask as convenient); with_aux returns
    (outputs, aux_sum).
    """
    from .collective import axis_size
    n_stages = axis_size(axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = microbatches.shape[0]
    total = n_micro + n_stages - 1
    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        buf, aux_acc = carry
        # stage 0 ingests microbatch t (clamped; masked later)
        mb = microbatches[jnp.clip(t, 0, n_micro - 1)]
        x = jnp.where(stage == 0, mb, buf)
        args = (stage_params, x)
        if with_mb_index:
            args = args + (jnp.clip(t - stage, 0, n_micro - 1),)
        y = stage_fn(*args)
        if with_aux:
            y, aux = y
            valid = (t >= stage) & (t - stage < n_micro)
            aux_acc = aux_acc + jnp.where(valid, aux, 0.0)
        nxt = jax.lax.ppermute(y, axis_name, fwd_perm)
        return (nxt, aux_acc), y

    # mark the carry varying over pp (ppermute outputs are varying; an
    # unvarying init would make the scan carry types mismatch).
    # pcast(to='varying') is the post-0.9 spelling of pvary; fall back
    # for older jax so the module imports everywhere. Pre-vma jax has
    # neither and needs no marking — the carry types already match.
    def _mark_varying(x):
        if hasattr(jax.lax, 'pcast'):
            return jax.lax.pcast(x, (axis_name,), to='varying')
        if hasattr(jax.lax, 'pvary'):
            return jax.lax.pvary(x, (axis_name,))
        return x

    buf0 = _mark_varying(jnp.zeros_like(microbatches[0]))
    aux0 = _mark_varying(jnp.zeros((), jnp.float32))
    (_, aux_sum), ys = jax.lax.scan(tick, (buf0, aux0),
                                    jnp.arange(total))
    # last stage emits microbatch m at tick m + n_stages - 1
    out = jax.lax.dynamic_slice_in_dim(ys, n_stages - 1, n_micro, axis=0)
    if with_aux:
        return out, aux_sum
    return out


def pipelined_apply(stage_fn, stacked_params, x, n_micro, mesh,
                    axis_name='pp'):
    """Host-level convenience: shard_map-wrap `pipeline` over `mesh`.

    stacked_params: pytree with leading dim n_stages (will shard on pp).
    x: [batch, ...] global input; split into n_micro microbatches.
    Returns [batch, ...] output of the whole stage stack.
    """
    from jax.sharding import PartitionSpec as P

    n_stages = mesh.shape[axis_name]
    batch = x.shape[0]
    assert batch % n_micro == 0, 'batch must divide into microbatches'
    mb_x = x.reshape((n_micro, batch // n_micro) + x.shape[1:])

    param_specs = jax.tree.map(
        lambda _: P(*((axis_name,) + (None,) * (_.ndim - 1))),
        stacked_params)
    mb_axes = (None,) * (mb_x.ndim)

    def inner(params, mb):
        # shard_map keeps the sharded stage dim as size 1 — strip it
        params = jax.tree.map(lambda p: p[0], params)
        out = pipeline(stage_fn, params, mb, axis_name)
        # emit only the last stage's result; zeros elsewhere so a psum
        # over pp reconstructs the true output on every device.
        from .collective import axis_size
        is_last = jax.lax.axis_index(axis_name) == \
            axis_size(axis_name) - 1
        out = jnp.where(is_last, out, jnp.zeros_like(out))
        return jax.lax.psum(out, axis_name)

    from .mesh import compat_shard_map
    mapped = compat_shard_map(
        inner, mesh=mesh,
        in_specs=(param_specs, P(*mb_axes)),
        out_specs=P(*mb_axes), check_vma=False)
    out = mapped(jax.tree.map(jnp.asarray, stacked_params), mb_x)
    return out.reshape((batch,) + out.shape[2:])


def pipeline_layer_scan(make_body, x, xs, mesh, n_micro, extras=(),
                        axis_name='pp', aux=False):
    """Pipeline a scan-over-layers op body over `mesh`'s pp axis — the
    Program-level pipeline path (a transformer_layer_stack op whose
    program was transpiled with ParallelStrategy(pipeline_parallel=True)
    lands here instead of one flat lax.scan).

    The [n_layer, ...] stacked weight pytree `xs` is read as n_stages
    contiguous chunks of n_layer/n_stages layers (shard_map splits the
    leading axis over 'pp'); each device's stage scans its local layers,
    activations hop stage->stage via the GPipe schedule in `pipeline`.
    Differentiable end-to-end, so the executor's value_and_grad recovers
    the backward pipeline and grads come back pp-sharded like their
    params (the transpiler pins both).

    make_body(ext_m, m) -> body(h, slice) builds the per-layer scan body:
    `ext_m` is the microbatch-m slice of `extras` (batch-aligned side
    inputs — a decoder stack's enc_out / src_length) and `m` is the
    microbatch index, for folding into dropout keys.

    x: [batch, ...] activations; batch must divide n_micro. The
    shard_map is MANUAL over 'pp' only (axis_names={'pp'}): every other
    mesh axis stays compiler-managed inside the stage, so 'dp' batch
    sharding flows through untouched and intra-stage 'tp' (Megatron
    column/row splits of the stacked weights, P('pp', None, 'tp') /
    P('pp', 'tp', None) from the transpiler) gets its psums from GSPMD
    — the scaling-book pp x tp composition with no hand collectives.
    """
    from jax.sharding import PartitionSpec as P

    mesh_shape = dict(mesh.shape)
    n_stages = mesh_shape[axis_name]
    n_layer = jax.tree.leaves(xs)[0].shape[0]
    if n_layer % n_stages:
        raise ValueError(
            'pipeline_layer_scan: n_layer %d not divisible by pp=%d'
            % (n_layer, n_stages))
    batch = x.shape[0]
    if batch % n_micro:
        raise ValueError(
            'pipeline_layer_scan: batch %d not divisible by n_micro %d'
            % (batch, n_micro))
    mb = batch // n_micro
    mb_x = x.reshape((n_micro, mb) + x.shape[1:])
    # batch-aligned side inputs are microbatched the same way; the stage
    # picks row-block m so cross attention sees ITS examples' memory
    mb_extras = jax.tree.map(
        lambda e: e.reshape((n_micro, mb) + e.shape[1:]), extras)

    # specs constrain the MANUAL axis only: stage dim of the stacked
    # weights on pp, activations replicated over pp (stage 0 ingests)
    param_specs = jax.tree.map(
        lambda a: P(*((axis_name,) + (None,) * (a.ndim - 1))), xs)

    def inner(local_xs, mbx, ext):
        def stage_fn(local, h, m):
            ext_m = jax.tree.map(lambda e: e[m], ext)
            body = make_body(ext_m, m)
            if aux:
                # body carry is (h, aux_sum) — MoE stacks accumulate
                # their per-layer load-balancing loss through the scan
                (out, a), _ = jax.lax.scan(
                    body, (h, jnp.zeros((), jnp.float32)), local)
                return out, a
            out, _ = jax.lax.scan(body, h, local)
            return out

        res = pipeline(stage_fn, local_xs, mbx, axis_name,
                       with_mb_index=True, with_aux=aux)
        out, aux_sum = res if aux else (res, None)
        # emit only the last stage's result; zeros elsewhere so the psum
        # over pp reconstructs the true output on every device
        is_last = jax.lax.axis_index(axis_name) == n_stages - 1
        out = jnp.where(is_last, out, jnp.zeros_like(out))
        out = jax.lax.psum(out, axis_name)
        if aux:
            # each stage summed its own layers' aux over its n_micro
            # valid ticks; psum totals the pipe, /n_micro recovers the
            # full-batch per-token mean the unpipelined scan computes
            return out, jax.lax.psum(aux_sum, axis_name) / n_micro
        return out

    out_specs = (P(), P()) if aux else P()
    from .mesh import compat_shard_map
    mapped = compat_shard_map(
        inner, mesh=mesh, axis_names=frozenset({axis_name}),
        in_specs=(param_specs, P(), jax.tree.map(lambda _: P(),
                                                 mb_extras)),
        out_specs=out_specs, check_vma=False)
    res = mapped(xs, mb_x, mb_extras)
    out, aux_total = res if aux else (res, None)
    out = out.reshape((batch,) + out.shape[2:])
    if aux:
        return out, aux_total
    return out
