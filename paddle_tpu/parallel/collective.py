"""Functional collectives (reference: paddle/pserver gradient aggregation,
NCCL allreduce in ParallelExecutor). Thin wrappers over jax.lax for use
inside shard_map bodies and custom kernels."""

import jax


def all_reduce(x, axis_name='dp', op='sum'):
    if op == 'sum':
        return jax.lax.psum(x, axis_name)
    if op == 'mean':
        return jax.lax.pmean(x, axis_name)
    if op == 'max':
        return jax.lax.pmax(x, axis_name)
    if op == 'min':
        return jax.lax.pmin(x, axis_name)
    raise ValueError('unsupported all_reduce op %r' % op)


def all_gather(x, axis_name='tp', axis=0):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)


def reduce_scatter(x, axis_name='tp', axis=0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def all_to_all(x, axis_name='sp', split_axis=0, concat_axis=0):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def broadcast(x, axis_name, root=0):
    import jax.numpy as jnp
    idx = jax.lax.axis_index(axis_name)
    return jax.lax.psum(jnp.where(idx == root, x, jnp.zeros_like(x)),
                        axis_name)
