"""Functional collectives (reference: paddle/pserver gradient aggregation,
NCCL allreduce in ParallelExecutor). Thin wrappers over jax.lax for use
inside shard_map bodies and custom kernels, plus the quantized
allreduce schedule (PAPERS "EQuARX: Efficient Quantized AllReduce in
XLA") the trainer's dp gradient path models, and the gradient-bucketing
policy/assignment the executor's bucketed-allreduce path uses
(``PADDLE_TPU_GRAD_BUCKET_MB`` — read per call, repo_lint enforced)."""

import os

import jax


# ------------------------------------------------- gradient bucketing
def grad_bucket_policy(program=None):
    """Per-call resolver for the gradient-allreduce bucketing knob.

    Precedence mirrors ``quant.core.grad_allreduce_policy``: an explicit
    ``PADDLE_TPU_GRAD_BUCKET_MB`` env value wins in either direction
    ('0'/'off' disables; a number is the per-bucket size target in MB);
    when unset, the program's ``grad_bucket_mb`` attribute (set by
    ``ParallelStrategy(grad_bucket_mb=...)``) decides. Returns a
    hashable policy tuple ``('mb', size_mb)`` — folded into the
    executor's compile-cache key so flipping the env recompiles instead
    of silently reusing the other mode — or None when off."""
    raw = os.environ.get('PADDLE_TPU_GRAD_BUCKET_MB')
    if raw is None or raw.strip() == '':
        mb = getattr(program, 'grad_bucket_mb', None)
    else:
        s = raw.strip().lower()
        mb = None if s in ('0', 'off', 'false') else float(s)
    if mb is None or float(mb) <= 0:
        return None
    return ('mb', float(mb))


def assign_grad_buckets(items, target_bytes):
    """Deterministic size-targeted bucket assignment.

    ``items`` is ``[(size_bytes, group), ...]`` in PARAMETER ORDER (the
    forward order); the walk runs in REVERSE — the backward produces
    gradients roughly last-layer-first, so reversed parameter order
    approximates production order and the first bucket closes (and its
    collective can issue) while earlier layers are still
    differentiating. Greedy: a bucket closes when adding the next
    gradient would exceed ``target_bytes`` (a single oversized gradient
    gets its own bucket) or when the group key changes (buckets never
    mix groups — concatenation must not promote dtypes). Returns a list
    of buckets, each a list of original item indices; pure and
    deterministic, so trace and re-trace agree bit-for-bit."""
    target = max(1, int(target_bytes))
    buckets = []
    cur, cur_bytes, cur_group = [], 0, None
    for i in reversed(range(len(items))):
        size, group = items[i]
        size = int(size)
        if cur and (cur_bytes + size > target or group != cur_group):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += size
        cur_group = group
    if cur:
        buckets.append(cur)
    return buckets


def _axis_size(axis_name):
    """Concrete size of a named axis inside shard_map/pmap. Newer jax
    has jax.lax.axis_size; elsewhere a psum of a python literal
    constant-folds to the axis extent."""
    size = getattr(jax.lax, 'axis_size', None)
    if size is not None:
        return size(axis_name)
    return jax.lax.psum(1, axis_name)


axis_size = _axis_size


def all_reduce(x, axis_name='dp', op='sum'):
    if op == 'sum':
        return jax.lax.psum(x, axis_name)
    if op == 'mean':
        return jax.lax.pmean(x, axis_name)
    if op == 'max':
        return jax.lax.pmax(x, axis_name)
    if op == 'min':
        return jax.lax.pmin(x, axis_name)
    raise ValueError('unsupported all_reduce op %r' % op)


def quantized_all_reduce(x, axis_name='dp', op='sum', block=256,
                         key=None):
    """Block-scaled int8 allreduce (EQuARX schedule, explicit form):

    1. quantize the local tensor per-``block`` to int8 (+ one fp32
       scale per block; stochastic rounding when ``key`` is given),
    2. **reduce_scatter in int8**: an all_to_all hands every device
       the n peer copies of its own block shard — int8 payload plus
       the fp32 scale sideband is all that crosses the wire,
    3. **fp32 accumulate**: each device dequantizes its n received
       copies and sums them in fp32,
    4. **all_gather of requantized shards**: the reduced shard is
       requantized to int8 and gathered, so the return leg is int8
       too; every device dequantizes the full result.

    Wire bytes per device ≈ 2·(n-1)/n·nelem·(1 + 4/block) vs the fp32
    ring's 2·(n-1)/n·nelem·4 — ~3.94x less at block=256 (the analytic
    model in quant.core.quantized_allreduce_wire_bytes, asserted by
    bench.py --workload quant). The result is identical on every
    device (rounding keys fold the sender's axis index, and the final
    gather is of already-rounded shards).

    ``op``: 'sum' or 'mean'. ``key=None`` rounds to nearest
    (deterministic); a PRNG key switches to unbiased stochastic
    rounding — what gradient traffic wants."""
    import jax.numpy as jnp

    from ..quant import core as _q

    if op not in ('sum', 'mean'):
        raise ValueError('quantized_all_reduce supports sum/mean, got '
                         '%r' % op)
    n = _axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    orig_dtype, orig_shape = x.dtype, x.shape
    flat = x.astype(jnp.float32).reshape(-1)
    numel = flat.shape[0]
    # pad so the block count divides the axis (every device owns an
    # equal shard of blocks)
    nblocks = -(-max(numel, 1) // block)
    nblocks = -(-nblocks // n) * n
    pad = nblocks * block - numel
    if pad:
        flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(nblocks, block)
    scales = jnp.maximum(jnp.max(jnp.abs(blocks), axis=1), 1e-30) \
        / _q.QMAX_INT8
    k1 = k2 = None
    if key is not None:
        k1 = jax.random.fold_in(key, me)
        k2 = jax.random.fold_in(k1, 1)
    q = _q._round_int8(blocks / scales[:, None], k1)

    # (2) int8 reduce_scatter: row-shard j of q goes to device j; the
    # received rows group as [n peers, my nblocks/n blocks, block]
    qr = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                            tiled=True)
    sr = jax.lax.all_to_all(scales, axis_name, split_axis=0,
                            concat_axis=0, tiled=True)
    shard_blocks = nblocks // n
    parts = qr.reshape(n, shard_blocks, block).astype(jnp.float32) \
        * sr.reshape(n, shard_blocks, 1)
    shard = parts.sum(axis=0)                      # (3) fp32 accumulate

    # (4) requantize the reduced shard, gather int8
    s2 = jnp.maximum(jnp.max(jnp.abs(shard), axis=1), 1e-30) \
        / _q.QMAX_INT8
    q2 = _q._round_int8(shard / s2[:, None], k2)
    qg = jax.lax.all_gather(q2, axis_name, axis=0, tiled=True)
    sg = jax.lax.all_gather(s2, axis_name, axis=0, tiled=True)
    out = (qg.astype(jnp.float32) * sg[:, None]).reshape(-1)
    if pad:
        out = out[:numel]
    if op == 'mean':
        out = out / n
    return out.reshape(orig_shape).astype(orig_dtype)


def all_gather(x, axis_name='tp', axis=0):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)


def reduce_scatter(x, axis_name='tp', axis=0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def all_to_all(x, axis_name='sp', split_axis=0, concat_axis=0):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def broadcast(x, axis_name, root=0):
    """Root's value on every device, by recursive doubling: ceil(log2 n)
    ppermute hops, each device selecting the received value exactly
    when the hop reaches it. O(1) compute per element — the previous
    psum(where(...)) formulation materialized a zeros tensor per
    device and paid a full N-way reduction tree for what is pure
    data movement."""
    import jax.numpy as jnp
    n = _axis_size(axis_name)
    if n == 1:
        return x
    idx = jax.lax.axis_index(axis_name)
    rel = (idx - root) % n                 # distance from the root
    val = x
    hop = 1
    while hop < n:
        recv = jax.lax.ppermute(
            val, axis_name, [(i, (i + hop) % n) for i in range(n)])
        take = (rel >= hop) & (rel < 2 * hop)
        val = jnp.where(take, recv, val)
        hop *= 2
    return val
