"""Device mesh construction.

Axes convention (scaling-book style):
  dp — data parallel (batch)          — outermost, DCN-friendly
  pp — pipeline stages
  tp — tensor parallel (hidden dims)  — innermost, ICI-bandwidth-hungry
  sp — sequence/context parallel (ring attention)
  ep — expert parallel (MoE)
"""

import numpy as np

AXES = ('dp', 'pp', 'sp', 'tp', 'ep')


class MeshConfig(object):
    def __init__(self, dp=1, pp=1, sp=1, tp=1, ep=1):
        self.sizes = {'dp': dp, 'pp': pp, 'sp': sp, 'tp': tp, 'ep': ep}

    @property
    def total(self):
        n = 1
        for v in self.sizes.values():
            n *= v
        return n

    def active_axes(self):
        return [a for a in AXES if self.sizes[a] > 1]

    def to_dict(self):
        """JSON-able {axis: size} — the form checkpoints record."""
        return {a: int(self.sizes[a]) for a in AXES}

    @classmethod
    def from_mesh(cls, mesh):
        """MeshConfig describing a jax Mesh's canonical axes (a mesh of
        None or without an axis means size 1 there)."""
        sizes = axis_sizes(mesh)
        return cls(**{a: sizes[a] for a in AXES})


def axis_sizes(mesh):
    """Canonical {axis: size} of a jax Mesh: every AXES entry present
    (missing -> 1), extra axis names preserved. None -> the unsharded
    all-ones topology. This is the topology signature checkpoints
    record and elastic restore compares."""
    sizes = {a: 1 for a in AXES}
    if mesh is not None:
        for a, s in dict(mesh.shape).items():
            sizes[str(a)] = int(s)
    return sizes


def make_mesh(dp=None, pp=1, sp=1, tp=1, ep=1, devices=None):
    """Build a jax Mesh. dp=None means 'use all remaining devices'."""
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    rest = pp * sp * tp * ep
    if dp is None:
        if n % rest:
            raise ValueError('device count %d not divisible by pp*sp*tp*ep'
                             ' = %d' % (n, rest))
        dp = n // rest
    total = dp * rest
    if total > n:
        raise ValueError('mesh needs %d devices, have %d' % (total, n))
    dev_array = np.asarray(devices[:total]).reshape(dp, pp, sp, tp, ep)
    return Mesh(dev_array, AXES)


def compat_shard_map(fn, mesh=None, in_specs=None, out_specs=None,
                     axis_names=None, check_vma=None):
    """jax.shard_map across the jax versions this repo supports.

    Newer jax exports ``jax.shard_map`` (optional mesh, partial-manual
    via ``axis_names``, varying-axis checking via ``check_vma``);
    older jax only has ``jax.experimental.shard_map.shard_map`` with a
    required mesh, ``auto`` as the complement of the manual axis set,
    and ``check_rep`` as the checker knob. One wrapper so callers
    never branch on version."""
    import jax
    sm = getattr(jax, 'shard_map', None)
    if sm is not None:
        kwargs = dict(in_specs=in_specs, out_specs=out_specs)
        if mesh is not None:
            kwargs['mesh'] = mesh
        if axis_names is not None:
            kwargs['axis_names'] = frozenset(axis_names)
        if check_vma is not None:
            kwargs['check_vma'] = check_vma
        return sm(fn, **kwargs)
    from jax.experimental.shard_map import shard_map as _esm
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if check_vma is not None:
        kwargs['check_rep'] = check_vma
    if axis_names is not None and mesh is not None:
        auto = frozenset(str(a) for a in dict(mesh.shape)) \
            - frozenset(axis_names)
        if auto:
            kwargs['auto'] = auto
    return _esm(fn, **kwargs)


def single_axis_mesh(axis='dp', devices=None):
    kwargs = {a: 1 for a in AXES if a != axis}
    return make_mesh(**{axis: None if axis == 'dp' else None}, **kwargs) \
        if axis == 'dp' else make_mesh(dp=1, **{axis: _all(devices)})


def _all(devices):
    import jax
    return len(devices if devices is not None else jax.devices())
