"""ParallelExecutor facade (reference: the fluid ParallelExecutor —
paddle/fluid/framework/parallel_executor.cc + python ParallelExecutor —
which replicated a Program over CUDA devices and allreduced grads with
NCCL).

TPU-native: there is nothing to replicate by hand — transpile() attaches
shardings and Executor's GSPMD path compiles ONE program whose
collectives ride the ICI mesh. This class keeps the reference's API
shape (build, run(fetch_list), bcast semantics are implicit) so fluid
ParallelExecutor call sites port unchanged.
"""

from ..core.executor import Executor
from ..core.program import default_main_program
from .mesh import make_mesh
from .transpiler import ParallelStrategy, transpile

__all__ = ['ParallelExecutor']


class ParallelExecutor(object):
    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, num_threads=None, mesh=None,
                 strategy=None, place=None):
        self.program = main_program if main_program is not None \
            else default_main_program()
        if mesh is None:
            mesh = make_mesh()  # dp over all visible devices
        self.mesh = mesh
        transpile(self.program, mesh,
                  strategy or ParallelStrategy(data_parallel=True))
        # share_vars_from: the reference shares device-replicated params
        # with another ParallelExecutor; scope state is global here, so
        # sharing is automatic — accept and ignore.
        self.exe = share_vars_from.exe if share_vars_from is not None \
            else Executor(place)
        self._loss_name = loss_name

    @property
    def device_count(self):
        return self.mesh.size

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        """feed batches are GLOBAL (the dp axis shards them across the
        mesh); fetches are replicated results, matching the reference's
        gathered fetch."""
        feed = feed if feed is not None else feed_dict
        return self.exe.run(program=self.program, feed=feed or {},
                            fetch_list=list(fetch_list),
                            return_numpy=return_numpy)

    def bcast_params(self):
        # GSPMD keeps replicated params consistent by construction (the
        # grad psum is part of the compiled step); nothing to broadcast.
        return None
