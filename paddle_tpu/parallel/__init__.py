"""SPMD parallelism over a TPU device mesh.

Replaces the reference's parameter-server stack (paddle/pserver, go/pserver,
fluid DistributeTranspiler, ParallelExecutor + NCCL) with GSPMD: build a
Mesh, attach PartitionSpecs to program vars, and let XLA insert collectives
over ICI/DCN (SURVEY.md §2.4).
"""

from .mesh import make_mesh, MeshConfig  # noqa: F401
from .transpiler import DistributeTranspiler, ParallelStrategy, transpile  # noqa: F401
from .collective import (all_gather, all_reduce, all_to_all, broadcast,  # noqa
                         ppermute, reduce_scatter)
from .ring_attention import ring_attention  # noqa: F401
from .pipeline import pipeline, pipelined_apply  # noqa: F401
from .executor import ParallelExecutor  # noqa: F401
from . import multihost  # noqa: F401
