"""Multi-host (pod-scale) runtime glue.

Reference analog: the reference trains multi-machine through MPI job
scripts + parameter servers over RDMA (paddle/pserver, go/pserver). The
TPU-native equivalent is jax.distributed: every host runs the SAME SPMD
program, jax.devices() spans the pod, and the Mesh lays DCN-crossing
axes (dp) outermost while ICI-hungry axes (tp/sp) stay inside a host's
slice (scaling-book recipe).

Environment contracts supported (first match wins):
- explicit args to init_distributed()
- PADDLE_TRAINERS / PADDLE_TRAINER_ID / PADDLE_COORDINATOR (reference
  fleet-style env names)
- TPU pod metadata (jax.distributed.initialize() with no args)
"""

import os
import threading
import time

from .. import observe as _obs

__all__ = ['init_distributed', 'is_initialized', 'global_device_mesh',
           'host_local_batch', 'process_index', 'process_count',
           'shard_reader', 'barrier']

_initialized = False


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Initialize jax.distributed for multi-host training. Safe to call
    on single host (no-op when no cluster env is present)."""
    global _initialized
    import jax
    if _initialized:
        return True
    if coordinator_address is None:
        coordinator_address = os.environ.get('PADDLE_COORDINATOR')
    if num_processes is None and os.environ.get('PADDLE_TRAINERS'):
        num_processes = int(os.environ['PADDLE_TRAINERS'])
    if process_id is None and os.environ.get('PADDLE_TRAINER_ID'):
        process_id = int(os.environ['PADDLE_TRAINER_ID'])
    if coordinator_address is not None:
        # An explicit coordinator means the caller REQUIRES the cluster:
        # failing to join must surface (a silent single-host fallback would
        # train on duplicate data and wrong global batch).
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
        _initialized = True
    elif num_processes is not None and num_processes > 1:
        try:
            jax.distributed.initialize()
            _initialized = True
        except Exception as e:
            # auto-detect path only: no pod metadata → single-host
            # fallback; everything below still works on local devices.
            # Loudly though — a pod with broken metadata would silently
            # train single-host on duplicate data otherwise.
            import warnings
            warnings.warn(
                'init_distributed: %d processes requested (num_processes '
                'arg or PADDLE_TRAINERS) but jax.distributed auto-init '
                'failed (%s: %s); continuing SINGLE-HOST — if this is a '
                'real cluster, set PADDLE_COORDINATOR to make joining '
                'mandatory' % (num_processes, type(e).__name__,
                               str(e)[:200]))
            _initialized = False
    return _initialized


def is_initialized():
    return _initialized


def process_index():
    import jax
    return jax.process_index()


def process_count():
    import jax
    return jax.process_count()


def global_device_mesh(pp=1, sp=1, tp=1, ep=1):
    """Pod-wide mesh: dp spans hosts (DCN-friendly outer axis); pp/sp/tp/
    ep subdivide within the pod slice (ICI). dp is inferred from the
    global device count."""
    from .mesh import make_mesh
    return make_mesh(dp=None, pp=pp, sp=sp, tp=tp, ep=ep)


def host_local_batch(global_batch):
    """Per-host slice size of a dp-sharded global batch."""
    import jax
    n = jax.process_count()
    if global_batch % n:
        raise ValueError('global batch %d not divisible by %d hosts'
                         % (global_batch, n))
    return global_batch // n


def barrier(tag, timeout=None):
    """Timeout-bounded pod-wide barrier (checkpoint commits must be
    single-writer + barrier, but an UNBOUNDED barrier turns one
    preempted host into a pod-wide hang). Raises TimeoutError when the
    sync does not complete within `timeout` seconds (default from
    PADDLE_TPU_BARRIER_TIMEOUT_SECS, 600) so the survivors can exit and
    be restarted to resume from the newest complete checkpoint.
    Single-process: no-op. timeout<=0 means wait forever."""
    import jax
    if jax.process_count() == 1:
        return
    if timeout is None:
        timeout = float(os.environ.get(
            'PADDLE_TPU_BARRIER_TIMEOUT_SECS', '600'))
    from jax.experimental import multihost_utils
    # per-tag wait histogram: the straggler detector — a host whose
    # peers' barrier waits grow is the slow one (observe enabled runs)
    t0 = time.perf_counter()
    if timeout <= 0:
        with _obs.span('multihost.barrier', tag=tag):
            multihost_utils.sync_global_devices(tag)
        _obs.record('multihost.barrier_wait_seconds',
                    time.perf_counter() - t0, tag=tag)
        return
    errbox = []

    def _sync():
        try:
            multihost_utils.sync_global_devices(tag)
        except BaseException as e:
            errbox.append(e)

    # the caller blocks on join(), so the sync never overlaps training
    # collectives; the thread only exists to make the wait interruptible
    t = threading.Thread(target=_sync, daemon=True,
                         name='paddle_tpu_barrier')
    t.start()
    with _obs.span('multihost.barrier', tag=tag):
        t.join(timeout)
    if t.is_alive():
        _obs.inc('multihost.barrier_timeout_total', tag=tag)
        _obs.flight_event('barrier_timeout', tag=tag,
                          timeout_seconds=timeout)
        raise TimeoutError(
            'barrier %r: pod sync did not complete within %.0fs — a peer '
            'host likely died or was preempted mid-checkpoint; restart '
            'the job and resume from the newest complete checkpoint'
            % (tag, timeout))
    _obs.record('multihost.barrier_wait_seconds',
                time.perf_counter() - t0, tag=tag)
    if errbox:
        raise errbox[0]


def shard_reader(reader, drop_uneven=True):
    """Shard a reader stream across hosts: host i of n yields samples
    i, i+n, ... (reader.decorator.shard keyed on jax.process_index).
    Without this every host would feed the SAME batches — dp over hosts
    would silently train on n duplicate epochs (go/master/service.go is
    the reference's answer; ours is positional, masterless)."""
    import jax
    n = jax.process_count()
    if n == 1:
        return reader
    from ..reader.state import CheckpointableReader
    if isinstance(reader, CheckpointableReader):
        # the wrapper pulls n global items per per-host yield: record
        # the width so a checkpoint's (offset, pending) pair stays in
        # global stream units — valid at this host count or, after an
        # elastic resume, any other (reader/state.py state_dict)
        reader.shard_width = n
    from ..reader.decorator import shard
    return shard(reader, n, jax.process_index(), drop_uneven=drop_uneven)
