"""Ring attention: exact attention over sequence-sharded inputs.

Long-context design (SURVEY.md §2.4): Q/K/V are sharded over the 'sp' mesh
axis on the time dimension. Each step computes a local block of scores
while K/V blocks rotate around the ring via ppermute, overlapping compute
with ICI transfers; running max/denominator accumulators keep the softmax
exact (the flash-attention recurrence, distributed).
"""

import jax
import jax.numpy as jnp


def _block_attn(q, k, v, bias=None):
    """One block of scores -> (unnormalized out, running max, denom)."""
    s = jnp.einsum('...qd,...kd->...qk', q, k)
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum('...qk,...kd->...qd', p, v)
    return o, m, l


def ring_attention(q, k, v, axis_name='sp', causal=False, scale=None,
                   kv_len=None):
    """Exact attention with K/V rotating over `axis_name`.

    q, k, v: [batch, heads, t_local, d] — the per-shard slices.
    kv_len: optional [batch] int — GLOBAL valid key count per example
    (padding masks, r5): key positions ≥ kv_len[b] contribute -1e30
    bias, so variable-length batches stay exact under sequence
    parallelism too. Returns [batch, heads, t_local, d].
    """
    from .collective import axis_size
    n = axis_size(axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    if scale is None:
        scale = q.shape[-1] ** -0.5
    q = q * scale
    t_local = q.shape[-2]

    perm = [(i, (i + 1) % n) for i in range(n)]

    def block_bias(kv_idx):
        # global positions: q_pos = my_idx*t + i ; k_pos = kv_idx*t + j
        qi = my_idx * t_local + jnp.arange(t_local)[:, None]
        kj = kv_idx * t_local + jnp.arange(t_local)[None, :]
        bias = None
        if causal:
            bias = jnp.where(qi >= kj, 0.0, -1e30)        # [tq, tk]
        if kv_len is not None:
            # [B, 1, tq, tk] — broadcasts over heads; finite -1e30
            # keeps the m/l recurrence NaN-free on fully-masked blocks
            key_ok = kj[None, :, :] < kv_len.reshape(-1, 1, 1)
            kbias = jnp.where(key_ok, 0.0, -1e30)[:, None, :, :]
            bias = kbias if bias is None else bias[None, None] + kbias
        return bias

    def step(carry, _):
        o_acc, m_acc, l_acc, kv_k, kv_v, kv_idx = carry
        bias = block_bias(kv_idx)
        o_b, m_b, l_b = _block_attn(q, kv_k, kv_v, bias)
        m_new = jnp.maximum(m_acc, m_b)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_b - m_new)
        o_acc = o_acc * alpha + o_b * beta
        l_acc = l_acc * alpha + l_b * beta
        kv_k = jax.lax.ppermute(kv_k, axis_name, perm)
        kv_v = jax.lax.ppermute(kv_v, axis_name, perm)
        kv_idx = jax.lax.ppermute(kv_idx, axis_name, perm)
        return (o_acc, m_new, l_acc, kv_k, kv_v, kv_idx), None

    # Derive accumulators from q so they carry q's varying ('sp') manual
    # axis — fresh constants would be unvarying and break the scan carry.
    o0 = jnp.zeros_like(q)
    m0 = jnp.full_like(q[..., :1], -1e30)
    l0 = jnp.zeros_like(q[..., :1])
    carry = (o0, m0, l0, k, v, my_idx)
    (o, m, l, _, _, _), _ = jax.lax.scan(step, carry, None, length=n)
    return o / jnp.maximum(l, 1e-20)
