"""DistributeTranspiler — SPMD edition.

Reference: python/paddle/fluid/distribute_transpiler.py splits a program
into trainer + pserver halves and inserts send/recv. TPU-native: the
program stays whole; this transpiler attaches a PartitionSpec to every var
(params, grads, activations, optimizer state) and sets program.mesh, after
which the Executor's GSPMD path lets XLA insert psum/all_gather/
reduce_scatter over the ICI mesh — the allreduce IS the pserver.

Strategies:
  data-parallel   : batch dim of data vars -> 'dp'; params replicated.
  tensor-parallel : fc/embedding weights column/row split on 'tp' by the
                    megatron pairing rule (column then row per block).
  sequence        : time dim of long activations -> 'sp' (ring attention).
  pipeline        : scan-stacked layer weights stage-sharded on 'pp'; the
                    layer-stack op runs the GPipe microbatch schedule
                    (pipeline.py) inside the jitted step.
  expert          : [E, ...] expert weights on 'ep' (set by switch_moe).
"""

import os

from jax.sharding import PartitionSpec as P

from .. import observe as _obs
from ..core.backward import GRAD_SUFFIX
from ..core.program import Parameter


class ParallelStrategy(object):
    def __init__(self, data_parallel=True, tensor_parallel=False,
                 sequence_parallel=False, tp_rules=None, sp_vars=None,
                 shard_embeddings=True, pipeline_parallel=False,
                 pipeline_microbatches=None, shard_optimizer_states=False,
                 fully_shard_parameters=False, quantized_allreduce=False,
                 shard_optimizer_state=None, grad_bucket_mb=None):
        self.data_parallel = data_parallel
        # Quantized gradient allreduce (PAPERS "EQuARX"): dense dp
        # gradients cross the wire as per-block-scaled int8 with
        # stochastic rounding instead of fp32 — ~3.9x less ICI traffic
        # on the training path's dominant collective. The executor
        # models the wire format on each dp-reduced gradient (see
        # quant/core.qdq); the explicit two-leg schedule lives in
        # collective.quantized_all_reduce. PADDLE_TPU_QUANT_ALLREDUCE
        # overrides per call.
        self.quantized_allreduce = quantized_allreduce
        # ZeRO-1 (beyond reference; the scaling-book optimizer-state
        # recipe): optimizer accumulators additionally shard over 'dp'
        # on their first free divisible axis. GSPMD then derives the
        # comms — the grad allreduce becomes reduce-scatter at the
        # update and the fresh params all-gather into the next forward;
        # per-chip state memory drops by ~dp x (2x params for Adam).
        # `shard_optimizer_state` (singular — the ZeRO-paper spelling)
        # is an explicit alias that wins over the plural default;
        # PADDLE_TPU_SHARD_OPT_STATE overrides both per transpile call.
        if shard_optimizer_state is not None:
            shard_optimizer_states = bool(shard_optimizer_state)
        self.shard_optimizer_states = shard_optimizer_states
        # Gradient-allreduce bucket size target in MB (see
        # collective.grad_bucket_policy / assign_grad_buckets; the
        # executor realizes one collective per bucket so XLA overlaps
        # them with the remaining backward). None = leave the dp
        # reduction as one fused collective after the whole backward.
        self.grad_bucket_mb = grad_bucket_mb
        # ZeRO-3 / FSDP: the PARAMETERS themselves (and their grads,
        # and — via the structural state loop — their accumulators)
        # also take 'dp' on a free divisible axis. XLA all-gathers each
        # weight at its use site and reduce-scatters its grad; weight
        # memory drops ~dp x at the cost of per-layer all-gathers.
        # Row-sharded sparse tables keep their own scheme (skipped).
        self.fully_shard_parameters = fully_shard_parameters
        self.tensor_parallel = tensor_parallel
        self.sequence_parallel = sequence_parallel
        # tp_rules: list of (param-name-substring, axis-index) pairs deciding
        # which weight dim is split over 'tp'.
        self.tp_rules = tp_rules or []
        self.sp_vars = sp_vars or []
        # Row-shard embedding tables flagged by layers.embedding(is_sparse/
        # is_distributed) — the pserver sparse-row role (go/pserver/
        # service.go) done as GSPMD gather partitioning.
        self.shard_embeddings = shard_embeddings
        # Pipeline parallelism over the mesh 'pp' axis: the program's
        # scan-stacked layer ops (transformer_layer_stack, built with
        # scan_layers=True) split their [n_layer, ...] weights into
        # contiguous stage chunks and run the GPipe microbatch schedule
        # (parallel/pipeline.py). Reference analog: the transpiler owns
        # program partitioning (distribute_transpiler.py:133 splits one
        # program into trainer/pserver halves); here it partitions the
        # layer stack across the pp axis.
        self.pipeline_parallel = pipeline_parallel
        # Microbatches per pipeline pass (default: the pp axis size).
        # Bubble fraction is (pp-1)/(n_micro+pp-1): at pp=4 the default
        # n_micro=4 idles ~43% of stage-ticks, n_micro=16 ~16%. Raise it
        # as far as per-microbatch batch size (batch % n_micro == 0 and
        # enough tokens per step to fill the MXU) allows.
        self.pipeline_microbatches = pipeline_microbatches


def shard_opt_state_env(default):
    """Per-call ``PADDLE_TPU_SHARD_OPT_STATE`` resolver (repo_lint
    env-scoped): '1'/'on'/'true' forces ZeRO-1 on, '0'/'off'/'false'
    forces it off, unset defers to the strategy flag — the env wins in
    either direction, matching the quant/bucket knob conventions."""
    raw = os.environ.get('PADDLE_TPU_SHARD_OPT_STATE')
    if raw is None or raw.strip() == '':
        return bool(default)
    return raw.strip().lower() not in ('0', 'off', 'false')


def optimizer_state_bytes(program, mesh=None):
    """Analytic optimizer-state memory model (the ZeRO-1 ledger, in the
    style of ``linalg.per_shard_peak_bytes``): walks every op carrying a
    'Param' input slot and sums the bytes of its persistable state
    inputs (Moment/Velocity/BetaPow/..., structurally — the same rule
    the accumulator-sharding loop in :func:`transpile` uses). Per-device
    bytes divide each accumulator by the extent of the mesh axes in its
    attached spec, so with ``shard_optimizer_states`` the reduction
    approaches dp x (minus the [1]-shaped beta-pow scalars that have no
    qualifying axis and stay replicated)."""
    import numpy as np

    from ..core.dtypes import to_jnp_dtype
    mesh = mesh if mesh is not None else program.mesh
    axes = dict(mesh.shape) if mesh is not None else {}
    block = program.global_block()
    shardings = program.var_shardings
    total = 0
    per_device = 0.0
    n_state = 0
    seen = set()
    for op in block.ops:
        if not op.inputs.get('Param'):
            continue
        for slot, names in op.inputs.items():
            if slot in ('Param', 'Grad', 'LearningRate'):
                continue
            for n in names:
                if n in seen:
                    continue
                v = block._find_var_recursive(n)
                if v is None or not v.persistable or v.shape is None:
                    continue
                seen.add(n)
                numel = 1
                for d in v.shape:
                    numel *= int(d)
                nbytes = numel * np.dtype(to_jnp_dtype(v.dtype)).itemsize
                extent = 1
                spec = shardings.get(n)
                for entry in (spec or ()):
                    parts = (entry,) if isinstance(entry, str) \
                        else tuple(entry or ())
                    for ax in parts:
                        extent *= int(axes.get(ax, 1))
                total += nbytes
                per_device += nbytes / max(extent, 1)
                n_state += 1
    per_device = int(per_device)
    return {'total': int(total), 'per_device': per_device,
            'reduction': float(total) / max(per_device, 1),
            'n_dp': int(axes.get('dp', 1)), 'n_state_vars': n_state}


def _tp_spec_for(param, rules):
    for substr, axis in rules:
        if substr in param.name:
            ndim = len(param.shape)
            spec = [None] * ndim
            spec[axis % ndim] = 'tp'
            return P(*spec)
    return None


_TP_PROPAGATE = frozenset((
    'relu', 'gelu', 'tanh', 'sigmoid', 'softsign', 'softplus', 'leaky_relu',
    'elu', 'dropout', 'scale', 'cast', 'elementwise_add', 'elementwise_mul',
    'elementwise_sub', 'elementwise_div'))


def _auto_tp_specs(program):
    """Derive Megatron column/row weight splits from the DATAFLOW, not
    names: a mul/matmul consuming an unsharded activation gets its weight
    column-split ('tp' on the output dim) and marks its activation
    tp-sharded; a mul/matmul consuming a tp-sharded activation gets its
    weight row-split (GSPMD inserts the psum), restoring replication.
    Elementwise/activation ops propagate the marker; the bias of a
    column-split layer is split the same way. Mis-detection only costs
    resharding traffic — GSPMD keeps numerics exact either way."""
    block = program.global_block()
    specs = {}
    tp_last = set()  # vars currently sharded 'tp' on their last dim
    for op in block.ops:
        if op.type in ('mul', 'matmul'):
            xn = op.inputs.get('X', [None])[0]
            yn = op.inputs.get('Y', [None])[0]
            yvar = block._find_var_recursive(yn) if yn else None
            if isinstance(yvar, Parameter) and yn not in specs:
                ndim = len(yvar.shape)
                if xn in tp_last:
                    specs[yn] = P(*(['tp'] + [None] * (ndim - 1)))
                else:
                    specs[yn] = P(*([None] * (ndim - 1) + ['tp']))
                    tp_last.update(op.output_names())
        elif op.type == 'elementwise_add' and \
                op.inputs.get('X', [None])[0] in tp_last:
            yn = op.inputs.get('Y', [None])[0]
            yvar = block._find_var_recursive(yn) if yn else None
            if isinstance(yvar, Parameter) and len(yvar.shape) == 1 \
                    and yn not in specs:
                specs[yn] = P('tp')  # bias of a column-split layer
            tp_last.update(op.output_names())
        elif op.type in _TP_PROPAGATE:
            if any(n in tp_last for n in op.input_names()):
                tp_last.update(op.output_names())
    return specs


# Megatron pairing for the stacked-layer weight slots (pp x tp): qkv +
# ffn-in column split (tp on the output-features dim), out-proj +
# ffn-out row split (tp on the input dim; GSPMD inserts the psum).
_STACK_TP_COL = frozenset(('SlfQ', 'SlfK', 'SlfV', 'CrossQ', 'CrossK',
                           'CrossV', 'FfnW1'))
_STACK_TP_ROW = frozenset(('SlfO', 'CrossO', 'FfnW2'))


_PP_STACK_OPS = ('transformer_layer_stack', 'moe_layer_stack')


def _pp_stack_specs(program, n_stages, with_tp=False, with_ep=False):
    """Stage-shard the scan-stacked layer weights: every parameter input
    of a transformer_layer_stack / moe_layer_stack op gets P('pp', ...)
    on its leading [n_layer] axis, so stage s of the GPipe schedule
    holds layers [s*L/pp, (s+1)*L/pp) — the op lowering runs the
    schedule itself (ops/transformer_ops.py pipelined paths). With
    with_tp, the 3-D matmul weights additionally column/row split over
    'tp' inside each stage; with with_ep, [n_layer, E, ...] expert
    weights keep their 'ep' split on axis 1. Both compose because the
    shard_map is manual over pp only — GSPMD manages the intra-stage
    tp/ep collectives."""
    specs = {}
    block = program.global_block()
    found_stack = False
    for op in block.ops:
        if op.type not in _PP_STACK_OPS:
            continue
        found_stack = True
        for slot, names in op.inputs.items():
            if slot in ('X', 'EncOut', 'SrcLength'):
                continue
            for n in names:
                v = block._find_var_recursive(n)
                if not isinstance(v, Parameter):
                    continue
                if v.shape[0] % n_stages:
                    raise ValueError(
                        'pipeline_parallel: stacked param %r has '
                        'n_layer=%d, not divisible by pp=%d'
                        % (n, v.shape[0], n_stages))
                spec = ['pp'] + [None] * (len(v.shape) - 1)
                if with_ep and getattr(v, 'expert_shard', False):
                    ax = getattr(v, 'expert_shard_axis', 1)
                    if ax < 1:
                        # axis 0 is the stage axis here; an [E, ...]
                        # expert annotation cannot sit on a stacked op
                        raise ValueError(
                            'stacked expert param %r has '
                            'expert_shard_axis=%d; scan-stacked MoE '
                            'weights are [n_layer, E, ...] (axis >= 1)'
                            % (n, ax))
                    spec[ax] = 'ep'
                elif with_tp and len(v.shape) == 3:
                    if slot in _STACK_TP_COL:
                        spec[2] = 'tp'
                    elif slot in _STACK_TP_ROW:
                        spec[1] = 'tp'
                specs[n] = P(*spec)
    if not found_stack:
        raise ValueError(
            'pipeline_parallel requires scan-stacked layers: build the '
            'model with scan_layers=True (transformer_layer_stack / '
            'moe_layer_stack ops) so the transpiler can partition the '
            'stack into pp stages')
    return specs


def _row_shard_axis(mesh):
    """Mesh axis for embedding row-sharding: prefer the model-parallel
    axis (rows stay put while dp batches move), fall back to dp."""
    for axis in ('tp', 'ep', 'sp', 'dp'):
        if mesh.shape.get(axis, 1) > 1:
            return axis
    return None


def _row_shard_spec_for(param, mesh):
    if not getattr(param, 'row_shard', False):
        return None
    axis = _row_shard_axis(mesh)
    if axis is None:
        return None
    return P(*([axis] + [None] * (len(param.shape) - 1)))


def _expert_shard_spec_for(param, mesh):
    """Expert-stacked weights (layers.switch_moe) shard their expert
    axis over 'ep' — each chip holds E/ep experts. The axis defaults to
    0 ([E, ...]); scan-stacked MoE layers ([n_layer, E, ...]) set
    expert_shard_axis = 1."""
    if not getattr(param, 'expert_shard', False):
        return None
    if dict(mesh.shape).get('ep', 1) <= 1:
        return None
    axis = getattr(param, 'expert_shard_axis', 0)
    spec = [None] * len(param.shape)
    spec[axis] = 'ep'
    return P(*spec)


def transpile(program, mesh, strategy=None):
    """Attach shardings for `mesh` to `program` in place; returns program."""
    strategy = strategy or ParallelStrategy()
    shardings = {}
    block = program.global_block()

    auto_tp = {}
    if strategy.tensor_parallel and not strategy.tp_rules:
        auto_tp = _auto_tp_specs(program)

    pp_specs = {}
    # re-transpiling with pipeline off must clear a previous schedule —
    # the stack lowerings key off program.pipeline, and the version bump
    # below guarantees they get re-traced with the new decision
    program.pipeline = None
    if strategy.pipeline_parallel:
        n_pp = dict(mesh.shape).get('pp', 1)
        if n_pp <= 1:
            raise ValueError(
                'pipeline_parallel=True but the mesh has no pp axis > 1 '
                '(mesh shape %s) — build it with make_mesh(pp=n_stages)'
                % dict(mesh.shape))
        pp_specs = _pp_stack_specs(
            program, n_pp,
            with_tp=(strategy.tensor_parallel and
                     dict(mesh.shape).get('tp', 1) > 1),
            with_ep=dict(mesh.shape).get('ep', 1) > 1)
        program.pipeline = {
            'n_micro': int(strategy.pipeline_microbatches or n_pp)}

    n_dp = dict(mesh.shape).get('dp', 1)
    shard_opt = shard_opt_state_env(strategy.shard_optimizer_states)

    def _dp_extend(spec, shape, enabled):
        """Extend a spec with 'dp' on the first free axis whose size
        divides the dp extent (the ZeRO family's sharding move).
        Returns the original spec when disabled, dp <= 1, 'dp' is
        already used, or no axis qualifies."""
        if not enabled or n_dp <= 1 or not shape:
            return spec
        parts = list(spec) + [None] * (len(shape) - len(spec))
        if 'dp' in parts:
            return spec
        for i, (p, dim) in enumerate(zip(parts, shape)):
            if p is None and dim and dim % n_dp == 0:
                parts[i] = 'dp'
                return P(*parts)
        return spec

    for var in program.list_vars():
        if var.shape is None:
            continue
        if isinstance(var, Parameter):
            spec = pp_specs.get(var.name)
            if spec is None and strategy.tensor_parallel:
                spec = _tp_spec_for(var, strategy.tp_rules) \
                    if strategy.tp_rules else auto_tp.get(var.name)
            if spec is None:
                spec = _expert_shard_spec_for(var, mesh)
            row_sharded = False
            if spec is None and strategy.shard_embeddings:
                spec = _row_shard_spec_for(var, mesh)
                row_sharded = spec is not None
            if not row_sharded:
                # ZeRO-3/FSDP: weights themselves take 'dp'; row-sharded
                # sparse tables keep their own scheme
                spec = _dp_extend(spec if spec is not None else P(),
                                  var.shape,
                                  strategy.fully_shard_parameters)
                if spec == P():
                    spec = None
            shardings[var.name] = spec if spec is not None else P()
            # ZeRO-1: the gradient additionally takes 'dp' on a free
            # divisible axis — the executor applies this spec at the
            # grad-assignment boundary, so XLA turns the dp allreduce
            # into a reduce-scatter feeding the shard-local update.
            gspec = _dp_extend(spec if spec is not None else P(),
                               var.shape, shard_opt)
            if spec is not None or gspec != P():
                shardings[var.name + GRAD_SUFFIX] = gspec
        elif var.is_data and strategy.data_parallel:
            ndim = len(var.shape)
            spec = ['dp'] + [None] * (ndim - 1)
            if strategy.sequence_parallel and var.name in strategy.sp_vars \
                    and ndim >= 2:
                spec[1] = 'sp'
            shardings[var.name] = P(*spec)

    # Optimizer accumulators follow their parameter's sharding — derived
    # STRUCTURALLY from the optimizer op (every op carrying a 'Param' input
    # slot pairs that param with its same-shape state inputs: Moment,
    # Velocity, ...). Name strings play no part, so colliding names
    # cannot mis-shard (reference analog: accumulators live beside the
    # param on its pserver shard, go/pserver/service.go).
    for op in block.ops:
        pnames = op.inputs.get('Param')
        if not pnames:
            continue
        pvar = block._find_var_recursive(pnames[0])
        spec = shardings.get(pnames[0])
        if pvar is None or spec is None:
            continue
        for slot, names in op.inputs.items():
            if slot in ('Param', 'Grad'):
                continue
            for n in names:
                v = block._find_var_recursive(n)
                if v is not None and v.persistable and n not in shardings \
                        and v.shape == pvar.shape:
                    shardings[n] = _dp_extend(spec, v.shape, shard_opt)

    # Remaining persistable state (lr, beta_pow, BN stats, ...) replicates.
    for var in program.list_vars():
        if var.persistable and var.shape is not None \
                and var.name not in shardings:
            shardings[var.name] = P()

    program.var_shardings.update(shardings)
    program.mesh = mesh
    program.quant_allreduce = bool(strategy.quantized_allreduce) or None
    program.grad_bucket_mb = strategy.grad_bucket_mb
    if _obs.enabled():
        m = optimizer_state_bytes(program, mesh)
        _obs.set_gauge('trainer.optimizer_state_bytes_total', m['total'])
        _obs.set_gauge('trainer.optimizer_state_bytes_per_device',
                       m['per_device'])
        _obs.set_gauge('trainer.optimizer_state_reduction_x',
                       m['reduction'])
    # invalidate compiled-step caches: a step compiled BEFORE transpile
    # has no sharding constraints (and no pipeline schedule) traced in —
    # reusing it would silently train without the requested layout
    program._bump_version()
    return program


class DistributeTranspiler(object):
    """API-compatible facade over transpile() (reference
    distribute_transpiler.py:DistributeTranspiler)."""

    def __init__(self):
        self._program = None

    def transpile(self, trainer_id=0, program=None, pservers=None,
                  trainers=1, mesh=None, strategy=None, **kwargs):
        from ..core.program import default_main_program
        program = program or default_main_program()
        if mesh is None:
            from .mesh import make_mesh
            mesh = make_mesh()
        self._program = transpile(program, mesh, strategy)
        return self._program

    def get_trainer_program(self):
        # SPMD: every worker runs the same whole program.
        return self._program

    def get_pserver_program(self, endpoint=None):
        # No parameter server exists under SPMD; updates are fused into the
        # train step and grads ride ICI collectives.
        return self._program
