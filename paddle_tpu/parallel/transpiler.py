"""DistributeTranspiler — SPMD edition.

Reference: python/paddle/fluid/distribute_transpiler.py splits a program
into trainer + pserver halves and inserts send/recv. TPU-native: the
program stays whole; this transpiler attaches a PartitionSpec to every var
(params, grads, activations, optimizer state) and sets program.mesh, after
which the Executor's GSPMD path lets XLA insert psum/all_gather/
reduce_scatter over the ICI mesh — the allreduce IS the pserver.

Strategies:
  data-parallel   : batch dim of data vars -> 'dp'; params replicated.
  tensor-parallel : fc/embedding weights column/row split on 'tp' by the
                    megatron pairing rule (column then row per block).
  sequence        : time dim of long activations -> 'sp' (ring attention).
"""

from jax.sharding import PartitionSpec as P

from ..core.backward import GRAD_SUFFIX
from ..core.program import Parameter


class ParallelStrategy(object):
    def __init__(self, data_parallel=True, tensor_parallel=False,
                 sequence_parallel=False, tp_rules=None, sp_vars=None):
        self.data_parallel = data_parallel
        self.tensor_parallel = tensor_parallel
        self.sequence_parallel = sequence_parallel
        # tp_rules: list of (param-name-substring, axis-index) pairs deciding
        # which weight dim is split over 'tp'.
        self.tp_rules = tp_rules or []
        self.sp_vars = sp_vars or []


def _tp_spec_for(param, rules):
    for substr, axis in rules:
        if substr in param.name:
            ndim = len(param.shape)
            spec = [None] * ndim
            spec[axis % ndim] = 'tp'
            return P(*spec)
    return None


def transpile(program, mesh, strategy=None):
    """Attach shardings for `mesh` to `program` in place; returns program."""
    strategy = strategy or ParallelStrategy()
    shardings = {}
    block = program.global_block()

    for var in program.list_vars():
        if var.shape is None:
            continue
        if isinstance(var, Parameter):
            spec = None
            if strategy.tensor_parallel:
                spec = _tp_spec_for(var, strategy.tp_rules)
            shardings[var.name] = spec if spec is not None else P()
            if strategy.tensor_parallel and spec is not None:
                shardings[var.name + GRAD_SUFFIX] = spec
        elif var.is_data and strategy.data_parallel:
            ndim = len(var.shape)
            spec = ['dp'] + [None] * (ndim - 1)
            if strategy.sequence_parallel and var.name in strategy.sp_vars \
                    and ndim >= 2:
                spec[1] = 'sp'
            shardings[var.name] = P(*spec)

    # Optimizer accumulators follow their parameter's sharding (matched by
    # same-shape name-prefix, e.g. fc_0.w_0_moment1_acc -> fc_0.w_0).
    for var in program.list_vars():
        if not var.persistable or var.shape is None:
            continue
        if var.name in shardings:
            continue
        matched = None
        for pname, spec in list(shardings.items()):
            if pname != var.name and var.name.startswith(pname + '_') and \
                    isinstance(block._find_var_recursive(pname), Parameter):
                pvar = block._find_var_recursive(pname)
                if pvar.shape == var.shape:
                    matched = spec
                    break
        shardings[var.name] = matched if matched is not None else P()

    program.var_shardings.update(shardings)
    program.mesh = mesh
    return program


class DistributeTranspiler(object):
    """API-compatible facade over transpile() (reference
    distribute_transpiler.py:DistributeTranspiler)."""

    def __init__(self):
        self._program = None

    def transpile(self, trainer_id=0, program=None, pservers=None,
                  trainers=1, mesh=None, strategy=None, **kwargs):
        from ..core.program import default_main_program
        program = program or default_main_program()
        if mesh is None:
            from .mesh import make_mesh
            mesh = make_mesh()
        self._program = transpile(program, mesh, strategy)
        return self._program

    def get_trainer_program(self):
        # SPMD: every worker runs the same whole program.
        return self._program

    def get_pserver_program(self, endpoint=None):
        # No parameter server exists under SPMD; updates are fused into the
        # train step and grads ride ICI collectives.
        return self._program
