"""v1 optimizer config objects (reference:
python/paddle/trainer_config_helpers/optimizers.py — `settings()` wrote
the optimization section of the trainer config protobuf). Here each
class adapts to a fluid optimizer via `.to_fluid(learning_rate)`, and
`settings()` returns a Settings whose `.minimize(loss)` applies the
configured optimizer + regularization to the default program — the one
piece of trainer-config behavior that still means something when the
Program is the config.
"""

from .. import optimizer as _opt
from .. import regularizer as _reg

__all__ = ['Optimizer', 'BaseSGDOptimizer', 'MomentumOptimizer',
           'AdamaxOptimizer', 'AdamOptimizer', 'AdaGradOptimizer',
           'RMSPropOptimizer', 'DecayedAdaGradOptimizer',
           'AdaDeltaOptimizer', 'BaseRegularization', 'L2Regularization',
           'settings', 'ModelAverage']


class Optimizer(object):
    def to_fluid(self, learning_rate, regularization=None):
        raise NotImplementedError


class BaseSGDOptimizer(Optimizer):
    pass


def _regularizer(regularization):
    if isinstance(regularization, L2Regularization):
        return _reg.L2Decay(regularization.rate)
    return None


class MomentumOptimizer(BaseSGDOptimizer):
    def __init__(self, momentum=0.9, sparse=False):
        self.momentum = momentum

    def to_fluid(self, learning_rate, regularization=None):
        return _opt.Momentum(learning_rate=learning_rate,
                             momentum=self.momentum,
                             regularization=_regularizer(regularization))


class AdamOptimizer(BaseSGDOptimizer):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def to_fluid(self, learning_rate, regularization=None):
        return _opt.Adam(learning_rate=learning_rate, beta1=self.beta1,
                         beta2=self.beta2, epsilon=self.epsilon,
                         regularization=_regularizer(regularization))


class AdamaxOptimizer(BaseSGDOptimizer):
    def __init__(self, beta1=0.9, beta2=0.999):
        self.beta1, self.beta2 = beta1, beta2

    def to_fluid(self, learning_rate, regularization=None):
        return _opt.Adamax(learning_rate=learning_rate, beta1=self.beta1,
                           beta2=self.beta2,
                           regularization=_regularizer(regularization))


class AdaGradOptimizer(BaseSGDOptimizer):
    def to_fluid(self, learning_rate, regularization=None):
        return _opt.Adagrad(learning_rate=learning_rate,
                            regularization=_regularizer(regularization))


class DecayedAdaGradOptimizer(BaseSGDOptimizer):
    def __init__(self, rho=0.95, epsilon=1e-6):
        self.rho, self.epsilon = rho, epsilon

    def to_fluid(self, learning_rate, regularization=None):
        return _opt.DecayedAdagrad(learning_rate=learning_rate,
                                   decay=self.rho, epsilon=self.epsilon,
                                   regularization=_regularizer(
                                       regularization))


class AdaDeltaOptimizer(BaseSGDOptimizer):
    def __init__(self, rho=0.95, epsilon=1e-6):
        self.rho, self.epsilon = rho, epsilon

    def to_fluid(self, learning_rate, regularization=None):
        return _opt.Adadelta(learning_rate=learning_rate, rho=self.rho,
                             epsilon=self.epsilon,
                             regularization=_regularizer(regularization))


class RMSPropOptimizer(BaseSGDOptimizer):
    def __init__(self, rho=0.95, epsilon=1e-6):
        self.rho, self.epsilon = rho, epsilon

    def to_fluid(self, learning_rate, regularization=None):
        return _opt.RMSProp(learning_rate=learning_rate, rho=self.rho,
                            epsilon=self.epsilon,
                            regularization=_regularizer(regularization))


class BaseRegularization(object):
    pass


class L2Regularization(BaseRegularization):
    def __init__(self, rate):
        self.rate = rate


class ModelAverage(object):
    """Recorded for config parity; the fluid-level ModelAverage hook is
    not implemented (SURVEY §6.1 absence list)."""

    def __init__(self, average_window, max_average_window=None):
        self.average_window = average_window


class Settings(object):
    def __init__(self, batch_size, learning_rate, learning_method,
                 regularization, gradient_clipping_threshold=None):
        self.batch_size = batch_size
        self.learning_rate = learning_rate
        self.learning_method = learning_method or MomentumOptimizer(0.0)
        self.regularization = regularization
        self.gradient_clipping_threshold = gradient_clipping_threshold

    def optimizer(self):
        return self.learning_method.to_fluid(self.learning_rate,
                                             self.regularization)

    def minimize(self, loss):
        if self.gradient_clipping_threshold:
            # v1 semantics are ELEMENT-WISE value clipping: the legacy
            # OptimizerWithGradientClipping does grad.clip(-t, t)
            # (reference paddle/parameter/FirstOrderOptimizer.cpp:
            # 306-326); 'global' there means config-global threshold
            # vs per-parameter override, NOT global-norm.
            from ..clip import GradientClipByValue, set_gradient_clip
            t = float(self.gradient_clipping_threshold)
            set_gradient_clip(GradientClipByValue(max=t, min=-t))
        return self.optimizer().minimize(loss)


def settings(batch_size=256, learning_rate=1e-3, learning_method=None,
             regularization=None, gradient_clipping_threshold=None,
             **kwargs):
    """v1 `settings(...)` configured the global trainer; here it returns
    a Settings handle — call `.minimize(loss)` where a v1 config would
    have relied on the trainer reading the global section.
    gradient_clipping_threshold maps to element-wise value clipping
    (the legacy semantics; see Settings.minimize)."""
    return Settings(batch_size, learning_rate, learning_method,
                    regularization, gradient_clipping_threshold)
