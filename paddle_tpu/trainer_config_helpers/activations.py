"""v1 activation objects (reference:
python/paddle/trainer_config_helpers/activations.py — each class carries
the config-time name of a gserver activation). Here each carries the
fluid activation string the layer shim hands to the registered lowering.
"""

__all__ = ['BaseActivation', 'TanhActivation', 'SigmoidActivation',
           'SoftmaxActivation', 'IdentityActivation', 'LinearActivation',
           'SequenceSoftmaxActivation', 'ExpActivation', 'ReluActivation',
           'BReluActivation', 'SoftReluActivation', 'STanhActivation',
           'AbsActivation', 'SquareActivation', 'LogActivation',
           'SqrtActivation', 'ReciprocalActivation', 'SoftSignActivation']


class BaseActivation(object):
    name = None

    def __repr__(self):
        return type(self).__name__


def _mk(cls_name, act):
    cls = type(cls_name, (BaseActivation,), {'name': act})
    return cls


TanhActivation = _mk('TanhActivation', 'tanh')
SigmoidActivation = _mk('SigmoidActivation', 'sigmoid')
SoftmaxActivation = _mk('SoftmaxActivation', 'softmax')
IdentityActivation = _mk('IdentityActivation', None)
LinearActivation = IdentityActivation
SequenceSoftmaxActivation = _mk('SequenceSoftmaxActivation',
                                'sequence_softmax')
ExpActivation = _mk('ExpActivation', 'exp')
ReluActivation = _mk('ReluActivation', 'relu')
BReluActivation = _mk('BReluActivation', 'brelu')
SoftReluActivation = _mk('SoftReluActivation', 'soft_relu')
STanhActivation = _mk('STanhActivation', 'stanh')
AbsActivation = _mk('AbsActivation', 'abs')
SquareActivation = _mk('SquareActivation', 'square')
LogActivation = _mk('LogActivation', 'log')
SqrtActivation = _mk('SqrtActivation', 'sqrt')
ReciprocalActivation = _mk('ReciprocalActivation', 'reciprocal')
SoftSignActivation = _mk('SoftSignActivation', 'softsign')
