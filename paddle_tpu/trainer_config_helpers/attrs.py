"""v1 parameter/layer attributes (reference:
python/paddle/trainer_config_helpers/attrs.py — ParameterAttribute
carries init/regularization/lr config into the config protobuf). Here
`ParameterAttribute.to_fluid()` builds the equivalent fluid ParamAttr;
the layer shim calls it on every param_attr it receives, so both v1
attribute objects and plain fluid ParamAttr work.
"""

__all__ = ['HookAttr', 'ParamAttr', 'ExtraAttr', 'ParameterAttribute',
           'ExtraLayerAttribute']


class HookAttr(object):
    """Config-time parameter hook (pruning era); recorded, not applied."""

    def __init__(self, type=None, sparsity_ratio=None):
        self.type = type
        self.sparsity_ratio = sparsity_ratio


class ParameterAttribute(object):
    def __init__(self, name=None, is_static=False, initial_std=None,
                 initial_mean=None, initial_max=None, initial_min=None,
                 l1_rate=None, l2_rate=None, learning_rate=None,
                 momentum=None, gradient_clipping_threshold=None,
                 sparse_update=False, update_hooks=None,
                 initializer=None):
        self.name = name
        self.is_static = is_static
        self.initial_std = initial_std
        self.initial_mean = initial_mean
        self.initial_max = initial_max
        self.initial_min = initial_min
        self.l1_rate = l1_rate
        self.l2_rate = l2_rate
        self.learning_rate = learning_rate
        self.sparse_update = sparse_update
        self.initializer = initializer

    def to_fluid(self):
        from ..param_attr import ParamAttr as FluidParamAttr
        from .. import initializer as I
        from .. import regularizer as R
        init = self.initializer
        if init is None and (self.initial_std is not None
                             or self.initial_mean is not None):
            init = I.Normal(loc=self.initial_mean or 0.0,
                            scale=self.initial_std
                            if self.initial_std is not None else 0.01)
        elif init is None and (self.initial_max is not None
                               or self.initial_min is not None):
            init = I.Uniform(low=self.initial_min or 0.0,
                             high=self.initial_max or 1.0)
        reg = None
        if self.l2_rate:
            reg = R.L2Decay(self.l2_rate)
        elif self.l1_rate:
            reg = R.L1Decay(self.l1_rate)
        return FluidParamAttr(
            name=self.name, initializer=init,
            learning_rate=self.learning_rate
            if self.learning_rate is not None else 1.0,
            regularizer=reg, trainable=not self.is_static)


class ExtraLayerAttribute(object):
    """drop_rate is honored (the shim appends a dropout op); device/
    error_clipping belong to Place/var.error_clip in this framework."""

    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate
        self.device = device


ParamAttr = ParameterAttribute
ExtraAttr = ExtraLayerAttribute


def to_fluid_param_attr(attr):
    """ParameterAttribute | fluid ParamAttr | str | None -> fluid form."""
    if attr is None or isinstance(attr, (str, bool)):
        return attr
    if isinstance(attr, ParameterAttribute):
        return attr.to_fluid()
    return attr


def apply_extra_attr(out, layer_attr):
    """Post-layer hook for ExtraLayerAttribute (drop_rate, error clip)."""
    if layer_attr is None:
        return out
    if getattr(layer_attr, 'error_clipping_threshold', None):
        out.error_clip = layer_attr.error_clipping_threshold
    if getattr(layer_attr, 'drop_rate', None):
        from .. import layers as _fl
        out = _fl.dropout(out, dropout_prob=layer_attr.drop_rate)
    return out
