"""v1 evaluators (reference:
python/paddle/trainer_config_helpers/evaluators.py — config-time
declarations resolved by gserver evaluator kernels). Each shim appends
the corresponding metric op(s) to the program and returns the metric
var(s) for fetch_list; printer evaluators map to layers.Print.
"""

from .. import layers as _fl

__all__ = ['evaluator_base', 'classification_error_evaluator',
           'auc_evaluator', 'pnpair_evaluator',
           'precision_recall_evaluator', 'ctc_error_evaluator',
           'chunk_evaluator', 'sum_evaluator', 'column_sum_evaluator',
           'value_printer_evaluator', 'gradient_printer_evaluator',
           'maxid_printer_evaluator', 'maxframe_printer_evaluator',
           'seqtext_printer_evaluator',
           'classification_error_printer_evaluator',
           'detection_map_evaluator']


def evaluator_base(*args, **kwargs):
    raise NotImplementedError('subclass-style evaluator declaration is '
                              'config-era; call a concrete *_evaluator')


def classification_error_evaluator(input, label, name=None, weight=None,
                                   top_k=1, **kwargs):
    acc = _fl.accuracy(input=input, label=label, k=top_k)
    return _fl.scale(acc, scale=-1.0, bias=1.0)  # error = 1 - accuracy


def auc_evaluator(input, label, name=None, weight=None):
    auc, _, _ = _fl.auc(input=input, label=label)
    return auc


def pnpair_evaluator(input, label, query_id, weight=None, name=None):
    pos, neg, _ = _fl.positive_negative_pair(input, label, query_id)
    return pos, neg


def precision_recall_evaluator(input, label, positive_label=None,
                               weight=None, name=None):
    idx = _fl.argmax(input, axis=-1)
    return _fl.precision_recall(indices=idx, labels=label,
                                class_number=int(input.shape[-1]))


def ctc_error_evaluator(input, label, name=None):
    decoded = _fl.ctc_greedy_decoder(input=input, blank=0)
    dist, _ = _fl.edit_distance(decoded, label)
    return dist


def chunk_evaluator(input, label, chunk_scheme, num_chunk_types,
                    name=None, excluded_chunk_types=None):
    return _fl.chunk_eval(input=input, label=label,
                          chunk_scheme=chunk_scheme,
                          num_chunk_types=num_chunk_types)


def sum_evaluator(input, name=None, weight=None):
    return _fl.reduce_sum(input)


def column_sum_evaluator(input, name=None, weight=None):
    return _fl.reduce_sum(input, dim=0)


def value_printer_evaluator(input, name=None):
    return _fl.Print(input, message=name or 'value')


def gradient_printer_evaluator(input, name=None):
    return _fl.Print(input, message=name or 'gradient',
                     print_phase='backward')


def maxid_printer_evaluator(input, name=None):
    return _fl.Print(_fl.argmax(input, axis=-1), message=name or 'maxid')


def maxframe_printer_evaluator(input, name=None):
    return _fl.Print(_fl.reduce_max(input, dim=-1),
                     message=name or 'maxframe')


def seqtext_printer_evaluator(input, result_file=None, name=None, **kw):
    return _fl.Print(input, message=name or 'seqtext')


def classification_error_printer_evaluator(input, label, name=None):
    err = classification_error_evaluator(input, label)
    return _fl.Print(err, message=name or 'classification_error')


def detection_map_evaluator(input, label, name=None, **kwargs):
    from ..metrics import DetectionMAP
    return DetectionMAP(**kwargs)
