"""v1 composite networks (reference:
python/paddle/trainer_config_helpers/networks.py — pre-assembled
combinations of v1 layers). Built on the layer shim + `paddle_tpu.nets`;
same eager-IR semantics as layers.py.
"""

from .. import layers as _fl
from .. import nets as _nets
from .activations import ReluActivation, SigmoidActivation, TanhActivation
from .attrs import to_fluid_param_attr as _pa
from .layers import (_act_name, _apply_act, _len_of, _propagate_len,
                     concat_layer, fc_layer, grumemory, img_conv_layer,
                     img_pool_layer, lstmemory, pooling_layer)
from .poolings import MaxPooling

__all__ = ['sequence_conv_pool', 'simple_lstm', 'simple_img_conv_pool',
           'img_conv_bn_pool', 'img_conv_group', 'small_vgg',
           'vgg_16_network', 'gru_unit', 'gru_group', 'simple_gru',
           'simple_gru2', 'bidirectional_gru', 'text_conv_pool',
           'bidirectional_lstm', 'lstmemory_group', 'lstmemory_unit',
           'simple_attention', 'dot_product_attention',
           'img_separable_conv', 'multi_head_attention',
           'inputs', 'outputs']


def sequence_conv_pool(input, context_len, hidden_size,
                       context_start=None, pool_type=None,
                       context_proj_param_attr=None, fc_param_attr=None,
                       fc_bias_attr=None, fc_act=None, pool_bias_attr=None,
                       fc_attr=None, context_attr=None, name=None):
    ptype = getattr(pool_type, 'name', pool_type) or 'max'
    return _nets.sequence_conv_pool(
        input=input, num_filters=hidden_size, filter_size=context_len,
        act=_act_name(fc_act) or 'tanh', pool_type=ptype,
        length=_len_of(input))


text_conv_pool = sequence_conv_pool


def simple_lstm(input, size, name=None, reverse=False, mat_param_attr=None,
                bias_param_attr=None, inner_param_attr=None, act=None,
                gate_act=None, state_act=None, mixed_layer_attr=None,
                lstm_cell_attr=None):
    """fc(4*size) + lstmemory, the reference composition."""
    proj = fc_layer(input, size * 4, act=None,
                    param_attr=mat_param_attr, bias_attr=False)
    return lstmemory(proj, size=size, reverse=reverse, act=act,
                     gate_act=gate_act, state_act=state_act,
                     param_attr=inner_param_attr,
                     bias_attr=bias_param_attr)


def lstmemory_unit(input, size, **kwargs):
    """Single-step form; over padded batches the scan form is the
    natural unit — delegate to simple_lstm."""
    return simple_lstm(input, size, **{k: v for k, v in kwargs.items()
                                       if k in ('act', 'gate_act',
                                                'state_act', 'name')})


def lstmemory_group(input, size, **kwargs):
    return simple_lstm(input, size, **{k: v for k, v in kwargs.items()
                                       if k in ('act', 'gate_act',
                                                'state_act', 'reverse',
                                                'name')})


def simple_gru(input, size, name=None, reverse=False, mixed_param_attr=None,
               mixed_bias_param_attr=None, gru_param_attr=None,
               gru_bias_attr=None, act=None, gate_act=None,
               mixed_layer_attr=None, gru_layer_attr=None):
    proj = fc_layer(input, size * 3, act=None, param_attr=mixed_param_attr,
                    bias_attr=False)
    return grumemory(proj, size=size, reverse=reverse, act=act,
                     gate_act=gate_act, param_attr=gru_param_attr,
                     bias_attr=gru_bias_attr)


simple_gru2 = simple_gru


def gru_group(input, memory_boot=None, size=None, name=None, reverse=False,
              gru_bias_attr=None, gru_param_attr=None, act=None,
              gate_act=None, gru_layer_attr=None, naive=False):
    """Reference networks.py:1002 gru_group: a GRU over an ALREADY
    3*size-projected input (it asserts input.size % 3 == 0 and defaults
    size to input.size/3) — unlike simple_gru, which adds the projection
    itself. grumemory consumes exactly that pre-projected form."""
    if memory_boot is not None:
        raise NotImplementedError(
            'gru_group(memory_boot=...): custom boot state needs the '
            'recurrent_group machinery; use fluid DynamicRNN with '
            'memory(init=...) instead')
    in_dim = int(input.shape[-1])
    if in_dim % 3 != 0:
        raise ValueError(
            'gru_group input width %d is not divisible by 3 — the input '
            'must already carry the 3*size gate projection (use '
            'simple_gru to have the projection added for you)' % in_dim)
    if size is not None and size * 3 != in_dim:
        raise ValueError(
            'gru_group: size=%d but input width %d != 3*size' % (size,
                                                                 in_dim))
    return grumemory(input, size=size, reverse=reverse, act=act,
                     gate_act=gate_act, param_attr=gru_param_attr,
                     bias_attr=gru_bias_attr)


def gru_unit(input, memory_boot=None, size=None, name=None,
             gru_bias_attr=None, gru_param_attr=None, act=None,
             gate_act=None, gru_layer_attr=None, naive=False):
    """Reference networks.py:940 gru_unit — the single-step form used
    inside recurrent_group; over a whole sequence it computes what
    gru_group does, so the shim shares that path."""
    return gru_group(input, memory_boot=memory_boot, size=size, name=name,
                     gru_bias_attr=gru_bias_attr,
                     gru_param_attr=gru_param_attr, act=act,
                     gate_act=gate_act, naive=naive)


def bidirectional_lstm(input, size, name=None, return_seq=False, **kwargs):
    fwd = simple_lstm(input, size, reverse=False)
    bwd = simple_lstm(input, size, reverse=True)
    if return_seq:
        return concat_layer([fwd, bwd])
    # full-sequence summaries: LAST step of the forward scan, FIRST of
    # the backward (bwd[:, 0] is the state after consuming the whole
    # reversed sequence), as in reference networks.py bidirectional_lstm
    return concat_layer([
        _fl.sequence_last_step(fwd, length=_len_of(input)),
        _fl.sequence_first_step(bwd, length=_len_of(input))])


def bidirectional_gru(input, size, name=None, return_seq=False, **kwargs):
    fwd = simple_gru(input, size, reverse=False)
    bwd = simple_gru(input, size, reverse=True)
    if return_seq:
        return concat_layer([fwd, bwd])
    return concat_layer([
        _fl.sequence_last_step(fwd, length=_len_of(input)),
        _fl.sequence_first_step(bwd, length=_len_of(input))])


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         name=None, pool_type=None, act=None,
                         groups=1, conv_stride=1, conv_padding=0,
                         bias_attr=None, num_channel=None, num_channels=None,
                         param_attr=None, shared_bias=True,
                         conv_layer_attr=None, pool_stride=1,
                         pool_padding=0, pool_layer_attr=None):
    from .layers import _maybe_image
    x = _maybe_image(input, num_channels or num_channel)
    ptype = getattr(pool_type, 'name', pool_type) or 'max'
    if ptype in ('average', 'sum', 'sqrt'):
        ptype = 'avg'
    conv = _fl.conv2d(input=x, num_filters=num_filters,
                      filter_size=filter_size, stride=conv_stride,
                      padding=conv_padding, groups=groups,
                      act=_act_name(act) or 'relu',
                      param_attr=_pa(param_attr),
                      bias_attr=_pa(bias_attr)
                      if bias_attr is not None else None)
    return _fl.pool2d(input=conv, pool_size=pool_size,
                      pool_stride=pool_stride or pool_size,
                      pool_padding=pool_padding, pool_type=ptype)


def img_conv_bn_pool(input, filter_size, num_filters, pool_size, name=None,
                     num_channels=None, conv_padding=0, conv_stride=1,
                     act=None, pool_stride=1, pool_type=None, **kwargs):
    conv = img_conv_layer(input, filter_size, num_filters,
                          num_channels=num_channels, stride=conv_stride,
                          padding=conv_padding, act=None)
    bn = _fl.batch_norm(input=conv, act=_act_name(act) or 'relu')
    return img_pool_layer(bn, pool_size, stride=pool_stride,
                          pool_type=pool_type)


def img_conv_group(input, conv_num_filter, pool_size, num_channels=None,
                   conv_padding=1, conv_filter_size=3, conv_act=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0,
                   pool_stride=1, pool_type=None, param_attr=None):
    from .layers import _maybe_image
    x = _maybe_image(input, num_channels)
    n = len(conv_num_filter)

    def rep(v):
        return v if isinstance(v, (list, tuple)) else [v] * n

    return _nets.img_conv_group(
        input=x, conv_num_filter=list(conv_num_filter),
        pool_size=pool_size, conv_padding=rep(conv_padding),
        conv_filter_size=rep(conv_filter_size),
        conv_act=_act_name(conv_act) or 'relu',
        conv_with_batchnorm=rep(conv_with_batchnorm),
        conv_batchnorm_drop_rate=rep(conv_batchnorm_drop_rate),
        pool_stride=pool_stride,
        pool_type=getattr(pool_type, 'name', pool_type) or 'max')


def small_vgg(input_image, num_channels, num_classes):
    """The cifar-scale VGG of reference networks.py small_vgg."""
    from ..models.vgg import vgg_bn_drop
    from .layers import _maybe_image
    x = _maybe_image(input_image, num_channels)
    return _fl.fc(input=vgg_bn_drop(x), size=num_classes, act='softmax')


def vgg_16_network(input_image, num_channels, num_classes=1000):
    x = input_image
    for filters, reps in [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]:
        x = img_conv_group(x, [filters] * reps, pool_size=2,
                           num_channels=num_channels if filters == 64
                           else None, pool_stride=2,
                           conv_act=ReluActivation())
    x = _fl.fc(input=x, size=4096, act='relu')
    x = _fl.dropout(x, dropout_prob=0.5)
    x = _fl.fc(input=x, size=4096, act='relu')
    x = _fl.dropout(x, dropout_prob=0.5)
    return _fl.fc(input=x, size=num_classes, act='softmax')


def img_separable_conv(input, num_channels, num_out_channels, filter_size,
                       stride=1, padding=0, depth_multiplier=1, act=None,
                       bias_attr=None, param_attr=None, shared_bias=True,
                       layer_attr=None, name=None):
    """Depthwise (groups=C) + pointwise 1x1, the mobilenet block."""
    from .layers import _maybe_image
    x = _maybe_image(input, num_channels)
    ch = num_channels or int(x.shape[1])
    depth = _fl.conv2d(input=x, num_filters=ch * depth_multiplier,
                       filter_size=filter_size, stride=stride,
                       padding=padding, groups=ch, act=None,
                       bias_attr=False)
    return _fl.conv2d(input=depth, num_filters=num_out_channels,
                      filter_size=1, act=_act_name(act),
                      bias_attr=_pa(bias_attr)
                      if bias_attr is not None else None)


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     name=None):
    """Bahdanau-style additive attention over a padded sequence
    (reference networks.py simple_attention). The math lives in
    models/rnn_search.py:additive_attention (one home); the param attrs
    are forwarded so name-based weight sharing keeps working."""
    from ..models.rnn_search import additive_attention
    return additive_attention(encoded_sequence, encoded_proj,
                              decoder_state,
                              int(encoded_proj.shape[-1]),
                              length=_len_of(encoded_sequence),
                              transform_param_attr=_pa(
                                  transform_param_attr),
                              score_param_attr=_pa(softmax_param_attr))


def dot_product_attention(attended_sequence, attending_sequence,
                          transformed_state, softmax_param_attr=None,
                          name=None):
    scores = _fl.matmul(attended_sequence,
                        _fl.unsqueeze(transformed_state, axes=[2]))
    weights = _fl.sequence_softmax(_fl.squeeze(scores, axes=[2]),
                                   length=_len_of(attended_sequence))
    ctx = _fl.matmul(_fl.unsqueeze(weights, axes=[1]), attending_sequence)
    return _fl.squeeze(ctx, axes=[1])


def multi_head_attention(query, key, value, key_proj_size, value_proj_size,
                         head_num, attention_type='dot-product attention',
                         softmax_param_attr=None, name=None):
    return _nets.scaled_dot_product_attention(
        queries=query, keys=key, values=value, num_heads=head_num)


def inputs(*args):
    """Declares the feed order (reference networks.py inputs); the
    Program already records data vars in creation order, so this is a
    no-op kept for config compatibility."""
    return list(args)


def outputs(*args):
    """Marks model outputs. Returns the vars; fetch_list plays the
    protobuf output-layer role."""
    outs = []
    for a in args:
        outs.extend(a if isinstance(a, (list, tuple)) else [a])
    return outs if len(outs) > 1 else outs[0]
