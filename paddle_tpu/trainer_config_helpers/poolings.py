"""v1 pooling-type objects (reference:
python/paddle/trainer_config_helpers/poolings.py). The `name` is the
fluid pool_type string; Cudnn* variants are spatial-pool aliases kept
for config compatibility (the XLA reduce_window lowering serves both).
"""

__all__ = ['BasePoolingType', 'MaxPooling', 'AvgPooling',
           'MaxWithMaskPooling', 'CudnnMaxPooling', 'CudnnAvgPooling',
           'CudnnAvgInclPadPooling', 'SumPooling', 'SquareRootNPooling']


class BasePoolingType(object):
    name = None

    def __repr__(self):
        return type(self).__name__


class MaxPooling(BasePoolingType):
    name = 'max'

    def __init__(self, output_max_index=False):
        self.output_max_index = output_max_index


class MaxWithMaskPooling(BasePoolingType):
    name = 'max'


class CudnnMaxPooling(BasePoolingType):
    name = 'max'


class AvgPooling(BasePoolingType):
    name = 'average'


class CudnnAvgPooling(BasePoolingType):
    name = 'average'


class CudnnAvgInclPadPooling(BasePoolingType):
    name = 'average'


class SumPooling(BasePoolingType):
    name = 'sum'


class SquareRootNPooling(BasePoolingType):
    name = 'sqrt'
