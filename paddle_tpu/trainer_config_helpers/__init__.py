"""v1 `trainer_config_helpers` compatibility surface.

Reference: python/paddle/trainer_config_helpers/__init__.py (star-export
of layers/networks/activations/poolings/attrs/optimizers/evaluators —
the declarative API the legacy trainer consumed, and the layer
vocabulary v2 re-exported). A v1 config ports by changing
`from paddle.trainer_config_helpers import *` to
`from paddle_tpu.trainer_config_helpers import *`; every helper builds
fluid IR eagerly (see layers.py for the semantics and the documented
divergences).
"""

from .activations import *      # noqa: F401,F403
from .attrs import *            # noqa: F401,F403
from .layers import *           # noqa: F401,F403
from .networks import *         # noqa: F401,F403
from .recurrent import *        # noqa: F401,F403
from .optimizers import *       # noqa: F401,F403
from .poolings import *         # noqa: F401,F403
from . import evaluators        # noqa: F401
