"""v1 layer API over the fluid IR.

Reference: python/paddle/trainer_config_helpers/layers.py:1 (7,531 LoC of
declarative layer definitions emitting the v1 config protobuf that the
legacy trainer/gserver stack consumed). Here every helper builds fluid
IR ops EAGERLY into the default program, exactly like the v2 shim
(`paddle_tpu/v2/layer.py`) — LayerOutput IS the fluid Variable, and a v1
config function becomes an ordinary model builder whose Program compiles
to one XLA computation. SURVEY §6.2 descoped the v1 *runtime* (gserver);
this module closes the v1 *API* gap on top of the lowerings we already
have, so legacy configs port by changing only the import line.

Divergences (documented, tested):
- Sequence-ness lives on the data layer (`seq_type=1` / `dtype=`), not
  in a DataProvider config — there is no config parser here. Sequences
  are padded [B, T, ...] with a companion '<name>_len' mask var
  (SURVEY §6 LoD stance), carried through sequence-preserving layers.
- recurrent_group / beam_search generation: use fluid DynamicRNN /
  layers.beam_search — the step-function style maps 1:1.
- Unlisted names raise NotImplementedError naming the fluid equivalent.
"""

import math

from .. import layers as _fl
from .activations import BaseActivation
from .attrs import apply_extra_attr, to_fluid_param_attr

__all__ = [
    'LayerOutput', 'data_layer', 'fc_layer', 'embedding_layer',
    'mixed_layer', 'full_matrix_projection', 'identity_projection',
    'table_projection', 'dotmul_projection', 'scaling_projection',
    'trans_full_matrix_projection', 'context_projection',
    'dotmul_operator',
    'pooling_layer', 'last_seq', 'first_seq', 'expand_layer',
    'repeat_layer', 'seq_reshape_layer', 'seq_concat_layer',
    'lstmemory', 'grumemory', 'recurrent_layer', 'gru_step_layer',
    'gru_step_naive_layer', 'lstm_step_layer', 'get_output_layer',
    'slice_projection',
    'img_conv_layer', 'img_pool_layer', 'batch_norm_layer',
    'img_cmrnorm_layer', 'maxout_layer', 'spp_layer', 'pad_layer',
    'roi_pool_layer', 'bilinear_interp_layer',
    'addto_layer', 'concat_layer', 'cos_sim', 'l2_distance_layer',
    'trans_layer', 'rotate_layer', 'scaling_layer', 'slope_intercept_layer',
    'interpolation_layer', 'power_layer', 'sum_to_one_norm_layer',
    'row_l2_norm_layer', 'clip_layer', 'dropout_layer', 'prelu_layer',
    'maxid_layer', 'sampling_id_layer', 'multiplex_layer',
    'tensor_layer', 'dot_prod_layer', 'out_prod_layer', 'row_conv_layer',
    'crop_layer', 'conv_shift_layer', 'gated_unit_layer',
    'linear_comb_layer', 'convex_comb_layer',
    'block_expand_layer', 'priorbox_layer', 'cross_channel_norm_layer',
    'detection_output_layer', 'multibox_loss_layer',
    'kmax_seq_score_layer', 'seq_slice_layer', 'sub_seq_layer',
    'switch_order_layer', 'scale_shift_layer', 'resize_layer',
    'square_error_cost', 'regression_cost', 'classification_cost',
    'cross_entropy', 'multi_binary_label_cross_entropy', 'sum_cost',
    'rank_cost', 'huber_regression_cost', 'huber_classification_cost',
    'smooth_l1_cost', 'lambda_cost', 'cross_entropy_with_selfnorm',
    'crf_layer', 'crf_decoding_layer', 'ctc_layer', 'warp_ctc_layer',
    'nce_layer', 'hsigmoid',
    'print_layer', 'printer_layer', 'eos_layer',
    'factorization_machine', 'selective_fc_layer', 'img_conv3d_layer',
    'AggregateLevel', 'ExpandLevel', 'layer_support',
]

#: v1 LayerOutput == fluid Variable (eager IR build; docstring above).
from ..core.program import Variable as LayerOutput  # noqa: E402


class AggregateLevel(object):
    TO_NO_SEQUENCE = 'non-seq'
    TO_SEQUENCE = 'seq'
    EACH_TIMESTEP = 'non-seq'


class ExpandLevel(object):
    FROM_NO_SEQUENCE = 'non-seq'
    FROM_SEQUENCE = 'seq'


def layer_support(*args, **kwargs):  # decorator in v1; identity here
    def deco(fn):
        return fn
    return deco if not (len(args) == 1 and callable(args[0])) else args[0]


def _act_name(act):
    if act is None:
        return None
    if isinstance(act, BaseActivation) or isinstance(type(act), type) and \
            hasattr(act, 'name'):
        return act.name
    return act


def _apply_act(x, act):
    name = _act_name(act)
    if name is None:
        return x
    fn = getattr(_fl, name, None)
    if fn is None:
        raise ValueError('unknown activation %r' % name)
    return fn(x)


def _pa(attr):
    return to_fluid_param_attr(attr)


def _act_or(act, default):
    """Activation name with a default for UNSPECIFIED only: an explicit
    LinearActivation()/IdentityActivation() (whose v1 name is None)
    maps to 'identity', not to the default nonlinearity."""
    if act is None:
        return default
    return _act_name(act) or 'identity'


def _propagate_len(src, out):
    lv = getattr(src, '_v2_len_var', None)
    if lv is not None:
        out._v2_len_var = lv
    return out


# recurrent_group support: v1 memories link to the step layer whose
# name matches the memory's (reference layers.py memory/recurrent_group
# contract). Named layers built inside an active recurrent context
# register themselves here; recurrent.py resolves the links.
_RG_ACTIVE = []


def _rg_note(name, var):
    if name and _RG_ACTIVE:
        _RG_ACTIVE[-1].names[name] = var
    return var


def _len_of(x):
    return getattr(x, '_v2_len_var', None)


def data_layer(name, size, depth=None, height=None, width=None,
               dtype='float32', seq_type=0, layer_attr=None):
    """v1 data_layer is a flat float slot of `size` (images reshape at
    the first conv). Divergence: integer-id and sequence slots are
    declared HERE (dtype='int64' / seq_type=1) instead of in a
    DataProvider config."""
    if seq_type:
        shape = [-1] if dtype.startswith('int') and size > 1 else \
            ([-1, size] if not dtype.startswith('int') else [-1])
        var = _fl.data(name=name, shape=shape, dtype=dtype, lod_level=1)
        var._v2_len_var = _fl.data(name=name + '_len', shape=[],
                                   dtype='int32')
    elif height and width:
        ch = size // (height * width)
        var = _fl.data(name=name, shape=[ch, height, width], dtype=dtype)
    else:
        var = _fl.data(name=name, shape=[size] if size > 1 or
                       not dtype.startswith('int') else [1], dtype=dtype)
    var._v1_size = size
    return var


def fc_layer(input, size, act=None, name=None, param_attr=None,
             bias_attr=None, layer_attr=None):
    # Seq-ness must be read off the ORIGINAL inputs: the concat below
    # produces a fresh Variable with no _v2_len_var, so deciding
    # num_flatten_dims from it would treat a [B,T,D] concat of
    # sequences as a flat [B,D] matrix (negative fan-in in Xavier).
    if isinstance(input, (list, tuple)):
        seq_src = next((v for v in input if _is_seq(v)), None)
        if seq_src is not None and not all(_is_seq(v) for v in input):
            raise ValueError(
                'fc_layer: mixed sequence and non-sequence inputs — the '
                'v1 contract is that all inputs to one layer share a '
                'sequence layout. expand_layer the flat input over time '
                '(or pool the sequence) first.')
        # v1 contract: all sequence inputs to one layer share the SAME
        # layout; the first input's length var stands for all of them
        # (feeding mismatched per-row lengths is a config error the
        # reference also only caught at runtime).
        input = _fl.concat(
            [v if _is_seq(v) else _flatten2(v) for v in input], axis=-1)
        if seq_src is not None:
            _propagate_len(seq_src, input)
    out = _fl.fc(input=input, size=size, act=_act_name(act),
                 param_attr=_pa(param_attr), bias_attr=_pa(bias_attr)
                 if bias_attr is not None else None, name=name,
                 num_flatten_dims=2 if _is_seq(input) else 1)
    return _rg_note(name, apply_extra_attr(_propagate_len(input, out),
                                           layer_attr))


def _is_seq(v):
    return _len_of(v) is not None


def _flatten2(v):
    if v.shape is not None and len(v.shape) > 2 and not _is_seq(v):
        return _fl.reshape(v, [v.shape[0] if v.shape[0] else -1, -1])
    return v


def embedding_layer(input, size, name=None, param_attr=None,
                    layer_attr=None):
    vocab = getattr(input, '_v1_size', None)
    if vocab is None or not str(input.dtype).startswith('int'):
        raise ValueError(
            "embedding_layer needs an integer data_layer input "
            "(data_layer(..., dtype='int64', seq_type=1), size=vocab)")
    out = _fl.embedding(input=input, size=[vocab, size],
                        param_attr=_pa(param_attr))
    return apply_extra_attr(_propagate_len(input, out), layer_attr)


# ---------------------------------------------------------------- mixed

class _Projection(object):
    """Config-time projection marker; materialized by mixed_layer
    (reference layers.py full_matrix_projection et al. — each became a
    gserver Projection appended to a MixedLayer)."""

    def __init__(self, kind, input, size=0, param_attr=None, **kw):
        self.kind = kind
        self.input = input
        self.size = size
        self.param_attr = param_attr
        self.kw = kw

    def build(self, size):
        x = self.input
        size = self.size or size
        if self.kind == 'full':
            return _fl.fc(input=x, size=size, bias_attr=False,
                          param_attr=_pa(self.param_attr),
                          num_flatten_dims=2 if _is_seq(x) else 1)
        if self.kind == 'trans_full':
            w = _fl.create_parameter(shape=[size, int(x.shape[-1])],
                                     dtype='float32',
                                     attr=_pa(self.param_attr))
            return _fl.matmul(x, w, transpose_y=True)
        if self.kind == 'identity':
            off = self.kw.get('offset')
            if off is not None:
                return _fl.slice(x, axes=[x.ndim - 1 if hasattr(x, 'ndim')
                                          else len(x.shape) - 1],
                                 starts=[off], ends=[off + size])
            return x
        if self.kind == 'table':
            vocab = getattr(x, '_v1_size')
            return _fl.embedding(input=x, size=[vocab, size],
                                 param_attr=_pa(self.param_attr))
        if self.kind == 'dotmul':
            w = _fl.create_parameter(shape=[int(x.shape[-1])],
                                     dtype='float32',
                                     attr=_pa(self.param_attr))
            return _fl.elementwise_mul(x, w)
        if self.kind == 'scaling':
            w = _fl.create_parameter(shape=[1], dtype='float32',
                                     attr=_pa(self.param_attr))
            return _fl.elementwise_mul(x, w)
        if self.kind == 'context':
            return _context_concat(x, self.kw['context_start'],
                                   self.kw['context_len'])
        if self.kind == 'slices':
            ax = len(x.shape) - 1
            parts = [_fl.slice(x, axes=[ax], starts=[b], ends=[e])
                     for b, e in self.kw['slices']]
            return _fl.concat(parts, axis=-1)
        raise NotImplementedError(self.kind)


def full_matrix_projection(input, size=0, param_attr=None):
    return _Projection('full', input, size, param_attr)


def trans_full_matrix_projection(input, size=0, param_attr=None):
    return _Projection('trans_full', input, size, param_attr)


def identity_projection(input, offset=None, size=None):
    return _Projection('identity', input, size or 0, offset=offset)


def table_projection(input, size=0, param_attr=None):
    return _Projection('table', input, size, param_attr)


def dotmul_projection(input, param_attr=None):
    return _Projection('dotmul', input, 0, param_attr)


def scaling_projection(input, param_attr=None):
    return _Projection('scaling', input, 0, param_attr)


def context_projection(input, context_len, context_start=None,
                       padding_attr=False):
    start = context_start if context_start is not None \
        else -(context_len // 2)
    return _Projection('context', input, 0, None,
                       context_start=start, context_len=context_len)


def dotmul_operator(a, b, scale=1.0):
    """Binary operator form: scale * a .* b (no parameter)."""
    out = _fl.elementwise_mul(a, b)
    if scale != 1.0:
        out = _fl.scale(out, scale=scale)
    return out


def _context_concat(x, start, length):
    """[B, T, D] -> [B, T, D*length]: concat of time-shifted copies,
    zero-padded at the borders (gserver ContextProjection semantics).
    T is dynamic at build time, so the shifts use end-relative slices."""
    outs = []
    for i in range(length):
        off = start + i
        if off > 0:   # y[t] = x[t+off]: drop the head, zero-pad the tail
            shifted = _fl.pad(x, [0, 0, 0, off, 0, 0])
            shifted = _fl.slice(shifted, axes=[1], starts=[off],
                                ends=[2 ** 31 - 1])
        elif off < 0:  # zero-pad the head, drop the tail
            shifted = _fl.pad(x, [0, 0, -off, 0, 0, 0])
            shifted = _fl.slice(shifted, axes=[1], starts=[0], ends=[off])
        else:
            shifted = x
        outs.append(shifted)
    return _fl.concat(outs, axis=-1)


def mixed_layer(size=0, input=None, name=None, act=None, bias_attr=None,
                layer_attr=None):
    projs = input if isinstance(input, (list, tuple)) else [input]
    terms = []
    src_seq = None
    for p in projs:
        if isinstance(p, _Projection):
            terms.append(p.build(size))
            if _is_seq(p.input):
                src_seq = p.input
        else:  # a raw var or operator result acts as identity
            terms.append(p)
            if _is_seq(p):
                src_seq = p
    out = terms[0]
    for t in terms[1:]:
        out = _fl.elementwise_add(out, t)
    if bias_attr is not None and bias_attr is not False:
        bias = _fl.create_parameter(
            shape=[int(out.shape[-1])], dtype='float32',
            attr=_pa(bias_attr) if not isinstance(bias_attr, bool) else None,
            is_bias=True)
        out = _fl.elementwise_add(out, bias)
    out = _apply_act(out, act)
    if src_seq is not None:
        out = _propagate_len(src_seq, out)
    return _rg_note(name, apply_extra_attr(out, layer_attr))


# ------------------------------------------------------------- sequence

def pooling_layer(input, pooling_type=None, name=None, bias_attr=None,
                  agg_level=None, layer_attr=None):
    ptype = getattr(pooling_type, 'name', pooling_type) or 'max'
    from ..layers import sequence
    return sequence.sequence_pool(input=input, pool_type=ptype,
                                  length=_len_of(input))


def last_seq(input, agg_level=None, name=None, layer_attr=None):
    return _fl.sequence_last_step(input, length=_len_of(input))


def first_seq(input, agg_level=None, name=None, layer_attr=None):
    return _fl.sequence_first_step(input, length=_len_of(input))


def expand_layer(input, expand_as, name=None, bias_attr=False,
                 expand_level=None, layer_attr=None):
    out = _fl.sequence_expand(input, expand_as)
    return _propagate_len(expand_as, out)


def repeat_layer(input, num_repeats, as_row_vector=True, act=None,
                 name=None, layer_attr=None):
    """[a b c] -> [a b c a b c] (row-vector mode) or
    [a a b b c c] (column-vector mode), per the reference docstring."""
    d = int(input.shape[-1])
    if as_row_vector:
        out = _fl.concat([input] * num_repeats, axis=-1)
    else:
        out = _fl.reshape(
            _fl.expand(_fl.unsqueeze(input, axes=[2]),
                       [1] * len(input.shape) + [num_repeats]),
            list(input.shape[:-1]) + [d * num_repeats])
    return _apply_act(out, act)


def seq_reshape_layer(input, reshape_size, act=None, name=None,
                      layer_attr=None, bias_attr=None):
    return _apply_act(_fl.sequence_reshape(input, reshape_size), act)


def seq_concat_layer(a, b, act=None, name=None, layer_attr=None,
                     bias_attr=None):
    out = _fl.sequence_concat([a, b])
    return _apply_act(out, act)


def lstmemory(input, size=None, name=None, reverse=False, act=None,
              gate_act=None, state_act=None, param_attr=None,
              bias_attr=None, layer_attr=None):
    """v1 lstmemory consumes a 4*size pre-projection (reference
    layers.py lstmemory doc: 'input of this layer should be the fc
    projected sum'); identical contract to fluid dynamic_lstm."""
    in_dim = int(input.shape[-1])
    hidden, _ = _fl.dynamic_lstm(
        input=input, size=in_dim, is_reverse=reverse,
        gate_activation=_act_or(gate_act, 'sigmoid'),
        cell_activation=_act_or(state_act, 'tanh'),
        candidate_activation=_act_or(act, 'tanh'),
        param_attr=_pa(param_attr), bias_attr=_pa(bias_attr),
        length=_len_of(input))
    return _propagate_len(input, hidden)


def grumemory(input, size=None, name=None, reverse=False, act=None,
              gate_act=None, param_attr=None, bias_attr=None,
              layer_attr=None):
    """Consumes a 3*size pre-projection, like fluid dynamic_gru."""
    in_dim = int(input.shape[-1])
    out = _fl.dynamic_gru(
        input=input, size=in_dim // 3, is_reverse=reverse,
        gate_activation=_act_or(gate_act, 'sigmoid'),
        candidate_activation=_act_or(act, 'tanh'),
        param_attr=_pa(param_attr), bias_attr=_pa(bias_attr),
        length=_len_of(input))
    return _propagate_len(input, out)


def recurrent_layer(input, act=None, bias_attr=None, param_attr=None,
                    name=None, reverse=False, layer_attr=None):
    """Plain elman recurrence h_t = act(x_t + W h_{t-1}) over the padded
    time axis (reference recurrent_layer; fluid has no direct analog so
    it is built from the rnn scan op)."""
    from ..layers.rnn import simple_rnn
    out = simple_rnn(input, act=_act_or(act, 'tanh'),
                     is_reverse=reverse, param_attr=_pa(param_attr),
                     bias_attr=_pa(bias_attr) if bias_attr is not None
                     else None, length=_len_of(input))
    return _propagate_len(input, out)


def gru_step_layer(input, output_mem, size=None, act=None,
                   name=None, gate_act=None, param_attr=None,
                   bias_attr=None, layer_attr=None):
    """One GRU step (inside a user-managed recurrence): input is the
    3*size pre-projection, output_mem the previous hidden state."""
    new_h, _, _ = _fl.gru_unit(
        input, output_mem, size=3 * int(output_mem.shape[-1]),
        activation=_act_or(act, 'tanh'),
        gate_activation=_act_or(gate_act, 'sigmoid'),
        param_attr=_pa(param_attr), bias_attr=_pa(bias_attr))
    return _rg_note(name, new_h)


gru_step_naive_layer = gru_step_layer


def lstm_step_layer(input, state, size=None, act=None, name=None,
                    gate_act=None, state_act=None, bias_attr=None,
                    layer_attr=None):
    """One LSTM step (reference layers.py lstm_step_layer, r5): `input`
    is the 4*size gate pre-projection (the v1 config supplies
    W_x·x + W_h·h_prev through a mixed_layer), `state` the previous
    CELL. Returns the new hidden — the layer this `name` registers for
    memory linkage — and the new cell rides
    get_output_layer(input=..., arg_name='state') like the reference.
    Divergences: gate order inside the projection is the lstm_unit
    op's (i,f,g,o — immaterial for freshly-trained shim params), and
    activations are pinned to the op's sigmoid/tanh contract."""
    for a, nm in ((act, 'act'), (state_act, 'state_act')):
        if a is not None and _act_name(a) not in (None, 'tanh'):
            raise NotImplementedError(
                'lstm_step_layer(%s=%s): the TPU lstm_unit op pins '
                'tanh state / sigmoid gates' % (nm, _act_name(a)))
    if gate_act is not None and _act_name(gate_act) not in (None,
                                                            'sigmoid'):
        raise NotImplementedError(
            'lstm_step_layer(gate_act=%s): sigmoid gates are pinned'
            % _act_name(gate_act))
    from ..layers.helper import LayerHelper
    helper = LayerHelper('lstm_step')
    c = helper.create_variable_for_type_inference(input.dtype)
    h = helper.create_variable_for_type_inference(input.dtype)
    c.shape = state.shape
    h.shape = state.shape
    helper.append_op(type='lstm_unit',
                     inputs={'X': [input], 'C_prev': [state]},
                     outputs={'C': [c], 'H': [h]},
                     attrs={'forget_bias': 0.0})
    h._v1_cell = c
    return _rg_note(name, h)


def get_output_layer(input, arg_name, name=None, layer_attr=None):
    """v1 selected a named secondary output of a layer. The shimmed
    lstm_step_layer stashes its cell on the hidden (r5) — selecting
    'state' returns it (and registers `name` for memory linkage, the
    lstmemory_unit pattern); whole-sequence lstmemory still routes to
    dynamic_lstm for the cell."""
    if arg_name in ('state', 'cell'):
        cell = getattr(input, '_v1_cell', None)
        if cell is not None:
            return _rg_note(name, cell)
        raise NotImplementedError(
            "get_output_layer(arg_name=%r): use layers.dynamic_lstm "
            "directly — it returns (hidden, cell) as a tuple" % arg_name)
    return input


def slice_projection(input, slices):
    """(begin, end) feature-axis slices CONCATENATED (v1 semantics) —
    one projection, so mixed_layer treats the concat as a single term
    rather than summing the slices."""
    return _Projection('slices', input, sum(e - b for b, e in slices),
                       slices=list(slices))


# ---------------------------------------------------------------- image

def _maybe_image(input, num_channels):
    """v1 conv/pool accept the flat data_layer slot; reshape to NCHW
    using the declared size (square images, like the reference's
    inferred height/width)."""
    if input.shape is not None and len(input.shape) == 2 and num_channels:
        hw = int(input.shape[-1]) // num_channels
        side = int(math.isqrt(hw))
        return _fl.reshape(input, [-1, num_channels, side, side])
    return input


def img_conv_layer(input, filter_size, num_filters, num_channels=None,
                   stride=1, padding=0, dilation=1, groups=1, act=None,
                   name=None, bias_attr=None, param_attr=None,
                   shared_biases=True, layer_attr=None, trans=False):
    x = _maybe_image(input, num_channels)
    fn = _fl.conv2d_transpose if trans else _fl.conv2d
    out = fn(input=x, num_filters=num_filters, filter_size=filter_size,
             stride=stride, padding=padding, groups=groups,
             act=_act_name(act), param_attr=_pa(param_attr),
             bias_attr=_pa(bias_attr) if bias_attr is not None else None)
    return apply_extra_attr(out, layer_attr)


def img_conv3d_layer(input, filter_size, num_filters, num_channels=None,
                     stride=1, padding=0, dilation=1, groups=1, act=None,
                     name=None, bias_attr=None, param_attr=None,
                     shared_biases=True, layer_attr=None, trans=False,
                     layer_type=None):
    """3-D convolution (reference img_conv3d_layer, r5): input must be
    a 5-D [B, C, D, H, W] var (fluid data with shape [C, D, H, W] — the
    v1 flat-slot inference has no depth metadata to recover)."""
    if trans:
        raise NotImplementedError('img_conv3d_layer(trans=True): no '
                                  'conv3d_transpose lowering')
    if input.shape is None or len(input.shape) != 5:
        raise ValueError('img_conv3d_layer needs a 5-D [B,C,D,H,W] '
                         'input var')
    out = _fl.conv3d(input=input, num_filters=num_filters,
                     filter_size=filter_size, stride=stride,
                     padding=padding, dilation=dilation, groups=groups,
                     act=_act_name(act), param_attr=_pa(param_attr),
                     bias_attr=_pa(bias_attr)
                     if bias_attr is not None else None)
    return _rg_note(name, apply_extra_attr(out, layer_attr))


def img_pool_layer(input, pool_size, num_channels=None, pool_type=None,
                   stride=1, padding=0, name=None, ceil_mode=True,
                   pool_size_y=None, stride_y=None, padding_y=None,
                   exclude_mode=None, layer_attr=None):
    x = _maybe_image(input, num_channels)
    ptype = getattr(pool_type, 'name', pool_type) or 'max'
    if ptype in ('average', 'sum', 'sqrt'):
        ptype = 'avg'
    return _fl.pool2d(input=x, pool_size=pool_size, pool_stride=stride,
                      pool_padding=padding, pool_type=ptype,
                      ceil_mode=ceil_mode)


def batch_norm_layer(input, act=None, name=None, num_channels=None,
                     bias_attr=None, param_attr=None, layer_attr=None,
                     batch_norm_type=None, moving_average_fraction=0.9,
                     use_global_stats=None, mean_var_names=None):
    x = _maybe_image(input, num_channels)
    return _fl.batch_norm(input=x, act=_act_name(act),
                          momentum=moving_average_fraction,
                          is_test=bool(use_global_stats))


def img_cmrnorm_layer(input, size, scale=0.0128, power=0.75, name=None,
                      num_channels=None, layer_attr=None):
    """Local response normalization across channels (reference
    img_cmrnorm_layer -> gserver CMRProjectionNormLayer; fluid lrn)."""
    x = _maybe_image(input, num_channels)
    return _fl.lrn(x, n=size, alpha=scale, beta=power)


def maxout_layer(input, groups, num_channels=None, name=None,
                 layer_attr=None):
    return _fl.maxout(_maybe_image(input, num_channels), groups=groups)


def spp_layer(input, name=None, num_channels=None, pool_type=None,
              pyramid_height=None, layer_attr=None):
    ptype = getattr(pool_type, 'name', pool_type) or 'max'
    return _fl.spp(_maybe_image(input, num_channels),
                   pyramid_height=pyramid_height or 2, pool_type=ptype)


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, name=None,
              layer_attr=None):
    pads = []
    for p in [(0, 0), tuple(pad_c or (0, 0)), tuple(pad_h or (0, 0)),
              tuple(pad_w or (0, 0))]:
        pads.extend(p)
    return _fl.pad(input, pads)


def roi_pool_layer(input, rois, pooled_width, pooled_height,
                   spatial_scale, num_channels=None, name=None):
    return _fl.roi_pool(input=_maybe_image(input, num_channels), rois=rois,
                        pooled_height=pooled_height,
                        pooled_width=pooled_width,
                        spatial_scale=spatial_scale)


def bilinear_interp_layer(input, out_size_x=None, out_size_y=None,
                          name=None, layer_attr=None):
    return _fl.resize_bilinear(input, out_shape=[out_size_y, out_size_x])


# ----------------------------------------------------------- arithmetic

def addto_layer(input, act=None, name=None, bias_attr=None,
                layer_attr=None):
    inputs = input if isinstance(input, (list, tuple)) else [input]
    out = inputs[0]
    for t in inputs[1:]:
        out = _fl.elementwise_add(out, t)
    return _rg_note(name, _propagate_len(inputs[0], _apply_act(out, act)))


def concat_layer(input, act=None, name=None, layer_attr=None,
                 bias_attr=None):
    out = _fl.concat(list(input), axis=-1)
    return _propagate_len(input[0], _apply_act(out, act))


def cos_sim(a, b, scale=1, size=1, name=None, layer_attr=None):
    return _fl.scale(_fl.cos_sim(a, b), scale=float(scale))


def l2_distance_layer(x, y, name=None, layer_attr=None):
    return _fl.sqrt(_fl.reduce_sum(_fl.square(
        _fl.elementwise_sub(x, y)), dim=-1, keep_dim=True))


def trans_layer(input, name=None, layer_attr=None):
    return _fl.transpose(input, [0, 2, 1] if len(input.shape) == 3
                         else [1, 0])


def rotate_layer(input, height, width, name=None, layer_attr=None):
    """90° CCW rotation of the [h, w] plane (gserver RotateLayer)."""
    c = int(input.shape[-1]) // (height * width)
    x = _fl.reshape(input, [-1, c, height, width])
    x = _fl.transpose(_fl.reverse(x, axis=[3]), [0, 1, 3, 2])
    return _fl.reshape(x, [-1, c * height * width])


def scaling_layer(input, weight, name=None, layer_attr=None):
    """Row-wise scale: weight [B, 1] * input [B, D]."""
    return _fl.elementwise_mul(input, weight)


def slope_intercept_layer(input, name=None, slope=1.0, intercept=0.0,
                          layer_attr=None):
    return _fl.scale(input, scale=slope, bias=intercept)


def interpolation_layer(input, weight, name=None, layer_attr=None):
    """w * a + (1 - w) * b, weight [B, 1] (gserver InterpolationLayer)."""
    a, b = input
    return _fl.elementwise_add(
        _fl.elementwise_mul(a, weight),
        _fl.elementwise_mul(b, _fl.scale(weight, scale=-1.0, bias=1.0)))


def power_layer(input, weight, name=None, layer_attr=None):
    return _fl.elementwise_pow(input, weight)


def sum_to_one_norm_layer(input, name=None, layer_attr=None):
    s = _fl.reduce_sum(input, dim=-1, keep_dim=True)
    return _fl.elementwise_div(input, s)


def row_l2_norm_layer(input, name=None, layer_attr=None):
    return _fl.l2_normalize(input, axis=-1)


def clip_layer(input, min, max, name=None):
    return _fl.clip(input, min=float(min), max=float(max))


def dropout_layer(input, dropout_rate, name=None):
    return _propagate_len(input, _fl.dropout(input,
                                             dropout_prob=dropout_rate))


def prelu_layer(input, name=None, partial_sum=1, param_attr=None,
                layer_attr=None):
    mode = 'all' if partial_sum == 1 else 'channel'
    return _fl.prelu(input, mode=mode, param_attr=_pa(param_attr))


def maxid_layer(input, name=None, layer_attr=None):
    return _fl.argmax(input, axis=-1)


def sampling_id_layer(input, name=None, layer_attr=None):
    """Sample an id from a probability row (gserver SamplingIdLayer):
    inverse-CDF on a uniform draw, vectorized."""
    u = _fl.uniform_random_batch_size_like(input, shape=[-1, 1], min=0.,
                                           max=1.)
    cdf = _fl.cumsum(input, axis=-1)
    return _fl.reduce_sum(_fl.cast(_fl.less_than(cdf, u), 'int64'), dim=-1)


def multiplex_layer(input, name=None, layer_attr=None):
    index, rest = input[0], input[1:]
    return _fl.multiplex(inputs=list(rest), index=index)


def tensor_layer(a, b, size, act=None, name=None, param_attr=None,
                 bias_attr=None, layer_attr=None):
    return _fl.bilinear_tensor_product(a, b, size, act=_act_name(act),
                                       param_attr=_pa(param_attr),
                                       bias_attr=_pa(bias_attr))


def dot_prod_layer(input1, input2, name=None, layer_attr=None):
    return _fl.reduce_sum(_fl.elementwise_mul(input1, input2), dim=-1,
                          keep_dim=True)


def out_prod_layer(input1, input2, name=None, layer_attr=None):
    return _fl.matmul(_fl.unsqueeze(input1, axes=[2]),
                      _fl.unsqueeze(input2, axes=[1]))


def row_conv_layer(input, context_len, act=None, name=None,
                   param_attr=None, layer_attr=None):
    return _fl.row_conv(input, context_len, param_attr=_pa(param_attr),
                        act=_act_name(act))


def crop_layer(input, offset, axis=2, shape=None, name=None,
               layer_attr=None):
    x, ref = input if isinstance(input, (list, tuple)) else (input, None)
    if shape is None and ref is not None:
        shape = list(ref.shape)
    return _fl.crop(x, shape=shape, offsets=offset)


def conv_shift_layer(a, b, name=None, layer_attr=None):
    return _fl.conv_shift(a, b)


def gated_unit_layer(input, size, act=None, name=None, gate_attr=None,
                     gate_param_attr=None, gate_bias_attr=None,
                     inproj_attr=None, inproj_param_attr=None,
                     inproj_bias_attr=None, layer_attr=None):
    proj = _fl.fc(input=input, size=size, act=_act_name(act),
                  param_attr=_pa(inproj_param_attr))
    gate = _fl.fc(input=input, size=size, act='sigmoid',
                  param_attr=_pa(gate_param_attr))
    return _fl.elementwise_mul(proj, gate)


def linear_comb_layer(weights, vectors, size=None, name=None,
                      layer_attr=None):
    """weights [B, M], vectors [B, M*size] -> [B, size]: per-row linear
    combination of M sub-vectors (gserver LinearCombLayer)."""
    m = int(weights.shape[-1])
    size = size or int(vectors.shape[-1]) // m
    v = _fl.reshape(vectors, [-1, m, size])
    return _fl.squeeze(_fl.matmul(_fl.unsqueeze(weights, axes=[1]), v),
                       axes=[1])


convex_comb_layer = linear_comb_layer


def block_expand_layer(input, block_x=1, block_y=1, stride_x=1,
                       stride_y=1, padding_x=0, padding_y=0,
                       num_channels=None, name=None, layer_attr=None):
    """v1 block_expand -> fluid im2sequence (same im2col semantics)."""
    return _fl.im2sequence(
        input=_maybe_image(input, num_channels),
        filter_size=[block_y, block_x], stride=[stride_y, stride_x],
        padding=[padding_y, padding_x])


def priorbox_layer(input, image, aspect_ratio, variance, min_size,
                   max_size=None, name=None):
    box, var = _fl.prior_box(
        input=input, image=image, min_sizes=list(min_size),
        max_sizes=list(max_size) if max_size else None,
        aspect_ratios=list(aspect_ratio), variance=list(variance))
    # flatten [H, W, P, 4] -> [N, 4]: the box_coder/iou consumers index
    # priors per row (multi_box_head does the same reshape)
    return _fl.reshape(box, [-1, 4]), _fl.reshape(var, [-1, 4])


def cross_channel_norm_layer(input, name=None, param_attr=None):
    """L2 norm across channels with a learned per-channel scale (the
    SSD conv4_3 norm; gserver CrossChannelNormLayer)."""
    normed = _fl.l2_normalize(input, axis=1)
    c = int(input.shape[1])
    scale = _fl.create_parameter(shape=[c], dtype='float32',
                                 attr=_pa(param_attr))
    return _fl.elementwise_mul(normed, _fl.reshape(scale, [1, c, 1, 1]))


def _cat_heads(x):
    """v1 passes one loc/conf layer per feature map as a list; concat
    along the prior axis (entries must already be [B, P_i, ...], the
    shape the fluid detection stack consumes)."""
    if isinstance(x, (list, tuple)):
        return _fl.concat(list(x), axis=1)
    return x


def detection_output_layer(input_loc, input_conf, priorbox, num_classes,
                           nms_threshold=0.45, nms_top_k=400,
                           keep_top_k=200, confidence_threshold=0.01,
                           background_id=0, name=None):
    from ..layers import detection as _det
    input_loc = _cat_heads(input_loc)
    input_conf = _cat_heads(input_conf)
    return _det.detection_output(
        loc=input_loc, scores=input_conf, prior_box=priorbox[0]
        if isinstance(priorbox, (list, tuple)) else priorbox,
        prior_box_var=priorbox[1]
        if isinstance(priorbox, (list, tuple)) else None,
        nms_threshold=nms_threshold, nms_top_k=nms_top_k,
        keep_top_k=keep_top_k, score_threshold=confidence_threshold,
        background_label=background_id)


def multibox_loss_layer(input_loc, input_conf, priorbox, label, num_classes,
                        overlap_threshold=0.5, neg_pos_ratio=3.0,
                        neg_overlap=0.5, background_id=0, name=None,
                        gt_box=None):
    """v1 multibox loss -> fluid ssd_loss. Divergences: the v1
    DataProvider packed (label, box) together — pass gt_box explicitly;
    neg_overlap is accepted for config compatibility but fluid's
    per-prediction matching has no separate negative-overlap knob (a
    warning is emitted when a non-default value would be dropped)."""
    import warnings

    from ..layers import detection as _det
    if gt_box is None:
        raise ValueError(
            'multibox_loss_layer: pass gt_box= (ground-truth boxes '
            '[B, G, 4]). The v1 DataProvider packed boxes with the '
            'label slot; this framework feeds them as a separate '
            'data layer (see models/ssd.py).')
    if neg_overlap != 0.5:
        warnings.warn('multibox_loss_layer: neg_overlap=%r has no fluid '
                      'equivalent and is ignored (hard-negative mining '
                      'uses neg_pos_ratio only)' % (neg_overlap,))
    input_loc = _cat_heads(input_loc)
    input_conf = _cat_heads(input_conf)
    pb = priorbox[0] if isinstance(priorbox, (list, tuple)) else priorbox
    pbv = priorbox[1] if isinstance(priorbox, (list, tuple)) else None
    return _det.ssd_loss(
        location=input_loc, confidence=input_conf, gt_box=gt_box,
        gt_label=label, prior_box=pb, prior_box_var=pbv,
        overlap_threshold=overlap_threshold,
        neg_pos_ratio=neg_pos_ratio, background_label=background_id)


def kmax_seq_score_layer(input, name=None, beam_size=1):
    """Top-k scores over the time axis -> indices; padded positions
    are masked to -inf through the data layer's length var (v1 uses
    this on beam log-probs, which are negative — an unmasked pad zero
    would win every top-k)."""
    x = input
    if x.shape and len(x.shape) == 3 and x.shape[-1] == 1:
        x = _fl.squeeze(x, axes=[2])
    from ..layers.helper import LayerHelper
    helper = LayerHelper('kmax_seq_score')
    idx = helper.create_variable_for_type_inference('int64')
    if x.shape is not None:
        idx.shape = (x.shape[0], beam_size)
    inputs = {'X': [x]}
    lv = _len_of(input)
    if lv is not None:
        inputs['Length'] = [lv]
    helper.append_op(type='kmax_seq_score', inputs=inputs,
                     outputs={'Out': [idx]},
                     attrs={'beam_size': beam_size})
    return idx


def seq_slice_layer(input, starts, ends, name=None):
    """v1 slice by START/END indices, END INCLUSIVE (gserver
    SequenceSliceLayer.cpp:151-156: seqLen = end - beg + 1)."""
    if starts is None:
        starts = 0
    if ends is None:
        raise NotImplementedError(
            'seq_slice_layer(ends=None) (slice-to-end) needs the per-'
            'row length; use layers.sequence_slice with an explicit '
            'length computed from the data layer\'s <name>_len var')
    if not isinstance(starts, int) or not isinstance(ends, int):
        raise NotImplementedError(
            'seq_slice_layer: v1 accepted per-row index LAYERS for '
            'starts/ends; the shim supports static ints only — gather '
            'with layers.sequence_slice / layers.gather for dynamic '
            'positions')
    return _fl.sequence_slice(input=input, offset=starts,
                              length=ends - starts + 1)


def sub_seq_layer(input, offsets, sizes, name=None):
    if not isinstance(offsets, int) or not isinstance(sizes, int):
        raise NotImplementedError(
            'sub_seq_layer: v1 accepted per-row offset/size LAYERS; '
            'the shim supports static ints only — use '
            'layers.sequence_slice / layers.gather for dynamic forms')
    return _fl.sequence_slice(input=input, offset=offsets, length=sizes)


def switch_order_layer(input, reshape_axis=None, name=None):
    """v1 switch_order: NCHW -> NHWC (channels to last). Only the
    default axis grouping is shimmed; other reshape_axis values raise
    rather than silently diverge — compose layers.transpose +
    layers.reshape for custom groupings."""
    if reshape_axis not in (None, 1):
        raise NotImplementedError(
            'switch_order_layer(reshape_axis=%r): only the default '
            'channels-last grouping is shimmed; use layers.transpose '
            '+ layers.reshape' % (reshape_axis,))
    n = len(input.shape)
    perm = [0] + list(range(2, n)) + [1]
    return _fl.transpose(input, perm)


def scale_shift_layer(input, name=None, param_attr=None, bias_attr=None):
    """y = w * x + b with scalar learned w (and optional b)."""
    w = _fl.create_parameter(shape=[1], dtype='float32',
                             attr=_pa(param_attr))
    out = _fl.elementwise_mul(input, w)
    if bias_attr is not False:
        b = _fl.create_parameter(shape=[1], dtype='float32',
                                 attr=_pa(bias_attr)
                                 if bias_attr is not None else None,
                                 is_bias=True)
        out = _fl.elementwise_add(out, b)
    return out


def resize_layer(input, size, name=None):
    return _fl.reshape(input, [-1, size])


# ---------------------------------------------------------------- costs

def square_error_cost(input, label, name=None, weight=None,
                      coeff=1.0, layer_attr=None):
    cost = _fl.mean(_fl.square_error_cost(input=input, label=label))
    return _fl.scale(cost, scale=coeff) if coeff != 1.0 else cost


regression_cost = square_error_cost


def classification_cost(input, label, name=None, weight=None,
                        evaluator=None, coeff=1.0, layer_attr=None):
    """input = class probabilities (fc + SoftmaxActivation), per the
    reference contract."""
    cost = _fl.cross_entropy(input=input, label=label)
    if weight is not None:
        cost = _fl.elementwise_mul(cost, weight)
    cost = _fl.mean(cost)
    return _fl.scale(cost, scale=coeff) if coeff != 1.0 else cost


def cross_entropy(input, label, name=None, coeff=1.0, weight=None,
                  layer_attr=None):
    return classification_cost(input, label, weight=weight, coeff=coeff)


def multi_binary_label_cross_entropy(input, label, name=None, coeff=1.0,
                                     layer_attr=None):
    """input = sigmoid probabilities; label = multi-hot."""
    eps = 1e-8
    cost = _fl.reduce_sum(
        _fl.scale(_fl.elementwise_add(
            _fl.elementwise_mul(label, _fl.log(
                _fl.scale(input, bias=eps))),
            _fl.elementwise_mul(
                _fl.scale(label, scale=-1.0, bias=1.0),
                _fl.log(_fl.scale(_fl.scale(input, scale=-1.0, bias=1.0),
                                  bias=eps)))), scale=-1.0),
        dim=-1)
    cost = _fl.mean(cost)
    return _fl.scale(cost, scale=coeff) if coeff != 1.0 else cost


def sum_cost(input, name=None, layer_attr=None):
    return _fl.reduce_sum(input)


def rank_cost(left, right, label, weight=None, name=None, coeff=1.0,
              layer_attr=None):
    cost = _fl.mean(_fl.rank_loss(label=label, left=left, right=right))
    return _fl.scale(cost, scale=coeff) if coeff != 1.0 else cost


def huber_regression_cost(input, label, name=None, delta=1.0, coeff=1.0,
                          layer_attr=None):
    cost = _fl.mean(_fl.huber_loss(input=input, label=label, delta=delta))
    return _fl.scale(cost, scale=coeff) if coeff != 1.0 else cost


def huber_classification_cost(input, label, name=None, coeff=1.0,
                              layer_attr=None):
    """Modified huber on {0,1} labels mapped to {-1,+1}."""
    y = _fl.scale(_fl.cast(label, 'float32'), scale=2.0, bias=-1.0)
    cost = _fl.mean(_fl.modified_huber_loss(x=input, y=y))
    return _fl.scale(cost, scale=coeff) if coeff != 1.0 else cost


def smooth_l1_cost(input, label, name=None, coeff=1.0, layer_attr=None):
    cost = _fl.mean(_fl.smooth_l1(x=input, y=label))
    return _fl.scale(cost, scale=coeff) if coeff != 1.0 else cost


def lambda_cost(input, score, name=None, NDCG_num=5, max_sort_size=-1,
                layer_attr=None):
    raise NotImplementedError(
        'lambda_cost (LambdaRank) has no fluid lowering; rank_cost and '
        'margin_rank_loss cover pairwise ranking')


def cross_entropy_with_selfnorm(input, label, name=None, coeff=1.0,
                                softmax_selfnorm_alpha=0.1,
                                layer_attr=None):
    raise NotImplementedError(
        'cross_entropy_with_selfnorm is NCE-era; use nce_layer or '
        'softmax_with_cross_entropy')


# ------------------------------------------------------------- seq tags

def crf_layer(input, label, size=None, weight=None, param_attr=None,
              name=None, coeff=1.0, layer_attr=None):
    ll = _fl.linear_chain_crf(input=input, label=label,
                              param_attr=_pa(param_attr),
                              length=_len_of(input))
    return _fl.mean(ll)


def crf_decoding_layer(input, size=None, label=None, param_attr=None,
                       name=None, layer_attr=None):
    return _fl.crf_decoding(input=input, param_attr=_pa(param_attr),
                            length=_len_of(input))


def ctc_layer(input, label, size=None, name=None, norm_by_times=False,
              layer_attr=None):
    return _fl.warpctc(input=input, label=label,
                       norm_by_times=norm_by_times,
                       input_length=_len_of(input),
                       label_length=_len_of(label))


warp_ctc_layer = ctc_layer


def nce_layer(input, label, num_classes=None, act=None, param_attr=None,
              weight=None, num_neg_samples=10, neg_distribution=None,
              name=None, bias_attr=None, layer_attr=None):
    if isinstance(input, (list, tuple)):
        input = _fl.concat(list(input), axis=-1)
    return _fl.mean(_fl.nce(input=input, label=label,
                            num_total_classes=num_classes,
                            param_attr=_pa(param_attr),
                            bias_attr=_pa(bias_attr),
                            num_neg_samples=num_neg_samples))


def hsigmoid(input, label, num_classes=None, name=None, param_attr=None,
             bias_attr=None, layer_attr=None):
    raise NotImplementedError(
        'hierarchical sigmoid is served by nce_layer here (same '
        'large-softmax-approximation role, better MXU shape)')


# ----------------------------------------------------------------- misc

def print_layer(input, format=None, name=None):
    inputs = input if isinstance(input, (list, tuple)) else [input]
    for v in inputs:
        _fl.Print(v, message=format or '')
    return inputs[0]


printer_layer = print_layer


def eos_layer(input, eos_id, name=None, layer_attr=None):
    return _fl.cast(_fl.equal(input, _fl.fill_constant(
        shape=[1], dtype=input.dtype, value=eos_id)), 'float32')


def factorization_machine(input, factor_size, act=None, name=None,
                          param_attr=None, layer_attr=None):
    """2-order FM interactions (reference layers.py
    factorization_machine): y = Σ_{i<j} <v_i, v_j> x_i x_j via the
    sum-square identity 0.5·Σ_k[(xV)_k² − (x²)(V²)_k] — one [B,n]×[n,k]
    matmul instead of the O(n²) pair loop, MXU-shaped."""
    n = int(input.shape[-1])
    v = _fl.create_parameter(shape=[n, factor_size], dtype='float32',
                             attr=_pa(param_attr))
    xv = _fl.matmul(input, v)                              # [B, k]
    x2v2 = _fl.matmul(_fl.square(input), _fl.square(v))    # [B, k]
    out = _fl.scale(_fl.reduce_sum(
        _fl.elementwise_sub(_fl.square(xv), x2v2), dim=-1,
        keep_dim=True), scale=0.5)
    return _rg_note(name, _apply_act(out, act))


def selective_fc_layer(input, size, select=None, act=None, name=None,
                       pass_generation=False, has_selected_colums=True,
                       mul_ratio=0.02, param_attr=None, bias_attr=None,
                       layer_attr=None):
    """Reference selective_fc_layer: fc whose output is masked to the
    selected columns (select=None behaves exactly like fc_layer).
    Divergence: the reference computed ONLY the selected columns (a
    CPU-sparse trick); on the MXU the dense [B,n]×[n,size] matmul IS
    the fast path, so this computes dense and multiplies by the
    0/1 `select` mask — same output, TPU-shaped."""
    # list inputs go straight to fc_layer, which concats while
    # preserving sequence layout (a local _flatten2 pass would destroy
    # the [B,T,D] shape and drop the length var)
    out = fc_layer(input=input, size=size, act=act, name=name,
                   param_attr=param_attr, bias_attr=bias_attr)
    if select is not None:
        out = _fl.elementwise_mul(out, _fl.cast(select, 'float32'))
    return out


_FLUID_EQUIV = {
    # recurrent_group / memory / beam_search / StaticInput /
    # GeneratedInput are REAL since round 5: see recurrent.py
    # selective_fc_layer / factorization_machine are REAL since r5
    'sub_nested_seq_layer': 'SURVEY §6 LoD stance: depth>1 descoped',
    'img_pool3d_layer': 'layers.pool2d pattern over 3d',
    'scale_sub_region_layer': 'layers.crop + scale + paste',
    'conv_projection': 'img_conv_layer',
    'conv_operator': 'img_conv_layer',
    'SubsequenceInput': 'SURVEY §6 LoD stance: depth>1 descoped',
    'BeamInput': 'layers.beam_search',
    'cross_entropy_over_beam': 'layers.beam_search + softmax_with_cross_entropy',
}


def __getattr__(name):
    if name in _FLUID_EQUIV:
        raise NotImplementedError(
            'v1 %s is not shimmed; use %s' % (name, _FLUID_EQUIV[name]))
    raise AttributeError(name)
