"""v1 recurrent_group / memory / StaticInput / GeneratedInput /
beam_search — the seqToseq-era step-function API.

Reference: python/paddle/trainer_config_helpers/layers.py:4082
(recurrent_group), :4215 (GeneratedInput), :4406 (beam_search), :4051
(StaticInput), and memory() (the named-link protocol: a memory reads
the previous timestep's value of the step layer whose NAME matches).

Mapping (VERDICT r4 next-#5): training/eval recurrence lowers onto the
fluid DynamicRNN (the proven models/rnn_search.py shape — the step
function traces ONCE into a lax.scan body); generation lowers onto ONE
generation_decode op (ops/rnn_ops.py) — the step sub-block inside a
lax.scan with beam feedback, beams folded into the batch axis, instead
of the reference's per-token step-net re-runs. Divergences: memories
link to named layers via the same name protocol, but the name must be
produced by a shimmed layer that accepts name= (fc_layer, mixed_layer,
addto_layer, gru_step_layer); SubsequenceInput (nested LoD) stays
descoped per SURVEY §6.
"""

from .. import layers as _fl
from ..layers.control_flow import DynamicRNN, _in_parent_block
from ..layers.helper import LayerHelper
from ..param_attr import ParamAttr
from ..core.program import default_main_program
from .layers import _RG_ACTIVE, _len_of, _propagate_len

__all__ = ['StaticInput', 'GeneratedInput', 'memory', 'recurrent_group',
           'beam_search']


class StaticInput(object):
    """Non-scattered input: imported whole into every time step
    (reference :4051). is_seq marks a full [B, T, D] sequence read each
    step (attention sources)."""

    def __init__(self, input, is_seq=False, size=None):
        self.input = input
        self.is_seq = is_seq or _len_of(input) is not None
        self.size = size


class GeneratedInput(object):
    """Generation feedback: each step receives the embedding of the
    previously generated token (reference :4215)."""

    def __init__(self, size, embedding_name, embedding_size):
        self.size = size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


class _Memory(object):
    def __init__(self, name, pre, init=None):
        self.name = name
        self.pre = pre
        self.init = init
        self.cur = None


class _RgCtx(object):
    """Active recurrent context: memory() registers here; named layers
    built during the step register in .names (layers._rg_note)."""

    def __init__(self, drnn=None, gen_batch_ref=None):
        self.drnn = drnn          # training mode: fluid DynamicRNN
        self.pending = []         # [_Memory]
        self.names = {}           # v1 layer name -> var
        self.gen_batch_ref = gen_batch_ref  # generation: [B,...] var


def memory(name=None, size=0, memory_name=None, is_seq=False,
           boot_layer=None, boot_bias=None, boot_bias_active_type=None,
           boot_with_const_id=None):
    """Previous-timestep value of the step layer named `name`
    (zero/boot_layer at t=0). Must be called inside a recurrent_group
    or beam_search step function."""
    if not _RG_ACTIVE:
        raise ValueError(
            'memory() outside a recurrent_group/beam_search step — the '
            'v1 memory protocol only exists inside a step function '
            '(use fluid DynamicRNN.memory for direct IR building)')
    if boot_with_const_id is not None:
        raise NotImplementedError(
            'memory(boot_with_const_id=...) is the GeneratedInput '
            'feedback slot — pass a GeneratedInput to beam_search '
            'instead of booting an id memory by hand')
    ctx = _RG_ACTIVE[-1]
    if ctx.drnn is not None:
        if boot_layer is not None:
            pre = ctx.drnn.memory(init=boot_layer)
        else:
            pre = ctx.drnn.memory(shape=[size], value=0.0)
        m = _Memory(name or memory_name, pre)
    else:
        helper = LayerHelper('rg_memory')
        if boot_layer is not None:
            init = boot_layer
        else:
            with _in_parent_block(default_main_program()):
                from ..layers.tensor import fill_constant_batch_size_like
                init = fill_constant_batch_size_like(
                    ctx.gen_batch_ref, [1, size], 'float32', 0.0)
        pre = helper.create_variable_for_type_inference(init.dtype)
        pre.shape = tuple(init.shape) if init.shape is not None else None
        m = _Memory(name or memory_name, pre, init=init)
    ctx.pending.append(m)
    return pre


def _resolve_memories(ctx, outs):
    """Link each pending memory to the step layer carrying its name
    (the v1 protocol); fall back to the single returned layer when
    there's exactly one of each and no name matched."""
    for m in ctx.pending:
        cur = ctx.names.get(m.name)
        if cur is None and len(ctx.pending) == 1 and len(outs) == 1:
            cur = outs[0]
        if cur is None:
            raise ValueError(
                'recurrent_group: no step layer named %r to update its '
                'memory — name the producing layer (fc_layer/'
                'mixed_layer/addto_layer/gru_step_layer accept name=) '
                'or return it as the single step output' % m.name)
        m.cur = cur


def recurrent_group(step, input, reverse=False, name=None,
                    targetInlink=None):
    """Iterate `step` over sequence input(s) (reference :4082).
    Sequence inputs scatter into per-timestep slices; StaticInput
    imports whole. Returns the gathered output sequence(s)."""
    if reverse:
        raise NotImplementedError(
            'recurrent_group(reverse=True): use grumemory/lstmemory '
            '(reverse=True) — the shimmed group form only runs forward')
    inputs = input if isinstance(input, (list, tuple)) else [input]
    if any(isinstance(x, GeneratedInput) for x in inputs):
        raise ValueError(
            'GeneratedInput only makes sense under beam_search '
            '(generation); recurrent_group consumes real sequences')
    seqs = [x for x in inputs
            if not isinstance(x, StaticInput) and _len_of(x) is not None]
    if not seqs:
        raise ValueError('recurrent_group needs at least one sequence '
                         'input (data_layer(..., seq_type=1))')
    # targetInlink (reference :4133): which input link's sequence
    # layout the output follows; default = the first sequence input
    len_src = seqs[0]
    if targetInlink is not None:
        tgt = targetInlink.input if isinstance(targetInlink,
                                               StaticInput) else targetInlink
        if _len_of(tgt) is None:
            raise ValueError('recurrent_group: targetInlink must be a '
                             'sequence input')
        len_src = tgt
    length = _len_of(len_src)

    drnn = DynamicRNN(length=length)
    ctx = _RgCtx(drnn=drnn)
    with drnn.block():
        args = []
        for x in inputs:
            if isinstance(x, StaticInput):
                args.append(x.input)       # closed over by the scan
            elif _len_of(x) is not None:
                args.append(drnn.step_input(x))
            else:
                args.append(x)             # non-seq var: closed over
        _RG_ACTIVE.append(ctx)
        try:
            outs = step(*args) if len(args) > 1 else step(args[0])
        finally:
            _RG_ACTIVE.pop()
        outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        _resolve_memories(ctx, outs)
        for m in ctx.pending:
            drnn.update_memory(m.pre, m.cur)
        drnn.output(*outs)
    result = drnn()
    results = result if isinstance(result, list) else [result]
    for r in results:
        _propagate_len(len_src, r)
    return results[0] if len(results) == 1 else results


def beam_search(step, input, bos_id, eos_id, beam_size, max_length=500,
                name=None, num_results_per_sample=None):
    """Beam-search generation over a step function (reference :4406):
    the input list carries exactly one GeneratedInput (the feedback
    slot) and StaticInputs; the step's FIRST output must be the next-
    word probability layer. Returns the generated ids [B, n, T] (int64,
    best-first; n = num_results_per_sample or beam_size) with the
    per-sequence log-prob scores attached as ._beam_scores."""
    n_results = num_results_per_sample or beam_size
    if n_results > beam_size:
        raise ValueError('num_results_per_sample %d > beam_size %d'
                         % (n_results, beam_size))
    inputs = list(input) if isinstance(input, (list, tuple)) else [input]
    gens = [x for x in inputs if isinstance(x, GeneratedInput)]
    if len(gens) != 1:
        raise ValueError('beam_search needs exactly one GeneratedInput '
                         '(got %d)' % len(gens))
    gen = gens[0]
    statics = [x for x in inputs if isinstance(x, StaticInput)]
    if not statics:
        raise ValueError('beam_search needs at least one StaticInput '
                         '(the encoder context) to size the batch')

    program = default_main_program()
    parent = program.current_block()
    helper = LayerHelper('generation_decode', name=name)
    batch_ref = statics[0].input

    sub = program.create_block()
    ctx = _RgCtx(gen_batch_ref=batch_ref)
    # the feedback slot: prev ids enter the step as their embedding
    id_pre = helper.create_variable_for_type_inference('int64')
    id_pre.shape = (None,)
    _RG_ACTIVE.append(ctx)
    try:
        emb = _fl.embedding(
            input=id_pre, size=[gen.size, gen.embedding_size],
            param_attr=ParamAttr(name=gen.embedding_name))
        args = [emb if isinstance(x, GeneratedInput) else x.input
                for x in inputs]
        outs = step(*args) if len(args) > 1 else step(args[0])
        outs = list(outs) if isinstance(outs, (list, tuple)) else [outs]
        _resolve_memories(ctx, outs)
    finally:
        _RG_ACTIVE.pop()
        program.rollback()

    # batch-shaped closure vars to beam-expand inside the op: statics
    # and their sequence-length vars
    batch_names = []
    for s in statics:
        batch_names.append(s.input.name)
        lv = _len_of(s.input)
        if lv is not None:
            batch_names.append(lv.name)
    # statics often share a length var (or a var is passed twice) — a
    # duplicate name would beam-expand twice in the lowering
    batch_names = list(dict.fromkeys(batch_names))

    ids = helper.create_variable_for_type_inference('int64')
    scores = helper.create_variable_for_type_inference('float32')
    bdim = batch_ref.shape[0] if batch_ref.shape is not None else None
    ids.shape = (bdim, n_results, max_length)
    scores.shape = (bdim, n_results)
    parent.append_op(
        type='generation_decode',
        inputs={'BootMemories': [m.init for m in ctx.pending],
                'BatchRef': [batch_ref]},
        outputs={'SentenceIds': [ids], 'SentenceScores': [scores]},
        attrs={'sub_block': sub.idx,
               'memory_names': [(m.pre.name, m.cur.name)
                                for m in ctx.pending],
               'id_pre_name': id_pre.name,
               'prob_name': outs[0].name,
               'batch_var_names': batch_names,
               'max_out_len': max_length,
               'beam_size': beam_size,
               'bos_id': bos_id, 'eos_id': eos_id,
               'num_results': n_results})
    ids._beam_scores = scores
    return ids
