"""ParamAttr (reference: python/paddle/fluid/param_attr.py)."""


class ParamAttr(object):
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, gradient_clip=None,
                 do_model_average=None):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.gradient_clip = gradient_clip
        self.do_model_average = do_model_average

    @staticmethod
    def to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, (list, tuple)):
            return [ParamAttr.to_attr(a) for a in arg]
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, bool):
            return ParamAttr() if arg else False
        if isinstance(arg, (int, float)):
            return ParamAttr(learning_rate=float(arg))
        from .initializer import Initializer
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        raise TypeError('Cannot convert %r to ParamAttr' % (arg,))

    def set_default_initializer(self, initializer):
        if self.initializer is None:
            self.initializer = initializer

    def to_kwargs(self, with_initializer=False):
        kwargs = {
            'name': self.name,
            'optimize_attr': {'learning_rate': self.learning_rate},
            'regularizer': self.regularizer,
            'trainable': self.trainable,
            'gradient_clip_attr': self.gradient_clip,
            'do_model_average': self.do_model_average,
        }
        if with_initializer:
            kwargs['initializer'] = self.initializer
        return kwargs


class WeightNormParamAttr(ParamAttr):
    """Weight normalization (reference param_attr.py:WeightNormParamAttr):
    the consuming layer's weight is reparameterized as
    w = g * v / ||v||, with the norm taken over every axis EXCEPT `dim`
    (dim=None normalizes over all axes to a scalar g). The helper
    creates `<name>.wn_v` (direction, the layer initializer) and
    `<name>.wn_g` (magnitude, initialized to ||v|| at startup so
    training starts at the unnormalized parameterization) and emits one
    weight_norm op; gradients flow to v and g."""

    def __init__(self, dim=None, **kwargs):
        super(WeightNormParamAttr, self).__init__(**kwargs)
        self.dim = dim
