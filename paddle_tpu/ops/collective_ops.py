"""Collective ops over mesh axes.

Reference analog: paddle/pserver + go/pserver gradient aggregation and the
reference's NCCL allreduce path. TPU-native: these lower to XLA collectives
(psum / all_gather / ppermute / all_to_all) which ride the ICI mesh. Under
the GSPMD executor path most collectives are INSERTED BY XLA from sharding
annotations; these explicit ops exist for shard_map-style programs and for
parity with the reference's Send/Recv surface.
"""

import jax

from ..core.registry import register


def _axis(ctx):
    return ctx.attr('axis_name', 'dp')


@register('c_allreduce_sum')
def _c_allreduce_sum(ctx):
    ctx.set_output('Out', jax.lax.psum(ctx.input('X'), _axis(ctx)))


@register('c_allreduce_mean')
def _c_allreduce_mean(ctx):
    ctx.set_output('Out', jax.lax.pmean(ctx.input('X'), _axis(ctx)))


@register('c_allreduce_max')
def _c_allreduce_max(ctx):
    ctx.set_output('Out', jax.lax.pmax(ctx.input('X'), _axis(ctx)))


@register('c_allgather')
def _c_allgather(ctx):
    ctx.set_output('Out', jax.lax.all_gather(
        ctx.input('X'), _axis(ctx), axis=ctx.attr('concat_axis', 0),
        tiled=True))


@register('c_reducescatter')
def _c_reducescatter(ctx):
    ctx.set_output('Out', jax.lax.psum_scatter(
        ctx.input('X'), _axis(ctx),
        scatter_dimension=ctx.attr('scatter_axis', 0), tiled=True))


@register('c_all_to_all')
def _c_all_to_all(ctx):
    ctx.set_output('Out', jax.lax.all_to_all(
        ctx.input('X'), _axis(ctx),
        split_axis=ctx.attr('split_axis', 0),
        concat_axis=ctx.attr('concat_axis', 0),
        tiled=True))


@register('c_ppermute')
def _c_ppermute(ctx):
    perm = [tuple(p) for p in ctx.attr('perm')]
    ctx.set_output('Out', jax.lax.ppermute(ctx.input('X'), _axis(ctx), perm))


@register('c_broadcast')
def _c_broadcast(ctx):
    # recursive-doubling ppermute/select (O(1) compute per element)
    # instead of the old psum(where(...)) full reduction
    from ..parallel.collective import broadcast
    ctx.set_output('Out', broadcast(ctx.input('X'), _axis(ctx),
                                    root=ctx.attr('root', 0)))


@register('c_quant_allreduce')
def _c_quant_allreduce(ctx):
    """Block-scaled int8 allreduce (EQuARX schedule) as an IR op for
    shard_map-style programs; see collective.quantized_all_reduce."""
    from ..parallel.collective import quantized_all_reduce
    key = None
    if ctx.attr('stochastic', False):
        key = ctx.rng_key()
    ctx.set_output('Out', quantized_all_reduce(
        ctx.input('X'), _axis(ctx), op=ctx.attr('op', 'sum'),
        block=ctx.attr('block', 256), key=key))
