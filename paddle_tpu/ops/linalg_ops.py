"""Distributed linear-algebra IR ops (ROADMAP item 4, the non-NN
workload tier).

Four ops lower to the shard_map kernels in ``paddle_tpu/linalg/
kernels.py`` when the program runs on a mesh, and to single-device jnp
references otherwise — proving the Program IR generalizes beyond ML:

- ``summa_matmul``      X [N,K], Y [K,M] -> Out [N,M], all blocked
                        P('dp','tp'); attr ``panel`` (0 = resolve)
- ``blocked_cholesky``  X [N,N] SPD -> Out [N,N] lower factor, both
                        row-blocked P('dp', None); attr ``block``
- ``blocked_qr``        X [N,M] -> Q [N,M] row-blocked, R [M,M]
                        replicated; attr ``block``
- ``power_iter_step``   X [N,N] column-blocked P(None,'dp'),
                        V [N] replicated -> VOut [N], Eigval [1];
                        attrs ``quantized`` / ``qblock`` route the
                        Rayleigh reduction through psum or the PR 13
                        quantized allreduce

Panel/block resolution order (per call, never at import): explicit op
attr > ``PADDLE_TPU_SUMMA_PANEL`` / ``PADDLE_TPU_LINALG_BLOCK`` env >
the autotuner's linalg family (``PADDLE_TPU_AUTOTUNE=on``) > the
heuristic default. Illegal requests round DOWN to the nearest legal
size (the pallas ``_pick_block`` convention) — the blocked-layout
analysis pass flags truly indivisible shapes before any trace.
"""

import os

from .. import observe as _obs
from ..core.registry import register


def _mesh(ctx):
    return getattr(ctx.block.program, 'mesh', None)


def _round_down_legal(value, legal):
    """Largest legal size <= the requested one (smallest legal when the
    request is below the whole ladder); `legal` is sorted ascending."""
    picks = [x for x in legal if x <= int(value)]
    if picks:
        return picks[-1]
    return legal[0] if legal else int(value)


def _resolve_panel(ctx, n, k, m, dtype, mesh):
    from .. import tuning
    from ..linalg import kernels
    n_dp, n_tp = kernels.axis_sizes_of(mesh, 'dp', 'tp')
    legal = kernels.legal_panels(k, n_dp, n_tp)
    attr = int(ctx.attr('panel', 0) or 0)
    if attr > 0:
        return _round_down_legal(attr, legal)
    env = os.environ.get('PADDLE_TPU_SUMMA_PANEL')
    if env:
        return _round_down_legal(int(env), legal)
    if tuning.autotune_mode() != 'off':
        win = tuning.decide_summa_panel(n, k, m, str(dtype), mesh)
        if win and win.get('panel'):
            return _round_down_legal(int(win['panel']), legal)
    return kernels.default_panel(k, n_dp, n_tp, n=n, m=m,
                                 dtype=str(dtype))


def _resolve_block(ctx, op, n, m, dtype, mesh):
    from .. import tuning
    from ..linalg import kernels
    (n_dp,) = kernels.axis_sizes_of(mesh, 'dp')
    if op == 'blocked_cholesky':
        legal = kernels.legal_blocks(n, local=n // max(n_dp, 1))
    else:
        legal = kernels.legal_blocks(m)
    attr = int(ctx.attr('block', 0) or 0)
    if attr > 0:
        return _round_down_legal(attr, legal)
    env = os.environ.get('PADDLE_TPU_LINALG_BLOCK')
    if env:
        return _round_down_legal(int(env), legal)
    if tuning.autotune_mode() != 'off':
        win = tuning.decide_linalg_block(op, n, m, str(dtype), mesh)
        if win and win.get('block'):
            return _round_down_legal(int(win['block']), legal)
    local = n // max(n_dp, 1) if op == 'blocked_cholesky' else None
    return kernels.default_block(n if op == 'blocked_cholesky' else m,
                                 local=local)


def _memory_gauges(op, model, extra=None):
    """Trace-time memory-contract telemetry (shapes are concrete at
    lowering, so the analytic model is exact here)."""
    if not _obs.enabled():
        return
    _obs.set_gauge('linalg.per_shard_peak_bytes', model['peak'], op=op)
    _obs.set_gauge('linalg.memory_factor', model['factor'], op=op)
    for k, v in (extra or {}).items():
        _obs.set_gauge('linalg.%s' % k, v, op=op)


@register('summa_matmul')
def _summa_matmul(ctx):
    from ..linalg import kernels
    x = ctx.input('X')
    y = ctx.input('Y')
    mesh = _mesh(ctx)
    if mesh is None:
        ctx.set_output('Out', kernels.matmul_reference(x, y))
        return
    n, k = x.shape
    m = y.shape[1]
    panel = _resolve_panel(ctx, n, k, m, x.dtype, mesh)
    _memory_gauges('summa_matmul', kernels.per_shard_peak_bytes(
        'summa_matmul', mesh, (n, k, m), dtype=str(x.dtype),
        panel=panel), {'summa_panel': panel})
    ctx.set_output('Out', kernels.summa_matmul(
        x, y, mesh, panel=panel,
        row_axis=ctx.attr('row_axis', 'dp'),
        col_axis=ctx.attr('col_axis', 'tp')))


@register('blocked_cholesky')
def _blocked_cholesky(ctx):
    from ..linalg import kernels
    x = ctx.input('X')
    mesh = _mesh(ctx)
    if mesh is None:
        ctx.set_output('Out', kernels.cholesky_reference(x))
        return
    n = x.shape[0]
    block = _resolve_block(ctx, 'blocked_cholesky', n, n, x.dtype, mesh)
    _memory_gauges('blocked_cholesky', kernels.per_shard_peak_bytes(
        'blocked_cholesky', mesh, (n, n), dtype=str(x.dtype),
        block=block), {'factor_block': block})
    ctx.set_output('Out', kernels.blocked_cholesky(
        x, mesh, block=block, axis=ctx.attr('axis', 'dp')))


@register('blocked_qr')
def _blocked_qr(ctx):
    from ..linalg import kernels
    x = ctx.input('X')
    mesh = _mesh(ctx)
    if mesh is None:
        q, r = kernels.qr_reference(x)
        ctx.set_output('Q', q)
        ctx.set_output('R', r)
        return
    n, m = x.shape
    block = _resolve_block(ctx, 'blocked_qr', n, m, x.dtype, mesh)
    _memory_gauges('blocked_qr', kernels.per_shard_peak_bytes(
        'blocked_qr', mesh, (n, m), dtype=str(x.dtype), block=block),
        {'factor_block': block})
    q, r = kernels.blocked_qr(x, mesh, block=block,
                              axis=ctx.attr('axis', 'dp'))
    ctx.set_output('Q', q)
    ctx.set_output('R', r)


@register('power_iter_step')
def _power_iter_step(ctx):
    from ..linalg import kernels
    x = ctx.input('X')
    v = ctx.input('V')
    mesh = _mesh(ctx)
    quantized = bool(ctx.attr('quantized', False))
    qblock = int(ctx.attr('qblock', 256))
    n = x.shape[0]
    if mesh is not None and _obs.enabled():
        from ..quant import core as _q
        (n_dp,) = kernels.axis_sizes_of(mesh, ctx.attr('axis', 'dp'))
        if n_dp > 1:
            fp32_b = _q.allreduce_wire_bytes(n, n_dp)
            q_b = _q.quantized_allreduce_wire_bytes(n, n_dp, qblock)
            _obs.set_gauge('linalg.powit_bytes_fp32', fp32_b)
            _obs.set_gauge('linalg.powit_bytes_quant', q_b)
            _obs.set_gauge('linalg.powit_compression',
                           fp32_b / max(q_b, 1.0))
    vn, lam = kernels.power_iter_step(
        x, v, mesh, axis=ctx.attr('axis', 'dp'), quantized=quantized,
        qblock=qblock)
    ctx.set_output('VOut', vn)
    ctx.set_output('Eigval', lam)
