"""NN ops: embedding, losses, dropout, normalization helpers.

Reference: paddle/fluid/operators/{lookup_table_op,cross_entropy_op,
softmax_with_cross_entropy_op,dropout_op,accuracy_op,...}.cc
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register


def _fused_ce_enabled():
    # Read at TRACE time: the leg is frozen into the compiled graph, so
    # flipping it needs a fresh process (bench A/Bs run workload
    # children) or a program-version bump — same contract as the other
    # env knobs (PADDLE_TPU_BN_COMPUTE, PADDLE_TPU_CONV_LAYOUT).
    return os.environ.get('PADDLE_TPU_FUSED_CE', '1') != '0'


@register('lookup_table')
def _lookup_table(ctx):
    """Embedding lookup (lookup_table_op.cc). On TPU a dense gather —
    XLA lowers to an efficient dynamic-gather on HBM.

    Sparse gradients (the reference's SelectedRows path,
    lookup_table_op.cc:119-127): when the executor planted a zero "row
    seed" for this lookup's output (is_sparse tables under an
    SGD/Adagrad minimize), the table itself is detached and the seed —
    shaped like the OUTPUT, O(batch x dim) — carries the gradient; the
    optimizer op scatters those rows into the table in place. A
    1e8-row CTR table then never materializes a 1e8-row grad."""
    from ..core.backward import SPARSE_SEED_PREFIX
    w = ctx.input('W')
    ids = ctx.input('Ids')
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze_last:
        ids = ids.squeeze(-1)
    padding_idx = ctx.attr('padding_idx', -1)
    seed = ctx.env.get(SPARSE_SEED_PREFIX + ctx.op.output('Out'))
    if seed is not None:
        w = jax.lax.stop_gradient(w)
    out = jnp.take(w, ids, axis=0)
    if seed is not None:
        out = out + seed.reshape(out.shape)
    if padding_idx is not None and padding_idx >= 0:
        # mask AFTER the seed add so padding rows' seed grads zero out
        # exactly like the dense grad's masked rows
        mask = (ids != padding_idx)[..., None]
        out = out * mask.astype(out.dtype)
    ctx.set_output('Out', out)


@register('cross_entropy')
def _cross_entropy(ctx):
    """-log(p[label]); soft_label supported (cross_entropy_op.cc)."""
    x = ctx.input('X')
    label = ctx.input('Label')
    eps = 1e-8
    if ctx.attr('soft_label', False):
        loss = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        if label.ndim == x.ndim and label.shape[-1] == 1:
            label = label.squeeze(-1)
        p = jnp.take_along_axis(x, label[..., None].astype(jnp.int32),
                                axis=-1)
        loss = -jnp.log(p + eps)
    ctx.set_output('Y', loss)


@register('softmax_with_cross_entropy')
def _softmax_xent(ctx):
    logits = ctx.input('Logits')
    label = ctx.input('Label')
    if ctx.attr('soft_label', False):
        log_probs = jax.nn.log_softmax(logits, axis=-1)
        loss = -jnp.sum(label * log_probs, axis=-1, keepdims=True)
        ctx.set_output('Softmax', jnp.exp(log_probs))
        ctx.set_output('Loss', loss)
        return
    if label.ndim == logits.ndim and label.shape[-1] == 1:
        label = label.squeeze(-1)
    if _fused_ce_enabled():
        # hard labels: NLL == the eps=0 point of the fused label-
        # smoothed CE — same custom_vjp, so no fp32 [.., V] log-prob
        # tensor is materialized or saved (see _ls_ce_fused). The
        # Softmax output is computed independently and DCE'd by XLA
        # whenever unfetched; both outputs keep the logits dtype, as
        # the materializing form did.
        loss = _ls_ce_fused(logits, label, 0.0)[..., None] \
            .astype(logits.dtype)
        softmax = jax.nn.softmax(logits.astype(jnp.float32),
                                 axis=-1).astype(logits.dtype)
    else:
        log_probs = jax.nn.log_softmax(logits, axis=-1)
        loss = -jnp.take_along_axis(log_probs,
                                    label[..., None].astype(jnp.int32),
                                    axis=-1)
        softmax = jnp.exp(log_probs)
    ignore_index = ctx.attr('ignore_index', -100)
    if ignore_index is not None and ignore_index >= 0:
        mask = (label[..., None] != ignore_index)
        loss = loss * mask.astype(loss.dtype)
    ctx.set_output('Softmax', softmax)
    ctx.set_output('Loss', loss)


@register('sigmoid_cross_entropy_with_logits')
def _sigmoid_xent(ctx):
    x = ctx.input('X')
    label = ctx.input('Label')
    loss = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    ctx.set_output('Out', loss)


@register('square_error_cost')
def _square_error_cost(ctx):
    x = ctx.input('X')
    y = ctx.input('Y')
    ctx.set_output('Out', jnp.square(x - y))


@register('smooth_l1_loss')
def _smooth_l1(ctx):
    x = ctx.input('X')
    y = ctx.input('Y')
    sigma = ctx.attr('sigma', 1.0)
    sigma2 = sigma * sigma
    diff = x - y
    if ctx.has_input('InsideWeight'):
        diff = diff * ctx.input('InsideWeight')
    absd = jnp.abs(diff)
    loss = jnp.where(absd < 1.0 / sigma2, 0.5 * sigma2 * jnp.square(diff),
                     absd - 0.5 / sigma2)
    if ctx.has_input('OutsideWeight'):
        loss = loss * ctx.input('OutsideWeight')
    ctx.set_output('Diff', diff)
    if ctx.attr('last_dim_only', False):
        ctx.set_output('Out', jnp.sum(loss, axis=-1))
    else:
        ctx.set_output('Out', jnp.sum(loss,
                                      axis=tuple(range(1, loss.ndim)),
                                      keepdims=False)[..., None]
                       if loss.ndim > 1 else loss)


@register('dropout')
def _dropout(ctx):
    """dropout_op.cc semantics: train: out = x*mask (downgrade_in_infer)
    or x*mask/(1-p) (upscale_in_train); test: x*(1-p) or x."""
    x = ctx.input('X')
    p = ctx.attr('dropout_prob', 0.5)
    impl = ctx.attr('dropout_implementation', 'downgrade_in_infer')
    is_test = ctx.attr('is_test', False) or ctx.is_test
    if is_test:
        out = x * (1.0 - p) if impl == 'downgrade_in_infer' else x
        mask = jnp.ones_like(x)
    else:
        keep = jax.random.bernoulli(ctx.rng_key(), 1.0 - p, x.shape)
        mask = keep.astype(x.dtype)
        out = x * mask
        if impl == 'upscale_in_train' and p < 1.0:
            out = out / (1.0 - p)
    ctx.set_output('Mask', mask)
    ctx.set_output('Out', out)


@register('accuracy')
def _accuracy(ctx):
    """accuracy_op.cc: fraction of rows where any of top-k indices == label."""
    indices = ctx.input('Indices')
    label = ctx.input('Label')
    if label.ndim == 2 and label.shape[-1] == 1:
        label_cmp = label
    else:
        label_cmp = label[..., None]
    correct = jnp.any(indices == label_cmp, axis=-1)
    acc = jnp.mean(correct.astype(jnp.float32)).reshape(1)
    ctx.set_output('Accuracy', acc)
    ctx.set_output('Correct', jnp.sum(correct.astype(jnp.int32)).reshape(1))
    ctx.set_output('Total', jnp.asarray([indices.shape[0]], dtype=jnp.int32))


@register('auc')
def _auc(ctx):
    """Streaming-free AUC approximation over the batch (auc_op.cc)."""
    probs = ctx.input('Predict')
    label = ctx.input('Label').reshape(-1)
    pos_score = probs[:, 1] if probs.ndim == 2 and probs.shape[1] == 2 \
        else probs.reshape(-1)
    label_f = label.astype(jnp.float32)
    pos = label_f
    neg = 1.0 - label_f
    # rank-based AUC: P(score_pos > score_neg)
    diff = pos_score[:, None] - pos_score[None, :]
    wins = (diff > 0).astype(jnp.float32) + 0.5 * (diff == 0)
    num = jnp.sum(wins * pos[:, None] * neg[None, :])
    den = jnp.sum(pos) * jnp.sum(neg)
    ctx.set_output('AUC', (num / jnp.maximum(den, 1.0)).reshape(1))


@register('nce')
def _nce(ctx):
    """NCE via uniform negative sampling (nce_op.cc), fused sampled-softmax
    form: loss = -log σ(s_pos) - Σ log σ(-s_neg)."""
    x = ctx.input('Input')          # [b, d]
    label = ctx.input('Label')      # [b, 1]
    w = ctx.input('Weight')         # [V, d]
    b = ctx.input('Bias')           # [V, 1]
    num_neg = ctx.attr('num_neg_samples', 10)
    num_classes = ctx.attr('num_total_classes')
    ids = label.reshape(-1).astype(jnp.int32)
    pos_w = jnp.take(w, ids, axis=0)                    # [b, d]
    pos_b = jnp.take(b.reshape(-1), ids)                # [b]
    s_pos = jnp.sum(x * pos_w, axis=-1) + pos_b
    neg_ids = jax.random.randint(ctx.rng_key(), (num_neg,), 0, num_classes)
    neg_w = jnp.take(w, neg_ids, axis=0)                # [k, d]
    neg_b = jnp.take(b.reshape(-1), neg_ids)            # [k]
    s_neg = x @ neg_w.T + neg_b                         # [b, k]
    loss = -jax.nn.log_sigmoid(s_pos) - \
        jnp.sum(jax.nn.log_sigmoid(-s_neg), axis=-1)
    ctx.set_output('Cost', loss[:, None])


@register('l2_normalize')
def _l2_normalize(ctx):
    x = ctx.input('X')
    axis = ctx.attr('axis', -1)
    eps = ctx.attr('epsilon', 1e-12)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True))
    ctx.set_output('Out', x / jnp.maximum(norm, eps))
    ctx.set_output('Norm', norm)


@register('maxout')
def _maxout(ctx):
    x = ctx.input('X')  # NCHW
    groups = ctx.attr('groups')
    n, c, h, w = x.shape
    out = x.reshape(n, c // groups, groups, h, w).max(axis=2)
    ctx.set_output('Out', out)


@register('im2sequence')
def _im2sequence(ctx):
    """im2sequence_op.cc: extract patches as a sequence (OCR models)."""
    x = ctx.input('X')  # NCHW
    kh, kw = ctx.attr('kernels')
    sh, sw = ctx.attr('strides', [1, 1])
    ph0, pw0, ph1, pw1 = ctx.attr('paddings', [0, 0, 0, 0])
    x = jnp.pad(x, ((0, 0), (0, 0), (ph0, ph1), (pw0, pw1)))
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), 'VALID',
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    # patches: [n, c*kh*kw, oh, ow] -> [n*oh*ow, c*kh*kw]
    out = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, c * kh * kw)
    ctx.set_output('Out', out)


@register('label_smooth')
def _label_smooth(ctx):
    x = ctx.input('X')
    eps = ctx.attr('epsilon', 0.1)
    k = x.shape[-1]
    if ctx.has_input('PriorDist'):
        prior = ctx.input('PriorDist')
        out = (1.0 - eps) * x + eps * prior
    else:
        out = (1.0 - eps) * x + eps / k
    ctx.set_output('Out', out)


@register('huber_loss')
def _huber_loss(ctx):
    x = ctx.input('X')
    y = ctx.input('Y')
    delta = ctx.attr('delta', 1.0)
    r = y - x
    absr = jnp.abs(r)
    loss = jnp.where(absr <= delta, 0.5 * jnp.square(r),
                     delta * (absr - 0.5 * delta))
    ctx.set_output('Residual', r)
    ctx.set_output('Out', loss)


@register('rank_loss')
def _rank_loss(ctx):
    label = ctx.input('Label')
    left = ctx.input('Left')
    right = ctx.input('Right')
    out = jnp.log1p(jnp.exp(left - right)) - label * (left - right)
    ctx.set_output('Out', out)


@register('margin_rank_loss')
def _margin_rank_loss(ctx):
    label = ctx.input('Label')
    x1 = ctx.input('X1')
    x2 = ctx.input('X2')
    margin = ctx.attr('margin', 0.0)
    out = jnp.maximum(0.0, -label * (x1 - x2) + margin)
    ctx.set_output('Out', out)
    ctx.set_output('Activated', (out > 0).astype(x1.dtype))


@register('hinge_loss')
def _hinge_loss(ctx):
    logits = ctx.input('Logits')
    labels = ctx.input('Labels')
    ctx.set_output('Loss', jnp.maximum(
        0.0, 1.0 - (2.0 * labels - 1.0) * logits))


@register('log_loss')
def _log_loss(ctx):
    pred = ctx.input('Predicted')
    label = ctx.input('Labels')
    eps = ctx.attr('epsilon', 1e-7)
    ctx.set_output('Loss', -label * jnp.log(pred + eps) -
                   (1.0 - label) * jnp.log(1.0 - pred + eps))


@register('bilinear_tensor_product')
def _bilinear_tensor_product(ctx):
    x = ctx.input('X')  # [b, m]
    y = ctx.input('Y')  # [b, n]
    w = ctx.input('Weight')  # [k, m, n]
    out = jnp.einsum('bm,kmn,bn->bk', x, w, y)
    if ctx.has_input('Bias'):
        out = out + ctx.input('Bias')
    ctx.set_output('Out', out)


@register('pixel_shuffle')
def _pixel_shuffle(ctx):
    x = ctx.input('X')  # NCHW
    r = ctx.attr('upscale_factor')
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = out.transpose(0, 1, 4, 2, 5, 3).reshape(n, c // (r * r), h * r, w * r)
    ctx.set_output('Out', out)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _ls_ce_fused(logits, label, eps):
    """loss = -( (1-eps)·logp[y] + (eps/V)·Σ_j logp[j] ) with NO
    [.., V]-sized intermediate ever CREATED beyond the input logits:
    the residuals are (logits, label, lse) — logits are the op's input
    (alive regardless), lse is [.., 1]-sized — and the backward
    recomputes softmax from them in-register. jax.nn.log_softmax by
    contrast materializes (and autodiff saves) an ADDITIONAL fp32
    [.., V] log-prob tensor — at the Transformer's 32k vocab ~0.5 GB of
    HBM write+read traffic plus the same again held across the step as
    a second residual. Reductions accumulate fp32 (dtype=); elementwise
    fp32 stays in-register under XLA fusion."""
    loss, _ = _ls_ce_fwd(logits, label, eps)
    return loss


def _ls_ce_rows(logits, label):
    x = logits
    m = jnp.max(x, axis=-1).astype(jnp.float32)
    se = jnp.sum(jnp.exp(x.astype(jnp.float32) - m[..., None]), axis=-1,
                 dtype=jnp.float32)
    lse = m + jnp.log(se)
    x_y = jnp.take_along_axis(x, label[..., None].astype(jnp.int32),
                              axis=-1)[..., 0].astype(jnp.float32)
    x_mean = jnp.mean(x, axis=-1, dtype=jnp.float32)
    return lse, x_y, x_mean


def _ls_ce_fwd(logits, label, eps):
    lse, x_y, x_mean = _ls_ce_rows(logits, label)
    # logp[j] = x[j] - lse; nll = lse - x_y; uniform = lse - mean(x)
    loss = (1.0 - eps) * (lse - x_y) + eps * (lse - x_mean)
    return loss, (logits, label, lse)


def _ls_ce_bwd(eps, res, g):
    logits, label, lse = res
    v = logits.shape[-1]
    # d loss / d x_j = p_j - (1-eps)·1[j=y] - eps/V,  p = exp(x - lse)
    p = jnp.exp(logits.astype(jnp.float32) - lse[..., None])
    onehot = (jnp.arange(v, dtype=jnp.int32) ==
              label[..., None].astype(jnp.int32))
    dx = p - (1.0 - eps) * onehot.astype(jnp.float32) - eps / v
    dx = (g[..., None].astype(jnp.float32) * dx).astype(logits.dtype)
    return dx, np.zeros(label.shape, dtype=jax.dtypes.float0)


_ls_ce_fused.defvjp(_ls_ce_fwd, _ls_ce_bwd)


@register('label_smoothed_cross_entropy')
def _label_smoothed_xent(ctx):
    """Fused label-smoothed softmax CE over hard int labels.

    Equals one_hot -> label_smooth -> softmax_with_cross_entropy(soft)
    but via _ls_ce_fused: no [.., V] smoothed target, no materialized
    log-prob tensor, no V-sized autodiff residual (the backward
    recomputes softmax in-register from the logits). For the
    Transformer's 32k vocab this removes multiple full-logit-sized HBM
    round-trips from the loss — the dominant non-matmul cost."""
    logits = ctx.input('Logits')
    label = ctx.input('Label')
    eps = ctx.attr('epsilon', 0.1)
    if label.ndim == logits.ndim:
        label = label.squeeze(-1)
    if not _fused_ce_enabled():
        # ablation leg: the naive materializing form, benchable A/B
        lsm = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(lsm, label[..., None].astype(jnp.int32),
                                   axis=-1)[..., 0]
        loss = (1.0 - eps) * nll + eps * -jnp.mean(lsm, axis=-1)
    else:
        loss = _ls_ce_fused(logits, label, float(eps))
    ctx.set_output('Loss', loss[..., None])


@register('modified_huber_loss')
def _modified_huber_loss(ctx):
    """Binary classification loss (modified_huber_loss_op.h:37-72):
    z = x * (2y - 1); loss = -4z for z < -1, (1-z)^2 for z < 1, else 0."""
    x = ctx.input('X')
    y = ctx.input('Y').astype(x.dtype)
    z = x * (2.0 * y - 1.0)
    loss = jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, jnp.square(1.0 - z), 0.0))
    ctx.set_output('IntermediateVal', z)
    ctx.set_output('Out', loss)
