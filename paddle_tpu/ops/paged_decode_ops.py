"""Paged incremental-decode ops for the decode-serving engine.

Three IR ops over a decoder-only (GPT-block) transformer whose KV
cache lives in a paged pool (ops/pallas/paged_attention.py layouts):

- ``paged_prefill`` — extend a sequence whose first ``Cached`` tokens
  already have KV materialized (prefix-cache hit; ``Cached == 0`` is
  the cold case) by a padded suffix [1, S]: write each suffix
  position's K/V into the sequence's pages through its block table,
  attend each suffix query against the table at its own absolute
  length (one ragged paged-attention pass — S queries, per-query
  lengths cached+1 .. cached+S), and emit the next token. S is
  bucketed by the engine so the signature set is small and warmable.
- ``paged_decode_step`` — one token for EVERY slot of a fixed-size
  decode batch [B]: append each sequence's K/V at its own position
  (scatter through the block table; rows whose table entry is >= NB
  drop their write, which is how empty slots ride along for free),
  ragged paged attention at per-sequence true lengths, then greedy or
  temperature sampling per row. ONE feed signature regardless of which
  sequences occupy which slots — the continuous-batching scheduler
  swaps sequences in and out without ever producing a new XLA
  signature (zero steady-state cache misses).
- ``paged_spec_verify`` — speculative-decoding verification: score
  ``k+1`` tokens (the pending token + k draft proposals) for every
  slot of the [B] batch in ONE ragged paged-attention pass over
  ``B*(k+1)`` mixed-length rows (row (b, j) attends at length
  lens[b]+j+1 — exactly the ragged shape the paged kernel was built
  for). ``k`` is a static attr, so the verify step is one more fixed
  signature beside the decode step's. Writes K/V for all k+1
  positions; the engine's longest-accepted-prefix rule decides how
  many become real (rejected positions sit above the advanced
  ``cache_len`` and are overwritten before they can be read).

Per-row math mirrors the incremental-decode path in
transformer_ops.py (``_incremental_layer_scan``): the layer stack is
one ``lax.scan`` over [L, ...]-stacked weights, residual+LN via
``fused_layer_norm``. Every per-row computation is independent of the
other rows — and all three ops attend through the same
``paged_attention`` gather over the same [P*bs] extent — so a
sequence's token stream is bit-identical whether it decodes alone,
packed into a full batch, resumed from a cached prefix, or advanced
k-at-a-time under speculation: the invariant
tests/test_decode_serving.py's e2es assert.

Sampling: token at position i draws from
``categorical(fold_in(PRNGKey(seed), i), logits / temp)`` (greedy at
temp == 0), so a request's stream depends only on (seed, positions),
never on batch composition, speculation depth, or a global step
counter.

Quantized arenas (docs/quantization.md): when the K/V arenas are int8
or fp8, ``_extend_rows`` quantizes each written row independently
(one fp32 scale per (token, head) row into the KScale/VScale arenas,
deterministic rounding) and the attention gather dequantizes through
the same table indices — so every invariant above, including
bit-consistency across batching/speculation/caching, holds unchanged
at the quantized dtypes.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register
from .transformer_ops import ENC_SLOTS, _slot_to_input

LM_SLOTS = ENC_SLOTS   # decoder-only block reuses the encoder slot layout


def _split_heads(x, n_head):
    """[..., H*D] -> [..., H, D]."""
    return x.reshape(x.shape[:-1] + (n_head, x.shape[-1] // n_head))


def _ln(h, p, slot):
    from .pallas.layer_norm import fused_layer_norm
    return fused_layer_norm(h, p[slot + '_w'], p[slot + '_b'], eps=1e-5,
                            begin_norm_axis=-1)


def _ffn(h, p):
    return jax.nn.relu(h @ p['ffn_w1'] + p['ffn_b1']) @ p['ffn_w2'] + \
        p['ffn_b2']


def _write_positions(pages, new, phys, off):
    """Scatter per-position K/V rows into the page arena.
    pages [NB, H, bs, D]; new [N, H, D]; phys/off [N] int32 — rows with
    phys >= NB are dropped (empty batch slots / padded prompt tail)."""
    n_head = new.shape[1]
    return pages.at[phys[:, None], jnp.arange(n_head)[None, :],
                    off[:, None]].set(new, mode='drop')


def _write_scales(scales, new, phys, off):
    """Scatter per-row scales beside a quantized arena write.
    scales [NB, H, bs]; new [N, H]; same drop semantics as the pages."""
    n_head = new.shape[1]
    return scales.at[phys[:, None], jnp.arange(n_head)[None, :],
                     off[:, None]].set(new, mode='drop')


def _arena_kv_dtype(kc):
    """Canonical quantized-arena dtype from the arena's jnp dtype, or
    None for the unquantized (fp32 / bf16) arenas."""
    name = str(kc.dtype)
    return name if name in ('int8', 'float8_e4m3fn') else None


def _sample_token(logits, seed, pos, temp):
    """logits [V] fp32 -> int32 token. temp == 0 is greedy; otherwise
    categorical at temperature with a (seed, position)-derived key."""
    greedy = jnp.argmax(logits).astype(jnp.int32)
    key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
    t = jnp.maximum(temp, 1e-6)
    sampled = jax.random.categorical(key, logits / t).astype(jnp.int32)
    return jnp.where(temp > 0.0, sampled, greedy)


def _lm_inputs(ctx):
    emb = ctx.input('Emb')
    pos_enc = ctx.input('PosEnc')
    wout = ctx.input('OutProj')
    params = {s: ctx.env[ctx.op.input(_slot_to_input(s))]
              for s in LM_SLOTS}
    kc = ctx.input('KCache')            # [L, NB, H, bs, dk]
    vc = ctx.input('VCache')
    ks = ctx.input('KScale') if ctx.has_input('KScale') else None
    vs = ctx.input('VScale') if ctx.has_input('VScale') else None
    return emb, pos_enc, wout, params, kc, vc, ks, vs


def _set_arena_outputs(ctx, kcs, vcs, kss, vss):
    ctx.set_output('KCacheOut', kcs)
    ctx.set_output('VCacheOut', vcs)
    if kss is not None:
        ctx.set_output('KScaleOut', kss)
        ctx.set_output('VScaleOut', vss)


@register('paged_decode_step')
def _paged_decode_step(ctx):
    emb, pos_enc, wout, params, kcs, vcs, kss, vss = _lm_inputs(ctx)
    n_head = ctx.attr('n_head', 1)

    tokens = ctx.input('Tokens').reshape(-1).astype(jnp.int32)     # [B]
    lens = ctx.input('SeqLens').reshape(-1).astype(jnp.int32)      # [B]
    tables = ctx.input('BlockTables').astype(jnp.int32)            # [B, P]
    temps = ctx.input('Temps').reshape(-1).astype(jnp.float32)
    seeds = ctx.input('Seeds').reshape(-1).astype(jnp.int32)

    # one new token per row at position lens (empty slots feed all->NB
    # tables, so phys lands out of bounds and every write drops)
    live = jnp.ones(lens.shape, dtype=bool)
    logits, kcs, vcs, kss, vss = _extend_rows(
        emb, pos_enc, wout, params, kcs, vcs, n_head,
        tokens, lens, live, tables, kss, vss)
    nxt = jax.vmap(_sample_token)(logits, seeds, lens + 1, temps)
    ctx.set_output('NextTokens',
                   nxt.astype(ctx.out_dtype('NextTokens', 'int64')))
    _set_arena_outputs(ctx, kcs, vcs, kss, vss)


def _extend_rows(emb, pos_enc, wout, params, kcs, vcs, n_head,
                 tokens, pos, live, tables, kscales=None, vscales=None):
    """Shared core of prefill and spec-verify: write N new tokens'
    K/V at absolute positions ``pos`` through per-row block
    ``tables`` [N, P], attend each row at its own ragged length
    (``pos + 1``), and return fp32 logits [N, V] plus the updated
    arenas. Rows that are not ``live``, sit past the table's capacity,
    or hit a table entry >= NB drop their writes (padded tails /
    empty batch slots).

    Quantized arenas (``kscales``/``vscales`` [L, NB, H, bs] given):
    each new K/V row is quantized independently (one fp32 scale per
    (token, head) row, deterministic rounding — quant.core
    quantize_rows) before the scatter, and the attention gather
    dequantizes through the same table indices. Because rows quantize
    independently, every path (prefill, decode, spec-verify, cache
    hits) stores identical bits for identical tokens — the
    concurrent == sequential invariant survives at int8/fp8."""
    from ..quant.core import quantize_rows
    from .pallas.paged_attention import paged_attention
    bs = kcs.shape[3]
    nb = kcs.shape[1]
    d_model = emb.shape[-1]
    p_cap = tables.shape[1]
    kv_q = _arena_kv_dtype(kcs)
    quantized = kv_q is not None

    logical = jnp.clip(pos // bs, 0, p_cap - 1)
    phys = jnp.take_along_axis(tables, logical[:, None], axis=1)[:, 0]
    phys = jnp.where((pos < p_cap * bs) & live, phys, nb)
    off = pos % bs

    x = jnp.take(emb, tokens, axis=0) * (d_model ** 0.5) + \
        jnp.take(pos_enc, pos, axis=0, mode='clip')
    att_lens = pos + 1

    def body(h, sl):
        if quantized:
            p, kc, vc, ksc, vsc = sl
        else:
            p, kc, vc = sl
            ksc = vsc = None
        k_new = _split_heads(h @ p['slf_k'], n_head)       # [N, H, dk]
        v_new = _split_heads(h @ p['slf_v'], n_head)
        if quantized:
            kq, ks_row = quantize_rows(k_new, kv_q)
            vq, vs_row = quantize_rows(v_new, kv_q)
            kc = _write_positions(kc, kq, phys, off)
            vc = _write_positions(vc, vq, phys, off)
            ksc = _write_scales(ksc, ks_row, phys, off)
            vsc = _write_scales(vsc, vs_row, phys, off)
        else:
            kc = _write_positions(kc, k_new.astype(kc.dtype), phys, off)
            vc = _write_positions(vc, v_new.astype(vc.dtype), phys, off)
        q = _split_heads(h @ p['slf_q'], n_head)
        attn = paged_attention(q, kc, vc, tables, att_lens,
                               k_scales=ksc, v_scales=vsc)
        h = _ln(h + attn.reshape(h.shape[0], -1).astype(h.dtype)
                @ p['slf_o'], p, 'ln1')
        h = _ln(h + _ffn(h, p), p, 'ln2')
        if quantized:
            return h, (kc, vc, ksc, vsc)
        return h, (kc, vc)

    if quantized:
        h, (kcs, vcs, kscales, vscales) = jax.lax.scan(
            body, x, (params, kcs, vcs, kscales, vscales))
    else:
        h, (kcs, vcs) = jax.lax.scan(body, x, (params, kcs, vcs))
    return (h @ wout).astype(jnp.float32), kcs, vcs, kscales, vscales


@register('paged_prefill')
def _paged_prefill(ctx):
    emb, pos_enc, wout, params, kcs, vcs, kss, vss = _lm_inputs(ctx)
    n_head = ctx.attr('n_head', 1)

    ids = ctx.input('Ids').reshape(-1).astype(jnp.int32)   # [S] (padded)
    length = ctx.input('Len').reshape(()).astype(jnp.int32)
    cached = ctx.input('Cached').reshape(()).astype(jnp.int32)
    table = ctx.input('BlockTable').astype(jnp.int32).reshape(-1)  # [P]
    temp = ctx.input('Temp').reshape(()).astype(jnp.float32)
    seed = ctx.input('Seed').reshape(()).astype(jnp.int32)
    s = ids.shape[0]

    # suffix position t lives at absolute position cached + t; its
    # query attends to everything at or below it — the cached pages
    # plus this step's own earlier writes — through the table gather
    t_idx = jnp.arange(s, dtype=jnp.int32)
    pos = cached + t_idx
    tables = jnp.broadcast_to(table, (s, table.shape[0]))
    logits, kcs, vcs, kss, vss = _extend_rows(
        emb, pos_enc, wout, params, kcs, vcs, n_head,
        ids, pos, t_idx < length, tables, kss, vss)

    logits_last = jax.lax.dynamic_index_in_dim(
        logits, jnp.maximum(length - 1, 0), keepdims=False)     # [V]
    nxt = _sample_token(logits_last, seed, cached + length, temp)
    ctx.set_output('NextToken',
                   nxt.reshape(1).astype(ctx.out_dtype('NextToken',
                                                       'int64')))
    _set_arena_outputs(ctx, kcs, vcs, kss, vss)


@register('paged_spec_verify')
def _paged_spec_verify(ctx):
    emb, pos_enc, wout, params, kcs, vcs, kss, vss = _lm_inputs(ctx)
    n_head = ctx.attr('n_head', 1)

    tokens = ctx.input('Tokens').astype(jnp.int32)         # [B, K1]
    lens = ctx.input('SeqLens').reshape(-1).astype(jnp.int32)   # [B]
    tables = ctx.input('BlockTables').astype(jnp.int32)    # [B, P]
    temps = ctx.input('Temps').reshape(-1).astype(jnp.float32)
    seeds = ctx.input('Seeds').reshape(-1).astype(jnp.int32)
    b, k1 = tokens.shape

    # flatten to B*K1 single-token rows: row (b, j) holds the j-th
    # speculative token at absolute position lens[b] + j and attends
    # at its own length — one ragged paged-attention batch scores the
    # whole tree of proposals (empty slots ride along exactly as in
    # the decode step: all-NB tables drop every write)
    j = jnp.arange(k1, dtype=jnp.int32)
    pos = (lens[:, None] + j[None, :]).reshape(-1)         # [B*K1]
    tables_rep = jnp.repeat(tables, k1, axis=0)            # [B*K1, P]
    live = jnp.ones(pos.shape, dtype=bool)
    logits, kcs, vcs, kss, vss = _extend_rows(
        emb, pos_enc, wout, params, kcs, vcs, n_head,
        tokens.reshape(-1), pos, live, tables_rep, kss, vss)

    nxt = jax.vmap(_sample_token)(
        logits, jnp.repeat(seeds, k1), pos + 1, jnp.repeat(temps, k1))
    ctx.set_output('NextTokens',
                   nxt.reshape(b, k1).astype(
                       ctx.out_dtype('NextTokens', 'int64')))
    _set_arena_outputs(ctx, kcs, vcs, kss, vss)
