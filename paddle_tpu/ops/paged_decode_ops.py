"""Paged incremental-decode ops for the decode-serving engine.

Two IR ops over a decoder-only (GPT-block) transformer whose KV cache
lives in a paged pool (ops/pallas/paged_attention.py layouts):

- ``paged_prefill`` — run ONE padded prompt [1, S] densely through the
  stack (causal attention, fp32 softmax), write each position's K/V
  into the sequence's pages through its block table, and emit the
  first generated token. S is bucketed by the engine so the signature
  set is small and warmable.
- ``paged_decode_step`` — one token for EVERY slot of a fixed-size
  decode batch [B]: append each sequence's K/V at its own position
  (scatter through the block table; rows whose table entry is >= NB
  drop their write, which is how empty slots ride along for free),
  ragged paged attention at per-sequence true lengths, then greedy or
  temperature sampling per row. ONE feed signature regardless of which
  sequences occupy which slots — the continuous-batching scheduler
  swaps sequences in and out without ever producing a new XLA
  signature (zero steady-state cache misses).

Per-row math mirrors the incremental-decode path in
transformer_ops.py (``_incremental_layer_scan``): the layer stack is
one ``lax.scan`` over [L, ...]-stacked weights, residual+LN via
``fused_layer_norm``. Every per-row computation is independent of the
other rows, so a sequence's token stream is bit-identical whether it
decodes alone or packed into a full batch — the invariant
tests/test_decode_serving.py's continuous-batching e2e asserts.

Sampling: token at position i draws from
``categorical(fold_in(PRNGKey(seed), i), logits / temp)`` (greedy at
temp == 0), so a request's stream depends only on (seed, positions),
never on batch composition or a global step counter.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register
from .transformer_ops import ENC_SLOTS, _slot_to_input

LM_SLOTS = ENC_SLOTS   # decoder-only block reuses the encoder slot layout

_NEG_INF = -1e9


def _split_heads(x, n_head):
    """[..., H*D] -> [..., H, D]."""
    return x.reshape(x.shape[:-1] + (n_head, x.shape[-1] // n_head))


def _ln(h, p, slot):
    from .pallas.layer_norm import fused_layer_norm
    return fused_layer_norm(h, p[slot + '_w'], p[slot + '_b'], eps=1e-5,
                            begin_norm_axis=-1)


def _ffn(h, p):
    return jax.nn.relu(h @ p['ffn_w1'] + p['ffn_b1']) @ p['ffn_w2'] + \
        p['ffn_b2']


def _write_positions(pages, new, phys, off):
    """Scatter per-position K/V rows into the page arena.
    pages [NB, H, bs, D]; new [N, H, D]; phys/off [N] int32 — rows with
    phys >= NB are dropped (empty batch slots / padded prompt tail)."""
    n_head = new.shape[1]
    return pages.at[phys[:, None], jnp.arange(n_head)[None, :],
                    off[:, None]].set(new, mode='drop')


def _sample_token(logits, seed, pos, temp):
    """logits [V] fp32 -> int32 token. temp == 0 is greedy; otherwise
    categorical at temperature with a (seed, position)-derived key."""
    greedy = jnp.argmax(logits).astype(jnp.int32)
    key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
    t = jnp.maximum(temp, 1e-6)
    sampled = jax.random.categorical(key, logits / t).astype(jnp.int32)
    return jnp.where(temp > 0.0, sampled, greedy)


def _lm_inputs(ctx):
    emb = ctx.input('Emb')
    pos_enc = ctx.input('PosEnc')
    wout = ctx.input('OutProj')
    params = {s: ctx.env[ctx.op.input(_slot_to_input(s))]
              for s in LM_SLOTS}
    kc = ctx.input('KCache')            # [L, NB, H, bs, dk]
    vc = ctx.input('VCache')
    return emb, pos_enc, wout, params, kc, vc


@register('paged_decode_step')
def _paged_decode_step(ctx):
    from .pallas.paged_attention import paged_attention

    emb, pos_enc, wout, params, kcs, vcs = _lm_inputs(ctx)
    n_head = ctx.attr('n_head', 1)
    bs = kcs.shape[3]
    d_model = emb.shape[-1]

    tokens = ctx.input('Tokens').reshape(-1).astype(jnp.int32)     # [B]
    lens = ctx.input('SeqLens').reshape(-1).astype(jnp.int32)      # [B]
    tables = ctx.input('BlockTables').astype(jnp.int32)            # [B, P]
    temps = ctx.input('Temps').reshape(-1).astype(jnp.float32)
    seeds = ctx.input('Seeds').reshape(-1).astype(jnp.int32)

    # this token's page: logical block lens // bs through the table
    # (empty slots feed all->NB tables, so phys lands out of bounds and
    # every write below drops)
    logical = jnp.clip(lens // bs, 0, tables.shape[1] - 1)
    phys = jnp.take_along_axis(tables, logical[:, None], axis=1)[:, 0]
    off = lens % bs

    x = jnp.take(emb, tokens, axis=0) * (d_model ** 0.5) + \
        jnp.take(pos_enc, lens, axis=0)

    def body(h, sl):
        p, kc, vc = sl
        k_new = _split_heads(h @ p['slf_k'], n_head)       # [B, H, dk]
        v_new = _split_heads(h @ p['slf_v'], n_head)
        kc = _write_positions(kc, k_new, phys, off)
        vc = _write_positions(vc, v_new, phys, off)
        q = _split_heads(h @ p['slf_q'], n_head)
        attn = paged_attention(q, kc, vc, tables, lens + 1)
        h = _ln(h + attn.reshape(h.shape[0], -1) @ p['slf_o'], p, 'ln1')
        h = _ln(h + _ffn(h, p), p, 'ln2')
        return h, (kc, vc)

    h, (kcs, vcs) = jax.lax.scan(body, x, (params, kcs, vcs))
    logits = (h @ wout).astype(jnp.float32)                # [B, V]
    nxt = jax.vmap(_sample_token)(logits, seeds, lens + 1, temps)
    ctx.set_output('NextTokens',
                   nxt.astype(ctx.out_dtype('NextTokens', 'int64')))
    ctx.set_output('KCacheOut', kcs)
    ctx.set_output('VCacheOut', vcs)


@register('paged_prefill')
def _paged_prefill(ctx):
    emb, pos_enc, wout, params, kcs, vcs = _lm_inputs(ctx)
    n_head = ctx.attr('n_head', 1)
    bs = kcs.shape[3]
    nb = kcs.shape[1]
    d_model = emb.shape[-1]
    dk = params['slf_q'].shape[-1] // n_head

    ids = ctx.input('Ids').reshape(-1).astype(jnp.int32)   # [S] (padded)
    length = ctx.input('Len').reshape(()).astype(jnp.int32)
    table = ctx.input('BlockTable').astype(jnp.int32).reshape(-1)  # [P]
    temp = ctx.input('Temp').reshape(()).astype(jnp.float32)
    seed = ctx.input('Seed').reshape(()).astype(jnp.int32)
    s = ids.shape[0]

    t_idx = jnp.arange(s, dtype=jnp.int32)
    logical = jnp.clip(t_idx // bs, 0, table.shape[0] - 1)
    phys = jnp.where(t_idx < length, table[logical], nb)   # nb => drop
    off = t_idx % bs

    x = jnp.take(emb, ids, axis=0) * (d_model ** 0.5) + pos_enc[:s]

    causal = t_idx[:, None] >= t_idx[None, :]              # [S, S]

    def body(h, sl):
        p, kc, vc = sl
        k3 = _split_heads(h @ p['slf_k'], n_head)          # [S, H, dk]
        v3 = _split_heads(h @ p['slf_v'], n_head)
        kc = _write_positions(kc, k3, phys, off)
        vc = _write_positions(vc, v3, phys, off)
        q3 = _split_heads(h @ p['slf_q'], n_head)
        logits = jnp.einsum('qhd,khd->hqk', q3 * (dk ** -0.5), k3)
        logits = jnp.where(causal[None], logits, _NEG_INF)
        w = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        attn = jnp.einsum('hqk,khd->qhd', w.astype(v3.dtype), v3)
        h = _ln(h + attn.reshape(s, -1) @ p['slf_o'], p, 'ln1')
        h = _ln(h + _ffn(h, p), p, 'ln2')
        return h, (kc, vc)

    h, (kcs, vcs) = jax.lax.scan(body, x, (params, kcs, vcs))
    h_last = jax.lax.dynamic_index_in_dim(
        h, jnp.maximum(length - 1, 0), keepdims=False)
    logits = (h_last @ wout).astype(jnp.float32)           # [V]
    nxt = _sample_token(logits, seed, length, temp)
    ctx.set_output('NextToken',
                   nxt.reshape(1).astype(ctx.out_dtype('NextToken',
                                                       'int64')))
    ctx.set_output('KCacheOut', kcs)
    ctx.set_output('VCacheOut', vcs)
