"""Scan-over-layers transformer stack op.

Reference parity: the reference transformer config unrolls its 6 encoder /
decoder layers into the ProgramDesc op list (one op chain per layer).
TPU-first design: identical layers are ONE `lax.scan` over weights stacked
along a leading [n_layer, ...] axis — XLA compiles the layer body once
instead of n_layer times, so compile time stays flat as stacks deepen
(SURVEY §5 "scan-over-layers" lever). The per-layer math exactly mirrors
models/transformer.py encoder_layer/decoder_layer (fused attention →
residual+LN → FFN → residual+LN, dropout in the same places with the same
downgrade_in_infer scheme layers.dropout uses).

Emitted by models/transformer.py when scan_layers=True; parity with the
unrolled graph is asserted in tests/test_transformer_scan.py.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register
from .attention_ops import fused_attention


def _dropout(x, rate, key, is_test):
    """layers.dropout default (downgrade_in_infer) semantics."""
    if not rate:
        return x
    if is_test:
        return x * (1.0 - rate)
    mask = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return x * mask.astype(x.dtype)


def _post_process(prev, out, p, rate, key, is_test, ln_slot):
    # fused_layer_norm: fp32 statistics, activation handed back in x's
    # dtype, Pallas kernel when profitable — the same path the
    # layer_norm op lowering dispatches through.
    from .pallas.layer_norm import fused_layer_norm
    out = _dropout(out, rate, key, is_test)
    return fused_layer_norm(prev + out, p[ln_slot + '_w'],
                            p[ln_slot + '_b'], eps=1e-5,
                            begin_norm_axis=-1)


def _attn(x, mem, p, pre, n_head, causal, key_length, rate, key, is_test,
          mesh):
    q3 = x @ p[pre + '_q']
    k3 = mem @ p[pre + '_k']
    v3 = mem @ p[pre + '_v']
    out = fused_attention(q3, k3, v3, n_head, causal=causal,
                          key_length=key_length, dropout_rate=rate,
                          rng=key, is_test=is_test, mesh=mesh)
    return out @ p[pre + '_o']


def _ffn(x, p, rate, key, is_test):
    h = jax.nn.relu(x @ p['ffn_w1'] + p['ffn_b1'])
    h = _dropout(h, rate, key, is_test)
    return h @ p['ffn_w2'] + p['ffn_b2']


ENC_SLOTS = ('slf_q', 'slf_k', 'slf_v', 'slf_o', 'ln1_w', 'ln1_b',
             'ffn_w1', 'ffn_b1', 'ffn_w2', 'ffn_b2', 'ln2_w', 'ln2_b')
DEC_SLOTS = ('slf_q', 'slf_k', 'slf_v', 'slf_o', 'ln1_w', 'ln1_b',
             'cross_q', 'cross_k', 'cross_v', 'cross_o', 'ln2_w', 'ln2_b',
             'ffn_w1', 'ffn_b1', 'ffn_w2', 'ffn_b2', 'ln3_w', 'ln3_b')


def _slot_to_input(slot):
    """'slf_q' -> the op input slot name 'SlfQ'."""
    return ''.join(part.capitalize() for part in slot.split('_'))


@register('transformer_layer_stack')
def _transformer_layer_stack(ctx):
    x = ctx.input('X')
    is_decoder = ctx.has_input('EncOut')
    enc_out = ctx.input('EncOut') if is_decoder else None
    key_length = ctx.input('SrcLength') if ctx.has_input('SrcLength') \
        else None
    n_head = ctx.attr('n_head', 1)
    rate = ctx.attr('dropout_rate', 0.0)
    is_test = ctx.attr('is_test', False) or ctx.is_test
    mesh = getattr(ctx.block.program, 'mesh', None)

    slots = DEC_SLOTS if is_decoder else ENC_SLOTS
    params = {s: ctx.env[ctx.op.input(_slot_to_input(s))] for s in slots}
    n_layer = next(iter(params.values())).shape[0]

    if ctx.amp == 'bf16':
        x = x.astype(jnp.bfloat16)
        if enc_out is not None:
            enc_out = enc_out.astype(jnp.bfloat16)
        for s in slots:
            # matmul operands ride the MXU in bf16; LN params stay fp32
            # (their math runs in fp32 inside _layer_norm)
            if not s.startswith('ln'):
                params[s] = params[s].astype(jnp.bfloat16)

    # one folded key per (layer, dropout site); scanned alongside params
    n_sites = 6 if is_decoder else 4
    if rate and not is_test:
        site_keys = jax.random.split(
            ctx.rng_key(), n_layer * n_sites).reshape(n_layer, n_sites)
        xs = (params, site_keys)
    else:
        xs = (params,)

    def body(h, sl):
        p = sl[0]
        kk = list(sl[1]) if len(sl) > 1 else [None] * n_sites
        slf = _attn(h, h, p, 'slf', n_head, is_decoder,
                    None if is_decoder else key_length,
                    rate, kk[0], is_test, mesh)
        h = _post_process(h, slf, p, rate, kk[1], is_test, 'ln1')
        if is_decoder:
            cross = _attn(h, enc_out, p, 'cross', n_head, False,
                          key_length, rate, kk[4], is_test, mesh)
            h = _post_process(h, cross, p, rate, kk[5], is_test, 'ln2')
        ffn = _ffn(h, p, rate, kk[2], is_test)
        h = _post_process(h, ffn, p, rate, kk[3], is_test,
                          'ln3' if is_decoder else 'ln2')
        return h, None

    out, _ = jax.lax.scan(body, x, xs)
    ctx.set_output('Out', out)
