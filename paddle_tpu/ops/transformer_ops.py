"""Scan-over-layers transformer stack op.

Reference parity: the reference transformer config unrolls its 6 encoder /
decoder layers into the ProgramDesc op list (one op chain per layer).
TPU-first design: identical layers are ONE `lax.scan` over weights stacked
along a leading [n_layer, ...] axis — XLA compiles the layer body once
instead of n_layer times, so compile time stays flat as stacks deepen
(SURVEY §5 "scan-over-layers" lever). The per-layer math exactly mirrors
models/transformer.py encoder_layer/decoder_layer (fused attention →
residual+LN → FFN → residual+LN, dropout in the same places with the same
downgrade_in_infer scheme layers.dropout uses).

Emitted by models/transformer.py when scan_layers=True; parity with the
unrolled graph is asserted in tests/test_transformer_scan.py.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register
from .attention_ops import fused_attention


def _dropout(x, rate, key, is_test):
    """layers.dropout default (downgrade_in_infer) semantics."""
    if not rate:
        return x
    if is_test:
        return x * (1.0 - rate)
    mask = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return x * mask.astype(x.dtype)


def _post_process(prev, out, p, rate, key, is_test, ln_slot):
    # fused_layer_norm: fp32 statistics, activation handed back in x's
    # dtype, Pallas kernel when profitable — the same path the
    # layer_norm op lowering dispatches through.
    from .pallas.layer_norm import fused_layer_norm
    out = _dropout(out, rate, key, is_test)
    return fused_layer_norm(prev + out, p[ln_slot + '_w'],
                            p[ln_slot + '_b'], eps=1e-5,
                            begin_norm_axis=-1)


def _attn(x, mem, p, pre, n_head, causal, key_length, rate, key, is_test,
          mesh):
    q3 = x @ p[pre + '_q']
    k3 = mem @ p[pre + '_k']
    v3 = mem @ p[pre + '_v']
    out = fused_attention(q3, k3, v3, n_head, causal=causal,
                          key_length=key_length, dropout_rate=rate,
                          rng=key, is_test=is_test, mesh=mesh)
    return out @ p[pre + '_o']


def _ffn(x, p, rate, key, is_test):
    h = jax.nn.relu(x @ p['ffn_w1'] + p['ffn_b1'])
    h = _dropout(h, rate, key, is_test)
    return h @ p['ffn_w2'] + p['ffn_b2']


ENC_SLOTS = ('slf_q', 'slf_k', 'slf_v', 'slf_o', 'ln1_w', 'ln1_b',
             'ffn_w1', 'ffn_b1', 'ffn_w2', 'ffn_b2', 'ln2_w', 'ln2_b')
DEC_SLOTS = ('slf_q', 'slf_k', 'slf_v', 'slf_o', 'ln1_w', 'ln1_b',
             'cross_q', 'cross_k', 'cross_v', 'cross_o', 'ln2_w', 'ln2_b',
             'ffn_w1', 'ffn_b1', 'ffn_w2', 'ffn_b2', 'ln3_w', 'ln3_b')


def _slot_to_input(slot):
    """'slf_q' -> the op input slot name 'SlfQ'."""
    return ''.join(part.capitalize() for part in slot.split('_'))


def _pipeline_state(ctx):
    """(mesh, pp_conf, pipelined) for a stack op. pipelined is True when
    the program was transpiled with ParallelStrategy(pipeline_parallel=
    True) onto a mesh with an active 'pp' axis — the lowering then runs
    the GPipe microbatch schedule (parallel/pipeline.py) instead of one
    flat lax.scan, with stage s holding layers [s*L/pp, (s+1)*L/pp)."""
    program = ctx.block.program
    mesh = getattr(program, 'mesh', None)
    pp_conf = getattr(program, 'pipeline', None)
    pipelined = bool(pp_conf) and mesh is not None and \
        dict(mesh.shape).get('pp', 1) > 1
    return mesh, pp_conf, pipelined


@register('transformer_layer_stack')
def _transformer_layer_stack(ctx):
    x = ctx.input('X')
    is_decoder = ctx.has_input('EncOut')
    enc_out = ctx.input('EncOut') if is_decoder else None
    key_length = ctx.input('SrcLength') if ctx.has_input('SrcLength') \
        else None
    n_head = ctx.attr('n_head', 1)
    rate = ctx.attr('dropout_rate', 0.0)
    is_test = ctx.attr('is_test', False) or ctx.is_test
    mesh, pp_conf, pipelined = _pipeline_state(ctx)

    slots = DEC_SLOTS if is_decoder else ENC_SLOTS
    params = {s: ctx.env[ctx.op.input(_slot_to_input(s))] for s in slots}
    n_layer = next(iter(params.values())).shape[0]

    if ctx.amp == 'bf16':
        x = x.astype(jnp.bfloat16)
        if enc_out is not None:
            enc_out = enc_out.astype(jnp.bfloat16)
        for s in slots:
            # matmul operands ride the MXU in bf16; LN params stay fp32
            # (their math runs in fp32 inside _layer_norm)
            if not s.startswith('ln'):
                params[s] = params[s].astype(jnp.bfloat16)

    # one folded key per (layer, dropout site); scanned alongside params
    n_sites = 6 if is_decoder else 4
    if rate and not is_test:
        site_keys = jax.random.split(
            ctx.rng_key(), n_layer * n_sites).reshape(n_layer, n_sites)
        xs = (params, site_keys)
    else:
        xs = (params,)

    # The pipelined stage runs inside a shard_map that is manual over
    # 'pp' only: GSPMD still manages dp/tp within the stage, and the
    # ring-attention dispatch nests as an sp-manual inner shard_map
    # that inherits the context mesh (_ring_dispatch) — pp composes
    # with dp, tp, AND sp, so attention sees the mesh either way.

    def make_body(ext, fold):
        # ext: this microbatch's slice of the batch-aligned side inputs
        # (full arrays in the non-pipelined path); fold: microbatch index
        # folded into dropout keys so masks stay per-microbatch
        enc_m = ext.get('enc')
        kl_m = ext.get('kl')

        def body(h, sl):
            p = sl[0]
            kk = list(sl[1]) if len(sl) > 1 else [None] * n_sites
            if fold is not None:
                kk = [None if k is None else jax.random.fold_in(k, fold)
                      for k in kk]
            slf = _attn(h, h, p, 'slf', n_head, is_decoder,
                        None if is_decoder else kl_m,
                        rate, kk[0], is_test, mesh)
            h = _post_process(h, slf, p, rate, kk[1], is_test, 'ln1')
            if is_decoder:
                cross = _attn(h, enc_m, p, 'cross', n_head, False,
                              kl_m, rate, kk[4], is_test, mesh)
                h = _post_process(h, cross, p, rate, kk[5], is_test, 'ln2')
            ffn = _ffn(h, p, rate, kk[2], is_test)
            h = _post_process(h, ffn, p, rate, kk[3], is_test,
                              'ln3' if is_decoder else 'ln2')
            return h, None

        return body

    extras = {}
    if enc_out is not None:
        extras['enc'] = enc_out
    if key_length is not None:
        extras['kl'] = key_length

    if pipelined:
        from ..parallel.pipeline import pipeline_layer_scan
        out = pipeline_layer_scan(make_body, x, xs, mesh,
                                  pp_conf['n_micro'], extras=extras)
    else:
        out, _ = jax.lax.scan(make_body(extras, None), x, xs)
    ctx.set_output('Out', out)


MOE_SLOTS = ('slf_q', 'slf_k', 'slf_v', 'slf_o', 'ln1_w', 'ln1_b',
             'gate_w', 'moe_w1', 'moe_b1', 'moe_w2', 'moe_b2',
             'ln2_w', 'ln2_b')


@register('moe_layer_stack')
def _moe_layer_stack(ctx):
    """Scan-over-layers for MoE transformer blocks: causal fused
    attention -> residual+LN -> Switch/top-k MoE FFN -> residual+LN,
    ONE lax.scan over [n_layer, ...] stacked weights (expert weights
    stack [n_layer, E, ...]). Mirrors models/moe.py's unrolled block;
    per-layer aux losses come back summed. Composes the two scaling
    levers: flat compile time over depth (transformer_layer_stack) and
    expert parallelism (the per-layer dispatch is switch_moe_reference,
    so 'ep' sharding constraints still apply inside the scan)."""
    from .moe_ops import (constrain_experts, moe_capacity,
                          switch_moe_reference)

    x = ctx.input('X')
    n_head = ctx.attr('n_head', 1)
    rate = ctx.attr('dropout_rate', 0.0)
    cap_factor = ctx.attr('capacity_factor', 1.25)
    k = ctx.attr('top_k', 1)
    is_test = ctx.attr('is_test', False) or ctx.is_test
    mesh, pp_conf, pipelined = _pipeline_state(ctx)
    params = {s: ctx.env[ctx.op.input(_slot_to_input(s))]
              for s in MOE_SLOTS}
    n_layer = next(iter(params.values())).shape[0]
    if ctx.amp == 'bf16':
        x = x.astype(jnp.bfloat16)
        for s in MOE_SLOTS:
            # router (gate_w) and LN params stay fp32
            if not s.startswith('ln') and s != 'gate_w':
                params[s] = params[s].astype(jnp.bfloat16)

    b, t, d = x.shape
    # pipelined: each microbatch routes independently, so capacity is
    # per-microbatch tokens (capacity_factor semantics preserved; the
    # routing population differs from full-batch by design, like any
    # microbatched MoE schedule)
    route_b = b // pp_conf['n_micro'] if pipelined else b
    capacity = moe_capacity(cap_factor, k, route_b * t,
                            params['gate_w'].shape[-1])

    if rate and not is_test:
        # one key per layer: dropout lives only inside the attention op
        # (models/moe.py's unrolled block has no post-process sites)
        site_keys = jax.random.split(
            ctx.rng_key(), n_layer).reshape(n_layer, 1)
        xs = (params, site_keys)
    else:
        xs = (params,)

    def make_body(_ext, fold):
        def body(carry, sl):
            h, aux_sum = carry
            p = sl[0]
            key = sl[1][0] if len(sl) > 1 else None
            if fold is not None and key is not None:
                key = jax.random.fold_in(key, fold)
            slf = _attn(h, h, p, 'slf', n_head, True, None, rate, key,
                        is_test, mesh)
            h = _post_process(h, slf, p, 0.0, None, is_test, 'ln1')
            hb, ht, hd = h.shape
            h2 = h.reshape(hb * ht, hd)
            w1, b1, w2, b2 = constrain_experts(
                mesh, (p['moe_w1'], p['moe_b1'], p['moe_w2'],
                       p['moe_b2']))
            moe_out, aux, _ = switch_moe_reference(
                h2, p['gate_w'], w1, b1, w2, b2, capacity, k=k)
            h = _post_process(h, moe_out.reshape(hb, ht, hd), p, 0.0,
                              None, is_test, 'ln2')
            return (h, aux_sum + aux), None

        return body

    if pipelined:
        from ..parallel.pipeline import pipeline_layer_scan
        out, aux_total = pipeline_layer_scan(
            make_body, x, xs, mesh, pp_conf['n_micro'], aux=True)
    else:
        (out, aux_total), _ = jax.lax.scan(
            make_body({}, None), (x, jnp.zeros((), jnp.float32)), xs)
    ctx.set_output('Out', out)
    ctx.set_output('AuxLoss', aux_total)


# --------------------------------------------------------- incremental decode
def _mha_one_step(q1, kc, vc, n_head, live):
    """One-query attention against a cached key/value buffer.

    q1: [B, HD] (the current position), kc/vc: [B, Tmax, HD] head-merged
    caches, live: [B] or scalar — number of valid cache positions; the
    rest are masked. Returns [B, HD]. fp32 softmax."""
    b, tmax, hd = kc.shape
    d = hd // n_head
    q = q1.reshape(b, n_head, 1, d)
    k = kc.reshape(b, tmax, n_head, d).transpose(0, 2, 1, 3)
    v = vc.reshape(b, tmax, n_head, d).transpose(0, 2, 1, 3)
    logits = jnp.einsum('bhqd,bhkd->bhqk', (q * d ** -0.5), k)
    mask = jnp.arange(tmax)[None, :] < jnp.reshape(live, (-1, 1))
    logits = jnp.where(mask[:, None, None, :], logits, -1e9)
    w = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum('bhqk,bhkd->bhqd', w.astype(v.dtype), v)
    return out.transpose(0, 2, 1, 3).reshape(b, hd)


def _incremental_layer_scan(params, n_head, cross_live, x, kcs, vcs, ck,
                            cv, t):
    """One decoder step through all layers (inner lax.scan): append this
    position's K/V into the caches, self-attend over live cache, cross-
    attend over the precomputed encoder K/V, FFN; residual+LN as in
    decoder_layer. Returns (h, new kcaches, new vcaches)."""
    from .pallas.layer_norm import fused_layer_norm

    def ln(h, p, slot):
        return fused_layer_norm(h, p[slot + '_w'], p[slot + '_b'],
                                eps=1e-5, begin_norm_axis=-1)

    def body(h, sl):
        p, kc, vc, ckl, cvl = sl
        kc = jax.lax.dynamic_update_slice(
            kc, (h @ p['slf_k'])[:, None, :], (0, t, 0))
        vc = jax.lax.dynamic_update_slice(
            vc, (h @ p['slf_v'])[:, None, :], (0, t, 0))
        slf = _mha_one_step(h @ p['slf_q'], kc, vc, n_head, t + 1)
        h = ln(h + slf @ p['slf_o'], p, 'ln1')
        cross = _mha_one_step(h @ p['cross_q'], ckl, cvl, n_head,
                              cross_live)
        h = ln(h + cross @ p['cross_o'], p, 'ln2')
        ffn = jax.nn.relu(h @ p['ffn_w1'] + p['ffn_b1']) \
            @ p['ffn_w2'] + p['ffn_b2']
        h = ln(h + ffn, p, 'ln3')
        return h, (kc, vc)

    h, (kcs, vcs) = jax.lax.scan(body, x, (params, kcs, vcs, ck, cv))
    return h, kcs, vcs


def _decode_op_inputs(ctx):
    """Shared input unpack + amp policy for the incremental decode ops."""
    enc_out = ctx.input('EncOut')
    src_len = ctx.input('SrcLength') if ctx.has_input('SrcLength') else None
    emb = ctx.input('Emb')
    pos = ctx.input('PosEnc')
    wout = ctx.input('OutProj')
    params = {s: ctx.env[ctx.op.input(_slot_to_input(s))]
              for s in DEC_SLOTS}
    if ctx.amp == 'bf16':
        enc_out = enc_out.astype(jnp.bfloat16)
        emb = emb.astype(jnp.bfloat16)
        wout = wout.astype(jnp.bfloat16)
        pos = pos.astype(jnp.bfloat16)
        for s in DEC_SLOTS:
            if not s.startswith('ln'):
                params[s] = params[s].astype(jnp.bfloat16)
    return enc_out, src_len, emb, pos, wout, params


@register('transformer_greedy_decode')
def _transformer_greedy_decode(ctx):
    """KV-cached greedy decode: ONE lax.scan over output positions (inner
    scan over decoder layers), instead of re-running the decoder over the
    whole prefix per emitted token as the reference's While-based infer
    program does. Compute drops from O(T^2 L) to O(T L); compile time is
    flat in max_out_len. Emitted by
    models.transformer.transformer_greedy_infer(incremental=True)."""
    enc_out, src_len, emb, pos, wout, params = _decode_op_inputs(ctx)
    n_head = ctx.attr('n_head', 1)
    t_max = ctx.attr('max_out_len')
    bos_id = ctx.attr('bos_id', 0)
    eos_id = ctx.attr('eos_id', 1)
    d_model = emb.shape[-1]

    b = enc_out.shape[0]
    n_layer = params['slf_q'].shape[0]
    hdk = params['slf_q'].shape[-1]
    hdv = params['slf_v'].shape[-1]
    s_len = enc_out.shape[1]
    cross_live = src_len if src_len is not None else s_len

    # cross-attention K/V never change over time: compute once per layer
    ck = jnp.einsum('bsd,ldh->lbsh', enc_out, params['cross_k'])
    cv = jnp.einsum('bsd,ldh->lbsh', enc_out, params['cross_v'])

    kc0 = jnp.zeros((n_layer, b, t_max, hdk), enc_out.dtype)
    vc0 = jnp.zeros((n_layer, b, t_max, hdv), enc_out.dtype)
    ids0 = jnp.full((b,), bos_id, jnp.int32)

    def step(carry, t):
        ids, kcs, vcs = carry
        x = jnp.take(emb, ids, axis=0) * (d_model ** 0.5) + \
            jax.lax.dynamic_index_in_dim(pos, t, keepdims=False)
        h, kcs, vcs = _incremental_layer_scan(
            params, n_head, cross_live, x, kcs, vcs, ck, cv, t)
        logits = (h @ wout).astype(jnp.float32)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (nxt, kcs, vcs), nxt

    _, steps = jax.lax.scan(step, (ids0, kc0, vc0),
                            jnp.arange(t_max - 1))
    ids = jnp.concatenate([jnp.full((b, 1), bos_id, jnp.int32),
                           steps.T], axis=1)          # [B, T]
    # freeze everything after the first EOS to EOS
    is_eos = (ids == eos_id).astype(jnp.int32)
    before = jnp.cumsum(is_eos, axis=1) - is_eos
    ids = jnp.where(before > 0, eos_id, ids)
    ctx.set_output('Out', ids.astype(ctx.out_dtype('Out', 'int64')))


@register('transformer_beam_decode')
def _transformer_beam_decode(ctx):
    """KV-cached beam search in ONE lax.scan: the per-step candidate
    expansion/pruning is the exact math of the beam_search op
    (decode_ops.py), caches are reordered by parent in place of the
    unrolled graph's prefix beam_gather + full re-run, and the final
    backtrack is the beam_search_decode recurrence. Emits identical
    sequences to the unrolled transformer_beam_infer graph."""
    enc_out, src_len, emb, pos, wout, params = _decode_op_inputs(ctx)
    n_head = ctx.attr('n_head', 1)
    t_max = ctx.attr('max_out_len')
    beam = ctx.attr('beam_size', 4)
    bos_id = ctx.attr('bos_id', 0)
    eos_id = ctx.attr('eos_id', 1)
    d_model = emb.shape[-1]

    b = enc_out.shape[0]
    n_layer = params['slf_q'].shape[0]
    hdk = params['slf_q'].shape[-1]
    hdv = params['slf_v'].shape[-1]
    s_len = enc_out.shape[1]

    # tile examples over the beam: [B, S, D] -> [B*beam, S, D]
    enc_beam = jnp.repeat(enc_out, beam, axis=0)
    cross_live = jnp.repeat(src_len, beam, axis=0) \
        if src_len is not None else s_len
    ck = jnp.einsum('bsd,ldh->lbsh', enc_beam, params['cross_k'])
    cv = jnp.einsum('bsd,ldh->lbsh', enc_beam, params['cross_v'])

    kc0 = jnp.zeros((n_layer, b * beam, t_max, hdk), enc_out.dtype)
    vc0 = jnp.zeros((n_layer, b * beam, t_max, hdv), enc_out.dtype)
    last0 = jnp.full((b * beam,), bos_id, jnp.int32)
    pre_ids0 = jnp.full((b, beam), bos_id, jnp.int32)
    # only beam slot 0 live at t=0 (all beams start identical)
    pre_scores0 = jnp.where(jnp.arange(beam)[None, :] == 0, 0.0, -1e9) * \
        jnp.ones((b, 1), jnp.float32)

    def gather_caches(c, parent):
        # c: [L, B*beam, Tmax, HD]; parent: [B, beam] — reorder beams
        cb = c.reshape(n_layer, b, beam, t_max, c.shape[-1])
        idx = parent[None, :, :, None, None]
        return jnp.take_along_axis(cb, idx, axis=2).reshape(c.shape)

    def step(carry, t):
        last, pre_ids, pre_scores, kcs, vcs = carry
        x = jnp.take(emb, last, axis=0) * (d_model ** 0.5) + \
            jax.lax.dynamic_index_in_dim(pos, t, keepdims=False)
        h, kcs, vcs = _incremental_layer_scan(
            params, n_head, cross_live, x, kcs, vcs, ck, cv, t)
        logp = jax.nn.log_softmax((h @ wout).astype(jnp.float32), axis=-1)
        top_scores, top_ids = jax.lax.top_k(logp, beam)
        from .decode_ops import beam_search_step
        sel_ids, sel_scores, parent = beam_search_step(
            pre_ids, pre_scores, top_ids.reshape(b, beam, beam),
            top_scores.reshape(b, beam, beam), beam, eos_id)
        kcs = gather_caches(kcs, parent)
        vcs = gather_caches(vcs, parent)
        carry = (sel_ids.reshape(-1).astype(jnp.int32), sel_ids,
                 sel_scores, kcs, vcs)
        return carry, (sel_ids, parent)

    (_, _, final_scores, _, _), (step_ids, step_parents) = jax.lax.scan(
        step, (last0, pre_ids0, pre_scores0, kc0, vc0),
        jnp.arange(t_max - 1))

    from .decode_ops import beam_backtrack
    seq = beam_backtrack(step_ids, step_parents, eos_id)  # [B, beam, T-1]
    ctx.set_output('SentenceIds',
                   seq.astype(ctx.out_dtype('SentenceIds', 'int64')))
    ctx.set_output('SentenceScores', final_scores)
