"""Convolution / pooling ops.

Reference: paddle/fluid/operators/{conv_op,conv_transpose_op,pool_op}.cc.
IR semantics stay NCHW for reference-parity; the layout knob only
changes the lax.conv dimension numbers inside the lowering (boundary
transposes cancel in XLA). On TPU the default is NHWC: with the
bf16-elementwise BN it measured +8% ResNet-50 img/s (2,436 vs ~2,257,
r3 rehearsal) — channels-last matches the (8,128) vector tiling.
PADDLE_TPU_CONV_LAYOUT=NCHW|NHWC overrides; numerics are identical
either way (tests/test_amp.py::test_nhwc_conv_layout_matches_nchw) and
the bench records both, the faster one winning the headline.
"""

import os

import jax
import jax.numpy as jnp

from ..core.registry import register


def _conv_layout():
    env = os.environ.get('PADDLE_TPU_CONV_LAYOUT')
    if env:
        return env.upper()
    from ..core.platform_boot import is_tpu_backend
    return 'NHWC' if is_tpu_backend() else 'NCHW'


def _s2d_stem(x_nhwc, w_oihw):
    """Space-to-depth rewrite of the ResNet stem conv (k=7, s=2, p=3,
    small Cin): exactly equivalent to the original conv, but over a
    2x2-space-to-depth input — [B, H/2, W/2, 4*Cin] with a 4x4 stride-1
    kernel — so the contraction dim grows 4x toward the MXU's 128 lanes
    and the stride-2 pattern disappears (the MLPerf ResNet stem trick).

    Derivation: out[y,x,o] = Σ_{dy,dx,c} w[dy,dx,c,o]·in[2y+dy-3, ...].
    Write 2y+dy-3 = 2(y+uy)+py with py=(dy+1)%2, uy=(dy-3-py)//2 ∈
    [-2,1]: a 4-tap stride-1 conv over the (py,c)-stacked planes with
    asymmetric padding (2,1); kernel slot (uy,py) holds w[2uy+py+3]
    (the single out-of-range slot dy=-1 is zero)."""
    b, h, wdt, c = x_nhwc.shape
    # [B, H/2, 2, W/2, 2, C] -> [B, H/2, W/2, 2, 2, C] -> merge
    x2 = x_nhwc.reshape(b, h // 2, 2, wdt // 2, 2, c) \
        .transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, wdt // 2, 4 * c)
    o = w_oihw.shape[0]
    # build w2[uy+2, ux+2, (py,px,c), o] = w[o, c, 2uy+py+3, 2ux+px+3]
    w_hwio = w_oihw.transpose(2, 3, 1, 0)  # [7,7,C,O]
    wp = jnp.pad(w_hwio, [(1, 0), (1, 0), (0, 0), (0, 0)])  # dy=-1 slot
    # wp index = dy+1 = 2uy+py+4 = 2(uy+2)+py: reshape [4,2,4,2,C,O]
    w2 = wp.reshape(4, 2, 4, 2, c, o).transpose(0, 2, 1, 3, 4, 5) \
        .reshape(4, 4, 4 * c, o)
    return jax.lax.conv_general_dilated(
        x2, w2, window_strides=(1, 1), padding=[(2, 1), (2, 1)],
        dimension_numbers=('NHWC', 'HWIO', 'NHWC'))


def _s2d_applicable(x_nhwc, w, strides, pads, dilations, groups):
    if os.environ.get('PADDLE_TPU_CONV_S2D', '0') != '1':
        return False
    return (w.shape[2] == 7 and w.shape[3] == 7 and strides == (2, 2)
            and tuple(pads) in ((3, 3), (3, 3, 3, 3))
            and dilations == (1, 1) and groups == 1
            and w.shape[1] <= 4 and x_nhwc.shape[1] % 2 == 0
            and x_nhwc.shape[2] % 2 == 0)


@register('conv2d')
def _conv2d(ctx):
    x = ctx.input('Input')  # NCHW (or NHWC when data_format says so)
    w = ctx.input('Filter')  # OIHW (parameter layout is fixed either way)
    strides = tuple(ctx.attr('strides', [1, 1]))
    pads = ctx.attr('paddings', [0, 0])
    dilations = tuple(ctx.attr('dilations', [1, 1]))
    groups = ctx.attr('groups', 1)
    padding = [(pads[0], pads[0]), (pads[1], pads[1])] if len(pads) == 2 \
        else [(pads[0], pads[1]), (pads[2], pads[3])]
    pref = x.dtype if x.dtype == jnp.float32 else None
    if ctx.attr('data_format', 'NCHW') == 'NHWC':
        if _s2d_applicable(x, w, strides, pads, dilations, groups):
            ctx.set_output('Output', _s2d_stem(x, w))
            return
        # Activations are NHWC *in the IR* (layers.conv2d data_format=
        # 'NHWC'): no boundary transposes at all — the whole network
        # stays channels-last end-to-end, which is the TPU-native
        # layout ((8,128) vector tiling over W,C).
        out = jax.lax.conv_general_dilated(
            x, w.transpose(2, 3, 1, 0),
            window_strides=strides, padding=padding,
            rhs_dilation=dilations, feature_group_count=groups,
            dimension_numbers=('NHWC', 'HWIO', 'NHWC'),
            preferred_element_type=pref)
    elif _conv_layout() == 'NHWC':
        out = jax.lax.conv_general_dilated(
            x.transpose(0, 2, 3, 1), w.transpose(2, 3, 1, 0),
            window_strides=strides, padding=padding,
            rhs_dilation=dilations, feature_group_count=groups,
            dimension_numbers=('NHWC', 'HWIO', 'NHWC'),
            preferred_element_type=pref).transpose(0, 3, 1, 2)
    else:
        out = jax.lax.conv_general_dilated(
            x, w, window_strides=strides, padding=padding,
            rhs_dilation=dilations, feature_group_count=groups,
            dimension_numbers=('NCHW', 'OIHW', 'NCHW'),
            preferred_element_type=pref)
    ctx.set_output('Output', out)


@register('conv2d_transpose')
def _conv2d_transpose(ctx):
    """Fractionally-strided conv: lhs_dilation=stride + flipped kernel,
    the gradient-of-conv formulation XLA lowers best on TPU.
    out = (in-1)*stride - 2*pad + dilation*(k-1) + 1 (conv_transpose_op.cc).
    """
    x = ctx.input('Input')  # NCHW
    w = ctx.input('Filter')  # paddle layout [Cin, Cout/groups, kh, kw]
    strides = tuple(ctx.attr('strides', [1, 1]))
    pads = ctx.attr('paddings', [0, 0])
    dilations = tuple(ctx.attr('dilations', [1, 1]))
    groups = ctx.attr('groups', 1)
    cin, cout_g, kh, kw = w.shape
    # -> [Cout, Cin/groups, kh, kw], spatially flipped
    w_t = w.reshape(groups, cin // groups, cout_g, kh, kw)
    w_t = w_t.swapaxes(1, 2).reshape(groups * cout_g, cin // groups, kh, kw)
    w_t = jnp.flip(w_t, axis=(2, 3))
    padding = [(dilations[i] * ([kh, kw][i] - 1) - pads[i],) * 2
               for i in range(2)]
    out = jax.lax.conv_general_dilated(
        x, w_t, window_strides=(1, 1), padding=padding,
        lhs_dilation=strides, rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
    ctx.set_output('Output', out)


@register('conv3d')
def _conv3d(ctx):
    x = ctx.input('Input')  # NCDHW
    w = ctx.input('Filter')  # OIDHW
    strides = tuple(ctx.attr('strides', [1, 1, 1]))
    pads = ctx.attr('paddings', [0, 0, 0])
    dilations = tuple(ctx.attr('dilations', [1, 1, 1]))
    groups = ctx.attr('groups', 1)
    padding = [(p, p) for p in pads]
    out = jax.lax.conv_general_dilated(
        x, w, window_strides=strides, padding=padding,
        rhs_dilation=dilations, feature_group_count=groups,
        dimension_numbers=('NCDHW', 'OIDHW', 'NCDHW'))
    ctx.set_output('Output', out)


def _pool2d_impl(x, pooling_type, ksize, strides, pads, global_pooling,
                 ceil_mode=False, exclusive=True, adaptive=False,
                 data_format='NCHW'):
    if data_format == 'NHWC':
        n, h, w, c = x.shape
        spatial = (1, 2)
    else:
        n, c, h, w = x.shape
        spatial = (2, 3)
    if global_pooling or (adaptive and tuple(ksize) == (1, 1)):
        if pooling_type == 'max':
            return x.max(axis=spatial, keepdims=True)
        return x.mean(axis=spatial, keepdims=True)
    kh, kw = ksize
    sh, sw = strides
    ph, pw = pads
    eh = ew = 0
    if ceil_mode:
        # pad extra on the bottom/right so ceil-division windows fit
        eh = max(0, (-(h + 2 * ph - kh) % sh))
        ew = max(0, (-(w + 2 * pw - kw) % sw))
    if data_format == 'NHWC':
        window = (1, kh, kw, 1)
        stride = (1, sh, sw, 1)
        padding = ((0, 0), (ph, ph + eh), (pw, pw + ew), (0, 0))
        ones_shape = (1, h, w, 1)
    else:
        window = (1, 1, kh, kw)
        stride = (1, 1, sh, sw)
        padding = ((0, 0), (0, 0), (ph, ph + eh), (pw, pw + ew))
        ones_shape = (1, 1, h, w)
    if pooling_type == 'max':
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return jax.lax.reduce_window(x, init, jax.lax.max, window, stride,
                                     padding)
    summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, stride,
                                   padding)
    if exclusive and (ph or pw or ceil_mode):
        ones = jnp.ones(ones_shape, dtype=x.dtype)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window,
                                       stride, padding)
        return summed / jnp.maximum(counts, 1.0)
    return summed / (kh * kw)


@register('pool2d')
def _pool2d(ctx):
    x = ctx.input('X')
    out = _pool2d_impl(
        x,
        ctx.attr('pooling_type', 'max'),
        ctx.attr('ksize', [2, 2]),
        ctx.attr('strides', [2, 2]) if not ctx.attr('global_pooling', False)
        else [1, 1],
        ctx.attr('paddings', [0, 0]),
        ctx.attr('global_pooling', False),
        ceil_mode=ctx.attr('ceil_mode', False),
        exclusive=ctx.attr('exclusive', True),
        data_format=ctx.attr('data_format', 'NCHW'))
    ctx.set_output('Out', out)


@register('row_conv')
def _row_conv(ctx):
    """row_conv_op.cc (lookahead conv for DeepSpeech): out[t] =
    sum_{i=0..k-1} w[i] * x[t+i], per feature."""
    x = ctx.input('X')  # [batch, seq, dim] (padded dense form)
    w = ctx.input('Filter')  # [k, dim]
    k = w.shape[0]
    pads = [(0, 0), (0, k - 1), (0, 0)]
    xp = jnp.pad(x, pads)
    out = jnp.zeros_like(x)
    for i in range(k):
        out = out + xp[:, i:i + x.shape[1], :] * w[i]
    ctx.set_output('Out', out)


@register('conv_shift')
def _conv_shift(ctx):
    """conv_shift_op.cc: circular convolution (NTM addressing)."""
    x = ctx.input('X')  # [b, m]
    y = ctx.input('Y')  # [b, n], n odd, n <= m
    b, m = x.shape
    n = y.shape[1]
    half = n // 2
    idx = (jnp.arange(m)[:, None] + jnp.arange(-half, half + 1)[None, :]) % m
    gathered = x[:, idx]  # [b, m, n]
    ctx.set_output('Out', jnp.einsum('bmn,bn->bm', gathered, y))


@register('spp')
def _spp(ctx):
    """Spatial pyramid pooling (spp_op.cc)."""
    x = ctx.input('X')
    levels = ctx.attr('pyramid_height', 2)
    pooling_type = ctx.attr('pooling_type', 'max')
    n, c, h, w = x.shape
    outs = []
    for lv in range(levels):
        bins = 2 ** lv
        kh, kw = -(-h // bins), -(-w // bins)
        sh, sw = kh, kw
        out = _pool2d_impl(x, pooling_type, [kh, kw], [sh, sw], [0, 0], False,
                           ceil_mode=True)
        outs.append(out.reshape(n, -1))
    ctx.set_output('Out', jnp.concatenate(outs, axis=1))
