"""Op lowerings: IR op type -> JAX/lax tracing functions.

Importing this package registers every lowering (the analog of the
reference's REGISTER_OP kernel registrations in paddle/fluid/operators/*).
"""

from . import tensor_ops  # noqa: F401
from . import math_ops  # noqa: F401
from . import activation_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import conv_ops  # noqa: F401
from . import norm_ops  # noqa: F401
from . import random_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import attention_ops  # noqa: F401
from . import transformer_ops  # noqa: F401
from . import moe_ops  # noqa: F401
from . import misc_ops  # noqa: F401
from . import collective_ops  # noqa: F401
from . import control_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import decode_ops  # noqa: F401
from . import paged_decode_ops  # noqa: F401
from . import quant_ops  # noqa: F401
from . import linalg_ops  # noqa: F401
from . import lr_ops  # noqa: F401
from . import vision_ops  # noqa: F401
from . import metric_ops  # noqa: F401
