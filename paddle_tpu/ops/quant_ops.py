"""Weight-only int8 inference ops — the PTQ rewrite's targets.

Each op consumes an int8 weight plus its per-channel fp32 ``Scale``
var (quant/ptq.py pairs them; the ``quant`` analysis pass enforces the
pairing statically). Accumulation is fp32: the int8 weight upcasts at
the use site, the matmul runs in fp32, and the per-channel scale
multiplies the OUTPUT — algebraically identical to dequantizing the
weight first (``x @ (q * s) == (x @ q) * s`` for per-output-channel
scales) but keeps the weight int8 in HBM, which is the entire point.
"""

import jax.numpy as jnp

from ..core.registry import register
from .math_ops import _flatten_2d


@register('quant_mul')
def _quant_mul(ctx):
    """mul with an int8 Y: out = flatten(x) @ fp32(y_int8) * scale."""
    x = ctx.input('X')
    w = ctx.input('Y')
    scale = ctx.input('Scale')
    xd = ctx.attr('x_num_col_dims', 1)
    x2 = _flatten_2d(x, xd).astype(jnp.float32)
    out = (x2 @ w.astype(jnp.float32)) * scale[None, :]
    ctx.set_output('Out', out.reshape(x.shape[:xd] + (w.shape[1],)))


@register('quant_matmul')
def _quant_matmul(ctx):
    """matmul with an int8 2-D Y (per-output-column scales)."""
    x = ctx.input('X').astype(jnp.float32)
    w = ctx.input('Y')
    scale = ctx.input('Scale')
    if ctx.attr('transpose_X', False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    out = jnp.matmul(x, w.astype(jnp.float32)) * scale
    alpha = ctx.attr('alpha', 1.0)
    if alpha != 1.0:
        out = out * alpha
    ctx.set_output('Out', out)


@register('quant_lookup_table')
def _quant_lookup_table(ctx):
    """Embedding lookup over an int8 table with per-row scales.
    Inference-only (the PTQ rewrite runs on pruned infer programs), so
    the sparse-grad seed machinery of the fp32 lookup does not apply."""
    w = ctx.input('W')
    scale = ctx.input('Scale')
    ids = ctx.input('Ids')
    squeeze_last = ids.ndim >= 2 and ids.shape[-1] == 1
    if squeeze_last:
        ids = ids.squeeze(-1)
    rows = jnp.take(w, ids, axis=0).astype(jnp.float32) * \
        jnp.take(scale, ids, axis=0)[..., None]
    padding_idx = ctx.attr('padding_idx', -1)
    if padding_idx is not None and padding_idx >= 0:
        mask = (ids != padding_idx)[..., None]
        rows = rows * mask.astype(rows.dtype)
    ctx.set_output('Out', rows)
