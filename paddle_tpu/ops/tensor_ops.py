"""Tensor creation / manipulation ops.

Reference: paddle/fluid/operators/{fill_constant_op,cast_op,concat_op,
assign_op,sum_op,split_op,reshape_op,transpose_op,one_hot_op,...}.cc
"""

import jax
import jax.numpy as jnp

from ..core.dtypes import canonical_int
from ..core.registry import register


@register('fill_constant')
def _fill_constant(ctx):
    shape = [int(s) for s in ctx.attr('shape')]
    value = ctx.attr('value', 0.0)
    dtype = ctx.out_dtype('Out')
    ctx.set_output('Out', jnp.full(shape, value, dtype=dtype))


@register('fill_constant_batch_size_like')
def _fill_constant_bsl(ctx):
    ref = ctx.input('Input')
    shape = [int(s) for s in ctx.attr('shape')]
    in_idx = ctx.attr('input_dim_idx', 0)
    out_idx = ctx.attr('output_dim_idx', 0)
    shape[out_idx] = ref.shape[in_idx]
    dtype = ctx.out_dtype('Out')
    ctx.set_output('Out', jnp.full(shape, ctx.attr('value', 0.0), dtype=dtype))


@register('assign_value')
def _assign_value(ctx):
    import numpy as np
    values = np.asarray(ctx.attr('values'))
    shape = ctx.attr('shape', None)
    if shape:
        values = values.reshape(shape)
    ctx.set_output('Out', jnp.asarray(values, dtype=ctx.out_dtype('Out')))


@register('cast')
def _cast(ctx):
    from ..core.dtypes import to_jnp_dtype
    x = ctx.input('X')
    ctx.set_output('Out', x.astype(to_jnp_dtype(ctx.attr('out_dtype'))))


@register('concat')
def _concat(ctx):
    xs = ctx.input_list('X')
    ctx.set_output('Out', jnp.concatenate(xs, axis=ctx.attr('axis', 0)))


@register('assign')
def _assign(ctx):
    ctx.set_output('Out', ctx.input('X'))


@register('sum')
def _sum(ctx):
    xs = ctx.input_list('X')
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    ctx.set_output('Out', out)


@register('split')
def _split(ctx):
    x = ctx.input('X')
    axis = ctx.attr('axis', 0)
    sections = ctx.attr('sections', None)
    num = ctx.attr('num', 0)
    if sections:
        idx = []
        acc = 0
        for s in sections[:-1]:
            acc += s
            idx.append(acc)
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    ctx.set_output_list('Out', outs)


@register('reshape')
def _reshape(ctx):
    x = ctx.input('X')
    shape = list(ctx.attr('shape'))
    # fluid semantics: 0 -> copy dim from x, -1 -> infer
    for i, s in enumerate(shape):
        if s == 0:
            shape[i] = x.shape[i]
    ctx.set_output('Out', jnp.reshape(x, shape))


@register('transpose')
def _transpose(ctx):
    ctx.set_output('Out', jnp.transpose(ctx.input('X'), ctx.attr('axis')))


@register('one_hot')
def _one_hot(ctx):
    x = ctx.input('X')
    depth = ctx.attr('depth')
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x.squeeze(-1)
    ctx.set_output('Out', jax.nn.one_hot(x, depth,
                                         dtype=ctx.out_dtype('Out')))


@register('increment')
def _increment(ctx):
    x = ctx.input('X')
    ctx.set_output('Out', x + jnp.asarray(ctx.attr('step', 1.0), x.dtype))


@register('clip')
def _clip(ctx):
    x = ctx.input('X')
    ctx.set_output('Out', jnp.clip(x, ctx.attr('min'), ctx.attr('max')))


@register('clip_by_norm')
def _clip_by_norm(ctx):
    x = ctx.input('X')
    max_norm = ctx.attr('max_norm')
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12),
                      jnp.asarray(1.0, x.dtype))
    ctx.set_output('Out', x * scale.astype(x.dtype))


@register('global_norm_clip')
def _global_norm_clip(ctx):
    """Fused global-norm gradient clip (reference clip.py builds this from
    many small ops; one op here so XLA fuses the whole rescale)."""
    grads = ctx.input_list('X')
    max_norm = ctx.attr('max_global_norm')
    total = jnp.asarray(0.0, jnp.float32)
    for g in grads:
        total = total + jnp.sum(jnp.square(g.astype(jnp.float32)))
    norm = jnp.sqrt(total)
    scale = jnp.minimum(1.0, max_norm / jnp.maximum(norm, 1e-12))
    ctx.set_output_list('Out', [(g * scale.astype(g.dtype)) for g in grads])


@register('top_k')
def _top_k(ctx):
    x = ctx.input('X')
    k = ctx.attr('k', 1)
    values, indices = jax.lax.top_k(x, k)
    ctx.set_output('Out', values)
    ctx.set_output('Indices', indices.astype(canonical_int())
                   if ctx.out_var('Indices') is not None and
                   ctx.out_var('Indices').dtype == 'int64' else indices)


@register('cumsum')
def _cumsum(ctx):
    x = ctx.input('X')
    axis = ctx.attr('axis', -1)
    exclusive = ctx.attr('exclusive', False)
    reverse = ctx.attr('reverse', False)
    if reverse:
        x = jnp.flip(x, axis=axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis=axis)
    ctx.set_output('Out', out)


@register('expand')
def _expand(ctx):
    x = ctx.input('X')
    times = ctx.attr('expand_times')
    ctx.set_output('Out', jnp.tile(x, times))


@register('stack')
def _stack(ctx):
    xs = ctx.input_list('X')
    ctx.set_output('Out', jnp.stack(xs, axis=ctx.attr('axis', 0)))


@register('squeeze')
def _squeeze(ctx):
    x = ctx.input('X')
    axes = ctx.attr('axes', None)
    ctx.set_output('Out', jnp.squeeze(x, axis=tuple(axes) if axes else None))


@register('unsqueeze')
def _unsqueeze(ctx):
    x = ctx.input('X')
    for ax in sorted(ctx.attr('axes')):
        x = jnp.expand_dims(x, ax)
    ctx.set_output('Out', x)


@register('slice')
def _slice(ctx):
    # the reference slice_op names its input slot 'Input'
    x = ctx.input('Input') if ctx.has_input('Input') else ctx.input('X')
    axes = ctx.attr('axes')
    starts = ctx.attr('starts')
    ends = ctx.attr('ends')
    idx = [slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = slice(st, en)
    ctx.set_output('Out', x[tuple(idx)])


@register('gather')
def _gather(ctx):
    x = ctx.input('X')
    index = ctx.input('Index')
    if index.ndim == 2 and index.shape[1] == 1:
        index = index.squeeze(-1)
    ctx.set_output('Out', jnp.take(x, index, axis=0))


@register('scatter')
def _scatter(ctx):
    x = ctx.input('X')
    index = ctx.input('Ids')
    updates = ctx.input('Updates')
    if index.ndim == 2 and index.shape[1] == 1:
        index = index.squeeze(-1)
    ctx.set_output('Out', x.at[index].set(updates))


@register('shape')
def _shape(ctx):
    x = ctx.input('X')
    ctx.set_output('Out', jnp.asarray(x.shape, dtype=jnp.int32))


@register('pad')
def _pad(ctx):
    x = ctx.input('X')
    paddings = ctx.attr('paddings')
    pad_value = ctx.attr('pad_value', 0.0)
    cfg = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(x.ndim)]
    ctx.set_output('Out', jnp.pad(x, cfg, constant_values=pad_value))


@register('reverse')
def _reverse(ctx):
    x = ctx.input('X')
    axes = ctx.attr('axis')
    if isinstance(axes, int):
        axes = [axes]
    for ax in axes:
        x = jnp.flip(x, axis=ax)
    ctx.set_output('Out', x)


@register('multiplex')
def _multiplex(ctx):
    ids = ctx.input('Ids')
    xs = ctx.input_list('X')
    stacked = jnp.stack(xs, axis=0)  # [n, batch, ...]
    ids = ids.reshape(-1).astype(jnp.int32)
    rows = jnp.arange(ids.shape[0])
    ctx.set_output('Out', stacked[ids, rows])
