"""Ragged paged attention for autoregressive decode serving.

One decode step attends one query token per sequence against that
sequence's KV cache, which lives in a pool of fixed-size blocks
("pages") in HBM — the paged-KV design from PAPERS "Ragged Paged
Attention". Each sequence owns a *block table* (logical page i ->
physical page id) and a true length; batches are ragged (every row has
a different live length) so a dense [B, Tmax] cache would pay padding
FLOPs and, worse, padding HBM. Pages decouple cache capacity from
per-sequence reservation: a 17-token sequence holds ceil(17/bs) pages,
not Tmax slots.

Two backends, selected like ops/pallas/flash_attention.py:

- **XLA gather path** (default, and the CPU/tier-1 path): gather the
  per-sequence pages through the block table into [B, H, P*bs, D],
  mask columns >= seq_len, fp32 softmax. XLA fuses the gather into the
  attention chain; on small decode shapes this is already near-optimal.
- **Pallas kernel** (PADDLE_TPU_USE_PALLAS=1): the block table rides
  scalar prefetch (pltpu.PrefetchScalarGridSpec) so each grid step's
  page index map reads table[b, page] — the kernel DMAs exactly the
  pages a sequence owns, pages past seq_len are skipped entirely
  (ragged: short sequences cost proportionally less), and the online-
  softmax recurrence matches the flash kernel's.

Parity across mixed sequence lengths vs a dense masked reference is
asserted in tests/test_decode_serving.py (XLA path) and
tests/test_pallas_kernels.py (kernel, interpret mode).

Layouts:
    q            [B, H, D]      one query token per sequence
    k/v_pages    [NB, H, bs, D] the pooled page arena (one layer)
    block_tables [B, P] int32   physical page ids; >= NB means "no page"
    seq_lens     [B]  int32     live tokens (this token included)
"""

import functools
import os

import jax
import jax.numpy as jnp

from . import interpret_mode
from . import pallas_enabled
from . import tpu_compiler_params

_NEG_INF = -1e9


def paged_attention_reference(q, k_pages, v_pages, block_tables, seq_lens,
                              sm_scale=None, k_scales=None,
                              v_scales=None):
    """XLA gather path. Bit-stable contract with the Pallas kernel's
    masking: columns >= seq_lens[b] contribute exactly 0 (exp of a
    large-negative underflows), so the result is independent of the
    garbage content of unowned/partial pages.

    Quantized arenas: ``k_scales``/``v_scales`` [NB, H, bs] carry one
    fp32 scale per stored (page, head, slot) K/V row; the gather
    dequantizes to fp32 through the same table indices before the
    attention math (fp32 accumulation — int8/fp8 only ever live in
    HBM)."""
    nb, h, bs, d = k_pages.shape
    b, p = block_tables.shape
    scale = sm_scale if sm_scale is not None else d ** -0.5
    tables = jnp.clip(block_tables.astype(jnp.int32), 0, nb - 1)
    # [B, P, H, bs, D] -> [B, H, P*bs, D]
    k = jnp.transpose(k_pages[tables], (0, 2, 1, 3, 4)) \
        .reshape(b, h, p * bs, d)
    v = jnp.transpose(v_pages[tables], (0, 2, 1, 3, 4)) \
        .reshape(b, h, p * bs, v_pages.shape[-1])
    if k_scales is not None:
        ks = jnp.transpose(k_scales[tables], (0, 2, 1, 3)) \
            .reshape(b, h, p * bs)
        vs = jnp.transpose(v_scales[tables], (0, 2, 1, 3)) \
            .reshape(b, h, p * bs)
        k = k.astype(jnp.float32) * ks[..., None]
        v = v.astype(jnp.float32) * vs[..., None]
    logits = jnp.einsum('bhd,bhkd->bhk', (q * scale), k)
    mask = jnp.arange(p * bs)[None, :] < seq_lens.reshape(-1, 1)
    logits = jnp.where(mask[:, None, :], logits, _NEG_INF)
    w = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum('bhk,bhkd->bhd', w.astype(v.dtype), v)


def _paged_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, bs, num_pages, sm_scale):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    pi = pl.program_id(2)
    seq_len = len_ref[b]

    @pl.when(pi == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(pi * bs < seq_len)
    def _body():
        q = q_ref[0]                                   # [1, d]
        k = k_ref[0, 0]                                # [bs, d]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [1, bs]
        cols = pi * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        s = jnp.where(cols < seq_len, s, _NEG_INF)

        m_prev = m_scr[:]                              # [1, 128]
        l_prev = l_scr[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)      # [1, 1]
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next[:, :1])                 # [1, bs] f32
        l_cur = jnp.sum(p, axis=1, keepdims=True)
        m_scr[:] = m_next
        l_scr[:] = alpha * l_prev + l_cur
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)        # [1, d]
        acc_scr[:] = acc_scr[:] * alpha[:, :1] + pv

    @pl.when(pi == num_pages - 1)
    def _finish():
        denom = l_scr[:][:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def _paged_pallas(q, k_pages, v_pages, block_tables, seq_lens, sm_scale):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nb, h, bs, d = k_pages.shape
    b, p = block_tables.shape
    dv = v_pages.shape[-1]
    tables = jnp.clip(block_tables.astype(jnp.int32), 0, nb - 1)
    lens = seq_lens.astype(jnp.int32)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,        # block tables, lengths
        grid=(b, h, p),
        in_specs=[
            # q [B, H, D]: one (1, d) row per (b, h); page axis constant
            pl.BlockSpec((1, 1, d),
                         lambda bi, hi, pi, bt, ln: (bi, hi, 0)),
            # pages: the physical page id comes from the prefetched
            # block table — the ragged gather IS the index map
            pl.BlockSpec((1, 1, bs, d),
                         lambda bi, hi, pi, bt, ln: (bt[bi, pi], hi, 0, 0)),
            pl.BlockSpec((1, 1, bs, dv),
                         lambda bi, hi, pi, bt, ln: (bt[bi, pi], hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, dv),
                               lambda bi, hi, pi, bt, ln: (bi, hi, 0)),
        scratch_shapes=[
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, 128), jnp.float32),
            pltpu.VMEM((1, dv), jnp.float32),
        ],
    )
    kernel = functools.partial(_paged_kernel, bs=bs, num_pages=p,
                               sm_scale=sm_scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, dv), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=('parallel', 'parallel', 'arbitrary')),
        interpret=interpret_mode(),
    )(tables, lens, q, k_pages, v_pages)


def _use_pallas(q, k_pages, v_pages, block_tables):
    """The kernel wants lane-aligned page tiles; anything else takes the
    gather path (which handles every shape). Precedence: an EXPLICIT
    PADDLE_TPU_PAGED_PALLAS overrides everything (in either direction),
    then an explicit PADDLE_TPU_USE_PALLAS, then — with
    PADDLE_TPU_AUTOTUNE=on — the per-shape tuning table (this is the
    dispatch the decode engine's ops/paged_decode_ops.py hot loop rides
    through), then the pallas_enabled() default (off)."""
    nb, h, bs, d = k_pages.shape
    aligned = bs % 8 == 0 and d % 8 == 0
    env = os.environ.get('PADDLE_TPU_PAGED_PALLAS')
    if env is not None:
        return env not in ('0', 'false', 'False') and aligned
    from ... import tuning
    if tuning.autotune_mode() != 'off' and \
            not tuning.env_gate_set('PADDLE_TPU_USE_PALLAS'):
        b, p = block_tables.shape
        picked = tuning.decide_paged_attention(
            b, p, h, bs, d, v_pages.shape[-1], str(q.dtype))
        if picked is not None:
            return picked.get('impl') == 'pallas' and aligned
    return pallas_enabled() and aligned


def paged_attention(q, k_pages, v_pages, block_tables, seq_lens,
                    sm_scale=None, k_scales=None, v_scales=None):
    """Ragged paged attention: one query per sequence against its paged
    KV cache. q [B, H, D]; pages [NB, H, bs, D*]; block_tables [B, P]
    int32 (entries >= NB mean "no page" and are never read); seq_lens
    [B] int32. Quantized arenas pass their per-row fp32 scale arenas
    as ``k_scales``/``v_scales`` [NB, H, bs] and take the gather path
    (which dequantizes inline; the Pallas kernel stays fp32/bf16).
    Returns [B, H, Dv]."""
    nb, h, bs, d = k_pages.shape
    scale = sm_scale if sm_scale is not None else d ** -0.5
    if k_scales is None and str(k_pages.dtype) in ('float32', 'bfloat16') \
            and _use_pallas(q, k_pages, v_pages, block_tables):
        return _paged_pallas(q, k_pages, v_pages, block_tables, seq_lens,
                             scale)
    return paged_attention_reference(q, k_pages, v_pages, block_tables,
                                     seq_lens, sm_scale=scale,
                                     k_scales=k_scales,
                                     v_scales=v_scales)
