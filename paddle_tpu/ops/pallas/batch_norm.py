"""One-pass fused batch-norm (training) as a Pallas TPU kernel.

VERDICT r4 next-#2: the ResNet forward (~44 TF/s vs ~68 bwd) pays the
conv→BN-stats serialization — XLA schedules the stats reduction and the
normalize as separate HBM passes over the conv output, with whatever
fusion the compiler chooses. This kernel pins the schedule: ONE
pallas_call computes fp32-accumulated statistics AND the bf16
elementwise normalize, reading x exactly twice and writing y once,
with the per-channel a/b folding (y = x·a + b) done in VMEM between
the phases. Semantics match reference batch_norm_op.cc training mode
(biased variance, saved mean/var outputs).

Grid layout: (C/bc, 2, R/br) over x reshaped [R, C] (NHWC rows ×
channels — channels ride the lane dimension). Phase 0 accumulates
sum / sumsq tiles into VMEM scratch ([8, bc] sublane partials, folded
at the end); phase 1 replays the same row blocks through y = x·a + b.
The phase-0 output index map pins all writes to block 0 so the unwritten
output buffer is fetched/copied back at most once before phase 1
rewrites it (revisiting semantics: the buffer only flushes when its
mapped index changes).

Backward is the standard BN gradient in jnp (custom_vjp): the backward
phase is already the efficient one on chip (SURVEY §7.16), so only the
forward schedule needed pinning.

Opt-in: PADDLE_TPU_BN_PALLAS=1 (benched as resnet50_bn_pallas A/B).
"""

import functools
import os

import jax
import jax.numpy as jnp

from . import interpret_mode
from . import tpu_compiler_params

DEFAULT_BLOCK_R = 512


def _default_block_r():
    # read per call (not at import) so env changes after import — and
    # the autotuner's in-process sweeps — take effect
    return int(os.environ.get('PADDLE_TPU_BN_BLOCK_R',
                              str(DEFAULT_BLOCK_R)))


def bn_pallas_enabled():
    return os.environ.get('PADDLE_TPU_BN_PALLAS') == '1'


def _bn_kernel(x_ref, scale_ref, bias_ref, y_ref, mean_ref, var_ref,
               sum_scr, sq_scr, ab_scr, *, eps, rows_total, block_r,
               num_r_blocks):
    from jax.experimental import pallas as pl

    ph = pl.program_id(1)
    rb = pl.program_id(2)

    @pl.when((ph == 0) & (rb == 0))
    def _init():
        sum_scr[:] = jnp.zeros_like(sum_scr)
        sq_scr[:] = jnp.zeros_like(sq_scr)

    @pl.when(ph == 0)
    def _accumulate():
        x = x_ref[...]
        xf = x.astype(jnp.float32)
        # fold block rows onto the 8-sublane partials; full fp32 adds
        part = xf.reshape(block_r // 8, 8, xf.shape[-1])
        sum_scr[:] = sum_scr[:] + jnp.sum(part, axis=0)
        sq_scr[:] = sq_scr[:] + jnp.sum(jnp.square(part), axis=0)

    @pl.when((ph == 0) & (rb == num_r_blocks - 1))
    def _stats():
        n = jnp.float32(rows_total)
        mean = jnp.sum(sum_scr[:], axis=0, keepdims=True) / n   # [1, bc]
        var = jnp.maximum(
            jnp.sum(sq_scr[:], axis=0, keepdims=True) / n
            - jnp.square(mean), 0.0)
        mean_ref[...] = mean
        var_ref[...] = var
        inv = jax.lax.rsqrt(var + eps)
        a = scale_ref[...].astype(jnp.float32) * inv
        b = bias_ref[...].astype(jnp.float32) - mean * a
        ab_scr[0:1] = a
        ab_scr[1:2] = b

    @pl.when(ph == 1)
    def _normalize():
        x = x_ref[...]
        a = ab_scr[0:1].astype(x.dtype)
        b = ab_scr[1:2].astype(x.dtype)
        y_ref[...] = x * a + b


def _fused_bn_fwd(x2, scale, bias, eps, block_r):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    r, c = x2.shape
    block_r = min(block_r, r)
    while r % block_r != 0 or block_r % 8 != 0:
        block_r //= 2
        if block_r < 8:
            raise ValueError('fused BN needs rows divisible by 8; got %d'
                             % r)
    bc = min(c, 128)
    if c % bc != 0:
        raise ValueError('fused BN needs channels %% 128 == 0 or < 128; '
                         'got %d' % c)
    num_r_blocks = r // block_r
    grid = (c // bc, 2, num_r_blocks)
    kernel = functools.partial(
        _bn_kernel, eps=eps, rows_total=r, block_r=block_r,
        num_r_blocks=num_r_blocks)
    y, mean, var = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_r, bc), lambda cb, ph, rb: (rb, cb)),
            pl.BlockSpec((1, bc), lambda cb, ph, rb: (0, cb)),
            pl.BlockSpec((1, bc), lambda cb, ph, rb: (0, cb)),
        ],
        out_specs=[
            # phase 0 pins writes to block 0; phase 1 sweeps the rows
            pl.BlockSpec((block_r, bc),
                         lambda cb, ph, rb: (ph * rb, cb)),
            pl.BlockSpec((1, bc), lambda cb, ph, rb: (0, cb)),
            pl.BlockSpec((1, bc), lambda cb, ph, rb: (0, cb)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((r, c), x2.dtype),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((1, c), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((8, bc), jnp.float32),
            pltpu.VMEM((8, bc), jnp.float32),
            pltpu.VMEM((2, bc), jnp.float32),
        ],
        compiler_params=tpu_compiler_params(
            dimension_semantics=('parallel', 'arbitrary', 'arbitrary')),
        interpret=interpret_mode(),
    )(x2, scale.reshape(1, c), bias.reshape(1, c))
    return y, mean.reshape(c), var.reshape(c)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_bn_core(x2, scale, bias, eps, block_r):
    return _fused_bn_fwd(x2, scale, bias, eps, block_r)


def _bn_vjp_fwd(x2, scale, bias, eps, block_r):
    y, mean, var = _fused_bn_fwd(x2, scale, bias, eps, block_r)
    return (y, mean, var), (x2, scale, mean, var)


def _bn_vjp_bwd(eps, block_r, res, cts):
    """Standard training-BN gradient (reference batch_norm_grad_op
    semantics), in jnp — the backward phase is the one XLA already runs
    efficiently. Cotangents of the mean/var outputs are ignored: they
    feed stop_gradient'd running stats in the lowering."""
    x2, scale, mean, var = res
    gy = cts[0]
    n = jnp.float32(x2.shape[0])
    inv = jax.lax.rsqrt(var + eps)                          # [C] f32
    xf = x2.astype(jnp.float32)
    gyf = gy.astype(jnp.float32)
    xhat = (xf - mean[None, :]) * inv[None, :]
    dbias = jnp.sum(gyf, axis=0)                            # [C]
    dscale = jnp.sum(gyf * xhat, axis=0)                    # [C]
    dx = (scale.astype(jnp.float32) * inv)[None, :] * (
        gyf - dbias[None, :] / n - xhat * dscale[None, :] / n)
    return dx.astype(x2.dtype), dscale.astype(scale.dtype), \
        dbias.astype(scale.dtype)


_fused_bn_core.defvjp(_bn_vjp_fwd, _bn_vjp_bwd)


def fused_batch_norm_train(x, scale, bias, eps, layout='NHWC',
                           block_r=None):
    """Training-mode BN via the one-pass kernel. x: [N,H,W,C] (NHWC),
    [N,C,H,W] (NCHW — transposed through the kernel's row layout), or
    [N,C]. Returns (y, batch_mean, batch_var) with y in x.dtype and
    fp32 stats."""
    if x.ndim == 4 and layout == 'NCHW':
        xt = x.transpose(0, 2, 3, 1)
        y, m, v = fused_batch_norm_train(xt, scale, bias, eps, 'NHWC',
                                         block_r)
        return y.transpose(0, 3, 1, 2), m, v
    shape = x.shape
    c = shape[-1]
    x2 = x.reshape(-1, c)
    y, mean, var = _fused_bn_core(x2, scale, bias, eps,
                                  block_r or _default_block_r())
    return y.reshape(shape), mean, var


def _bn_reference(x2, scale, bias, eps):
    """jnp reference for parity tests."""
    xf = x2.astype(jnp.float32)
    mean = jnp.mean(xf, axis=0)
    var = jnp.mean(jnp.square(xf), axis=0) - jnp.square(mean)
    inv = jax.lax.rsqrt(var + eps)
    a = scale.astype(jnp.float32) * inv
    b = bias.astype(jnp.float32) - mean * a
    y = (xf * a[None, :] + b[None, :]).astype(x2.dtype)
    return y, mean, var
