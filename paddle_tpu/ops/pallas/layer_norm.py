"""Fused layer_norm as a Pallas TPU kernel.

XLA already fuses mean/var/normalize chains well; the win here is for
long rows (d_model >= 1024) where a single-pass Welford-style kernel
halves HBM traffic vs the two-pass XLA pattern by keeping the row tile
in VMEM across both statistics and normalization.

Gated by ops.pallas.pallas_enabled() like flash attention (tunneled
backends can't remote-compile Pallas); the jnp fallback matches
bit-for-bit at fp32.
"""

import functools

import jax
import jax.numpy as jnp

from . import interpret_mode

BLOCK_ROWS = 256


def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = xc * inv * g_ref[...].astype(jnp.float32) + \
        b_ref[...].astype(jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _ln_pallas(x2, gamma, beta, eps, block_rows=None):
    from jax.experimental import pallas as pl

    n, d = x2.shape
    rows = block_rows if block_rows else BLOCK_ROWS
    while n % rows:
        rows //= 2
    grid = (n // rows,)
    return pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((rows, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x2.dtype),
        interpret=interpret_mode(),
    )(x2, gamma, beta)


def _ln_reference(x2, gamma, beta, eps):
    x = x2.astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    y = xc * jax.lax.rsqrt(var + eps) * gamma + beta
    return y.astype(x2.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ln_2d(x2, gamma, beta, eps):
    from . import pallas_enabled
    n, d = x2.shape
    # Autotuned dispatch (r8): with PADDLE_TPU_AUTOTUNE=on and no
    # explicit PADDLE_TPU_USE_PALLAS the tuning table picks the impl
    # (and the Pallas row-block size) per (n, d, dtype). The decision is
    # memoized, so the forward and the vjp-fwd replay agree.
    from ... import tuning
    if tuning.autotune_mode() != 'off' and \
            not tuning.env_gate_set('PADDLE_TPU_USE_PALLAS'):
        picked = tuning.decide_layer_norm(n, d, str(x2.dtype))
        if picked is not None:
            if picked.get('impl') == 'pallas' and d % 128 == 0:
                return _ln_pallas(x2, gamma, beta, eps,
                                  block_rows=picked.get('block_rows'))
            return _ln_reference(x2, gamma, beta, eps)
    if pallas_enabled() and d % 128 == 0 and d >= 1024:
        return _ln_pallas(x2, gamma, beta, eps)
    return _ln_reference(x2, gamma, beta, eps)


def _ln_vjp_fwd(x2, gamma, beta, eps):
    return _ln_2d(x2, gamma, beta, eps), (x2, gamma, beta)


def _ln_vjp_bwd(eps, res, g):
    # Rematerializing XLA backward (Pallas kernels are not autodiffable);
    # the forward stays fused.
    x2, gamma, beta = res
    _, vjp = jax.vjp(lambda a, b, c: _ln_reference(a, b, c, eps),
                     x2, gamma, beta)
    return vjp(g)


_ln_2d.defvjp(_ln_vjp_fwd, _ln_vjp_bwd)


def fused_layer_norm(x, gamma, beta, eps=1e-5, begin_norm_axis=-1):
    """Normalize over the trailing dims from begin_norm_axis; gamma/beta
    are flat over the normalized extent."""
    shape = x.shape
    if begin_norm_axis < 0:
        begin_norm_axis = x.ndim + begin_norm_axis
    d = 1
    for s in shape[begin_norm_axis:]:
        d *= s
    x2 = x.reshape(-1, d)
    y = _ln_2d(x2, gamma.reshape(d), beta.reshape(d), eps)
    return y.reshape(shape)
