"""Hand-written Pallas TPU kernels for the hot ops (flash attention,
fused normalization). Everything here has a jnp fallback so the same IR
runs on CPU test meshes."""

import os


def pallas_enabled():
    """Whether to dispatch hot ops to Pallas kernels.

    Default: only on a directly-attached TPU backend. The 'axon' tunnel
    backend remote-compiles Pallas kernels and (as of this image) hangs
    on pallas_call lowering — measured: even a trivial kernel never
    returns — so it is excluded until the relay supports it. Override
    with PADDLE_TPU_USE_PALLAS=1/0.
    """
    import jax
    env = os.environ.get('PADDLE_TPU_USE_PALLAS')
    if env is not None:
        return env not in ('0', 'false', 'False')
    try:
        return jax.default_backend() == 'tpu'
    except Exception:
        return False
