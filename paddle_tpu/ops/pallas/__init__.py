"""Hand-written Pallas TPU kernels for the hot ops (flash attention,
fused normalization). Everything here has a jnp fallback so the same IR
runs on CPU test meshes."""
