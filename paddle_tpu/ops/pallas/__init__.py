"""Hand-written Pallas TPU kernels for the hot ops (flash attention,
fused normalization). Everything here has a jnp fallback so the same IR
runs on CPU test meshes."""

import os


def interpret_mode():
    """PADDLE_TPU_PALLAS_INTERPRET=1 runs kernels in interpret mode
    (CPU parity tests, tests/test_pallas_kernels.py)."""
    return os.environ.get('PADDLE_TPU_PALLAS_INTERPRET') == '1'


def tpu_compiler_params(**kwargs):
    """pltpu.CompilerParams was named TPUCompilerParams before jax 0.6;
    resolve whichever this jax ships."""
    from jax.experimental.pallas import tpu as pltpu
    cls = getattr(pltpu, 'CompilerParams', None) or pltpu.TPUCompilerParams
    return cls(**kwargs)


def pallas_enabled():
    """Whether to dispatch hot ops to Pallas kernels.

    Default: OFF — opt in with PADDLE_TPU_USE_PALLAS=1. Measured on the
    v5e chip (round 3, bench.py workloads end-to-end): flash attention
    is 25% SLOWER than XLA's fused attention at the bench shapes
    (seq 64: 76.5k vs 102.1k tok/s) — XLA's own attention fusion is
    already MXU-optimal here, so hand kernels must earn their place
    per-shape. The FA2 backward kernels are interpret-parity-tested vs
    the XLA VJP (tests/test_pallas_kernels.py); their on-chip
    measurement is pending — the tunneled relay's Pallas compile
    intermittently hangs (observed down to a trivial kernel), which is
    the reason this gate exists. On-chip numerics parity is attempted
    every bench run behind a watchdog (pallas_parity_max_abs_err in
    the BENCH detail), so the kernels stay correct for shapes where a
    future chip/toolchain flips the verdict.
    """
    env = os.environ.get('PADDLE_TPU_USE_PALLAS')
    if env is not None:
        return env not in ('0', 'false', 'False')
    return False
